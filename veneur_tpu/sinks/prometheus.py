"""Prometheus sink: statsd repeater to a prometheus statsd-exporter.

Parity: reference sinks/prometheus/prometheus.go — each flushed metric is
re-emitted as a DogStatsD line to a statsd_exporter address over UDP or
TCP; metric names and tags are sanitized to the exporter's accepted
character set.
"""

from __future__ import annotations

import logging
import re
import socket
from typing import Optional

from veneur_tpu.core.metrics import InterMetric, MetricType
from veneur_tpu.sinks import MetricSink
from veneur_tpu.sinks.delivery import make_manager
from veneur_tpu.sinks.journal_codec import HttpEnvelope

log = logging.getLogger("veneur_tpu.sinks.prometheus")

_INVALID_NAME = re.compile(r"[^a-zA-Z0-9_:.]")  # dots map to exporter paths
_INVALID_TAG = re.compile(r"[^a-zA-Z0-9_:,=\.]")
# exposition format: metric names allow [a-zA-Z0-9_:], label names
# [a-zA-Z0-9_] (the exposition writer has no dot-to-path mapping)
_INVALID_EXPO_NAME = re.compile(r"[^a-zA-Z0-9_:]")
_INVALID_EXPO_LABEL = re.compile(r"[^a-zA-Z0-9_]")


def sanitize_name(name: str) -> str:
    return _INVALID_NAME.sub("_", name)


def sanitize_tag(tag: str) -> str:
    return _INVALID_TAG.sub("_", tag)


def expo_value(v: float) -> str:
    """Exposition sample value rendering (pinned == the native
    emitter's expo_value_append)."""
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return str(v)


def expo_sample(name: str, tags: list[str], value: float,
                excluded_tags=None) -> str:
    """One exposition text line: name{label="value",...} value\\n.
    Label keys dedup by their SANITIZED form (last value wins, first
    position kept); exclusion matches the RAW tag key. Pinned
    byte-identical to vn_encode_prometheus_exposition."""
    labels: dict[str, str] = {}
    for tag in tags:
        rawkey, _, val = tag.partition(":")
        if excluded_tags and rawkey in excluded_tags:
            continue
        key = _INVALID_EXPO_LABEL.sub("_", rawkey)
        labels[key] = val
    line = _INVALID_EXPO_NAME.sub("_", name)
    if labels:
        line += "{" + ",".join(
            '%s="%s"' % (k, v.replace("\\", "\\\\").replace('"', '\\"')
                         .replace("\n", "\\n"))
            for k, v in labels.items()) + "}"
    return f"{line} {expo_value(value)}\n"


class PrometheusMetricSink(MetricSink):
    supports_columnar = True

    def __init__(self, repeater_address: str, network_type: str = "tcp",
                 flush_timeout_s: float = 10.0, delivery=None) -> None:
        host, _, port = repeater_address.rpartition(":")
        self.address = (host or "127.0.0.1", int(port))
        self.network_type = network_type
        self.flush_timeout_s = flush_timeout_s
        self._sock: Optional[socket.socket] = None
        self.delivery = make_manager("prometheus", delivery)
        self.flushed_metrics = 0
        self.flush_errors = 0

    def name(self) -> str:
        return "prometheus"

    def _connect(self, timeout: Optional[float] = None) -> socket.socket:
        if self._sock is None:
            if self.network_type == "udp":
                self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                self._sock.connect(self.address)
            else:
                self._sock = socket.create_connection(
                    self.address, timeout=timeout or self.flush_timeout_s)
        return self._sock

    def _statsd_line(self, m: InterMetric) -> Optional[bytes]:
        if m.type == MetricType.COUNTER:
            kind = "c"
        elif m.type == MetricType.GAUGE:
            kind = "g"
        else:
            return None  # statsd_exporter has no service-check concept
        line = f"{sanitize_name(m.name)}:{m.value}|{kind}"
        if m.tags:
            line += "|#" + ",".join(sanitize_tag(t) for t in m.tags)
        return line.encode("utf-8")

    def flush(self, metrics: list[InterMetric]) -> None:
        self._send([ln for ln in (self._statsd_line(m) for m in metrics)
                    if ln is not None])

    def _group_lines(self, g, excluded_tags, append) -> None:
        """Per-row Python formatter for one column group (the fallback
        when the native emit tier can't take it)."""
        counter = MetricType.COUNTER
        gauge = MetricType.GAUGE
        for fam in g.families:
            if fam.type == counter:
                kind = "c"
            elif fam.type == gauge:
                kind = "g"
            else:
                continue
            vals = fam.values.tolist()
            suffix = fam.suffix
            for i in g.rows_for(fam).tolist():
                name, tags, sinks = g.meta_at(i)
                if g.has_routing and sinks is not None \
                        and self.name() not in sinks:
                    continue
                if excluded_tags:
                    tags = [t for t in tags
                            if t.split(":", 1)[0] not in excluded_tags]
                line = (f"{sanitize_name(name + suffix if suffix else name)}"
                        f":{vals[i]}|{kind}")
                if tags:
                    line += "|#" + ",".join(
                        sanitize_tag(t) for t in tags)
                append(line.encode("utf-8"))

    def _extra_lines(self, batch, excluded_tags, append) -> None:
        counter = MetricType.COUNTER
        gauge = MetricType.GAUGE
        for m in batch.extras:
            if m.sinks is not None and self.name() not in m.sinks:
                continue
            if m.type == counter:
                kind = "c"
            elif m.type == gauge:
                kind = "g"
            else:
                continue
            tags = m.tags
            if excluded_tags:
                tags = [t for t in tags
                        if t.split(":", 1)[0] not in excluded_tags]
            line = f"{sanitize_name(m.name)}:{m.value}|{kind}"
            if tags:
                line += "|#" + ",".join(sanitize_tag(t) for t in tags)
            append(line.encode("utf-8"))

    def flush_columnar(self, batch, excluded_tags=None) -> None:
        """Columnar Python path: statsd lines straight from the batch
        columns, no InterMetric objects (core/columnar.py). The native
        serializer path is flush_columnar_native; the server negotiates
        between the two per flush."""
        lines: list[bytes] = []
        for g in batch.groups:
            self._group_lines(g, excluded_tags, lines.append)
        self._extra_lines(batch, excluded_tags, lines.append)
        self._send(lines)

    supports_native_emit = True

    def flush_columnar_native(self, batch, excluded_tags=None) -> bool:
        """Native emit path: the whole line blob comes out of
        vn_encode_prometheus_lines in one GIL-free pass over the batch's
        frag arena and value columns. Groups without a plan (routing,
        separator-laden names) fall back to the Python formatter;
        returns False when the native tier is unavailable."""
        from veneur_tpu import native as native_mod

        if not native_mod.emit_available():
            return False
        plans = batch.emit_plan()
        lines: list[bytes] = []
        excl = sorted(excluded_tags) if excluded_tags else []
        for g, plan in zip(batch.groups, plans):
            out = None
            if plan is not None:
                out = native_mod.encode_prometheus_lines(
                    plan.meta_blob, plan.nrows, plan.suffixes,
                    plan.family_types, plan.values, plan.masks, excl)
            if out is None:
                self._group_lines(g, excluded_tags, lines.append)
                continue
            blob, n = out
            if n:
                lines.append(blob)
        self._extra_lines(batch, excluded_tags, lines.append)
        self._send(lines)
        return True

    # max UDP datagram payload: statsd exporters accept multi-line
    # datagrams; stay under a jumbo-frame-safe size
    UDP_DATAGRAM_BYTES = 8192

    def _send(self, lines: list[bytes]) -> None:
        if not lines:
            return
        self.delivery.begin_flush()
        self.delivery.retry_spill()
        sent_lines = sum(e.count(b"\n") + 1 for e in lines)

        def send(timeout: float) -> None:
            try:
                sock = self._connect(timeout)
                if self.network_type == "udp":
                    # entries may be multi-line blobs (native emitter);
                    # repack into datagram-sized, line-aligned chunks
                    for entry in lines:
                        if len(entry) <= self.UDP_DATAGRAM_BYTES:
                            sock.send(entry)
                            continue
                        start = 0
                        n = len(entry)
                        while start < n:
                            end = min(start + self.UDP_DATAGRAM_BYTES, n)
                            if end < n:
                                nl = entry.rfind(b"\n", start, end)
                                if nl > start:
                                    end = nl
                            sock.send(entry[start:end])
                            start = end + (1 if end < n and
                                           entry[end:end + 1] == b"\n"
                                           else 0)
                else:
                    sock.settimeout(timeout)
                    sock.sendall(b"\n".join(lines) + b"\n")
                self.flushed_metrics += sent_lines
            except OSError:
                # stale socket: force a fresh connect on the next attempt
                self._sock = None
                raise

        if self.delivery.deliver(send, sum(len(e) for e in lines)) \
                != "delivered":
            self.flush_errors += 1
            log.warning("prometheus repeater send not delivered this flush")


class PrometheusExpositionSink(MetricSink):
    """Pushgateway-style exposition sink: each flush POSTs one
    text-format body (`name{label="value",...} value` lines) to the
    configured address. Samples are untyped (a pushgateway body carries
    no TYPE/HELP comments); only counters and gauges are expressible.

    The native emit tier (vn_encode_prometheus_exposition) builds the
    whole body in one GIL-free pass; the Python formatter (expo_sample)
    is pinned byte-identical by tests/test_emit_parity.py."""

    supports_columnar = True
    supports_native_emit = True

    def __init__(self, address: str, opener=None, delivery=None) -> None:
        from veneur_tpu.utils.http import default_opener

        self.address = address
        self.opener = opener or default_opener
        self.delivery = make_manager("prometheus", delivery)
        self.flushed_metrics = 0
        self.flush_errors = 0

    def name(self) -> str:
        return "prometheus"

    def _group_samples(self, g, excluded_tags, append) -> None:
        counter = MetricType.COUNTER
        gauge = MetricType.GAUGE
        for fam in g.families:
            if fam.type not in (counter, gauge):
                continue
            vals = fam.values.tolist()
            suffix = fam.suffix
            for i in g.rows_for(fam).tolist():
                name, tags, sinks = g.meta_at(i)
                if g.has_routing and sinks is not None \
                        and self.name() not in sinks:
                    continue
                append(expo_sample(name + suffix if suffix else name,
                                   tags, vals[i], excluded_tags))

    def _extra_samples(self, batch, excluded_tags, append) -> None:
        for m in batch.extras:
            if m.sinks is not None and self.name() not in m.sinks:
                continue
            if m.type not in (MetricType.COUNTER, MetricType.GAUGE):
                continue
            append(expo_sample(m.name, m.tags, m.value, excluded_tags))

    def flush(self, metrics) -> None:
        parts = []
        for m in metrics:
            if m.type in (MetricType.COUNTER, MetricType.GAUGE):
                parts.append(expo_sample(m.name, m.tags, m.value))
        self._post("".join(parts).encode("utf-8"), len(parts))

    def flush_columnar(self, batch, excluded_tags=None) -> None:
        parts: list[str] = []
        for g in batch.groups:
            self._group_samples(g, excluded_tags, parts.append)
        self._extra_samples(batch, excluded_tags, parts.append)
        self._post("".join(parts).encode("utf-8"), len(parts))

    def flush_columnar_native(self, batch, excluded_tags=None) -> bool:
        from veneur_tpu import native as native_mod

        if not native_mod.emit_available():
            return False
        plans = batch.emit_plan()
        chunks: list[bytes] = []
        count = 0
        excl = sorted(excluded_tags) if excluded_tags else []
        for g, plan in zip(batch.groups, plans):
            out = None
            if plan is not None:
                out = native_mod.encode_prometheus_exposition(
                    plan.meta_blob, plan.nrows, plan.suffixes,
                    plan.family_types, plan.values, plan.masks, excl)
            if out is None:
                parts: list[str] = []
                self._group_samples(g, excluded_tags, parts.append)
                chunks.append("".join(parts).encode("utf-8"))
                count += len(parts)
                continue
            blob, n = out
            chunks.append(blob)
            count += n
        parts = []
        self._extra_samples(batch, excluded_tags, parts.append)
        chunks.append("".join(parts).encode("utf-8"))
        count += len(parts)
        self._post(b"".join(chunks), count)
        return True

    def _post(self, body: bytes, count: int) -> None:
        from veneur_tpu.utils.http import post_bytes

        self.delivery.begin_flush()
        self.delivery.retry_spill()
        if not count:
            return

        hdrs = {"Content-Type": "text/plain; version=0.0.4"}

        def send(timeout: float) -> None:
            post_bytes(self.address, body, hdrs, timeout, self.opener)
            self.flushed_metrics += count

        env = HttpEnvelope(url=self.address, body=body, headers=hdrs,
                           count=count)
        if self.delivery.deliver(send, len(body), payload=env) != "delivered":
            self.flush_errors += 1
            log.warning("prometheus exposition post not delivered "
                        "this flush")
