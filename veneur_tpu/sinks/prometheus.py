"""Prometheus sink: statsd repeater to a prometheus statsd-exporter.

Parity: reference sinks/prometheus/prometheus.go — each flushed metric is
re-emitted as a DogStatsD line to a statsd_exporter address over UDP or
TCP; metric names and tags are sanitized to the exporter's accepted
character set.
"""

from __future__ import annotations

import logging
import re
import socket
from typing import Optional

from veneur_tpu.core.metrics import InterMetric, MetricType
from veneur_tpu.sinks import MetricSink

log = logging.getLogger("veneur_tpu.sinks.prometheus")

_INVALID_NAME = re.compile(r"[^a-zA-Z0-9_:.]")  # dots map to exporter paths
_INVALID_TAG = re.compile(r"[^a-zA-Z0-9_:,=\.]")


def sanitize_name(name: str) -> str:
    return _INVALID_NAME.sub("_", name)


def sanitize_tag(tag: str) -> str:
    return _INVALID_TAG.sub("_", tag)


class PrometheusMetricSink(MetricSink):
    supports_columnar = True

    def __init__(self, repeater_address: str, network_type: str = "tcp"
                 ) -> None:
        host, _, port = repeater_address.rpartition(":")
        self.address = (host or "127.0.0.1", int(port))
        self.network_type = network_type
        self._sock: Optional[socket.socket] = None
        self.flushed_metrics = 0
        self.flush_errors = 0

    def name(self) -> str:
        return "prometheus"

    def _connect(self) -> socket.socket:
        if self._sock is None:
            if self.network_type == "udp":
                self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                self._sock.connect(self.address)
            else:
                self._sock = socket.create_connection(self.address,
                                                      timeout=10)
        return self._sock

    def _statsd_line(self, m: InterMetric) -> Optional[bytes]:
        if m.type == MetricType.COUNTER:
            kind = "c"
        elif m.type == MetricType.GAUGE:
            kind = "g"
        else:
            return None  # statsd_exporter has no service-check concept
        line = f"{sanitize_name(m.name)}:{m.value}|{kind}"
        if m.tags:
            line += "|#" + ",".join(sanitize_tag(t) for t in m.tags)
        return line.encode("utf-8")

    def flush(self, metrics: list[InterMetric]) -> None:
        self._send([ln for ln in (self._statsd_line(m) for m in metrics)
                    if ln is not None])

    def flush_columnar(self, batch, excluded_tags=None) -> None:
        """Columnar path: statsd lines straight from the batch columns —
        built by the native line emitter (vn_encode_prometheus_lines)
        when available, per-row Python otherwise. Either way no
        InterMetric objects in between (core/columnar.py)."""
        import numpy as np

        from veneur_tpu import native as native_mod
        from veneur_tpu.core.metrics import MetricType as _MT

        lines = []
        append = lines.append
        counter = MetricType.COUNTER
        gauge = MetricType.GAUGE
        excl = sorted(excluded_tags) if excluded_tags else []
        for g in batch.groups:
            frags = None
            if g.frag_at is not None and not g.has_routing \
                    and native_mod.available():
                frags = []
                for i in range(g.nrows):
                    f = g.frag_at(i)
                    if f is None:
                        frags = None
                        break
                    frags.append(f)
            if frags is not None:
                fams = [fam for fam in g.families
                        if fam.type in (counter, gauge)]
                if not fams:
                    continue
                out = native_mod.encode_prometheus_lines(
                    b"\x1e".join(frags), g.nrows,
                    [fam.suffix for fam in fams],
                    np.asarray([0 if fam.type == _MT.COUNTER else 1
                                for fam in fams], np.int8),
                    np.stack([fam.values for fam in fams]),
                    np.stack([
                        fam.mask.astype(np.uint8) if fam.mask is not None
                        else np.ones(g.nrows, np.uint8)
                        for fam in fams]),
                    excl)
                if out is not None:
                    blob, n = out
                    if n:
                        append(blob)
                    continue
            # python path for this group
            for fam in g.families:
                if fam.type == counter:
                    kind = "c"
                elif fam.type == gauge:
                    kind = "g"
                else:
                    continue
                vals = fam.values.tolist()
                suffix = fam.suffix
                for i in g.rows_for(fam).tolist():
                    name, tags, sinks = g.meta_at(i)
                    if g.has_routing and sinks is not None \
                            and self.name() not in sinks:
                        continue
                    if excluded_tags:
                        tags = [t for t in tags
                                if t.split(":", 1)[0] not in excluded_tags]
                    line = (f"{sanitize_name(name + suffix if suffix else name)}"
                            f":{vals[i]}|{kind}")
                    if tags:
                        line += "|#" + ",".join(
                            sanitize_tag(t) for t in tags)
                    append(line.encode("utf-8"))
        for m in batch.extras:
            if m.sinks is not None and self.name() not in m.sinks:
                continue
            if m.type == counter:
                kind = "c"
            elif m.type == gauge:
                kind = "g"
            else:
                continue
            tags = m.tags
            if excluded_tags:
                tags = [t for t in tags
                        if t.split(":", 1)[0] not in excluded_tags]
            line = f"{sanitize_name(m.name)}:{m.value}|{kind}"
            if tags:
                line += "|#" + ",".join(sanitize_tag(t) for t in tags)
            append(line.encode("utf-8"))
        self._send(lines)

    # max UDP datagram payload: statsd exporters accept multi-line
    # datagrams; stay under a jumbo-frame-safe size
    UDP_DATAGRAM_BYTES = 8192

    def _send(self, lines: list[bytes]) -> None:
        if not lines:
            return
        sent_lines = sum(e.count(b"\n") + 1 for e in lines)
        try:
            sock = self._connect()
            if self.network_type == "udp":
                # entries may be multi-line blobs (native emitter);
                # repack into datagram-sized, line-aligned chunks
                for entry in lines:
                    if len(entry) <= self.UDP_DATAGRAM_BYTES:
                        sock.send(entry)
                        continue
                    start = 0
                    n = len(entry)
                    while start < n:
                        end = min(start + self.UDP_DATAGRAM_BYTES, n)
                        if end < n:
                            nl = entry.rfind(b"\n", start, end)
                            if nl > start:
                                end = nl
                        sock.send(entry[start:end])
                        start = end + (1 if end < n and
                                       entry[end:end + 1] == b"\n" else 0)
            else:
                sock.sendall(b"\n".join(lines) + b"\n")
            self.flushed_metrics += sent_lines
        except OSError as e:
            self.flush_errors += 1
            self._sock = None
            log.warning("prometheus repeater send failed: %s", e)
