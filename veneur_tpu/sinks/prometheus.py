"""Prometheus sink: statsd repeater to a prometheus statsd-exporter.

Parity: reference sinks/prometheus/prometheus.go — each flushed metric is
re-emitted as a DogStatsD line to a statsd_exporter address over UDP or
TCP; metric names and tags are sanitized to the exporter's accepted
character set.
"""

from __future__ import annotations

import logging
import re
import socket
from typing import Optional

from veneur_tpu.core.metrics import InterMetric, MetricType
from veneur_tpu.sinks import MetricSink

log = logging.getLogger("veneur_tpu.sinks.prometheus")

_INVALID_NAME = re.compile(r"[^a-zA-Z0-9_:.]")  # dots map to exporter paths
_INVALID_TAG = re.compile(r"[^a-zA-Z0-9_:,=\.]")


def sanitize_name(name: str) -> str:
    return _INVALID_NAME.sub("_", name)


def sanitize_tag(tag: str) -> str:
    return _INVALID_TAG.sub("_", tag)


class PrometheusMetricSink(MetricSink):
    supports_columnar = True

    def __init__(self, repeater_address: str, network_type: str = "tcp"
                 ) -> None:
        host, _, port = repeater_address.rpartition(":")
        self.address = (host or "127.0.0.1", int(port))
        self.network_type = network_type
        self._sock: Optional[socket.socket] = None
        self.flushed_metrics = 0
        self.flush_errors = 0

    def name(self) -> str:
        return "prometheus"

    def _connect(self) -> socket.socket:
        if self._sock is None:
            if self.network_type == "udp":
                self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                self._sock.connect(self.address)
            else:
                self._sock = socket.create_connection(self.address,
                                                      timeout=10)
        return self._sock

    def _statsd_line(self, m: InterMetric) -> Optional[bytes]:
        if m.type == MetricType.COUNTER:
            kind = "c"
        elif m.type == MetricType.GAUGE:
            kind = "g"
        else:
            return None  # statsd_exporter has no service-check concept
        line = f"{sanitize_name(m.name)}:{m.value}|{kind}"
        if m.tags:
            line += "|#" + ",".join(sanitize_tag(t) for t in m.tags)
        return line.encode("utf-8")

    def flush(self, metrics: list[InterMetric]) -> None:
        self._send([ln for ln in (self._statsd_line(m) for m in metrics)
                    if ln is not None])

    def flush_columnar(self, batch, excluded_tags=None) -> None:
        """Columnar path: statsd lines straight from the batch columns —
        the per-metric work here is the wire format itself, no
        InterMetric objects in between (core/columnar.py)."""
        lines = []
        append = lines.append
        counter = MetricType.COUNTER
        gauge = MetricType.GAUGE
        for name, value, tags, mtype, _ts in batch.iter_rows(
                self.name(), excluded_tags):
            if mtype == counter:
                kind = "c"
            elif mtype == gauge:
                kind = "g"
            else:
                continue
            line = f"{sanitize_name(name)}:{value}|{kind}"
            if tags:
                line += "|#" + ",".join(sanitize_tag(t) for t in tags)
            append(line.encode("utf-8"))
        self._send(lines)

    def _send(self, lines: list[bytes]) -> None:
        if not lines:
            return
        try:
            sock = self._connect()
            if self.network_type == "udp":
                for ln in lines:
                    sock.send(ln)
            else:
                sock.sendall(b"\n".join(lines) + b"\n")
            self.flushed_metrics += len(lines)
        except OSError as e:
            self.flush_errors += 1
            self._sock = None
            log.warning("prometheus repeater send failed: %s", e)
