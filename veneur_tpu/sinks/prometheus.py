"""Prometheus sink: statsd repeater to a prometheus statsd-exporter.

Parity: reference sinks/prometheus/prometheus.go — each flushed metric is
re-emitted as a DogStatsD line to a statsd_exporter address over UDP or
TCP; metric names and tags are sanitized to the exporter's accepted
character set.
"""

from __future__ import annotations

import logging
import socket
from typing import Optional

from veneur_tpu.core.metrics import InterMetric, MetricType
from veneur_tpu.sinks import MetricSink
from veneur_tpu.sinks.delivery import make_manager
from veneur_tpu.sinks.journal_codec import HttpEnvelope

# the exposition-text formatter lives in sinks/exposition.py so the
# live query surface (veneur_tpu/query/http.py) and this sink serialize
# series identically; the names are re-exported here for compatibility
from veneur_tpu.sinks.exposition import (  # noqa: F401
    expo_sample,
    expo_value,
    render_columnar,
    render_metrics,
    sanitize_name,
    sanitize_tag,
)

log = logging.getLogger("veneur_tpu.sinks.prometheus")


class PrometheusMetricSink(MetricSink):
    supports_columnar = True

    def __init__(self, repeater_address: str, network_type: str = "tcp",
                 flush_timeout_s: float = 10.0, delivery=None) -> None:
        host, _, port = repeater_address.rpartition(":")
        self.address = (host or "127.0.0.1", int(port))
        self.network_type = network_type
        self.flush_timeout_s = flush_timeout_s
        self._sock: Optional[socket.socket] = None
        self.delivery = make_manager("prometheus", delivery)
        self.flushed_metrics = 0
        self.flush_errors = 0

    def name(self) -> str:
        return "prometheus"

    def _connect(self, timeout: Optional[float] = None) -> socket.socket:
        if self._sock is None:
            if self.network_type == "udp":
                self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                self._sock.connect(self.address)
            else:
                self._sock = socket.create_connection(
                    self.address, timeout=timeout or self.flush_timeout_s)
        return self._sock

    def _statsd_line(self, m: InterMetric) -> Optional[bytes]:
        if m.type == MetricType.COUNTER:
            kind = "c"
        elif m.type == MetricType.GAUGE:
            kind = "g"
        else:
            return None  # statsd_exporter has no service-check concept
        line = f"{sanitize_name(m.name)}:{m.value}|{kind}"
        if m.tags:
            line += "|#" + ",".join(sanitize_tag(t) for t in m.tags)
        return line.encode("utf-8")

    def flush(self, metrics: list[InterMetric]) -> None:
        self._send([ln for ln in (self._statsd_line(m) for m in metrics)
                    if ln is not None])

    def _group_lines(self, g, excluded_tags, append) -> None:
        """Per-row Python formatter for one column group (the fallback
        when the native emit tier can't take it)."""
        counter = MetricType.COUNTER
        gauge = MetricType.GAUGE
        for fam in g.families:
            if fam.type == counter:
                kind = "c"
            elif fam.type == gauge:
                kind = "g"
            else:
                continue
            vals = fam.values.tolist()
            suffix = fam.suffix
            for i in g.rows_for(fam).tolist():
                name, tags, sinks = g.meta_at(i)
                if g.has_routing and sinks is not None \
                        and self.name() not in sinks:
                    continue
                if excluded_tags:
                    tags = [t for t in tags
                            if t.split(":", 1)[0] not in excluded_tags]
                line = (f"{sanitize_name(name + suffix if suffix else name)}"
                        f":{vals[i]}|{kind}")
                if tags:
                    line += "|#" + ",".join(
                        sanitize_tag(t) for t in tags)
                append(line.encode("utf-8"))

    def _extra_lines(self, batch, excluded_tags, append) -> None:
        counter = MetricType.COUNTER
        gauge = MetricType.GAUGE
        for m in batch.extras:
            if m.sinks is not None and self.name() not in m.sinks:
                continue
            if m.type == counter:
                kind = "c"
            elif m.type == gauge:
                kind = "g"
            else:
                continue
            tags = m.tags
            if excluded_tags:
                tags = [t for t in tags
                        if t.split(":", 1)[0] not in excluded_tags]
            line = f"{sanitize_name(m.name)}:{m.value}|{kind}"
            if tags:
                line += "|#" + ",".join(sanitize_tag(t) for t in tags)
            append(line.encode("utf-8"))

    def flush_columnar(self, batch, excluded_tags=None) -> None:
        """Columnar Python path: statsd lines straight from the batch
        columns, no InterMetric objects (core/columnar.py). The native
        serializer path is flush_columnar_native; the server negotiates
        between the two per flush."""
        lines: list[bytes] = []
        for g in batch.groups:
            self._group_lines(g, excluded_tags, lines.append)
        self._extra_lines(batch, excluded_tags, lines.append)
        self._send(lines)

    supports_native_emit = True

    def flush_columnar_native(self, batch, excluded_tags=None) -> bool:
        """Native emit path: the whole line blob comes out of
        vn_encode_prometheus_lines in one GIL-free pass over the batch's
        frag arena and value columns. Groups without a plan (routing,
        separator-laden names) fall back to the Python formatter;
        returns False when the native tier is unavailable."""
        from veneur_tpu import native as native_mod

        if not native_mod.emit_available():
            return False
        plans = batch.emit_plan()
        lines: list[bytes] = []
        excl = sorted(excluded_tags) if excluded_tags else []
        for g, plan in zip(batch.groups, plans):
            out = None
            if plan is not None:
                out = native_mod.encode_prometheus_lines(
                    plan.meta_blob, plan.nrows, plan.suffixes,
                    plan.family_types, plan.values, plan.masks, excl)
            if out is None:
                self._group_lines(g, excluded_tags, lines.append)
                continue
            blob, n = out
            if n:
                lines.append(blob)
        self._extra_lines(batch, excluded_tags, lines.append)
        self._send(lines)
        return True

    # max UDP datagram payload: statsd exporters accept multi-line
    # datagrams; stay under a jumbo-frame-safe size
    UDP_DATAGRAM_BYTES = 8192

    def _send(self, lines: list[bytes]) -> None:
        if not lines:
            return
        self.delivery.begin_flush()
        self.delivery.retry_spill()
        sent_lines = sum(e.count(b"\n") + 1 for e in lines)

        def send(timeout: float) -> None:
            try:
                sock = self._connect(timeout)
                if self.network_type == "udp":
                    # entries may be multi-line blobs (native emitter);
                    # repack into datagram-sized, line-aligned chunks
                    for entry in lines:
                        if len(entry) <= self.UDP_DATAGRAM_BYTES:
                            sock.send(entry)
                            continue
                        start = 0
                        n = len(entry)
                        while start < n:
                            end = min(start + self.UDP_DATAGRAM_BYTES, n)
                            if end < n:
                                nl = entry.rfind(b"\n", start, end)
                                if nl > start:
                                    end = nl
                            sock.send(entry[start:end])
                            start = end + (1 if end < n and
                                           entry[end:end + 1] == b"\n"
                                           else 0)
                else:
                    sock.settimeout(timeout)
                    sock.sendall(b"\n".join(lines) + b"\n")
                self.flushed_metrics += sent_lines
            except OSError:
                # stale socket: force a fresh connect on the next attempt
                self._sock = None
                raise

        if self.delivery.deliver(send, sum(len(e) for e in lines)) \
                != "delivered":
            self.flush_errors += 1
            log.warning("prometheus repeater send not delivered this flush")


class PrometheusExpositionSink(MetricSink):
    """Pushgateway-style exposition sink: each flush POSTs one
    text-format body (`name{label="value",...} value` lines) to the
    configured address. Samples are untyped (a pushgateway body carries
    no TYPE/HELP comments); only counters and gauges are expressible.

    The native emit tier (vn_encode_prometheus_exposition) builds the
    whole body in one GIL-free pass; the Python formatter (expo_sample)
    is pinned byte-identical by tests/test_emit_parity.py."""

    supports_columnar = True
    supports_native_emit = True

    def __init__(self, address: str, opener=None, delivery=None) -> None:
        from veneur_tpu.utils.http import default_opener

        self.address = address
        self.opener = opener or default_opener
        self.delivery = make_manager("prometheus", delivery)
        self.flushed_metrics = 0
        self.flush_errors = 0

    def name(self) -> str:
        return "prometheus"

    def flush(self, metrics) -> None:
        body, count = render_metrics(metrics)
        self._post(body, count)

    def flush_columnar(self, batch, excluded_tags=None) -> None:
        body, count = render_columnar(batch, self.name(), excluded_tags,
                                      native=False)
        self._post(body, count)

    def flush_columnar_native(self, batch, excluded_tags=None) -> bool:
        from veneur_tpu import native as native_mod

        if not native_mod.emit_available():
            return False
        body, count = render_columnar(batch, self.name(), excluded_tags,
                                      native=True)
        self._post(body, count)
        return True

    def _post(self, body: bytes, count: int) -> None:
        from veneur_tpu.utils.http import post_bytes

        self.delivery.begin_flush()
        self.delivery.retry_spill()
        if not count:
            return

        hdrs = {"Content-Type": "text/plain; version=0.0.4"}

        def send(timeout: float) -> None:
            post_bytes(self.address, body, hdrs, timeout, self.opener)
            self.flushed_metrics += count

        env = HttpEnvelope(url=self.address, body=body, headers=hdrs,
                           count=count)
        if self.delivery.deliver(send, len(body), payload=env) != "delivered":
            self.flush_errors += 1
            log.warning("prometheus exposition post not delivered "
                        "this flush")
