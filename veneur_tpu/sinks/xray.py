"""AWS X-Ray span sink: UDP segments to the X-Ray daemon.

Parity: reference sinks/xray/xray.go — spans become X-Ray segment JSON
datagrams prefixed with the daemon header, sampled by a percentage on the
trace id, with configured annotation tags lifted into annotations.
"""

from __future__ import annotations

import json
import logging
import socket

from veneur_tpu.sinks import SpanSink
from veneur_tpu.ssf import SSFSpan

log = logging.getLogger("veneur_tpu.sinks.xray")

_HEADER = b'{"format": "json", "version": 1}\n'


def _trace_id_for(span: SSFSpan) -> str:
    """X-Ray trace id format: 1-<8 hex epoch seconds>-<24 hex>."""
    epoch = span.start_timestamp // 1_000_000_000
    return f"1-{epoch:08x}-{span.trace_id & ((1 << 96) - 1):024x}"


class XRaySpanSink(SpanSink):
    def __init__(self, daemon_address: str = "127.0.0.1:2000",
                 sample_percentage: float = 100.0,
                 annotation_tags: list[str] | None = None) -> None:
        host, _, port = daemon_address.rpartition(":")
        self.address = (host or "127.0.0.1", int(port))
        self.sample_percentage = max(0.0, min(100.0, sample_percentage))
        self.annotation_tags = set(annotation_tags or [])
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.spans_flushed = 0
        self.spans_dropped = 0

    def name(self) -> str:
        return "xray"

    def ingest(self, span: SSFSpan) -> None:
        if self.sample_percentage < 100.0:
            if (span.trace_id % 10000) >= self.sample_percentage * 100:
                self.spans_dropped += 1
                return
        annotations = {
            k: v for k, v in span.tags.items() if k in self.annotation_tags
        }
        segment = {
            "name": (span.service or "unknown")[:200],
            "id": f"{span.id & ((1 << 64) - 1):016x}",
            "trace_id": _trace_id_for(span),
            "start_time": span.start_timestamp / 1e9,
            "end_time": span.end_timestamp / 1e9,
            "error": span.error,
            "annotations": annotations,
            "metadata": {"tags": dict(span.tags), "name": span.name},
        }
        if span.parent_id:
            segment["parent_id"] = f"{span.parent_id & ((1 << 64) - 1):016x}"
            segment["type"] = "subsegment"
        try:
            self.sock.sendto(
                _HEADER + json.dumps(segment).encode("utf-8"), self.address)
            self.spans_flushed += 1
        except OSError as e:
            self.spans_dropped += 1
            log.debug("xray send failed: %s", e)

    def flush(self) -> None:
        pass
