"""A from-scratch Kafka wire-protocol producer.

The reference's kafka sink is a sarama async producer
(sinks/kafka/kafka.go:109-141: ack requirement, hash/random partitioner,
retry max, flush thresholds by bytes/messages/frequency). This module
speaks the actual Kafka broker protocol so the sink produces bytes a
real broker accepts — no client library required:

* **Metadata v0** (api_key 3) to the bootstrap broker: discovers broker
  addresses and per-partition leaders.
* **Produce v1** (api_key 0) per leader: required_acks / timeout, one
  magic-1 MessageSet (CRC-32, timestamp) per topic-partition.
* **Hash partitioning** with fnv1a-32 over the message key, matching
  sarama's NewHashPartitioner, so a key lands on the same partition a
  sarama producer would pick; `random` partitioner supported.
* **Retriable-error handling**: on connection failure or a retriable
  partition error code (leader moved, etc.) the producer refreshes
  metadata and retries up to ``retry_max`` times.

Buffering matches the sink's produce semantics: ``send`` appends to a
per-(topic, partition) buffer; the buffer flushes when ``buffer_bytes``
/ ``buffer_messages`` thresholds are crossed or on an explicit
``flush()`` (the sink calls it every interval), mirroring sarama's
Flush.Bytes / Flush.Messages / Flush.Frequency triple.

Wire format notes (all integers big-endian):
  request  = int32 size, int16 api_key, int16 api_version,
             int32 correlation_id, nullable_string client_id, body
  string   = int16 length + bytes        (-1 = null)
  bytes    = int32 length + bytes        (-1 = null)
  array    = int32 count + elements
  message (magic 1) = int32 crc32-of-rest, int8 magic, int8 attrs,
             int64 timestamp_ms, bytes key, bytes value
  message_set entry = int64 offset, int32 message_size, message
"""

from __future__ import annotations

import logging
import random
import socket
import struct
import threading
import time
import zlib
from typing import Optional

log = logging.getLogger("veneur_tpu.sinks.kafka_wire")

API_PRODUCE = 0
API_METADATA = 3

ACKS_NONE = 0
ACKS_LOCAL = 1
ACKS_ALL = -1

# error codes a fresh metadata fetch can fix (broker moved / catching up)
RETRIABLE_ERRORS = {
    5,   # LEADER_NOT_AVAILABLE
    6,   # NOT_LEADER_FOR_PARTITION
    7,   # REQUEST_TIMED_OUT
    8,   # BROKER_NOT_AVAILABLE
    9,   # REPLICA_NOT_AVAILABLE
    13,  # NETWORK_EXCEPTION
}


def _fnv1a32(data: bytes) -> int:
    h = 0x811C9DC5
    for b in data:
        h ^= b
        h = (h * 0x01000193) & 0xFFFFFFFF
    return h


def enc_string(s: Optional[str]) -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    raw = s.encode("utf-8")
    return struct.pack(">h", len(raw)) + raw


def enc_bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


class _Reader:
    """Cursor over a response payload."""

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise ValueError("short kafka response")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def i16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def string(self) -> Optional[str]:
        n = self.i16()
        if n < 0:
            return None
        return self._take(n).decode("utf-8")


def encode_message(key: Optional[bytes], value: Optional[bytes],
                   timestamp_ms: int) -> bytes:
    """One magic-1 message: crc over everything after the crc field."""
    body = (struct.pack(">bbq", 1, 0, timestamp_ms)
            + enc_bytes(key) + enc_bytes(value))
    crc = zlib.crc32(body) & 0xFFFFFFFF
    return struct.pack(">I", crc) + body


def encode_message_set(messages: list[tuple[Optional[bytes],
                                            Optional[bytes], int]]) -> bytes:
    """MessageSet: offsets are producer-side placeholders (brokers assign
    real offsets; any value is legal in produce requests)."""
    out = []
    for i, (key, value, ts) in enumerate(messages):
        msg = encode_message(key, value, ts)
        out.append(struct.pack(">qi", i, len(msg)) + msg)
    return b"".join(out)


class BrokerConnection:
    """One TCP connection to one broker; request/response framing."""

    def __init__(self, host: str, port: int, client_id: str,
                 timeout: float = 10.0) -> None:
        self.host, self.port = host, port
        self.client_id = client_id
        self.timeout = timeout
        self.sock: Optional[socket.socket] = None
        self._corr = 0

    def connect(self) -> None:
        if self.sock is not None:
            return
        self.sock = socket.create_connection((self.host, self.port),
                                             timeout=self.timeout)

    def close(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None

    def request(self, api_key: int, api_version: int, body: bytes,
                expect_response: bool = True) -> Optional[_Reader]:
        self.connect()
        assert self.sock is not None
        self._corr += 1
        corr = self._corr
        header = (struct.pack(">hhi", api_key, api_version, corr)
                  + enc_string(self.client_id))
        frame = header + body
        self.sock.sendall(struct.pack(">i", len(frame)) + frame)
        if not expect_response:
            return None
        raw = self._read_exact(4)
        (size,) = struct.unpack(">i", raw)
        if not 0 <= size <= 100 * 1024 * 1024:
            # response sizes beyond any sane broker config mean a
            # corrupt/hostile peer; don't allocate on its say-so
            raise ValueError(f"implausible kafka response size {size}")
        payload = self._read_exact(size)
        r = _Reader(payload)
        got_corr = r.i32()
        if got_corr != corr:
            raise ValueError(
                f"correlation id mismatch: sent {corr}, got {got_corr}")
        return r

    def _read_exact(self, n: int) -> bytes:
        assert self.sock is not None
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("broker closed connection")
            buf += chunk
        return buf


class ClusterMetadata:
    def __init__(self) -> None:
        self.brokers: dict[int, tuple[str, int]] = {}
        # (topic, partition) -> leader node id
        self.leaders: dict[tuple[str, int], int] = {}
        # topic -> partition count
        self.partitions: dict[str, int] = {}


def parse_metadata_response(r: _Reader) -> ClusterMetadata:
    md = ClusterMetadata()
    for _ in range(r.i32()):
        node = r.i32()
        host = r.string() or ""
        port = r.i32()
        md.brokers[node] = (host, port)
    for _ in range(r.i32()):
        t_err = r.i16()
        topic = r.string() or ""
        nparts = r.i32()
        count = 0
        for _ in range(nparts):
            p_err = r.i16()
            pid = r.i32()
            leader = r.i32()
            for _ in range(r.i32()):  # replicas
                r.i32()
            for _ in range(r.i32()):  # isr
                r.i32()
            if t_err == 0 and p_err in (0, 9):  # 9: replica unavailable
                md.leaders[(topic, pid)] = leader
                count += 1
        if count:
            md.partitions[topic] = count
    return md


class KafkaWireProducer:
    """Buffering producer over the real broker protocol, with the
    reference sink's tuning surface (acks, retries, partitioner, flush
    thresholds). Thread-safe: sends may arrive from several span workers
    concurrently."""

    def __init__(self, brokers: str | list[str],
                 client_id: str = "veneur-tpu",
                 require_acks: str = "all",
                 retry_max: int = 3,
                 partitioner: str = "hash",
                 buffer_bytes: int = 0,
                 buffer_messages: int = 0,
                 buffer_ms: float = 0.0,
                 ack_timeout_ms: int = 10000,
                 connect_timeout: float = 10.0) -> None:
        if isinstance(brokers, str):
            brokers = [b.strip() for b in brokers.split(",") if b.strip()]
        self.bootstrap = []
        for b in brokers:
            host, _, port = b.rpartition(":")
            self.bootstrap.append((host or "127.0.0.1", int(port)))
        self.client_id = client_id
        self.acks = {"none": ACKS_NONE, "local": ACKS_LOCAL,
                     "all": ACKS_ALL}.get(require_acks, ACKS_ALL)
        self.retry_max = max(0, retry_max)
        self.partitioner = partitioner
        self.buffer_bytes = buffer_bytes
        self.buffer_messages = buffer_messages
        self.buffer_ms = buffer_ms
        self.ack_timeout_ms = ack_timeout_ms
        self.connect_timeout = connect_timeout

        # _lock guards the message buffer only (held for appends, never
        # across network I/O); _io_lock serializes every network path —
        # metadata refresh, broker connections, produce requests — so
        # concurrent send()/flush() callers can never interleave frames
        # on one socket. self._meta is replaced atomically and may be
        # READ without a lock; it is only written under _io_lock.
        self._lock = threading.Lock()
        self._io_lock = threading.Lock()
        # (topic, partition) -> list of (key, value, ts_ms)
        self._buf: dict[tuple[str, int],
                        list[tuple[Optional[bytes], Optional[bytes], int]]] \
            = {}
        self._buf_bytes = 0
        self._buf_msgs = 0
        self._last_flush = time.monotonic()
        self._conns: dict[int, BrokerConnection] = {}
        self._meta: Optional[ClusterMetadata] = None
        # topic -> monotonic deadline before which we won't re-fetch
        # metadata for a topic that wasn't there (avoids a per-send
        # metadata storm against a nonexistent topic)
        self._topic_retry_at: dict[str, float] = {}
        self.delivered = 0
        self.dropped = 0

    # -- metadata ------------------------------------------------------

    def _bootstrap_conn(self) -> BrokerConnection:
        errs = []
        for host, port in self.bootstrap:
            conn = BrokerConnection(host, port, self.client_id,
                                    self.connect_timeout)
            try:
                conn.connect()
                return conn
            except OSError as e:
                errs.append(f"{host}:{port}: {e}")
        raise ConnectionError("no bootstrap broker reachable: "
                              + "; ".join(errs))

    def refresh_metadata(self, topics: list[str]) -> ClusterMetadata:
        """Fetch cluster metadata. Callers must hold _io_lock."""
        body = struct.pack(">i", len(topics)) + b"".join(
            enc_string(t) for t in topics)
        conn = self._bootstrap_conn()
        try:
            r = conn.request(API_METADATA, 0, body)
            assert r is not None
            md = parse_metadata_response(r)
        finally:
            conn.close()
        self._meta = md
        return md

    def _ensure_topic(self, topic: str) -> Optional[ClusterMetadata]:
        """Metadata containing `topic`, refreshing at most once per
        backoff window for topics the cluster doesn't have. Returns None
        when the topic is (still) unknown."""
        with self._io_lock:
            meta = self._meta
            if meta is not None and topic in meta.partitions:
                return meta  # another thread already refreshed
            now = time.monotonic()
            if now < self._topic_retry_at.get(topic, 0.0):
                return None
            try:
                meta = self.refresh_metadata([topic])
            except (OSError, ValueError, ConnectionError) as e:
                log.warning("kafka metadata refresh failed: %s", e)
                self._topic_retry_at[topic] = now + 5.0
                return None
            if topic not in meta.partitions:
                self._topic_retry_at[topic] = now + 5.0
                return None
            self._topic_retry_at.pop(topic, None)
            return meta

    def _leader_conn(self, node: int) -> BrokerConnection:
        conn = self._conns.get(node)
        if conn is None:
            assert self._meta is not None
            host, port = self._meta.brokers[node]
            conn = BrokerConnection(host, port, self.client_id,
                                    self.connect_timeout)
            self._conns[node] = conn
        return conn

    # -- partitioning --------------------------------------------------

    def _partition_for(self, meta: ClusterMetadata, topic: str,
                       key: Optional[bytes]) -> int:
        n = meta.partitions.get(topic, 0)
        if n <= 0:
            raise ValueError(f"topic {topic!r} has no available partitions")
        if self.partitioner == "random" or not key:
            return random.randrange(n)
        # sarama NewHashPartitioner: fnv1a-32 of the key, modulo partition
        # count, negative-safe (int32 wrap then abs)
        h = _fnv1a32(key)
        if h >= 1 << 31:
            h -= 1 << 32
        return abs(h) % n

    # -- the producer surface used by the sinks ------------------------

    def send(self, topic: str, key: Optional[bytes],
             value: Optional[bytes]) -> None:
        ts = int(time.time() * 1000)
        meta = self._meta  # atomic read; written only under _io_lock
        if meta is None or topic not in meta.partitions:
            meta = self._ensure_topic(topic)
            if meta is None:
                # unknown topic (backoff window active): count the drop
                # rather than stall every sender on metadata round trips
                with self._lock:
                    self.dropped += 1
                return
        part = self._partition_for(meta, topic, key)
        with self._lock:
            self._buf.setdefault((topic, part), []).append((key, value, ts))
            self._buf_msgs += 1
            self._buf_bytes += (len(key or b"") + len(value or b"") + 34)
            due = (
                (self.buffer_messages
                 and self._buf_msgs >= self.buffer_messages)
                or (self.buffer_bytes
                    and self._buf_bytes >= self.buffer_bytes)
                or (self.buffer_ms and (time.monotonic() - self._last_flush)
                    * 1000.0 >= self.buffer_ms))
            batches = self._take_buffer() if due else None
        if batches:
            self._produce(batches)

    def flush(self) -> None:
        with self._lock:
            batches = self._take_buffer()
        if batches:
            self._produce(batches)

    def close(self) -> None:
        self.flush()
        with self._io_lock:
            for conn in self._conns.values():
                conn.close()
            self._conns.clear()

    def _take_buffer(self):
        batches, self._buf = self._buf, {}
        self._buf_bytes = 0
        self._buf_msgs = 0
        self._last_flush = time.monotonic()
        return batches

    # -- produce -------------------------------------------------------

    def _produce(self, batches) -> None:
        """Send buffered message sets to their partition leaders,
        refreshing metadata and retrying retriable failures. All network
        I/O (including broker connections shared in self._conns) runs
        under _io_lock so concurrent send()/flush() callers can never
        interleave frames on a socket."""
        with self._io_lock:
            self._produce_locked(batches)

    def _produce_locked(self, batches) -> None:
        attempt = 0
        while batches and attempt <= self.retry_max:
            if attempt:
                time.sleep(min(0.1 * (2 ** (attempt - 1)), 2.0))
            failed = {}
            by_leader: dict[int, dict] = {}
            topics = sorted({t for (t, _p) in batches})
            try:
                if self._meta is None:
                    self.refresh_metadata(topics)
                for (topic, part), msgs in batches.items():
                    assert self._meta is not None
                    leader = self._meta.leaders.get((topic, part))
                    if leader is None:
                        # partition vanished: re-partition by count
                        n = self._meta.partitions.get(topic, 0)
                        if n:
                            leader = self._meta.leaders.get(
                                (topic, part % n))
                    if leader is None:
                        failed[(topic, part)] = msgs
                        continue
                    by_leader.setdefault(leader, {})[(topic, part)] = msgs
            except (OSError, ValueError) as e:
                log.warning("kafka metadata refresh failed: %s", e)
                failed = batches
                by_leader = {}

            for leader, parts in by_leader.items():
                bad = self._produce_to_leader(leader, parts)
                failed.update(bad)

            if failed:
                # force a metadata refresh before the next attempt: the
                # usual cause is a moved leader
                self._meta = None
            batches = failed
            attempt += 1
        if batches:
            lost = sum(len(m) for m in batches.values())
            self.dropped += lost
            log.warning("kafka: dropping %d messages after %d attempts",
                        lost, self.retry_max + 1)

    def _produce_to_leader(self, leader: int, parts: dict) -> dict:
        """One Produce v1 request to one broker. Returns the
        (topic, partition) -> msgs map that should be retried."""
        per_topic: dict[str, list[tuple[int, bytes, list]]] = {}
        for (topic, part), msgs in parts.items():
            per_topic.setdefault(topic, []).append(
                (part, encode_message_set(msgs), msgs))

        body = [struct.pack(">hii", self.acks, self.ack_timeout_ms,
                            len(per_topic))]
        for topic, plist in per_topic.items():
            body.append(enc_string(topic))
            body.append(struct.pack(">i", len(plist)))
            for part, mset, _msgs in plist:
                body.append(struct.pack(">ii", part, len(mset)))
                body.append(mset)
        payload = b"".join(body)

        conn = self._leader_conn(leader)
        try:
            r = conn.request(API_PRODUCE, 1, payload,
                             expect_response=self.acks != ACKS_NONE)
        except (OSError, ValueError, ConnectionError) as e:
            log.warning("kafka produce to node %d failed: %s", leader, e)
            conn.close()
            return parts
        if r is None:  # acks=none: fire and forget
            self.delivered += sum(len(m) for _, _, m in
                                  (x for pl in per_topic.values()
                                   for x in pl))
            return {}
        # Produce v1 response: topics array, then throttle_time
        retry = {}
        try:
            for _ in range(r.i32()):
                topic = r.string() or ""
                for _ in range(r.i32()):
                    part = r.i32()
                    err = r.i16()
                    r.i64()  # base_offset
                    msgs = parts.get((topic, part))
                    if msgs is None:
                        continue
                    if err == 0:
                        self.delivered += len(msgs)
                    elif err in RETRIABLE_ERRORS:
                        retry[(topic, part)] = msgs
                    else:
                        self.dropped += len(msgs)
                        log.warning(
                            "kafka: fatal error %d for %s[%d]; dropping"
                            " %d messages", err, topic, part, len(msgs))
        except ValueError as e:
            log.warning("kafka: bad produce response from node %d: %s",
                        leader, e)
            conn.close()
            return parts
        return retry
