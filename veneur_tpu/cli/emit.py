"""veneur-tpu-emit: CLI metric/event/service-check/span emitter.

Parity: reference cmd/veneur-emit/main.go (763 LoC) — emit one-off
metrics via statsd or SSF, events and service checks, and `-command` mode
which times a subprocess and emits a timer (statsd) or a span (SSF) with
its exit status.
"""

from __future__ import annotations

import argparse
import random
import shlex
import socket
import subprocess
import sys
import time

from veneur_tpu import ssf
from veneur_tpu.protocol import ssf_wire


def _parse_hostport(hostport: str) -> tuple[str, str]:
    """Returns (scheme, address)."""
    if "://" in hostport:
        scheme, _, rest = hostport.partition("://")
        return scheme, rest
    return "udp", hostport


def _send_statsd(address: str, lines: list[bytes]) -> None:
    host, _, port = address.rpartition(":")
    payload = b"\n".join(lines)
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.sendto(payload, (host or "127.0.0.1", int(port)))
    sock.close()


def _send_ssf(scheme: str, address: str, span: ssf.SSFSpan) -> None:
    if scheme in ("udp", "ssf"):
        host, _, port = address.rpartition(":")
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sock.sendto(ssf_wire.encode_datagram(span),
                    (host or "127.0.0.1", int(port)))
        sock.close()
    elif scheme == "unix":
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.connect(address)
        f = sock.makefile("wb")
        ssf_wire.write_ssf(f, span)
        f.flush()
        sock.close()
    else:
        raise ValueError(f"unsupported ssf scheme {scheme}")


def _tag_arg_to_dict(tag_args: list[str]) -> dict[str, str]:
    tags = {}
    for entry in tag_args:
        for t in entry.split(","):
            if not t:
                continue
            k, _, v = t.partition(":")
            tags[k] = v
    return tags


def _parse_time_ns(spec: str) -> int:
    """Accepts unix seconds (int/float) or an ISO-8601 datetime
    (reference -span_starttime/-span_endtime take free-form dates).
    Raises SystemExit(2) with a usage message on unparseable input."""
    try:
        secs = float(spec)
        if secs != secs or secs in (float("inf"), float("-inf")):
            raise ValueError(spec)
        return int(secs * 1e9)
    except ValueError:
        pass
    import datetime

    try:
        dt = datetime.datetime.fromisoformat(spec)
    except ValueError:
        print(f"invalid time {spec!r}: pass unix seconds or an ISO-8601 "
              "datetime", file=sys.stderr)
        raise SystemExit(2) from None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return int(dt.timestamp() * 1e9)


def build_statsd_lines(args, timing_ms=None) -> list[bytes]:
    tags = ""
    tag_map = _tag_arg_to_dict(args.tag)
    if tag_map:
        joined = ",".join(f"{k}:{v}" if v else k for k, v in tag_map.items())
        tags = f"|#{joined}"
    lines = []
    if args.count is not None:
        lines.append(f"{args.name}:{args.count}|c{tags}".encode())
    if args.gauge is not None:
        lines.append(f"{args.name}:{args.gauge}|g{tags}".encode())
    if args.timing is not None:
        lines.append(f"{args.name}:{args.timing}|ms{tags}".encode())
    if timing_ms is not None:
        lines.append(f"{args.name}:{timing_ms}|ms{tags}".encode())
    if args.set is not None:
        lines.append(f"{args.name}:{args.set}|s{tags}".encode())
    return lines


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="veneur-tpu-emit")
    parser.add_argument("-hostport", default="udp://127.0.0.1:8125",
                        help="destination, e.g. udp://127.0.0.1:8125")
    parser.add_argument("-name", default="", help="metric name")
    parser.add_argument("-count", type=int, default=None)
    parser.add_argument("-gauge", type=float, default=None)
    parser.add_argument("-timing", type=float, default=None,
                        help="timing value in ms")
    parser.add_argument("-set", default=None)
    parser.add_argument("-tag", action="append", default=[],
                        help="tag(s), k:v comma separated; repeatable")
    parser.add_argument("-ssf", action="store_true",
                        help="emit over SSF instead of statsd")
    parser.add_argument("-mode", default="metric",
                        choices=["metric", "event", "sc"])
    parser.add_argument("-debug", action="store_true",
                        help="print what gets emitted")
    # event fields
    parser.add_argument("-e_title", default="")
    parser.add_argument("-e_text", default="")
    parser.add_argument("-e_time", type=int, default=None)
    parser.add_argument("-e_hostname", default="")
    parser.add_argument("-e_aggr_key", default="")
    parser.add_argument("-e_priority", default="")
    parser.add_argument("-e_source_type", default="")
    parser.add_argument("-e_alert_type", default="")
    parser.add_argument("-e_event_tags", default="",
                        help="event-only tags, comma separated")
    # service-check fields
    parser.add_argument("-sc_name", default="")
    parser.add_argument("-sc_status", type=int, default=None)
    parser.add_argument("-sc_time", type=int, default=None)
    parser.add_argument("-sc_hostname", default="")
    parser.add_argument("-sc_msg", default="")
    parser.add_argument("-sc_tags", default="",
                        help="service-check-only tags, comma separated")
    # span fields (SSF mode)
    parser.add_argument("-trace_id", type=int, default=None)
    parser.add_argument("-parent_span_id", type=int, default=None)
    parser.add_argument("-span_service", default="veneur-emit")
    parser.add_argument("-span_starttime", default="",
                        help="span start (unix seconds or RFC3339)")
    parser.add_argument("-span_endtime", default="",
                        help="span end; same formats as -span_starttime")
    parser.add_argument("-span_tags", default="",
                        help="span-only tags, comma separated")
    parser.add_argument("-indicator", action="store_true")
    parser.add_argument("-error", action="store_true")
    parser.add_argument("-command", nargs=argparse.REMAINDER, default=None,
                        help="run a command, time it, and emit the timing")
    args = parser.parse_args(argv)

    scheme, address = _parse_hostport(args.hostport)
    exit_code = 0
    timing_ms = None
    cmd_error = False
    start_ns = time.time_ns()

    if args.command:
        cmd = args.command
        if len(cmd) == 1:
            cmd = shlex.split(cmd[0])
        t0 = time.time_ns()
        proc = subprocess.run(cmd)
        timing_ms = (time.time_ns() - t0) / 1e6
        exit_code = proc.returncode
        cmd_error = exit_code != 0

    def _emit_statsd(lines: list[bytes]) -> None:
        if args.debug:
            for ln in lines:
                print(f"emitting: {ln.decode(errors='replace')}",
                      file=sys.stderr)
        _send_statsd(address, lines)

    if args.mode == "event":
        title, text = args.e_title, args.e_text
        packet = f"_e{{{len(title)},{len(text)}}}:{title}|{text}"
        for flag, prefix in [
            (args.e_time, "d:"), (args.e_hostname, "h:"),
            (args.e_aggr_key, "k:"), (args.e_priority, "p:"),
            (args.e_source_type, "s:"), (args.e_alert_type, "t:"),
        ]:
            if flag:
                packet += f"|{prefix}{flag}"
        # global -tag applies everywhere; -e_event_tags only to the event
        tag_map = _tag_arg_to_dict(args.tag + [args.e_event_tags])
        if tag_map:
            packet += "|#" + ",".join(
                f"{k}:{v}" if v else k for k, v in tag_map.items())
        _emit_statsd([packet.encode()])
        return exit_code

    if args.mode == "sc":
        packet = f"_sc|{args.sc_name}|{args.sc_status}"
        if args.sc_time:
            packet += f"|d:{args.sc_time}"
        if args.sc_hostname:
            packet += f"|h:{args.sc_hostname}"
        tag_map = _tag_arg_to_dict(args.tag + [args.sc_tags])
        if tag_map:
            packet += "|#" + ",".join(
                f"{k}:{v}" if v else k for k, v in tag_map.items())
        if args.sc_msg:
            packet += f"|m:{args.sc_msg}"
        _emit_statsd([packet.encode()])
        return exit_code

    if args.ssf:
        span_id = random.getrandbits(62) + 1
        trace_id = args.trace_id or span_id
        span = ssf.SSFSpan(
            trace_id=trace_id, id=span_id,
            parent_id=args.parent_span_id or 0,
            start_timestamp=(_parse_time_ns(args.span_starttime)
                             if args.span_starttime else start_ns),
            end_timestamp=(_parse_time_ns(args.span_endtime)
                           if args.span_endtime else time.time_ns()),
            error=args.error or cmd_error,
            service=args.span_service, name=args.name or "veneur-emit",
            indicator=args.indicator,
            tags=_tag_arg_to_dict(args.tag + [args.span_tags]),
        )
        if args.debug:
            print(f"emitting span: trace_id={span.trace_id} "
                  f"id={span.id} service={span.service} "
                  f"tags={span.tags}", file=sys.stderr)
        tag_map = _tag_arg_to_dict(args.tag)
        if args.count is not None:
            span.metrics.append(ssf.count(args.name, args.count, tag_map))
        if args.gauge is not None:
            span.metrics.append(ssf.gauge(args.name, args.gauge, tag_map))
        if args.timing is not None:
            span.metrics.append(ssf.timing_ns(
                args.name, int(args.timing * 1e6), tag_map))
        if timing_ms is not None:
            span.metrics.append(ssf.timing_ns(
                args.name or "veneur-emit.command",
                int(timing_ms * 1e6), tag_map))
        if args.set is not None:
            span.metrics.append(ssf.set_sample(args.name, args.set, tag_map))
        _send_ssf(scheme, address, span)
        return exit_code

    lines = build_statsd_lines(args, timing_ms)
    if not lines:
        print("nothing to emit: pass -count/-gauge/-timing/-set or -command",
              file=sys.stderr)
        return exit_code or 1
    _emit_statsd(lines)
    return exit_code


if __name__ == "__main__":
    sys.exit(main())
