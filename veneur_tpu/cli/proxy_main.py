"""veneur-tpu-proxy: the consistent-hash proxy tier binary.

Parity: reference cmd/veneur-proxy/main.go:20-58 — reads the proxy config,
starts the gRPC proxy with Consul/Kubernetes discovery (or a static
forward address), and refreshes destinations periodically.
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading

from veneur_tpu.core.config import load_proxy_config, parse_duration
from veneur_tpu.distributed.proxy import DestinationRefresher, ProxyServer


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="veneur-tpu-proxy")
    parser.add_argument("-f", dest="config", required=True)
    parser.add_argument("-validate-config", action="store_true",
                        dest="validate")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    log = logging.getLogger("veneur_tpu.proxy-main")

    try:
        cfg = load_proxy_config(args.config)
    except Exception as e:
        print(f"config invalid: {e}", file=sys.stderr)
        return 1
    if args.validate:
        print("config valid")
        return 0
    if cfg.debug:
        logging.getLogger().setLevel(logging.DEBUG)

    static = [cfg.forward_address] if cfg.forward_address else []
    proxy = ProxyServer(static,
                        timeout_s=parse_duration(cfg.forward_timeout))
    address = cfg.grpc_address or "127.0.0.1:8128"
    port = proxy.start_grpc(address)
    log.info("proxy serving gRPC on %s (port %s)", address, port)

    refresher = None
    if cfg.consul_forward_service_name:
        from veneur_tpu.distributed.discovery import ConsulDiscoverer

        refresher = DestinationRefresher(
            proxy, ConsulDiscoverer(cfg.consul_url),
            cfg.consul_forward_service_name,
            parse_duration(cfg.consul_refresh_interval))
    elif cfg.kubernetes_forward_service_name:
        from veneur_tpu.distributed.discovery import KubernetesDiscoverer

        refresher = DestinationRefresher(
            proxy, KubernetesDiscoverer(namespace=cfg.kubernetes_namespace),
            cfg.kubernetes_forward_service_name,
            parse_duration(cfg.consul_refresh_interval))
    if refresher is not None:
        refresher.start()
    elif not static:
        log.warning("no destinations configured: set forward_address or a"
                    " discovery service name")

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    if refresher is not None:
        refresher.stop()
    proxy.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
