"""veneur-tpu-proxy: the consistent-hash proxy tier binary.

Parity: reference cmd/veneur-proxy/main.go:20-58 — reads the proxy config,
starts the gRPC proxy with Consul/Kubernetes discovery (or a static
forward address), and refreshes destinations periodically.
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading

from veneur_tpu.core.config import load_proxy_config, parse_duration
from veneur_tpu.distributed.proxy import (
    DestinationRefresher,
    ProxyHTTPServer,
    ProxyRuntimeReporter,
    ProxyServer,
    TraceProxy,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="veneur-tpu-proxy")
    parser.add_argument("-f", dest="config", required=True)
    parser.add_argument("-validate-config", action="store_true",
                        dest="validate")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    log = logging.getLogger("veneur_tpu.proxy-main")

    try:
        cfg = load_proxy_config(args.config)
    except Exception as e:
        print(f"config invalid: {e}", file=sys.stderr)
        return 1
    if args.validate:
        print("config valid")
        return 0
    if cfg.debug:
        logging.getLogger().setLevel(logging.DEBUG)

    # this proxy forwards downstream over one (gRPC) ring, so the
    # reference's separate HTTP and gRPC forward rings (proxy.go:163-166,
    # 184-187) unify. A DIFFERING pair of static addresses is rejected
    # at validation (validate_proxy_config) — by here at most one
    # distinct address survives.
    static = list(dict.fromkeys(
        a for a in (cfg.forward_address, cfg.grpc_forward_address) if a))
    forward_service = (cfg.consul_forward_service_name
                       or cfg.consul_forward_grpc_service_name)
    accepting_forwards = bool(static or forward_service
                              or cfg.kubernetes_forward_service_name
                              or cfg.elastic_membership_file)
    accepting_traces = bool(cfg.trace_address
                            or cfg.consul_trace_service_name)
    if not accepting_forwards and not accepting_traces:
        # reference proxy.go:190-199: refusing to start with no discovery
        # service names and no static addresses is an error, not a warning
        print("refusing to start with no discovery service names or"
              " static addresses in config", file=sys.stderr)
        return 1
    if not accepting_forwards:
        log.warning("no forward destinations configured: the forward "
                    "endpoints will drop every batch (trace proxying "
                    "only)")

    idle_s = (parse_duration(cfg.idle_connection_timeout)
              if cfg.idle_connection_timeout else 0.0)
    from veneur_tpu.sinks.delivery import DeliveryPolicy

    timeout_s = parse_duration(cfg.forward_timeout)
    policy = DeliveryPolicy(
        retry_max=cfg.forward_retry_max,
        breaker_threshold=cfg.forward_breaker_threshold,
        spill_max_bytes=cfg.forward_spill_max_bytes,
        spill_max_payloads=cfg.forward_spill_max_payloads,
        timeout_s=min(timeout_s, cfg.handoff_window_s),
        deadline_s=cfg.handoff_window_s)
    journal = None
    if cfg.spill_journal_dir:
        from veneur_tpu.utils.journal import SpillJournal

        journal = SpillJournal(
            cfg.spill_journal_dir,
            fsync=cfg.spill_journal_fsync,
            max_bytes=cfg.spill_journal_max_bytes,
            max_segments=cfg.spill_journal_max_segments,
            log=log.warning)
    proxy = ProxyServer(static,
                        timeout_s=timeout_s,
                        idle_timeout_s=idle_s,
                        max_idle_conns=cfg.max_idle_conns,
                        delivery=policy,
                        routing_workers=cfg.routing_pool_workers,
                        routing_queue_max=cfg.routing_queue_max,
                        handoff_window_s=cfg.handoff_window_s,
                        journal=journal,
                        dedup=cfg.forward_dedup,
                        streaming=cfg.forward_streaming,
                        stream_window=cfg.forward_stream_window,
                        stream_adaptive=getattr(
                            cfg, "forward_stream_adaptive", True),
                        stream_window_min=getattr(
                            cfg, "forward_stream_window_min", 1),
                        stream_window_max=getattr(
                            cfg, "forward_stream_window_max", 128))
    if journal is not None:
        # re-route the previous incarnation's durable spill under the
        # current ring before accepting fresh traffic
        rec = proxy.recover_journal()
        if rec["recovered_payloads"]:
            log.info("journal recovery: %s", rec)
    address = cfg.grpc_address or "127.0.0.1:8128"
    port = proxy.start_grpc(address)
    log.info("proxy serving gRPC on %s (port %s)", address, port)

    trace_proxy = None
    if cfg.trace_address or cfg.consul_trace_service_name:
        if cfg.http_address:
            trace_proxy = TraceProxy(
                [cfg.trace_address] if cfg.trace_address else [])
        else:
            # /spans over the HTTP front is the only ingest path into the
            # trace ring; without it the pipeline would be silently dead
            log.warning("trace_address/consul_trace_service_name configured"
                        " but http_address is not: trace proxying disabled"
                        " (spans arrive via POST /spans on http_address)")

    http_front = None
    if cfg.http_address:
        from veneur_tpu.utils.http import parse_host_port

        host, hport = parse_host_port(cfg.http_address, what="http_address")
        http_front = ProxyHTTPServer(proxy, trace_proxy=trace_proxy)
        http_front.start(host, hport)
        log.info("proxy serving HTTP on %s", cfg.http_address)

    refresher = None
    trace_refresher = None
    if cfg.consul_trace_service_name and trace_proxy is not None:
        from veneur_tpu.distributed.discovery import ConsulDiscoverer

        trace_refresher = DestinationRefresher(
            trace_proxy, ConsulDiscoverer(cfg.consul_url),
            cfg.consul_trace_service_name,
            parse_duration(cfg.consul_refresh_interval))
        trace_refresher.start()
    if (cfg.consul_forward_service_name
            and cfg.consul_forward_grpc_service_name
            and cfg.consul_forward_grpc_service_name != forward_service):
        log.warning("consul_forward_grpc_service_name %r ignored: this "
                    "proxy routes HTTP and gRPC forwards over one ring, "
                    "discovered from consul_forward_service_name %r",
                    cfg.consul_forward_grpc_service_name, forward_service)
    controller = None
    if cfg.elastic_membership_file:
        # elastic tier: watchable file membership, health-gated through
        # the refresher (consul/k8s answers are already health-filtered
        # upstream; the file is raw desired state, so the gate probes)
        from veneur_tpu.distributed.discovery import FileWatchDiscoverer
        from veneur_tpu.distributed.elastic import (
            ElasticController,
            HealthGate,
            ProxyPressureSource,
        )

        watcher = FileWatchDiscoverer(cfg.elastic_membership_file)
        gate = HealthGate(
            proxy,
            probe_timeout_s=cfg.elastic_probe_timeout_s,
            quarantine_after=cfg.elastic_quarantine_intervals,
            min_admitted=cfg.elastic_min_members)
        refresher = DestinationRefresher(
            proxy, watcher, "",
            parse_duration(cfg.consul_refresh_interval), gate=gate)
        if cfg.elastic_autoscale:
            psource = ProxyPressureSource(proxy)
            controller = ElasticController(
                watcher, psource,
                hysteresis_k=cfg.elastic_hysteresis_intervals,
                cooldown_s=cfg.elastic_cooldown_s,
                min_members=cfg.elastic_min_members,
                max_members=cfg.elastic_max_members,
                drained_fn=proxy.destination_idle,
                member_load_fn=psource.member_load)
    elif forward_service:
        from veneur_tpu.distributed.discovery import ConsulDiscoverer

        refresher = DestinationRefresher(
            proxy, ConsulDiscoverer(cfg.consul_url),
            forward_service,
            parse_duration(cfg.consul_refresh_interval))
    elif cfg.kubernetes_forward_service_name:
        from veneur_tpu.distributed.discovery import KubernetesDiscoverer

        refresher = DestinationRefresher(
            proxy, KubernetesDiscoverer(namespace=cfg.kubernetes_namespace),
            cfg.kubernetes_forward_service_name,
            parse_duration(cfg.consul_refresh_interval))
    if refresher is not None:
        refresher.start()
    if controller is not None:
        controller.start(cfg.elastic_observe_interval_s)

    fleet_controller = None
    if cfg.fleet_membership_file and cfg.fleet_autoscale:
        # elastic PROXY tier: this proxy observes its own fan-in
        # pressure (admission timeouts, stream window stalls, routing
        # sheds/depth) and writes the desired proxy member set back
        # through the shared fleet file every local-tier sender watches
        # via forward_discovery_file. Exactly one proxy per fleet should
        # arm this. No drained_fn: a demoted proxy keeps draining its
        # own spill toward the globals after it leaves the fleet file —
        # senders simply stop picking it.
        from veneur_tpu.distributed.discovery import FileWatchDiscoverer
        from veneur_tpu.distributed.elastic import (
            ElasticController,
            ProxyTierPressureSource,
        )

        fleet_watcher = FileWatchDiscoverer(cfg.fleet_membership_file)
        tier_source = ProxyTierPressureSource(
            lambda: {address: proxy.forward_stats()})
        fleet_controller = ElasticController(
            fleet_watcher, tier_source,
            hysteresis_k=cfg.elastic_hysteresis_intervals,
            cooldown_s=cfg.elastic_cooldown_s,
            min_members=1,
            max_members=cfg.elastic_max_members,
            member_load_fn=tier_source.member_load)
        fleet_controller.start(cfg.elastic_observe_interval_s)

    reporter = None
    if cfg.stats_address:
        from veneur_tpu import scopedstatsd

        stats = scopedstatsd.ScopedClient(
            scopedstatsd.UDPSender(cfg.stats_address),
            namespace="veneur_proxy.")
        reporter = ProxyRuntimeReporter(
            proxy, stats,
            interval_s=parse_duration(cfg.runtime_metrics_interval),
            trace_proxy=trace_proxy)
        reporter.start()

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    # graceful drain: bounded spill-settling passes before teardown —
    # whatever the deadline clips stays durable in the journal (when
    # configured) for the next incarnation's recover_journal
    if cfg.shutdown_drain_deadline_s > 0:
        import time as _time

        drain_deadline = _time.monotonic() + cfg.shutdown_drain_deadline_s
        while _time.monotonic() < drain_deadline:
            proxy.drain_spill(
                min(cfg.handoff_window_s,
                    max(0.05, drain_deadline - _time.monotonic())))
            if proxy.spilled_metrics <= 0:
                break
            _time.sleep(0.05)
        if proxy.spilled_metrics > 0:
            log.warning("shutdown drain deadline clipped: %d metric(s) "
                        "still spilled%s", proxy.spilled_metrics,
                        " (journaled for next start)" if journal is not None
                        else "")
    if reporter is not None:
        reporter.stop()
    if fleet_controller is not None:
        fleet_controller.stop()
    if controller is not None:
        controller.stop()
    if refresher is not None:
        refresher.stop()
    if trace_refresher is not None:
        trace_refresher.stop()
    if http_front is not None:
        http_front.stop()
    if trace_proxy is not None:
        trace_proxy.stop()
    proxy.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
