"""veneur-tpu-prometheus: poll a Prometheus /metrics endpoint and repeat
it as statsd.

Parity: reference cmd/veneur-prometheus — scrapes on an interval
(mTLS-capable), parses the Prometheus text exposition format, translates
counters/gauges/histograms/summaries to statsd, and dedupes monotonic
counters through a count cache so only deltas are emitted
(cmd/veneur-prometheus/main.go:27-100, translate.go, prometheus.go).
"""

from __future__ import annotations

import argparse
import logging
import re
import socket
import ssl
import sys
import time
import urllib.parse
import urllib.request
from typing import Optional

log = logging.getLogger("veneur_tpu.prometheus-poller")

_LINE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>[^ ]+)(?:\s+\d+)?$")
_LABEL = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_prometheus_text(body: str) -> tuple[dict[str, str], list[tuple]]:
    """Parse the exposition format → (type-by-name, [(name, labels, value)]).
    """
    types: dict[str, str] = {}
    samples: list[tuple] = []
    for line in body.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) >= 4:
                types[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue
        m = _LINE.match(line)
        if not m:
            continue
        labels = {}
        if m.group("labels"):
            for lm in _LABEL.finditer(m.group("labels")):
                labels[lm.group(1)] = lm.group(2).replace('\\"', '"')
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        samples.append((m.group("name"), labels, value))
    return types, samples


class CountCache:
    """Monotonic-counter dedupe: remembers the last seen value per series
    and emits only positive deltas; resets (counter restarts) emit the new
    value whole (reference countCache)."""

    def __init__(self) -> None:
        self._last: dict[tuple, float] = {}

    def delta(self, key: tuple, value: float) -> Optional[float]:
        last = self._last.get(key)
        self._last[key] = value
        if last is None:
            return None  # first scrape: establish the baseline only
        if value < last:
            return value  # counter reset
        return value - last


def translate(types: dict[str, str], samples: list[tuple],
              cache: CountCache, added_tags: list[str],
              ignored: Optional[list] = None,
              ignored_labels: Optional[list] = None,
              prefix: str = "") -> list[bytes]:
    """Prometheus samples → statsd lines (reference translate.go).

    ignored / ignored_labels: lists of compiled regexes — metric names
    matching any `ignored` entry are skipped, labels whose NAME matches
    any `ignored_labels` entry are dropped from the tag set (reference
    -ignored-metrics / -ignored-labels). prefix is prepended verbatim
    (reference -p, e.g. "myservice.")."""
    lines = []
    for name, labels, value in samples:
        if ignored and any(rx.search(name) for rx in ignored):
            continue
        base = name
        mtype = types.get(name)
        if mtype is None:
            # histogram/summary series carry suffixed names
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix):
                    base = name[: -len(suffix)]
                    mtype = types.get(base)
                    break
        kept_labels = {
            k: v for k, v in labels.items()
            if not (ignored_labels
                    and any(rx.search(k) for rx in ignored_labels))
        }
        tags = [f"{k}:{v}" for k, v in sorted(kept_labels.items())]
        tags += added_tags
        tag_part = ("|#" + ",".join(tags)) if tags else ""
        key = (name, tuple(sorted(labels.items())))
        out_name = prefix + name

        if mtype == "counter":
            d = cache.delta(key, value)
            if d is not None and d != 0:
                lines.append(f"{out_name}:{d}|c{tag_part}".encode())
        elif mtype == "gauge" or mtype is None:
            lines.append(f"{out_name}:{value}|g{tag_part}".encode())
        elif mtype in ("histogram", "summary"):
            if name.endswith(("_bucket", "_count", "_sum")):
                d = cache.delta(key, value)
                if d is not None and d != 0:
                    lines.append(f"{out_name}:{d}|c{tag_part}".encode())
            else:
                # summary quantile series: instantaneous gauge
                lines.append(f"{out_name}:{value}|g{tag_part}".encode())
    return lines


def scrape(url: str, cert: str = "", key: str = "", cacert: str = "",
           timeout: float = 10.0, unix_socket: str = "") -> str:
    if unix_socket:
        # scrape over a unix socket (reference -socket: proxy-style
        # transports); plain HTTP semantics over an AF_UNIX stream
        import http.client

        class _UDSConn(http.client.HTTPConnection):
            def connect(self):
                self.sock = socket.socket(socket.AF_UNIX,
                                          socket.SOCK_STREAM)
                self.sock.settimeout(timeout)
                self.sock.connect(unix_socket)

        parts = urllib.parse.urlsplit(url)
        path = parts.path or "/metrics"
        if parts.query:
            path += "?" + parts.query
        conn = _UDSConn("localhost", timeout=timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            if resp.status != 200:
                raise RuntimeError(f"scrape returned {resp.status}")
            return resp.read().decode("utf-8")
        finally:
            conn.close()
    ctx = None
    if url.startswith("https"):
        ctx = ssl.create_default_context(cafile=cacert or None)
        if cert and key:
            ctx.load_cert_chain(cert, key)
    with urllib.request.urlopen(url, timeout=timeout, context=ctx) as resp:
        return resp.read().decode("utf-8")


def send_statsd(address: str, lines: list[bytes],
                max_datagram: int = 1400) -> None:
    host, _, port = address.rpartition(":")
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    batch = b""
    for line in lines:
        if batch and len(batch) + 1 + len(line) > max_datagram:
            sock.sendto(batch, (host or "127.0.0.1", int(port)))
            batch = b""
        batch = batch + b"\n" + line if batch else line
    if batch:
        sock.sendto(batch, (host or "127.0.0.1", int(port)))
    sock.close()


def main(argv=None) -> int:
    # -h is the metrics URL (matching the reference's flag surface,
    # cmd/veneur-prometheus/main.go:12-24), so argparse's automatic -h
    # help is disabled; --help still works
    parser = argparse.ArgumentParser(prog="veneur-tpu-prometheus",
                                     add_help=False)
    parser.add_argument("--help", "-help", action="help",
                        help="show this help message and exit")
    parser.add_argument("-h", "--host", dest="prometheus_host",
                        default="http://localhost:9090/metrics",
                        help="prometheus metrics endpoint URL")
    parser.add_argument("-s", dest="statsd_host",
                        default="127.0.0.1:8126",
                        help="statsd destination host:port")
    parser.add_argument("-i", dest="interval", default="10s")
    parser.add_argument("-p", dest="prefix", default="",
                        help="prefix prepended to every metric name "
                             "(include the trailing period)")
    parser.add_argument("-d", dest="debug", action="store_true")
    parser.add_argument("-t", dest="tags", action="append", default=[],
                        help="tag to add to every metric")
    parser.add_argument("-ignored-metrics", default="",
                        help="comma-separated metric-name regexes to skip")
    parser.add_argument("-ignored-labels", default="",
                        help="comma-separated label-name regexes to drop")
    parser.add_argument("-cert", default="")
    parser.add_argument("-key", default="")
    parser.add_argument("-cacert", default="")
    parser.add_argument("-socket", default="",
                        help="unix socket path for the scrape transport")
    parser.add_argument("-once", action="store_true",
                        help="scrape once and exit (for testing)")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.DEBUG if args.debug else logging.INFO)
    from veneur_tpu.core.config import parse_duration

    interval = parse_duration(args.interval)

    def _regexes(spec: str):
        # comma-separated regex list (the reference splits the same way,
        # so comma-containing regexes are inexpressible there too)
        try:
            return [re.compile(s) for s in spec.split(",") if s] or None
        except re.error as e:
            parser.error(f"bad regex in {spec!r}: {e}")

    ignored = _regexes(args.ignored_metrics)
    ignored_labels = _regexes(args.ignored_labels)
    cache = CountCache()

    while True:
        try:
            body = scrape(args.prometheus_host, args.cert, args.key,
                          args.cacert, unix_socket=args.socket)
            types, samples = parse_prometheus_text(body)
            lines = translate(types, samples, cache, args.tags, ignored,
                              ignored_labels=ignored_labels,
                              prefix=args.prefix)
            if lines:
                send_statsd(args.statsd_host, lines)
            log.info("scraped %d samples → %d statsd lines",
                     len(samples), len(lines))
        except Exception as e:
            log.warning("scrape failed: %s", e)
            if args.once:
                return 1
        if args.once:
            return 0
        time.sleep(interval)


if __name__ == "__main__":
    sys.exit(main())
