"""veneur-tpu: the aggregation server binary.

Parity: reference cmd/veneur/main.go:25-95 — `-f config.yaml` plus
`-validate-config` / `-validate-config-strict` modes, watchdog startup,
and signal-driven graceful shutdown.
"""

from __future__ import annotations

import json
import argparse
import os
import logging
import signal
import sys
import threading

from veneur_tpu.core.config import load_config, redacted_dict
from veneur_tpu.core.factory import build_server


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="veneur-tpu")
    parser.add_argument("-f", dest="config", required=True,
                        help="path to config yaml")
    parser.add_argument("-validate-config", action="store_true",
                        dest="validate")
    parser.add_argument("-validate-config-strict", action="store_true",
                        dest="validate_strict")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    try:
        cfg = load_config(args.config, strict=args.validate_strict)
    except Exception as e:
        print(f"config invalid: {e}", file=sys.stderr)
        return 1
    if args.validate or args.validate_strict:
        print("config valid")
        return 0

    if cfg.debug:
        logging.getLogger().setLevel(logging.DEBUG)
        logging.getLogger("veneur_tpu").debug(
            "config: %s", redacted_dict(cfg))

    # zero-downtime restart: adopt listener fds handed off by the
    # previous process image (datagrams queued in their kernel buffers
    # during the exec are delivered, not dropped)
    inherited = None
    manifest_env = os.environ.pop("VENEUR_INHERITED_FDS", "")
    if manifest_env:
        try:
            raw = json.loads(manifest_env)
            inherited = {str(k): [int(fd) for fd in v]
                         for k, v in raw.items()}
            logging.getLogger("veneur_tpu").info(
                "adopting inherited listener fds: %s", inherited)
        except Exception:
            logging.getLogger("veneur_tpu").warning(
                "bad VENEUR_INHERITED_FDS manifest; binding fresh")
            inherited = None
            # close whatever fds the malformed manifest names: leaving
            # them open keeps the old sockets bound alongside the fresh
            # ones and splits datagram delivery between them
            try:
                raw = json.loads(manifest_env)
                values = raw.values() if isinstance(raw, dict) else []
                for v in values:
                    for fd in (v if isinstance(v, list) else [v]):
                        if isinstance(fd, int) and fd > 2:
                            try:
                                os.close(fd)
                            except OSError:
                                pass
            except Exception:
                pass

    server = build_server(cfg, inherited_fds=inherited)
    ports = server.start()
    server.start_watchdog()
    logging.getLogger("veneur_tpu").info(
        "veneur-tpu %s serving (local=%s) listeners=%s",
        server.version, server.is_local, ports)

    # config hot-reload (mtime-watch; SIGHUP is taken by the graceful
    # restart): whitelisted keys — tenant budgets, journal knobs, drain
    # deadline — apply live, everything else logs-and-ignores
    reloader = None
    if cfg.config_reload_s > 0:
        from veneur_tpu.core.reload import ConfigReloader

        reloader = ConfigReloader(args.config, server,
                                  poll_s=cfg.config_reload_s)
        reloader.start()

    stop = threading.Event()
    restart = threading.Event()

    def _handle(signum, frame):
        stop.set()

    def _handle_restart(signum, frame):
        # Graceful restart (reference einhorn handoff + SIGHUP/SIGUSR2,
        # server.go:1401-1429): drain (final flush), then re-exec in place
        # so the supervised PID survives. Aggregation state loss is bounded
        # by one interval, the reference's own restart-gap contract
        # (README.md:133-141).
        restart.set()
        stop.set()

    signal.signal(signal.SIGTERM, _handle)
    signal.signal(signal.SIGINT, _handle)
    signal.signal(signal.SIGHUP, _handle_restart)
    signal.signal(signal.SIGUSR2, _handle_restart)
    # wake on signal OR server-initiated shutdown (POST /quitquitquit sets
    # server._shutdown; the process must exit too, reference http.go:37-44)
    while not stop.is_set() and not server._shutdown.is_set():
        stop.wait(0.5)
    if reloader is not None:
        reloader.stop()
    manifest = None
    if restart.is_set():
        # quiesce readers FIRST — from here, datagrams queue in the
        # kernel socket buffers and ride the handoff to the successor —
        # then drain the partial interval with a final flush (the
        # reference accepts losing it, README.md:133-141; draining is
        # strictly better and cheap here)
        manifest = server.prepare_handoff()
        try:
            server.flush()
        except Exception:
            logging.getLogger("veneur_tpu").exception(
                "final flush before restart failed")
    elif not server._shutdown.is_set():
        # plain SIGTERM/SIGINT: graceful drain — final-epoch flush, then
        # bounded spill settling with honest shutdown.* counters for
        # whatever the deadline clips (Server.graceful_drain)
        try:
            drain = server.graceful_drain()
            logging.getLogger("veneur_tpu").info(
                "graceful drain: %s", drain)
        except Exception:
            logging.getLogger("veneur_tpu").exception("graceful drain"
                                                      " failed")
    clean = server.shutdown()
    if not clean and not restart.is_set():
        # a compute thread is still inside XLA/C++ after the bounded
        # join — letting the interpreter finalize under it aborts the
        # process (glibc "FATAL: exception not rethrown"). Everything
        # is flushed; skip finalization.
        logging.getLogger("veneur_tpu").warning(
            "compute thread still in XLA at shutdown; fast-exiting")
        os._exit(0)
    if restart.is_set():
        logging.getLogger("veneur_tpu").info(
            "graceful restart: drained, re-executing with %d listener"
            " fds", sum(len(v) for v in (manifest or {}).values()))
        env = dict(os.environ)
        if manifest:
            env["VENEUR_INHERITED_FDS"] = json.dumps(manifest)
        os.execve(sys.executable, [sys.executable, "-m",
                                   "veneur_tpu.cli.veneur_main",
                                   *(argv or sys.argv[1:])], env)
    return 0


if __name__ == "__main__":
    sys.exit(main())
