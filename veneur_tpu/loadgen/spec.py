"""Declarative workload spec for the wire-rate load generator.

The spec is the single description of synthesized traffic shape —
metric-type mix, Zipf-distributed key cardinality, tag shape, datagram
packing — shared by the sustained-pipeline bench, the CI smoke lane and
the differential encoder tests. Ring synthesis itself happens in C++
(native/loadgen.cpp vn_lg_ring_synth); SSF rings are built here once at
setup time via the generated protobuf (the per-packet send path never
re-enters Python either way).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import TYPE_CHECKING

from veneur_tpu import native

if TYPE_CHECKING:
    from veneur_tpu.core.config import Config


@dataclass
class WorkloadSpec:
    seed: int = 7
    num_keys: int = 10000
    zipf_s: float = 1.1  # 0 = uniform key popularity
    # weights over the fixed type order {c, g, ms, h, s}
    type_mix: list[float] = field(
        default_factory=lambda: [0.35, 0.15, 0.25, 0.15, 0.10])
    num_tags: int = 3
    tag_cardinality: int = 50
    prefix: str = "lg"
    datagram_bytes: int = 1400
    ring_lines: int = 200000
    # multi-tenant dimension (per-tenant QoS soak): tenant_count > 1
    # stamps every line with a trailing tenant:tN tag. The LAST tenant
    # is the abusive one — tenant_abusive_frac of lines go to it and
    # its key space churns over tenant_churn_keys names beyond
    # num_keys (the cardinality attack the series budget defends
    # against); innocents draw Zipf(tenant_zipf_s; 0 = uniform) over
    # the remaining ids. 1 emits byte-identical single-tenant output.
    tenant_count: int = 1
    tenant_abusive_frac: float = 0.0
    tenant_zipf_s: float = 0.0
    tenant_churn_keys: int = 0

    @classmethod
    def from_config(cls, cfg: "Config") -> "WorkloadSpec":
        return cls(
            seed=cfg.loadgen_seed,
            num_keys=cfg.loadgen_num_keys,
            zipf_s=cfg.loadgen_zipf_s,
            type_mix=list(cfg.loadgen_type_mix),
            num_tags=cfg.loadgen_num_tags,
            tag_cardinality=cfg.loadgen_tag_cardinality,
            prefix=cfg.loadgen_prefix,
            datagram_bytes=cfg.loadgen_datagram_bytes,
            ring_lines=cfg.loadgen_ring_lines,
            tenant_count=cfg.loadgen_tenant_count,
            tenant_abusive_frac=cfg.loadgen_tenant_abusive_frac,
            tenant_zipf_s=cfg.loadgen_tenant_zipf_s,
            tenant_churn_keys=cfg.loadgen_tenant_churn_keys,
        )

    def validate(self) -> None:
        if not (1 <= self.num_keys <= (1 << 24)):
            raise ValueError("num_keys must be in [1, 2^24]")
        if self.zipf_s < 0:
            raise ValueError("zipf_s must be >= 0")
        if (len(self.type_mix) != len(native.LOADGEN_TYPES)
                or any(w < 0 for w in self.type_mix)
                or sum(self.type_mix) <= 0):
            raise ValueError("type_mix must be 5 non-negative weights"
                             " with a positive sum")
        if not (0 <= self.num_tags <= 16):
            raise ValueError("num_tags must be in [0,16]")
        if self.tag_cardinality < 1:
            raise ValueError("tag_cardinality must be >= 1")
        if not (64 <= self.datagram_bytes <= 65507):
            raise ValueError("datagram_bytes must fit a UDP datagram")
        if self.ring_lines < 1:
            raise ValueError("ring_lines must be >= 1")
        if not self.prefix:
            raise ValueError("prefix must be non-empty")
        if not (1 <= self.tenant_count <= 4096):
            raise ValueError("tenant_count must be in [1, 4096]")
        if not (0.0 <= self.tenant_abusive_frac <= 1.0):
            raise ValueError("tenant_abusive_frac must be in [0,1]")
        if self.tenant_zipf_s < 0:
            raise ValueError("tenant_zipf_s must be >= 0")
        if self.tenant_churn_keys < 0:
            raise ValueError("tenant_churn_keys must be >= 0")

    def to_dict(self) -> dict:
        return {
            "seed": self.seed, "num_keys": self.num_keys,
            "zipf_s": self.zipf_s, "type_mix": list(self.type_mix),
            "num_tags": self.num_tags,
            "tag_cardinality": self.tag_cardinality,
            "prefix": self.prefix, "datagram_bytes": self.datagram_bytes,
            "ring_lines": self.ring_lines,
            "tenant_count": self.tenant_count,
            "tenant_abusive_frac": self.tenant_abusive_frac,
            "tenant_zipf_s": self.tenant_zipf_s,
            "tenant_churn_keys": self.tenant_churn_keys,
        }

    def build_ring(self) -> "native.LoadgenRing":
        """Synthesize the DogStatsD send ring in C++ (deterministic for
        a given spec: same spec → same content hash)."""
        self.validate()
        ring = native.LoadgenRing()
        ring.synth(self.seed, self.num_keys, self.zipf_s, self.type_mix,
                   self.num_tags, self.tag_cardinality,
                   self.prefix.encode("utf-8"), self.datagram_bytes,
                   self.ring_lines,
                   tenant_count=self.tenant_count,
                   tenant_abusive_frac=self.tenant_abusive_frac,
                   tenant_zipf_s=self.tenant_zipf_s,
                   tenant_churn_keys=self.tenant_churn_keys)
        return ring

    def build_ssf_ring(self, n_spans: int = 2000) -> "native.LoadgenRing":
        """SSF span ring: payloads built ONCE here via the generated
        protobuf (one span per datagram), then cycled by the C++ sender
        — setup cost is Python, the send path is not."""
        self.validate()
        from veneur_tpu.gen import ssf_pb2

        rng = random.Random(self.seed)
        ring = native.LoadgenRing()
        services = ["api", "db", "web", "worker"]
        for i in range(n_spans):
            pb = ssf_pb2.SSFSpan()
            pb.trace_id = rng.randrange(1, 1 << 62)
            pb.id = rng.randrange(1, 1 << 62)
            pb.parent_id = rng.randrange(1, 1 << 62)
            pb.start_timestamp = 10**9 + i * 1000
            pb.end_timestamp = pb.start_timestamp + rng.randrange(
                10**5, 10**8)
            pb.service = services[i % len(services)]
            pb.name = "%s.span%d" % (self.prefix,
                                     rng.randrange(self.num_keys))
            pb.indicator = (i % 10) == 0
            pb.error = (i % 17) == 0
            pb.tags["host"] = "h%d" % (i % 8)
            m = pb.metrics.add()
            m.metric = ssf_pb2.SSFSample.COUNTER
            m.name = "%s.ssf.hits" % self.prefix
            m.value = 1.0
            m.sample_rate = 1.0
            ring.append(pb.SerializeToString(), lines=1)
        return ring
