"""Wire-rate load generation / capture / replay (ISSUE 2 tentpole).

The C++ half (native/loadgen.cpp, bound in veneur_tpu.native) owns the
per-packet work: ring synthesis from a declarative workload spec, paced
sending with absolute deadlines, and datagram capture for bit-exact
replay. This package owns orchestration: the workload spec
(spec.WorkloadSpec), the in-process server harness, and the closed-loop
sustained-rate search that produces SUSTAINED_PIPELINE.json
(controller.search_sustained via tools/bench_sustained.py).
"""

from veneur_tpu.loadgen.spec import WorkloadSpec  # noqa: F401
from veneur_tpu.loadgen.controller import (  # noqa: F401
    LoadHarness, run_trial, search_sustained)
