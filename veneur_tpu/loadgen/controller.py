"""Closed-loop sustained-rate controller for the full ingest pipeline.

Drives a live in-process Server through its REAL sockets with the C++
paced sender (zero Python per packet), measures accepted-sample
throughput and flush cadence per flush interval via Server.ingress_stats
(cumulative counters — loss over a window is a subtraction of two
snapshots), and searches for the maximum offered rate the pipeline holds
without loss or cadence collapse: multiplicative growth to bracket the
cliff, then bisection inside the bracket, then a long confirmation run
(≥10 flush intervals) at the found rate. The confirmation run's
*accepted* rate — not the offered rate — is what
SUSTAINED_PIPELINE.json reports as sustained_pipeline_lines_per_s: loss
shows up as the gap between them, never as an inflated headline.

Loss here is end-to-end: kernel rcvbuf drops (invisible to the server)
and overload sheds (counted) both surface as sent-vs-accepted gap.
"""

from __future__ import annotations

import logging
import socket
import time
from typing import Optional

from veneur_tpu import native
from veneur_tpu.loadgen.spec import WorkloadSpec

log = logging.getLogger("veneur_tpu.loadgen")

# BASELINE.json north star: 50M samples/s per chip; cores_needed is the
# reader-core budget to feed it at the measured sustained rate
NORTH_STAR_LINES_PER_S = 50e6

# At most this many leading cadence misses of a trial may be classed as
# warmup. One is the honest number: a trial's first interval is where a
# first-encounter XLA compile lands (pow2 shape buckets mean a new rate
# tier compiles once), and a SECOND straggler is a pipeline problem, not
# a compile.
WARMUP_GRACE_INTERVALS = 1


def classify_warmup(intervals: list[dict],
                    grace: int = WARMUP_GRACE_INTERVALS) -> dict:
    """Split a trial's interval records into warmup vs steady state.

    A leading interval that missed cadence is warmup — the flush that
    closed it paid first-encounter XLA compiles (multi-second on CPU),
    which is a property of the trial boundary, not of the pipeline. At
    most `grace` intervals qualify, they must be a prefix, and an
    interval that made cadence is never reclassified. Mutates each
    record with a "warmup" bool and returns the steady-state view:

        warmup_intervals    how many leading records were excluded
        cadence_frac_steady misses / steady count (1.0 when no steady
                            records exist — an all-warmup trial judges
                            nothing)
        <m>_steady          mean over steady records for each of
                            tick_block_ms, ingest_stall_ms, flush_ms,
                            drain_ms

    Pure beyond the "warmup" stamp: no controller state, no clocks —
    unit-testable against synthetic interval lists.
    """
    n_warm = 0
    for rec in intervals:
        if n_warm >= grace or rec.get("cadence_ok", False):
            break
        n_warm += 1
    for k, rec in enumerate(intervals):
        rec["warmup"] = k < n_warm
    steady = intervals[n_warm:]
    n = len(steady)
    out = {
        "warmup_intervals": n_warm,
        "cadence_frac_steady": round(
            sum(1 for i in steady if i["cadence_ok"]) / n, 4)
        if n else 1.0,
    }
    for m in ("tick_block_ms", "ingest_stall_ms", "flush_ms", "drain_ms"):
        out[m + "_steady"] = round(
            sum(i.get(m, 0.0) for i in steady) / n, 2) if n else 0.0
    return out


class LoadHarness:
    """A running Server plus a connected send socket and a prebuilt
    ring. Owns both ends; close() tears everything down."""

    def __init__(self, cfg, spec: Optional[WorkloadSpec] = None,
                 transport: str = "udp",
                 ring: Optional["native.LoadgenRing"] = None,
                 sink_mode: str = "channel",
                 ssf_frac: float = 0.0,
                 ssf_spans: int = 2000) -> None:
        from veneur_tpu.core.server import Server

        self.spec = spec or WorkloadSpec.from_config(cfg)
        self.transport = transport
        self.interval = cfg.interval_seconds()
        self.ring = ring if ring is not None else self.spec.build_ring()
        # mixed statsd+SSF workload: a second paced sender offers SSF
        # span datagrams at rate*ssf_frac against a real SSF listener;
        # egress goes through a serialize-only SpanBatchSink (full VSB1
        # encode + delivery manager, zero network variance)
        self.ssf_frac = ssf_frac
        self.ssf_ring = None
        self.span_sink = None
        self._ssf_sock: Optional[socket.socket] = None
        self._ssf_sender: Optional["native.LoadgenSender"] = None
        span_sinks: list = []
        if ssf_frac > 0:
            from veneur_tpu.sinks.delivery import DeliveryPolicy
            from veneur_tpu.spans import DiscardWriter, SpanBatchSink

            if not cfg.ssf_listen_addresses:
                cfg.ssf_listen_addresses = ["udp://127.0.0.1:0"]
            self._ssf_specs = list(cfg.ssf_listen_addresses)
            self.span_sink = SpanBatchSink(
                DiscardWriter(), name="loadgen_discard",
                delivery=DeliveryPolicy.from_config(cfg, self.interval),
                batch_rows=cfg.span_batch_rows,
                pending_cap=cfg.span_pending_cap)
            span_sinks = [self.span_sink]
            self.ssf_ring = self.spec.build_ssf_ring(ssf_spans)
        if sink_mode == "serialize":
            # a real serializing sink: the datadog formatter builds the
            # full chunked JSON series bodies (deflate included) against
            # a discarding opener, so the emit stage pays its production
            # serialization cost with zero network. This is the sink the
            # --ab-axis emit-native A/B measures — the channel sink
            # never serializes, so it can't see the native emit tier.
            from veneur_tpu.sinks.datadog import DatadogMetricSink

            self.sink = DatadogMetricSink(
                interval=self.interval, flush_max_per_body=25000,
                hostname="loadgen", tags=["veneur:loadgen"],
                dd_hostname="http://invalid.localdomain", api_key="x",
                opener=lambda req, timeout: b"")
        elif sink_mode == "channel":
            from veneur_tpu.sinks.channel import ChannelMetricSink

            self.sink = ChannelMetricSink()
        else:
            raise ValueError("sink_mode must be channel or serialize")
        # flush archival rides the measured flush path when configured:
        # the --ab-axis archive "on" side attaches the real
        # MetricArchiveSink (native VMB1 serialize + segmented append
        # behind the delivery manager) alongside the measurement sink,
        # so the A/B prices exactly what production would pay
        self.archive_sink = None
        metric_sinks = [self.sink]
        if cfg.archive_dir:
            from veneur_tpu.archive import (MetricArchiveSink,
                                            SegmentedArchiveWriter)
            from veneur_tpu.sinks.delivery import DeliveryPolicy

            self.archive_sink = MetricArchiveSink(
                SegmentedArchiveWriter(
                    cfg.archive_dir,
                    max_segment_bytes=cfg.archive_max_bytes,
                    max_segments=cfg.archive_max_segments),
                hostname="loadgen",
                delivery=DeliveryPolicy.from_config(cfg, self.interval))
            metric_sinks.append(self.archive_sink)
        self.server = Server(cfg, metric_sinks=metric_sinks,
                             span_sinks=span_sinks)
        ports = self.server.start()
        self._sock = self._connect(ports)
        if ssf_frac > 0:
            self._ssf_sock = self._connect_ssf(ports)
        self.flushed_series = 0
        self._sender: Optional["native.LoadgenSender"] = None

    def _connect(self, ports: dict) -> socket.socket:
        if self.transport == "udp":
            spec_port = [(s, p) for s, p in ports.items()
                         if s.startswith("udp://")]
            if not spec_port:
                raise RuntimeError("no udp listener in %s" % ports)
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 1 << 22)
            s.connect(("127.0.0.1", spec_port[0][1]))
            return s
        if self.transport == "tcp":
            spec_port = [(s, p) for s, p in ports.items()
                         if s.startswith("tcp://")]
            if not spec_port:
                raise RuntimeError("no tcp listener in %s" % ports)
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.connect(("127.0.0.1", spec_port[0][1]))
            return s
        if self.transport == "unixgram":
            spec_port = [s for s in ports if s.startswith("unixgram://")]
            if not spec_port:
                raise RuntimeError("no unixgram listener in %s" % ports)
            s = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
            s.connect(spec_port[0][len("unixgram://"):])
            return s
        raise ValueError("transport must be udp, tcp or unixgram")

    def _connect_ssf(self, ports: dict) -> socket.socket:
        # server.start() prefixes the SSF port with "ssf:" only when its
        # spec collides with a statsd listener's
        cand = [(s, p) for s, p in ports.items()
                if s.startswith("ssf:udp://")]
        if not cand:
            cand = [(s, p) for s, p in ports.items()
                    if s.startswith("udp://") and s in self._ssf_specs]
        if not cand:
            raise RuntimeError("no ssf udp listener in %s" % ports)
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 1 << 22)
        s.connect(("127.0.0.1", cand[0][1]))
        return s

    def warmup(self, rate: float = 100_000.0,
               timeout: float = 300.0) -> bool:
        """Prime the pipeline before measuring. Two effects must
        settle, both of which show up as multi-second XLA compiles
        billed to whatever interval they land in: (1) directory growth
        — each new series re-buckets the pow2-padded pool shapes, so
        the FULL series set must exist up front (the ring is finite;
        sending it fully twice rides out any rcvbuf drop); (2) the
        load-path program shapes — staged planes, spill-fold chunks —
        which only compile while traffic is flowing, so the
        stabilization wait runs UNDER continuous load at a
        representative rate, until three consecutive flushes land on
        cadence. The shape space is pow2-bucketed, so this converges."""
        sender = native.LoadgenSender(
            self.ring, self._sock.fileno(), rate,
            stream=(self.transport == "tcp"))
        deadline = time.time() + timeout
        sent_all = 2 * self.ring.total_lines
        good = 0
        last = self.server.flush_count
        t_last = time.time()
        try:
            while time.time() < deadline and good < 3:
                time.sleep(0.05)
                fc = self.server.flush_count
                if fc > last:
                    dt = time.time() - t_last
                    on_time = (dt <= self.interval * 1.5
                               and sender.sent_lines >= sent_all)
                    good = good + 1 if on_time else 0
                    last, t_last = fc, time.time()
                self._drain_sink()
        finally:
            sender.stop()
        self._drain_sink()
        return good >= 3

    # -- measurement ---------------------------------------------------------

    def snapshot(self) -> dict:
        snap = self.server.ingress_stats()
        snap["t"] = time.time()
        sender = self._sender
        snap["sent_lines"] = sender.sent_lines if sender else 0
        snap["sent_packets"] = sender.sent_packets if sender else 0
        snap["send_errors"] = sender.send_errors if sender else 0
        ssf_sender = self._ssf_sender
        snap["ssf_sent_spans"] = (ssf_sender.sent_lines
                                  if ssf_sender else 0)
        arch = self.archive_sink
        if arch is not None:
            snap["archive"] = {
                "frames": arch.frames_encoded,
                "bytes": arch.bytes_encoded,
                "samples": arch.metrics_flushed,
                "dropped": arch.metrics_dropped,
                "deferred": arch.metrics_deferred,
            }
        return snap

    def _drain_sink(self) -> None:
        # keep the channel sink bounded over long runs; tally series so
        # the artifact can show the flush path really emitted
        if not hasattr(self.sink, "queue"):
            # serializing sinks tally their own emitted series
            self.flushed_series = getattr(self.sink, "flushed_metrics", 0)
            return
        while not self.sink.queue.empty():
            self.flushed_series += len(self.sink.queue.get_nowait())
        while not self.sink.other_samples.empty():
            self.sink.other_samples.get_nowait()

    def run_intervals(self, rate: float, n_intervals: int,
                      settle: bool = True) -> dict:
        """Send at `rate` lines/s while `n_intervals` flushes complete;
        returns the trial record (per-interval stats + aggregates).

        The first flush boundary after the sender starts opens the
        measurement window, so a partial interval never dilutes the
        per-interval numbers. A hard deadline of 3x the nominal span
        bounds a wedged flush loop; hitting it fails the trial
        (cadence_ok False on the missing intervals)."""
        self._drain_sink()
        self._sender = native.LoadgenSender(
            self.ring, self._sock.fileno(), rate,
            stream=(self.transport == "tcp"))
        if self.ssf_frac > 0:
            self._ssf_sender = native.LoadgenSender(
                self.ssf_ring, self._ssf_sock.fileno(),
                max(1.0, rate * self.ssf_frac), stream=False)
        intervals = []
        try:
            if settle:
                self._await_flush(self.snapshot()["flush_count"])
            prev = self.snapshot()
            hard_deadline = (time.time()
                             + 3.0 * self.interval * n_intervals
                             + 5.0)
            for _ in range(n_intervals):
                ok = self._await_flush(prev["flush_count"],
                                       deadline=hard_deadline)
                snap = self.snapshot()
                dt = snap["t"] - prev["t"]
                sent = snap["sent_lines"] - prev["sent_lines"]
                acc = (snap["samples_processed"]
                       - prev["samples_processed"])
                shed = (snap["overload_dropped"]
                        - prev["overload_dropped"])
                # cadence decomposition: how long the tick held the
                # ticker thread (the whole serial flush; just the
                # swap+enqueue when pipelined), how long ingest was
                # stalled under the worker locks (the swap phase), and
                # the total flush work of the last COMPLETED flush —
                # on a 1-core rig the gap between tick_block_ms and
                # flush_ms is exactly what the stage pipeline buys
                flush_phases = snap.get("last_flush_phases") or {}
                intervals.append({
                    "duration_s": round(dt, 4),
                    "flushes": snap["flush_count"] - prev["flush_count"],
                    "sent_lines": sent,
                    "accepted_lines": acc,
                    "shed_lines": shed,
                    "accepted_lines_per_s": round(acc / dt, 1) if dt > 0
                    else 0.0,
                    "loss_frac": round(max(0.0, 1.0 - acc / sent), 5)
                    if sent > 0 else 0.0,
                    "cadence_ok": bool(ok and dt <= self.interval * 1.5),
                    "tick_block_ms": round(
                        snap.get("last_tick_s", 0.0) * 1e3, 2),
                    "ingest_stall_ms": round(
                        flush_phases.get("swap_s", 0.0) * 1e3, 2),
                    "flush_ms": round(
                        sum(flush_phases.values()) * 1e3, 2),
                    # always-hot flush: micro-folds that ran during this
                    # window (lifetime-counter delta, so folds landing
                    # near the flush boundary are never lost) and the
                    # swap-time residual drain + mirror handoff
                    "micro_folds": (snap.get("micro_folds_total", 0)
                                    - prev.get("micro_folds_total", 0)),
                    "drain_ms": round(
                        flush_phases.get("drain_s", 0.0) * 1e3, 2),
                    # the emit A/B's two phases of interest: columnar
                    # batch assembly and sink serialization+emission
                    "generate_ms": round(
                        flush_phases.get("generate_s", 0.0) * 1e3, 2),
                    "emit_ms": round(
                        flush_phases.get("sink_flush_s", 0.0) * 1e3, 2),
                })
                rs_now = snap.get("reader_shards")
                if rs_now:
                    # shared-nothing ingest: per-context committed/
                    # dropped deltas for this window (index 0 = home
                    # context, 1.. = reader shards) — the reader-balance
                    # evidence in the --readers bench artifact
                    rs_prev = (prev.get("reader_shards") or
                               {"committed": [], "dropped": []})

                    def _deltas(key):
                        now = rs_now.get(key) or []
                        before = rs_prev.get(key) or []
                        before = before + [0] * (len(now) - len(before))
                        return [int(a - b) for a, b in zip(now, before)]

                    intervals[-1]["per_reader"] = {
                        "committed": _deltas("committed"),
                        "dropped": _deltas("dropped"),
                    }
                if self.archive_sink is not None:
                    # per-interval archive egress deltas: the A/B
                    # artifact's evidence that archival kept pace with
                    # the flush cadence, and at what byte cost
                    a_now = snap.get("archive") or {}
                    a_prev = prev.get("archive") or {}
                    intervals[-1].update({
                        "archive_frames": (a_now.get("frames", 0)
                                           - a_prev.get("frames", 0)),
                        "archive_bytes": (a_now.get("bytes", 0)
                                          - a_prev.get("bytes", 0)),
                        "archive_samples": (a_now.get("samples", 0)
                                            - a_prev.get("samples", 0)),
                    })
                if self.ssf_frac > 0:
                    sp_now = snap.get("spans") or {}
                    sp_prev = prev.get("spans") or {}
                    intervals[-1].update({
                        "spans_sent": (snap["ssf_sent_spans"]
                                       - prev["ssf_sent_spans"]),
                        "spans_received": (sp_now.get("received", 0)
                                           - sp_prev.get("received", 0)),
                        "spans_derived": (sp_now.get("derived", 0)
                                          - sp_prev.get("derived", 0)),
                        "spans_dropped": (sp_now.get("dropped", 0)
                                          - sp_prev.get("dropped", 0)),
                        "span_metric_rows": (
                            sp_now.get("derived_rows", 0)
                            - sp_prev.get("derived_rows", 0)),
                    })
                prev = snap
                self._drain_sink()
                if not ok:
                    break
        finally:
            self._sender.stop()
            self._sender = None
            if self._ssf_sender is not None:
                self._ssf_sender.stop()
                self._ssf_sender = None
        total_sent = sum(i["sent_lines"] for i in intervals)
        total_acc = sum(i["accepted_lines"] for i in intervals)
        total_dt = sum(i["duration_s"] for i in intervals)
        n_ok = sum(1 for i in intervals if i["cadence_ok"])
        n_iv = max(1, len(intervals))
        pipeline_stats = self.server.ingress_stats().get("pipeline")
        # warmup vs steady state: a first-interval cadence miss from a
        # first-encounter XLA compile is a trial-boundary artifact, not
        # a pipeline failure. The judged cadence_frac excludes warmup
        # from BOTH numerator and denominator (a trial of N intervals
        # with one warmup is judged on the other N-1, or on
        # n_intervals-1 when the run aborted early); the raw fraction
        # over all requested intervals stays in the record.
        steady = classify_warmup(intervals)
        n_warm = steady["warmup_intervals"]
        n_ok_steady = sum(1 for i in intervals
                          if i["cadence_ok"] and not i["warmup"])
        span_agg = {}
        if self.ssf_frac > 0:
            sp_sent = sum(i.get("spans_sent", 0) for i in intervals)
            sp_recv = sum(i.get("spans_received", 0) for i in intervals)
            span_agg = {
                "offered_spans_per_s": rate * self.ssf_frac,
                "total_spans_sent": sp_sent,
                "total_spans_received": sp_recv,
                "total_spans_derived": sum(
                    i.get("spans_derived", 0) for i in intervals),
                "total_spans_dropped": sum(
                    i.get("spans_dropped", 0) for i in intervals),
                "span_metric_rows": sum(
                    i.get("span_metric_rows", 0) for i in intervals),
                # sent-vs-received gap is UDP loss; received-vs-derived
                # is pipeline shed (counted) or pending carryover
                "span_loss_frac": round(
                    max(0.0, 1.0 - sp_recv / sp_sent), 5)
                if sp_sent > 0 else 0.0,
            }
        return {
            **span_agg,
            "tick_block_ms_mean": round(
                sum(i["tick_block_ms"] for i in intervals) / n_iv, 2),
            "ingest_stall_ms_mean": round(
                sum(i["ingest_stall_ms"] for i in intervals) / n_iv, 2),
            "flush_ms_mean": round(
                sum(i["flush_ms"] for i in intervals) / n_iv, 2),
            "generate_ms_mean": round(
                sum(i["generate_ms"] for i in intervals) / n_iv, 2),
            "emit_ms_mean": round(
                sum(i["emit_ms"] for i in intervals) / n_iv, 2),
            "drain_ms_mean": round(
                sum(i["drain_ms"] for i in intervals) / n_iv, 2),
            "micro_folds_total": sum(i["micro_folds"] for i in intervals),
            **({"archive_frames_total": sum(
                    i.get("archive_frames", 0) for i in intervals),
                "archive_bytes_total": sum(
                    i.get("archive_bytes", 0) for i in intervals),
                "archive_samples_total": sum(
                    i.get("archive_samples", 0) for i in intervals),
                "archive_bytes_per_interval_mean": round(sum(
                    i.get("archive_bytes", 0) for i in intervals) / n_iv)}
               if self.archive_sink is not None else {}),
            **steady,
            **({"pipeline": pipeline_stats} if pipeline_stats else {}),
            "offered_lines_per_s": rate,
            "intervals": intervals,
            "total_sent": total_sent,
            "total_accepted": total_acc,
            "total_shed": sum(i["shed_lines"] for i in intervals),
            "duration_s": round(total_dt, 3),
            "accepted_lines_per_s": round(total_acc / total_dt, 1)
            if total_dt > 0 else 0.0,
            "loss_frac": round(max(0.0, 1.0 - total_acc / total_sent), 5)
            if total_sent > 0 else 1.0,
            "cadence_frac": round(
                n_ok_steady / max(1, n_intervals - n_warm), 4),
            "cadence_frac_raw": round(n_ok / n_intervals, 4),
            "intervals_completed": len(intervals),
        }

    def _await_flush(self, since: int, deadline: float = 0.0) -> bool:
        """Block until flush_count exceeds `since` (poll at 20Hz).
        False when the deadline passes first — a collapsed cadence."""
        if deadline <= 0.0:
            deadline = time.time() + 3.0 * self.interval + 5.0
        while time.time() < deadline:
            if self.server.flush_count > since:
                return True
            time.sleep(0.05)
        return False

    def span_conservation(self) -> dict:
        """The server's span books, with the exactness bit: on the
        columnar path received == derived + dropped + pending holds at
        any quiescent instant (no sender running, flush not mid-tick)."""
        s = dict(self.server.ingress_stats().get("spans") or {})
        if s:
            s["balanced"] = (
                s["received"] == s["derived"] + s["dropped"] + s["pending"])
        return s

    def archive_stats(self) -> dict:
        """The archive sink's sample ledger plus its delivery manager's
        payload ledger — the A/B artifact's conservation evidence."""
        a = self.archive_sink
        if a is None:
            return {}
        return {
            "frames_encoded": a.frames_encoded,
            "bytes_encoded": a.bytes_encoded,
            "metrics_flushed": a.metrics_flushed,
            "metrics_dropped": a.metrics_dropped,
            "metrics_deferred": a.metrics_deferred,
            "delivery": a.delivery.stats(),
            "conserved": a.delivery.conserved(),
        }

    def close(self) -> None:
        if self._sender is not None:
            self._sender.stop()
            self._sender = None
        if self._ssf_sender is not None:
            self._ssf_sender.stop()
            self._ssf_sender = None
        try:
            self.server.shutdown()
        finally:
            self._sock.close()
            if self._ssf_sock is not None:
                self._ssf_sock.close()


def trial_passes(trial: dict, n_intervals: int, max_loss: float,
                 min_cadence: float) -> bool:
    return (trial["intervals_completed"] == n_intervals
            and trial["loss_frac"] <= max_loss
            and trial["cadence_frac"] >= min_cadence)


def run_trial(harness: LoadHarness, rate: float, n_intervals: int,
              max_loss: float = 0.01,
              min_cadence: float = 0.75) -> dict:
    t = harness.run_intervals(rate, n_intervals)
    t["passed"] = trial_passes(t, n_intervals, max_loss, min_cadence)
    log.info("trial @ %.0f lines/s: accepted %.0f/s loss %.4f "
             "cadence %.2f -> %s", rate, t["accepted_lines_per_s"],
             t["loss_frac"], t["cadence_frac"],
             "pass" if t["passed"] else "FAIL")
    return t


def search_sustained(harness: LoadHarness, *,
                     start_rate: float = 50_000.0,
                     max_rate: float = 20e6,
                     growth: float = 1.6,
                     trial_intervals: int = 3,
                     confirm_intervals: int = 10,
                     bisect_steps: int = 4,
                     max_loss: float = 0.01,
                     min_cadence: float = 0.8,
                     trial_min_cadence: float = 0.6) -> dict:
    """Bracket-then-bisect rate search plus a long confirmation run.

    Growth phase multiplies the offered rate by `growth` until a short
    trial fails (or max_rate holds), bracketing the cliff; bisection
    narrows the bracket; the confirmation run re-validates the found
    rate across >= confirm_intervals flush intervals, backing off 10%
    per retry if the long run exposes what the short trials missed.
    Short bracketing trials use the laxer trial_min_cadence (one stray
    recompile must not end the growth phase); only the confirmation run
    applies min_cadence."""
    trials = []
    lo, hi = 0.0, 0.0
    rate = start_rate
    while rate <= max_rate:
        t = run_trial(harness, rate, trial_intervals, max_loss,
                      trial_min_cadence)
        trials.append(t)
        if t["passed"]:
            lo = rate
            rate *= growth
        else:
            hi = rate
            break
    if lo == 0.0:
        # even the floor rate failed: report the floor trial honestly
        hi = hi or start_rate
        lo = hi * 0.25
    if hi > 0.0:
        for _ in range(bisect_steps):
            mid = (lo + hi) / 2.0
            if mid <= lo * 1.05:  # bracket below resolution
                break
            t = run_trial(harness, mid, trial_intervals, max_loss,
                          trial_min_cadence)
            trials.append(t)
            if t["passed"]:
                lo = mid
            else:
                hi = mid
    # unrecorded warm pass at the found rate: this rate tier's
    # pow2-bucketed spill shapes may not have compiled yet, and a
    # first-encounter compile inside the confirmation run would be
    # reported as a cadence failure of the pipeline
    run_trial(harness, lo, 2, max_loss, trial_min_cadence)
    # confirmation: the headline number comes from THIS run only
    confirm = None
    rate = lo
    for _ in range(3):
        confirm = run_trial(harness, rate, confirm_intervals, max_loss,
                            min_cadence)
        if confirm["passed"]:
            break
        rate *= 0.9
    return {
        "search_trials": trials,
        "confirm": confirm,
        "sustained_offered_lines_per_s": rate,
        "sustained_pipeline_lines_per_s":
            confirm["accepted_lines_per_s"] if confirm else 0.0,
        "confirmed": bool(confirm and confirm["passed"]),
    }


def result_artifact(spec: WorkloadSpec, harness: LoadHarness,
                    search: dict, platform: str) -> dict:
    """Assemble the SUSTAINED_PIPELINE.json payload."""
    measured = search["sustained_pipeline_lines_per_s"]
    confirm = search.get("confirm") or {}
    return {
        "schema": "sustained_pipeline_v1",
        "platform": platform,
        "transport": harness.transport,
        "flush_interval_s": harness.interval,
        "workload": spec.to_dict(),
        "ring_datagrams": len(harness.ring),
        "ring_lines": harness.ring.total_lines,
        "ring_bytes": harness.ring.total_bytes,
        "sustained_pipeline_lines_per_s": measured,
        "sustained_offered_lines_per_s":
            search["sustained_offered_lines_per_s"],
        "confirmed": search["confirmed"],
        "confirm_intervals": confirm.get("intervals", []),
        "loss_frac": confirm.get("loss_frac"),
        "shed_lines": confirm.get("total_shed"),
        "cadence_frac": confirm.get("cadence_frac"),
        "flushed_series": harness.flushed_series,
        # cadence decomposition of the confirmation run: how long the
        # tick held the ticker thread vs how long ingest stalled under
        # the worker locks vs the full flush work — on a 1-core rig
        # tick_block ≈ flush is the cadence-bound serial signature,
        # tick_block ≈ ingest_stall « flush is the pipelined one
        "tick_block_ms_mean": confirm.get("tick_block_ms_mean"),
        "ingest_stall_ms_mean": confirm.get("ingest_stall_ms_mean"),
        "flush_ms_mean": confirm.get("flush_ms_mean"),
        "generate_ms_mean": confirm.get("generate_ms_mean"),
        "emit_ms_mean": confirm.get("emit_ms_mean"),
        # steady-state decomposition (warmup excluded) plus the
        # always-hot flush accounting of the confirmation run
        "warmup_intervals": confirm.get("warmup_intervals"),
        "cadence_frac_raw": confirm.get("cadence_frac_raw"),
        "tick_block_ms_steady": confirm.get("tick_block_ms_steady"),
        "ingest_stall_ms_steady": confirm.get("ingest_stall_ms_steady"),
        "flush_ms_steady": confirm.get("flush_ms_steady"),
        "drain_ms_mean": confirm.get("drain_ms_mean"),
        "micro_folds_total": confirm.get("micro_folds_total"),
        **({"pipeline": confirm["pipeline"]}
           if confirm.get("pipeline") else {}),
        "search_trials": [
            {k: t.get(k) for k in ("offered_lines_per_s",
                                   "accepted_lines_per_s", "loss_frac",
                                   "cadence_frac", "cadence_frac_raw",
                                   "warmup_intervals", "passed",
                                   "tick_block_ms_mean",
                                   "ingest_stall_ms_mean", "flush_ms_mean",
                                   "tick_block_ms_steady",
                                   "ingest_stall_ms_steady",
                                   "generate_ms_mean", "emit_ms_mean",
                                   "drain_ms_mean", "micro_folds_total",
                                   "total_shed")}
            for t in search["search_trials"]],
        "north_star_lines_per_s": NORTH_STAR_LINES_PER_S,
        "cores_needed_for_north_star":
            round(NORTH_STAR_LINES_PER_S / measured, 2)
            if measured > 0 else None,
        # mixed statsd+SSF runs: the confirmation run's span-side
        # aggregates plus the final conservation check (exact on the
        # columnar path: received == derived + dropped + pending)
        **({"spans": {
            k: confirm.get(k)
            for k in ("offered_spans_per_s", "total_spans_sent",
                      "total_spans_received", "total_spans_derived",
                      "total_spans_dropped", "span_metric_rows",
                      "span_loss_frac")},
            "span_conservation": harness.span_conservation()}
           if harness.ssf_frac > 0 else {}),
        # archive-sink runs: the confirmation run's archival volume
        # (per-interval frames/bytes ride in confirm_intervals) plus
        # the sink's lifetime sample/payload ledgers
        **({"archive_confirm": {
            k: confirm.get(k)
            for k in ("archive_frames_total", "archive_bytes_total",
                      "archive_samples_total",
                      "archive_bytes_per_interval_mean")},
            "archive_ledger": harness.archive_stats()}
           if harness.archive_sink is not None else {}),
    }
