"""SSF (Sensor Sample Format) sample and span model.

Schema parity with the reference's ssf/sample.proto; the protobuf wire form
lives in veneur_tpu/ssf/ssf_pb2 (generated from proto/ssf.proto). This module
holds the Python-side model plus the sample-constructor helpers of the
reference's ssf/samples.go.
"""

from __future__ import annotations

import enum
import random
import time
from dataclasses import dataclass, field
from typing import Optional


class SSFMetricType(enum.IntEnum):
    # reference ssf/sample.proto Metric enum
    COUNTER = 0
    GAUGE = 1
    HISTOGRAM = 2
    SET = 3
    STATUS = 4


class SSFStatus(enum.IntEnum):
    # reference ssf/sample.proto Status enum (Nagios-style)
    OK = 0
    WARNING = 1
    CRITICAL = 2
    UNKNOWN = 3


class SSFScope(enum.IntEnum):
    # reference ssf/sample.proto Scope enum
    DEFAULT = 0
    LOCAL = 1
    GLOBAL = 2


@dataclass
class SSFSample:
    """One measurement attached to a span (reference ssf/sample.proto).

    The enum-typed fields may carry RAW INTS for values outside the
    known range: proto3 treats unknown enum values as data, and the
    decode passthrough (protocol/ssf_wire._enum_or_raw) preserves them
    so the per-sample converter can skip-and-count like the reference
    (samplers/parser.go:103-120). Don't assume .name/.value exist on
    them."""

    metric: SSFMetricType | int = SSFMetricType.COUNTER
    name: str = ""
    value: float = 0.0
    timestamp: int = 0
    message: str = ""
    status: SSFStatus | int = SSFStatus.OK
    sample_rate: float = 1.0
    tags: dict[str, str] = field(default_factory=dict)
    unit: str = ""
    scope: SSFScope | int = SSFScope.DEFAULT


@dataclass
class SSFSpan:
    """A trace span carrying samples (reference ssf/sample.proto SSFSpan)."""

    version: int = 0
    trace_id: int = 0
    id: int = 0
    parent_id: int = 0
    start_timestamp: int = 0  # nanoseconds
    end_timestamp: int = 0  # nanoseconds
    error: bool = False
    service: str = ""
    tags: dict[str, str] = field(default_factory=dict)
    indicator: bool = False
    name: str = ""
    metrics: list[SSFSample] = field(default_factory=list)


# ---------------------------------------------------------------------------
# Sample constructors (reference ssf/samples.go)


def _mk(
    metric: SSFMetricType,
    name: str,
    value: float,
    tags: Optional[dict[str, str]] = None,
    unit: str = "",
    timestamp: Optional[int] = None,
) -> SSFSample:
    return SSFSample(
        metric=metric,
        name=name,
        value=value,
        timestamp=int(time.time()) if timestamp is None else timestamp,
        sample_rate=1.0,
        tags=dict(tags) if tags else {},
        unit=unit,
    )


def count(name: str, value: float, tags: Optional[dict[str, str]] = None) -> SSFSample:
    return _mk(SSFMetricType.COUNTER, name, value, tags)


def gauge(name: str, value: float, tags: Optional[dict[str, str]] = None) -> SSFSample:
    return _mk(SSFMetricType.GAUGE, name, value, tags)


def histogram(
    name: str, value: float, tags: Optional[dict[str, str]] = None, unit: str = ""
) -> SSFSample:
    return _mk(SSFMetricType.HISTOGRAM, name, value, tags, unit)


def timing_ns(
    name: str, duration_ns: int, tags: Optional[dict[str, str]] = None
) -> SSFSample:
    """A timer expressed in nanoseconds (reference ssf.Timing with
    time.Nanosecond resolution)."""
    return _mk(SSFMetricType.HISTOGRAM, name, float(duration_ns), tags, unit="ns")


def set_sample(
    name: str, value: str, tags: Optional[dict[str, str]] = None
) -> SSFSample:
    s = _mk(SSFMetricType.SET, name, 0.0, tags)
    s.message = value
    return s


def status(
    name: str, st: SSFStatus, message: str = "", tags: Optional[dict[str, str]] = None
) -> SSFSample:
    s = _mk(SSFMetricType.STATUS, name, 0.0, tags)
    s.status = st
    s.message = message
    return s


def randomly_sample(rate: float, *samples: SSFSample) -> list[SSFSample]:
    """Keep samples with probability ``rate``, recording the rate on the
    survivors (reference ssf/samples.go RandomlySample)."""
    if rate >= 1.0:
        return list(samples)
    out = []
    for s in samples:
        if random.random() < rate:
            s.sample_rate = rate
            out.append(s)
    return out


def valid_trace_span(span: SSFSpan) -> bool:
    """A span is a valid trace span if it has id, trace id, start, end and
    a name (reference protocol/wire.go:85-89 ValidTrace)."""
    return (
        span.id != 0
        and span.trace_id != 0
        and span.start_timestamp != 0
        and span.end_timestamp != 0
        and span.name != ""
    )
