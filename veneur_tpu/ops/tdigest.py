"""Batched, array-native t-digest for TPU.

Semantics spec: the reference's merging t-digest
(tdigest/merging_digest.go:115-389 — Add/mergeAllTemps/mergeOne/Quantile/CDF/
Merge), re-derived for SIMD execution instead of translated:

* The reference maintains one Go slice of centroids per series and merges a
  temp buffer with an inherently sequential in-place walk (mergeAllTemps,
  :140-224), deciding greedily whether each element opens a new centroid
  (mergeOne :229-254, arcsine index estimate :259-262).

* Here a *pool* of digests is a pair of dense arrays `means/weights: f32[S,C]`
  (rows sorted by mean, empty slots mean=+inf/weight=0) plus per-row scalars
  min/max/reciprocal-sum. Compression is one data-parallel program over all
  rows at once:

      sort by mean  →  per-row cumulative weight  →  arcsine k-function
      bucket quantization  →  flat segment-sum into [S*C] slots  →  re-sort

  Elements whose left cumulative quantile falls in the same integer bucket of
  k(q) = δ·(asin(2q−1)/π + ½) merge into one centroid (exact weighted mean —
  the order-independent closed form of the reference's Welford update,
  :245-246). Since k ranges over [0, δ], a row holds ≤ δ+1 centroids; with the
  default δ=100 that fits C=128, one TPU lane tile. The reference's own merge
  order is randomized (Merge :374-389 shuffles), so bit-equality is not a
  goal; the tests hold the same quantile-error budget the reference's
  statistical tests use.

Raw-sample ingest (`add_batch`) consumes an unordered batch of (row, value,
weight) triples: the batch is first collapsed into per-row "batch digests"
with the same bucketing math (a segmented sort + one segment-sum), then
concatenated with the existing rows and re-compressed — the batched analog of
the reference's temp-buffer merge. Cross-digest merge for the global tier
(`merge`) concatenates centroid rows and re-compresses, replacing the
reference's shuffled re-Add loop with one deterministic program.
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from veneur_tpu.ops import exactnum as exn
from veneur_tpu.ops import segments


def _prefix_scans_xla(srows, svals, sw, n):
    """The XLA scan stack: three prefix sums + forward/backward
    segmented sums (see add_batch for what each feeds).

    All float scans run order-pinned (ops/exactnum.py Hillis-Steele,
    product rounded via exn.block before the adds) so the host fallback
    engine's NumPy twin reproduces them bitwise.

    RESOLVED (round 4): a fused two-pass Pallas kernel for these five
    scans (ops/pallas_scan.py, gated behind VENEUR_FUSED_SCANS) was
    deleted rather than enabled. The staged-ingest redesign
    (core/worker._histo_fold_staged) moved add_batch off the hot ingest
    path — samples stage host-side and the per-interval fold never runs
    these scans — so the kernel's only remaining callers are the hot-row
    spill and import merge paths, whose batches are too small for a
    custom kernel to pay for itself. The Pallas kernel that remains on a
    hot path is flush_extract (ops/pallas_kernels.py)."""
    zero1 = jnp.zeros((1,), sw.dtype)
    pre_w = jnp.concatenate([zero1, exn.cumsum(sw)])  # [N+1]
    pre_vw = jnp.concatenate([zero1, exn.cumsum(exn.block(svals * sw))])
    pre_recip = jnp.concatenate(
        [zero1, exn.cumsum(jnp.where(sw > 0, sw / svals, 0.0))])
    row_starts = jnp.concatenate(
        [jnp.ones((1,), bool), srows[1:] != srows[:-1]])
    seg_cum = segments.segmented_cumsum(sw, row_starts)
    row_ends = jnp.concatenate([row_starts[1:], jnp.ones((1,), bool)])
    suffix = segments.segmented_cumsum(sw[::-1], row_ends[::-1])[::-1]
    return pre_w, pre_vw, pre_recip, seg_cum, suffix


DEFAULT_COMPRESSION = 100.0
# Capacity per row: δ+1 buckets can be produced by the k-function; round up
# to the TPU lane width. δ up to 127 fits C=128.
DEFAULT_CAPACITY = 128

_INF = jnp.inf


class TDigestPool(NamedTuple):
    """A pool of S t-digests as dense device arrays.

    means:   f32[S, C], rows sorted ascending, empty slots +inf
    weights: f32[S, C], empty slots 0
    min:     f32[S], +inf when empty   (reference MergingDigest.min)
    max:     f32[S], -inf when empty   (reference MergingDigest.max)
    recip:   f32[S], reciprocal sum    (reference MergingDigest.reciprocalSum)
    """

    means: jax.Array
    weights: jax.Array
    min: jax.Array
    max: jax.Array
    recip: jax.Array

    @property
    def num_rows(self) -> int:
        return self.means.shape[0]

    @property
    def capacity(self) -> int:
        return self.means.shape[1]


def capacity_for(compression: float) -> int:
    """Smallest multiple of 128 that can hold δ+1 bucket centroids."""
    need = int(math.floor(compression)) + 2
    return max(128, ((need + 127) // 128) * 128)


def init_pool(num_rows: int, capacity: int = DEFAULT_CAPACITY) -> TDigestPool:
    return TDigestPool(
        means=jnp.full((num_rows, capacity), _INF, dtype=jnp.float32),
        weights=jnp.zeros((num_rows, capacity), dtype=jnp.float32),
        min=jnp.full((num_rows,), _INF, dtype=jnp.float32),
        max=jnp.full((num_rows,), -_INF, dtype=jnp.float32),
        recip=jnp.zeros((num_rows,), dtype=jnp.float32),
    )


def _k_bucket(q: jax.Array, compression: float, capacity: int) -> jax.Array:
    """floor of the t-digest k1 scale function δ·(asin(2q−1)/π + ½)
    (reference tdigest/merging_digest.go:259-262), clipped to the row
    capacity. Table form (exactnum.kscale_bucket): the arcsin is
    inverted once on the host into the δ bucket-boundary quantiles and
    the device does a comparison-exact searchsorted — bitwise
    reproducible by the host engine's NumPy twin, and cheaper than a
    transcendental on every element."""
    return jnp.clip(exn.kscale_bucket(q, compression), 0, capacity - 1)


def _compress_rows(
    means: jax.Array, weights: jax.Array, compression: float, capacity: int
) -> tuple[jax.Array, jax.Array]:
    """Compress candidate centroid rows [S, M] → [S, capacity].

    Empty candidate slots must have weight 0 (mean value is then ignored).
    Output rows are sorted by mean with +inf padding.
    """
    s, m = means.shape
    # 1. Sort each row by mean, carrying weights. Zero-weight slots are
    #    keyed to +inf so they sort to the end.
    sort_keys = jnp.where(weights > 0, means, _INF)
    sorted_means, sorted_w = jax.lax.sort(
        (sort_keys, weights), dimension=-1, num_keys=1
    )
    # Stage barriers: each stage's outputs feed several consumers below;
    # without them XLA's fusion duplicates whole producer chains into
    # every consumer (measured 1.8x end-to-end at S=262k on CPU, and the
    # same recompute heuristic exists on TPU).
    sorted_means, sorted_w = jax.lax.optimization_barrier(
        (sorted_means, sorted_w))
    # 2. Per-row cumulative weight and left-edge quantile. (Order-pinned
    #    Hillis scan — the host engine twin mirrors it bitwise.)
    w_cum = exn.cumsum(sorted_w)
    total = w_cum[:, -1:]
    q_left = (w_cum - sorted_w) / jnp.maximum(total, 1e-30)
    # 3. Quantize to k-function buckets. (Zero-weight padding slots land in
    #    whatever bucket q=1 maps to; they only ever extend a run with zero
    #    weight, so the sums below are unaffected.)
    bucket = _k_bucket(q_left, compression, capacity)
    w_cum, bucket = jax.lax.optimization_barrier((w_cum, bucket))
    # 4. Bucket accumulation, scatter- AND broadcast-free: buckets are
    #    non-decreasing along a sorted row, so each bucket is one
    #    contiguous run; its sum is a difference of row-prefix sums at the
    #    run ends. Run placement is irrelevant — step 5 re-sorts by mean —
    #    so results stay where the run ends and a sort compacts them.
    #    (The previous [S, M, C] compare+select+reduce formulation was
    #    fused but compute-bound: ~34G lane-ops at S=1M; this is O(S·M).)
    mw_cum = exn.cumsum(
        jnp.where(sorted_w > 0, sorted_means * sorted_w, 0.0))
    nxt = jnp.concatenate(
        [bucket[:, 1:], jnp.full((s, 1), -1, jnp.int32)], axis=-1)
    is_end = bucket != nxt  # last slot of each bucket run (row end included)
    w_before, mw_before = segments.last_marked_carry(is_end, w_cum, mw_cum)
    seg_w = w_cum - w_before
    seg_mw = mw_cum - mw_before
    live = is_end & (seg_w > 0)
    new_means = jnp.where(live, seg_mw / jnp.maximum(seg_w, 1e-30), _INF)
    new_w = jnp.where(live, seg_w, 0.0)
    new_means, new_w = jax.lax.optimization_barrier((new_means, new_w))
    # 5. Sort by mean (empties keyed +inf sort last) and keep the first
    #    `capacity` slots — the k-function emits ≤ δ+1 ≤ capacity buckets,
    #    so the slice only ever drops padding.
    new_means, new_w = jax.lax.sort((new_means, new_w), dimension=-1,
                                    num_keys=1)
    return new_means[:, :capacity], new_w[:, :capacity]


@functools.partial(jax.jit, static_argnames=("compression", "capacity"))
def compress_rows(
    means: jax.Array,
    weights: jax.Array,
    compression: float = DEFAULT_COMPRESSION,
    capacity: int = DEFAULT_CAPACITY,
) -> tuple[jax.Array, jax.Array]:
    return _compress_rows(means, weights, compression, capacity)


class BatchStats(NamedTuple):
    """Per-row statistics of one raw-sample batch; feeds both the digest
    scalars and the sampler's host-local aggregates (the reference keeps
    LocalWeight/Min/Max/Sum/ReciprocalSum outside the digest,
    samplers/samplers.go:467-494)."""

    weight: jax.Array  # [K] Σ sample weights
    min: jax.Array  # [K]
    max: jax.Array  # [K]
    sum: jax.Array  # [K] Σ value·weight
    recip: jax.Array  # [K] Σ weight/value


@functools.partial(jax.jit, static_argnames=("compression",))
def add_batch(
    means: jax.Array,
    weights: jax.Array,
    dmin: jax.Array,
    dmax: jax.Array,
    drecip: jax.Array,
    rows: jax.Array,
    values: jax.Array,
    sample_weights: jax.Array,
    compression: float = DEFAULT_COMPRESSION,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, BatchStats]:
    """Ingest a batch of raw samples into digest rows.

    means/weights: f32[K, C] digest rows (typically a gathered active set)
    dmin/dmax/drecip: f32[K] digest scalars for those rows
    rows: i32[N] row index per sample in [0, K); padding samples must carry
          sample_weights == 0 (their row/value are ignored).
    values, sample_weights: f32[N]

    Returns updated (means, weights, dmin, dmax, drecip, BatchStats).

    The batched analog of reference Add (tdigest/merging_digest.go:115-137) +
    mergeAllTemps (:140-224): the batch is collapsed to per-row bucket
    centroids, then merged with the existing rows in one compression pass.
    """
    k, c = means.shape
    n = rows.shape[0]
    live = sample_weights > 0
    # Padding lanes get row index k so they sort into a tail run past every
    # real row: within a real row, every sorted sample is then live, which
    # makes per-row min/max plain boundary gathers (no segment reductions —
    # those are the most expensive primitive in this whole function on TPU).
    rows = jnp.where(live, rows, k)
    safe_vals = jnp.where(live, values, 1.0)

    # --- 1. Sort the batch by (row, value). Padding is the tail run.
    srows, svals, sw = jax.lax.sort(
        (rows, safe_vals, sample_weights), dimension=0, num_keys=2
    )

    # --- 2. Per-row stats, scatter-free (TPU-first): rows are contiguous
    #        runs in the sorted order, so every per-row reduction is either
    #        a prefix-sum difference at run boundaries or — because values
    #        sort ascending within a row — a boundary gather (min = first
    #        live element, max = last).
    pre_w, pre_vw, pre_recip, seg_cum, suffix = _prefix_scans_xla(
        srows, svals, sw, n)

    kbins = jnp.arange(k, dtype=jnp.int32)
    row_upper = jnp.searchsorted(srows, kbins, side="right").astype(jnp.int32)
    row_lower = jnp.concatenate([jnp.zeros((1,), jnp.int32), row_upper[:-1]])

    seg_w = (jnp.take(pre_w, row_upper) - jnp.take(pre_w, row_lower))
    seg_sum = (jnp.take(pre_vw, row_upper) - jnp.take(pre_vw, row_lower))
    seg_recip = (jnp.take(pre_recip, row_upper)
                 - jnp.take(pre_recip, row_lower))
    # min/max: every sample inside a real row's run is live (padding was
    # keyed past row k-1) and values sort ascending within the row, so the
    # row min/max are the run's first/last elements — two boundary gathers.
    has = seg_w > 0
    seg_min = jnp.where(has, jnp.take(svals, row_lower), _INF)
    seg_max = jnp.where(
        has, jnp.take(svals, jnp.maximum(row_upper - 1, 0)), -_INF)
    stats = BatchStats(seg_w, seg_min, seg_max, seg_sum, seg_recip)

    # --- 3. Batch digest: segmented cumulative weight → k-bucket per
    #        sample → per-(row, bucket) run sums. Scatter-free and
    #        gather-light: each (row, bucket) is one contiguous run of the
    #        sorted batch, so its sum is a difference of the global prefix
    #        sums at the run's end positions; run-start positions compact
    #        into a dense per-run table with one single-key sort. (The
    #        previous run-sum scheme resolved runs with a searchsorted over
    #        chunk offsets — a [K·C]-sized gather-chain binary search that
    #        alone cost ~80% of add_batch on v5e.)
    row_total = seg_cum + suffix - sw  # per-sample total weight of its row
    q_left = (seg_cum - sw) / jnp.maximum(row_total, 1e-30)
    bucket = _k_bucket(q_left, compression, c)
    # Non-decreasing run id; padding (row k) forms its own tail runs that
    # no real row's run window reaches.
    seg_id = srows * c + bucket
    starts = jnp.concatenate(
        [jnp.ones((1,), bool), seg_id[1:] != seg_id[:-1]])
    grank = jnp.cumsum(starts.astype(jnp.int32)) - 1  # global run index [N]
    # Dense run-start position table: ascending sort compacts the R true
    # start positions to the front, sentinel n after — so pos_ext[r] is
    # run r's first element and pos_ext[r+1] its end (the next run's
    # start, or n for the last run).
    pos = jnp.where(starts, jnp.arange(n, dtype=jnp.int32), n)
    pos_ext = jnp.concatenate(
        [jax.lax.sort(pos), jnp.full((1,), n, jnp.int32)])
    run_lo = jnp.take(grank, jnp.clip(row_lower, 0, n - 1))  # [K]
    run_hi = jnp.take(grank, jnp.maximum(row_upper - 1, 0)) + 1
    n_runs_row = jnp.where(has, run_hi - run_lo, 0)  # [K]
    j = jnp.arange(c, dtype=jnp.int32)
    runs = jnp.clip(run_lo[:, None] + j[None, :], 0, n - 1)  # [K, C]
    valid = j[None, :] < n_runs_row[:, None]
    # every [K, C]-shaped gather below is ~2M probes at fixed per-element
    # cost — the dominant fixed cost of this function on TPU — so: fetch
    # run starts once; run ends are the NEXT run's start (shift within
    # the row window), and the last run of a row ends where the row does
    # (row_upper — already known, no gather)
    r_start = jnp.take(pos_ext, runs)
    last = j[None, :] == (n_runs_row - 1)[:, None]
    # prefix sums fetched as 2-lane pairs: one gather of [K, C, 2]
    # instead of two of [K, C] per endpoint
    pre = jnp.stack([pre_w, pre_vw], axis=-1)  # [N+1, 2]
    at_start = jnp.take(pre, r_start, axis=0)  # [K, C, 2]
    # run ends need no second [K, C, 2] gather: a run ends where the NEXT
    # run starts, so at_end is at_start shifted one lane left — except a
    # row's last run, which ends at the row end (pre[row_upper], a plain
    # [K, 2] gather). Halves the dominant gather volume of this step.
    at_row_end = jnp.take(pre, row_upper, axis=0)  # [K, 2]
    at_next = jnp.concatenate(
        [at_start[:, 1:, :], jnp.zeros((k, 1, 2), at_start.dtype)], axis=1)
    at_end = jnp.where(last[:, :, None], at_row_end[:, None, :], at_next)
    diff = at_end - at_start
    bd_w = jnp.where(valid, diff[..., 0], 0.0)
    bd_mw = jnp.where(valid, diff[..., 1], 0.0)
    bd_means = jnp.where(bd_w > 0, bd_mw / jnp.maximum(bd_w, 1e-30), _INF)

    # --- 4. Merge with the existing rows and recompress.
    cat_means = jnp.concatenate([means, bd_means], axis=-1)
    cat_w = jnp.concatenate([weights, bd_w], axis=-1)
    new_means, new_w = _compress_rows(cat_means, cat_w, compression, c)

    # --- 5. Digest scalars (reference Add :124-126 updates min/max/recip).
    new_min = jnp.minimum(dmin, seg_min)
    new_max = jnp.maximum(dmax, seg_max)
    new_recip = drecip + seg_recip
    return new_means, new_w, new_min, new_max, new_recip, stats


@functools.partial(jax.jit, static_argnames=("compression",))
def merge_pools(a: TDigestPool, b: TDigestPool, compression: float
                = DEFAULT_COMPRESSION) -> TDigestPool:
    """Row-wise merge of two digest pools (the global-aggregation reduce).

    Replaces the reference's per-series shuffled re-Add loop
    (tdigest/merging_digest.go:374-389) with one concat + compress pass.
    """
    c = a.means.shape[1]
    means = jnp.concatenate([a.means, b.means], axis=-1)
    weights = jnp.concatenate([a.weights, b.weights], axis=-1)
    means, weights = _compress_rows(means, weights, compression, c)
    return TDigestPool(
        means=means,
        weights=weights,
        min=jnp.minimum(a.min, b.min),
        max=jnp.maximum(a.max, b.max),
        recip=a.recip + b.recip,
    )


@functools.partial(jax.jit, static_argnames=("compression",))
def merge_many(stacked: TDigestPool, compression: float = DEFAULT_COMPRESSION
               ) -> TDigestPool:
    """Merge H digests per series: fields shaped [H, S, ...] → [S, ...].

    The 8-local→1-global cross-host merge runs through here: all hosts'
    centroid rows concatenate along the capacity axis and compress once.
    """
    h, s, c = stacked.means.shape
    means = jnp.transpose(stacked.means, (1, 0, 2)).reshape(s, h * c)
    weights = jnp.transpose(stacked.weights, (1, 0, 2)).reshape(s, h * c)
    means, weights = _compress_rows(means, weights, compression, c)
    return TDigestPool(
        means=means,
        weights=weights,
        min=jnp.min(stacked.min, axis=0),
        max=jnp.max(stacked.max, axis=0),
        recip=exn.tsum0(stacked.recip),
    )


def _row_bounds(means: jax.Array, weights: jax.Array, dmax: jax.Array
                ) -> tuple[jax.Array, jax.Array]:
    """Per-slot lower/upper value bounds under the uniform-centroid
    assumption (reference centroidUpperBound :364-370)."""
    s, c = means.shape
    nonempty = weights > 0
    count = jnp.sum(nonempty, axis=-1)  # [S] number of centroids
    idx = jnp.arange(c)
    next_means = jnp.concatenate(
        [means[:, 1:], jnp.full((s, 1), _INF, means.dtype)], axis=-1
    )
    mid = (means + next_means) / 2.0
    is_last = idx[None, :] == (count - 1)[:, None]
    ub = jnp.where(is_last, dmax[:, None], mid)
    return ub, count


@functools.partial(jax.jit, static_argnames=("use_gather",))
def _quantile_impl(
    means: jax.Array,
    weights: jax.Array,
    dmin: jax.Array,
    dmax: jax.Array,
    qs: jax.Array,
    use_gather: bool,
) -> jax.Array:
    s, c = means.shape
    ub, count = _row_bounds(means, weights, dmax)  # [S, C], [S]
    w_cum = exn.cumsum(weights)  # [S, C]
    total = w_cum[:, -1]  # [S]
    lb = jnp.concatenate([dmin[:, None], ub[:, :-1]], axis=-1)  # [S, C]

    target = exn.block(qs[None, :] * total[:, None])  # [S, P]
    # first slot whose cumulative weight reaches the target
    # (reference: q <= weightSoFar + c.Weight), then interpolate inside
    # it. Two equivalent formulations (bit-identical — pinned by
    # test_quantile_gather_and_mask_forms_agree):
    if use_gather:
        # hosts (CPU fallback): per-row binary search + gather is 4.4x
        # the masked-reduce form at 64k series — no [S, C, P]
        # materialization, O(P log C) per row instead of O(C·P)
        first_idx = jax.vmap(
            lambda cw, t: jnp.searchsorted(cw, t, side="left"))(
                w_cum, target)  # [S, P]
        first_idx = jnp.minimum(first_idx, c - 1)

        def _at(x):  # [S, C] → [S, P] value at the found slot
            return jnp.take_along_axis(x, first_idx, axis=1)
    else:
        # one-hot + masked reduces over [S, C, P]: at S=1M the
        # [S, P]-shaped take_along_axis gathers are the slow path on
        # TPU, while select+reduce streams through the VPU
        reached = target[:, None, :] <= w_cum[:, :, None]  # [S, C, P]
        first = reached & ~jnp.pad(
            reached[:, :-1, :], ((0, 0), (1, 0), (0, 0)))  # one-hot

        def _at(x):  # [S, C] → [S, P] value at the one-hot slot
            return jnp.sum(jnp.where(first, x[:, :, None], 0.0), axis=1)

    w_at = _at(weights)
    w_before = _at(w_cum) - w_at
    lb_at = _at(lb)
    ub_at = _at(ub)
    proportion = (target - w_before) / jnp.maximum(w_at, 1e-30)
    out = lb_at + exn.block(proportion * (ub_at - lb_at))
    return jnp.where((total[:, None] > 0) & (count[:, None] > 0), out, jnp.nan)


def quantile(
    means: jax.Array,
    weights: jax.Array,
    dmin: jax.Array,
    dmax: jax.Array,
    qs: jax.Array,
) -> jax.Array:
    """Batched quantile extraction: [S, C] digests × [P] quantiles → [S, P].

    Linear interpolation over centroid bounds, matching reference Quantile
    (tdigest/merging_digest.go:302-332). Empty digests yield NaN. The
    slot-selection strategy is backend-dependent (gather on hosts,
    select+reduce on TPU) with bit-identical results.
    """
    from veneur_tpu.utils.backend import is_tpu_backend

    return _quantile_impl(means, weights, dmin, dmax, qs,
                          use_gather=not is_tpu_backend())


@jax.jit
def cdf(
    means: jax.Array,
    weights: jax.Array,
    dmin: jax.Array,
    dmax: jax.Array,
    values: jax.Array,
) -> jax.Array:
    """Batched CDF: [S, C] digests × [S] values → [S] fractions below.

    Reference CDF (tdigest/merging_digest.go:266-298).
    """
    s, c = means.shape
    ub, count = _row_bounds(means, weights, dmax)
    w_cum = jnp.cumsum(weights, axis=-1)
    total = w_cum[:, -1]
    lb = jnp.concatenate([dmin[:, None], ub[:, :-1]], axis=-1)

    v = values[:, None]  # [S, 1]
    # weight fully below the value per slot, plus partial weight of the slot
    # the value falls in (uniform within centroid bounds)
    inside = (v >= lb) & (v < ub)
    frac = jnp.where(
        inside,
        weights * (v - lb) / jnp.maximum(ub - lb, 1e-30),
        jnp.where(v >= ub, weights, 0.0),
    )
    result = jnp.sum(frac, axis=-1) / jnp.maximum(total, 1e-30)
    result = jnp.where(values <= dmin, 0.0, result)
    result = jnp.where(values >= dmax, 1.0, result)
    return jnp.where((total > 0) & (count > 0), result, jnp.nan)


@jax.jit
def row_sum(means: jax.Array, weights: jax.Array) -> jax.Array:
    """Σ mean·weight per row (reference Sum :346-353)."""
    return exn.tsum(jnp.where(weights > 0, means * weights, 0.0))


@jax.jit
def row_count(weights: jax.Array) -> jax.Array:
    """Total weight per row (reference Count :340-342)."""
    return exn.tsum(weights)


# ---------------------------------------------------------------------------
# Host-side convenience (numpy) for codecs and tests


def pool_to_numpy(pool: TDigestPool) -> dict[str, np.ndarray]:
    return {
        "means": np.asarray(pool.means),
        "weights": np.asarray(pool.weights),
        "min": np.asarray(pool.min),
        "max": np.asarray(pool.max),
        "recip": np.asarray(pool.recip),
    }
