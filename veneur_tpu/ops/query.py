"""On-device query kernels for the live read path (veneur_tpu/query/).

The flush extract (core/worker._extract / SeriesSharding.flush_extract)
evaluates the WHOLE pool — O(S·C·P) — because a flush wants every row.
A live query usually wants a handful of series, so the kernel here is
gather-then-evaluate: pick the K requested digest rows, run the t-digest
quantile program over the [K, C] sub-pool — O(K·C·P) device work per
request regardless of pool size.

Two compile-variant disciplines keep ad-hoc request shapes from
compiling unboundedly (the PR 1 pow2-ladder idiom):

* `pad_quantiles` pads an arbitrary quantile vector to the next power of
  two (min 4) by repeating the last value; callers slice the result
  columns back down.
* `pad_rows` pads a row-index vector the same way by repeating the last
  index; duplicate gathers are harmless and callers slice rows back.

This module also holds the host-side numpy references for the query
differential fuzzer (tools/fuzz_differential.py --op query): independent
re-implementations of the quantile / HLL-estimate / CMS-point math that
the device kernels must agree with.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from veneur_tpu.ops import exactnum as exn
from veneur_tpu.ops import tdigest as td

# smallest padded quantile-vector shape: dashboards ask for 1-3 points;
# one compile covers them all
MIN_QS = 4


def _next_pow2(n: int, floor: int = 1) -> int:
    v = max(n, floor)
    return 1 << (v - 1).bit_length()


def pad_quantiles(qs) -> tuple[np.ndarray, int]:
    """Pow2-pad an arbitrary quantile vector (repeat the last value) →
    (padded f32[P'], original length). Repeating a quantile is free to
    evaluate and keeps the compile ladder at log2 variants."""
    q = np.asarray(qs, dtype=np.float32).reshape(-1)
    n = q.shape[0]
    target = _next_pow2(n, MIN_QS)
    if target == n:
        return q, n
    fill = q[-1] if n else np.float32(0.5)
    return np.concatenate([q, np.full(target - n, fill, np.float32)]), n


def pad_rows(rows) -> tuple[np.ndarray, int]:
    """Pow2-pad a row-index vector (repeat the last index) →
    (padded i32[K'], original length). A duplicated gather row just
    recomputes one digest; callers slice back to the true K."""
    r = np.asarray(rows, dtype=np.int32).reshape(-1)
    n = r.shape[0]
    target = _next_pow2(n, 1)
    if target == n:
        return r, n
    return np.concatenate([r, np.full(target - n, r[-1], np.int32)]), n


@jax.jit
def quantile_rows(means: jax.Array, weights: jax.Array, dmin: jax.Array,
                  dmax: jax.Array, rows: jax.Array, qs: jax.Array
                  ) -> jax.Array:
    """Gather-then-evaluate: [K] digest rows × [P] quantiles → [K, P].

    Same interpolation as the flush extract (ops/tdigest.quantile); the
    gather bounds per-query device work by the request size, not the
    pool size."""
    return td.quantile(means[rows], weights[rows], dmin[rows], dmax[rows],
                       qs)


@jax.jit
def scalar_rows(dmin: jax.Array, dmax: jax.Array, drecip: jax.Array,
                drecip_c: jax.Array, means: jax.Array, weights: jax.Array,
                rows: jax.Array) -> tuple:
    """Gathered scalar aggregates per requested row:
    (min, max, sum, count, recip) — the non-quantile half of the flush
    extract's packed columns, for K rows only."""
    w = weights[rows]
    m = means[rows]
    return (dmin[rows], dmax[rows],
            exn.tsum(jnp.where(w > 0, m * w, 0.0)),
            exn.tsum(w),
            drecip[rows] + drecip_c[rows])


# ---------------------------------------------------------------------------
# Host-side numpy references (tools/fuzz_differential.py --op query).
# Independent math, same semantics: the fuzzer randomizes pools and
# compares these against the device kernels within float32 tolerance.


def np_quantile(means: np.ndarray, weights: np.ndarray, dmin: np.ndarray,
                dmax: np.ndarray, qs: np.ndarray) -> np.ndarray:
    """Numpy mirror of ops/tdigest.quantile: [S, C] digests × [P]
    quantiles → [S, P], NaN for empty digests."""
    means = np.asarray(means, np.float64)
    weights = np.asarray(weights, np.float64)
    dmin = np.asarray(dmin, np.float64)
    dmax = np.asarray(dmax, np.float64)
    qs = np.asarray(qs, np.float64)
    s, c = means.shape
    nonempty = weights > 0
    count = nonempty.sum(axis=-1)
    next_means = np.concatenate(
        [means[:, 1:], np.full((s, 1), np.inf)], axis=-1)
    mid = (means + next_means) / 2.0
    idx = np.arange(c)
    is_last = idx[None, :] == (count - 1)[:, None]
    ub = np.where(is_last, dmax[:, None], mid)
    w_cum = np.cumsum(weights, axis=-1)
    total = w_cum[:, -1]
    lb = np.concatenate([dmin[:, None], ub[:, :-1]], axis=-1)
    target = qs[None, :] * total[:, None]
    out = np.empty((s, qs.shape[0]))
    for i in range(s):
        fi = np.minimum(np.searchsorted(w_cum[i], target[i], side="left"),
                        c - 1)
        w_at = weights[i, fi]
        w_before = w_cum[i, fi] - w_at
        lb_at = lb[i, fi]
        ub_at = ub[i, fi]
        prop = (target[i] - w_before) / np.maximum(w_at, 1e-30)
        out[i] = lb_at + prop * (ub_at - lb_at)
    empty = (total <= 0) | (count <= 0)
    out[empty, :] = np.nan
    return out


def np_hll_estimate(registers: np.ndarray, precision: int) -> np.ndarray:
    """Numpy mirror of ops/hll.estimate: int8[S, m] → f64[S]."""
    m = float(1 << precision)
    regs = np.asarray(registers, np.float64)
    inv_sum = np.sum(np.exp2(-regs), axis=-1)
    zeros = np.sum(np.asarray(registers) == 0, axis=-1).astype(np.float64)
    alpha = 0.7213 / (1.0 + 1.079 / m)
    raw = alpha * m * m / inv_sum
    linear = m * np.log(m / np.maximum(zeros, 1.0))
    use_linear = (raw <= 2.5 * m) & (zeros > 0)
    return np.where(use_linear, linear, raw)


def np_cms_query(pool: np.ndarray, rows: np.ndarray,
                 col_idx: np.ndarray) -> np.ndarray:
    """Numpy mirror of ops/heavyhitter.query: min over depth of the
    addressed counters. i32[T,D,W] × i32[N] × i32[D,N] → i64[N]."""
    pool = np.asarray(pool)
    d = pool.shape[1]
    picked = pool[rows[None, :], np.arange(d)[:, None], col_idx]
    return picked.min(axis=0).astype(np.int64)
