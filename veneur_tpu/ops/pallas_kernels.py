"""Pallas TPU kernels for the flush hot path.

The flush-time percentile extraction at high cardinality (BASELINE.md: p99
flush latency at 1M histogram series) reads the whole digest pool. The XLA
path materializes several intermediates ([S,C] bounds, [S,C,P] reach masks)
in HBM; this kernel fuses the entire extraction — cumulative weights,
centroid bounds, quantile interpolation, sum/count aggregates — into one
VMEM pass per row block:

* cumsum along the 128-wide centroid axis is a [B,C]×[C,C] lower-triangular
  matmul (MXU work instead of a serial scan),
* per-quantile slot selection is a one-hot mask-and-reduce (no gathers —
  dynamic per-lane gathers don't vectorize on TPU),
* all P quantiles and the sum/count aggregates come out of the single load
  of means/weights.

Falls back to the XLA implementation (ops/tdigest.quantile et al.) on
platforms without Pallas TPU support; tests run the kernel in interpret
mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from veneur_tpu.ops import tdigest as td

DEFAULT_BLOCK_ROWS = 256


def _extract_kernel(means_ref, weights_ref, dmin_ref, dmax_ref, qs_ref,
                    tril_ref, quant_ref, dsum_ref, dcount_ref):
    # Mosaic lowering constraints, all verified on the real chip by
    # tools/probe_pallas_minimal.py (interpret mode can't see them):
    #   * every ref is rank-2 — rank-1 memrefs don't tile onto the
    #     (sublane, lane) register layout
    #   * no negative static indices (x[:, -1] lowers to dynamic_slice,
    #     unimplemented) — use the explicit positive index
    #   * no argmax (int reductions unsupported) — one-hot via a float
    #     min-reduce over a lane iota instead
    #   * no sublane-axis iota inside the kernel — the lower-triangular
    #     cumsum matmul matrix arrives as an operand
    means = means_ref[...]  # [B, C]
    weights = weights_ref[...]  # [B, C]
    dmin = dmin_ref[...][:, 0]  # [B, 1] -> [B]
    dmax = dmax_ref[...][:, 0]
    qs = qs_ref[...][0, :]  # [1, P] -> [P]
    b, c = means.shape
    p = qs.shape[0]

    # cumulative weight via lower-triangular matmul (rides the MXU)
    w_cum = jnp.dot(weights, tril_ref[...],
                    preferred_element_type=jnp.float32)
    total = w_cum[:, c - 1]  # [B]

    nonempty = weights > 0
    count = jnp.sum(nonempty.astype(jnp.float32), axis=-1)  # [B]

    idx = jax.lax.broadcasted_iota(jnp.int32, (b, c), 1)
    idxf = idx.astype(jnp.float32)  # tpu.iota only produces integers
    # next-slot means: shift left, +inf in the last lane
    next_means = jnp.concatenate(
        [means[:, 1:], jnp.full((b, 1), jnp.inf, means.dtype)], axis=-1)
    mid = (means + next_means) * 0.5
    is_last = idx == (count.astype(jnp.int32) - 1)[:, None]
    ub = jnp.where(is_last, dmax[:, None], mid)
    lb = jnp.concatenate([dmin[:, None], ub[:, :-1]], axis=-1)

    # aggregates from the same load
    dsum_ref[...] = jnp.sum(jnp.where(nonempty, means * weights, 0.0),
                            axis=-1, keepdims=True)
    dcount_ref[...] = total[:, None]

    w_before = w_cum - weights
    safe_w = jnp.maximum(weights, 1e-30)
    empty_row = (total <= 0) | (count <= 0)
    cols = []
    for j in range(p):
        target = qs[j] * total  # [B]
        reached = target[:, None] <= w_cum  # [B, C]
        # first reached slot, argmax-free: min lane index where reached
        first = jnp.min(jnp.where(reached, idxf, jnp.inf), axis=-1)  # [B]
        sel = idxf == first[:, None]  # one-hot [B, C]
        proportion = (target[:, None] - w_before) / safe_w
        val_all = lb + proportion * (ub - lb)
        val = jnp.sum(jnp.where(sel, val_all, 0.0), axis=-1)
        cols.append(jnp.where(empty_row, jnp.nan, val))
    quant_ref[...] = jnp.stack(cols, axis=-1)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def flush_extract(means, weights, dmin, dmax, qs,
                  block_rows: int = DEFAULT_BLOCK_ROWS,
                  interpret: bool = False):
    """Fused flush extraction: (quantiles [S,P], dsum [S], dcount [S])."""
    s, c = means.shape
    p = qs.shape[0]
    if s % block_rows:
        block_rows = min(block_rows, s)
        while s % block_rows:
            block_rows //= 2
    grid = (s // block_rows,)
    # cum[j] = Σ_{i<=j} w_i as a [C,C] matmul operand (in-kernel sublane
    # iota fails Mosaic verification; see _extract_kernel header)
    tril = jnp.asarray(
        (np.arange(c)[:, None] <= np.arange(c)[None, :])
        .astype(np.float32))
    quant, dsum, dcount = pl.pallas_call(
        _extract_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, c), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, c), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, p), lambda i: (0, 0)),
            pl.BlockSpec((c, c), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, p), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((s, p), jnp.float32),
            jax.ShapeDtypeStruct((s, 1), jnp.float32),
            jax.ShapeDtypeStruct((s, 1), jnp.float32),
        ],
        interpret=interpret,
    )(means, weights, dmin[:, None], dmax[:, None], qs[None, :], tril)
    return quant, dsum[:, 0], dcount[:, 0]


def flush_extract_reference(means, weights, dmin, dmax, qs):
    """The XLA path producing identical outputs (fallback + test oracle)."""
    quant = td.quantile(means, weights, dmin, dmax, qs)
    return quant, td.row_sum(means, weights), td.row_count(weights)


def ab_verdict_ok() -> bool:
    """The A/B gate (TPU_BACKEND.md): the Pallas extract path is only
    the production default once PALLAS_AB.json proves it on the real
    target — platform "tpu" AND >=1.0x over XLA. The committed artifact
    is CPU interpret-mode (0.13x, latency not meaningful), so until an
    on-chip capture lands, XLA extraction is the default on every
    backend. VENEUR_PALLAS=1 overrides for benchmarking/bringup;
    VENEUR_PALLAS=0 force-disables regardless of the artifact."""
    import json
    import os

    force = os.environ.get("VENEUR_PALLAS")
    if force is not None:
        return force == "1"
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "PALLAS_AB.json")
    try:
        with open(path) as f:
            ab = json.load(f)
    except (OSError, ValueError):
        return False
    return (ab.get("platform") == "tpu"
            and float(ab.get("speedup_pallas_vs_xla", 0.0)) >= 1.0)


def supported() -> bool:
    # if Pallas lowering fails on a real TPU, DeviceWorker._extract
    # demotes to the XLA path and counts it in
    # veneur.flush.pallas_fallback_total
    from veneur_tpu.utils.backend import is_tpu_backend

    return is_tpu_backend() and ab_verdict_ok()
