"""Series-axis device sharding: shard_map-partitioned sketch pools.

Partitions the series axis S of the local aggregation state across a
1-D device mesh (`series_shards` in config), so the t-digest pools, HLL
register planes, and scalar segment ops all run shard-local — upload,
micro-fold, and fold touch no cross-device links until the one packed
readback at extract. ROADMAP direction 2: one chip holds ~1470x compute
headroom at 1M series (PERF_MODEL.md); an 8-way shard of the same
kernels is the 10M+-series-per-host unlock.

Layout: logical row r lives on shard ``d = r % D`` at local index
``l = r // D`` — round-robin, so append-ordered row adoption spreads
live rows evenly across shards (block-sharding would pile every live
row on shard 0 until the pool fills). The device arrays are plain
block-sharded over PHYSICAL rows ``p = (r % D) * cap + r // D`` with
``cap = pool_rows // D``; the interleave lives purely in host-side
index translation (`phys_rows`, `perm_l2p`, `perm_p2l`) — on device a
NamedSharding over the leading axis is all XLA ever sees. This is the
same row-interleave the global tier's MeshHistoPool established
(distributed/mesh.py), kept bit-compatible here.

Closure property (what makes growth, slicing, and chunking shard-local):
``a.reshape(D, cap, ...)[:, :ecap]`` keeps exactly logical rows
[0, s_eff) in s_eff-interleaved layout, because r % D and r // D are
both preserved when cap shrinks to ecap >= ceil(s_eff/D). Hence
slice/grow/chunk are all per-shard prefix ops with no resharding.

Bit-identity (sharded == unsharded, pinned per metric class by
tests/test_series_shard.py) holds because every kernel is either
per-row independent (fold_staged, flush_extract, import, HLL scatter-
max, segment ops) or — for the one batch-global kernel, the spill
ingest — the batch is kept BIT-IDENTICAL on every shard:
`_histo_ingest_step`'s per-row stats are differences of global f32
prefix sums over the whole sorted batch (ops/tdigest.add_batch), so a
shard may not drop or reweight foreign samples. Instead each shard
remaps only the `active` row-id vector: foreign entries map to the
out-of-range local index `cap` — gathers clamp (the fetched row is
ignored), scatters drop — so every shard folds the identical batch and
discards the writes it does not own. shard_map runs with the
replication checker off (check_vma=False): the scan inside add_batch
trips it, harmlessly.

Scope: this module owns the mesh, the shardings, the host-side
permutation caches, and the jitted/shard_mapped device programs. The
worker keeps all policy (when to grow, chunk, spill); microfold takes a
SeriesSharding handle for its scatter/grow/dense programs. Composes
under the global tier: the (hosts, series) mesh of distributed/mesh.py
is the cross-host reduce; this is the within-host series split.
"""

from __future__ import annotations

import functools
import logging
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from veneur_tpu.distributed.mesh import make_series_mesh, shard_map
from veneur_tpu.ops import hll as hll_ops
from veneur_tpu.ops import tdigest as td

log = logging.getLogger("veneur_tpu.ops.series_shard")

# escape hatch mirroring VENEUR_MICRO_FOLD / VENEUR_EMIT_NATIVE: 0
# forces the legacy single-device path regardless of config
_ENV_KEY = "VENEUR_SERIES_SHARDS"


def resolve_series_shards(cfg_value: int) -> int:
    """Config value with the env escape hatch applied (the CI lane runs
    the suite once per side: sharded default and VENEUR_SERIES_SHARDS=0)."""
    env = os.environ.get(_ENV_KEY)
    if env is not None:
        try:
            return int(env)
        except ValueError:
            log.warning("ignoring non-integer %s=%r", _ENV_KEY, env)
    return int(cfg_value)


def shards_usable(shards: int) -> bool:
    """Whether a series_shards request can actually be honored here:
    needs >1 shards, a power of two (pow2 pool sizes must divide), and
    that many addressable devices."""
    if shards <= 1:
        return False
    if shards & (shards - 1):
        return False
    try:
        return len(jax.devices()) >= shards
    except RuntimeError:  # pragma: no cover - no backend at all
        return False


class SeriesSharding:
    """The device programs + index math for one worker's series shards.

    One instance per DeviceWorker (jit caches are per-shape and the
    mesh is tiny; sharing across workers would only share compile
    cache, which XLA already does at the executable level).
    """

    def __init__(self, shards: int,
                 compression: float = td.DEFAULT_COMPRESSION) -> None:
        if shards & (shards - 1) or shards < 2:
            raise ValueError(f"series_shards must be a pow2 >= 2: {shards}")
        self.shards = int(shards)
        self.compression = float(compression)
        self.mesh = make_series_mesh(self.shards)
        self.sh1 = NamedSharding(self.mesh, P("series"))
        self.sh2 = NamedSharding(self.mesh, P("series", None))
        self.rep = NamedSharding(self.mesh, P())
        # host-side permutation caches, keyed by row count
        self._l2p: dict[int, np.ndarray] = {}
        self._p2l: dict[int, np.ndarray] = {}
        # per-static-closure program caches (jit handles shape retraces;
        # these key the *closure* constants baked into each shard_map)
        self._expand_cache: dict = {}
        self._slice_cache: dict = {}
        self._chunk_cache: dict = {}
        self._grow2_cache: dict = {}
        self._grow1_cache: dict = {}
        self._mirror_cache: dict = {}
        self._est_cache: dict = {}

    # -- host-side index math ---------------------------------------------

    def perm_l2p(self, rows: int) -> np.ndarray:
        """perm_l2p(n)[r] = physical slot of logical row r. Gathering a
        PHYS-order readback with it yields logical order."""
        p = self._l2p.get(rows)
        if p is None:
            d = self.shards
            cap = rows // d
            r = np.arange(rows, dtype=np.int64)
            p = ((r % d) * cap + r // d).astype(np.int64)
            self._l2p[rows] = p
        return p

    def perm_p2l(self, rows: int) -> np.ndarray:
        """perm_p2l(n)[p] = logical row stored at physical slot p.
        Gathering a LOGICAL-order host array with it yields the physical
        layout for upload."""
        p = self._p2l.get(rows)
        if p is None:
            d = self.shards
            cap = rows // d
            r = np.arange(rows, dtype=np.int64)
            p = ((r % cap) * d + r // cap).astype(np.int64)
            self._p2l[rows] = p
        return p

    def phys_rows(self, rows: np.ndarray, pool_rows: int) -> np.ndarray:
        """Vectorized logical row ids -> physical slots. Sentinel ids >=
        pool_rows (microfold's DROP_ROW) pass through unchanged — they
        stay out of range on every shard and scatter-drop there too."""
        d = self.shards
        cap = pool_rows // d
        r = np.asarray(rows, dtype=np.int64)
        p = (r % d) * cap + r // d
        return np.where(r < pool_rows, p, r).astype(np.int32)

    def chunk_perm(self, chunk_rows: int) -> np.ndarray:
        """Inverse permutation for ONE extraction chunk's readback.

        A chunk of c global rows starting at a D-aligned logical offset
        covers local rows [start//D, start//D + c//D) on every shard;
        the assembled host array is shard-major [D * (c//D)] and logical
        row j of the chunk sits at (j % D) * (c//D) + j // D — the same
        formula as a whole pool of c rows, so the cache is shared."""
        return self.perm_l2p(chunk_rows)

    # -- placement ---------------------------------------------------------

    def place(self, arr):
        """Commit one pool array to the mesh (leading axis = phys rows)."""
        sh = self.sh2 if getattr(arr, "ndim", 1) >= 2 else self.sh1
        return jax.device_put(arr, sh)

    def replicate(self, arr):
        """Commit one batch array replicated on every shard. The CALLER
        books ledger bytes x self.shards — replication is a real per-
        device transfer, and the ledger's O(samples) pin must stay
        honest about it."""
        return jax.device_put(arr, self.rep)

    # -- t-digest programs --------------------------------------------------

    @functools.cached_property
    def fold_staged(self):
        """Sharded `_histo_fold_staged`: per-row independent, so plain
        GSPMD jit with explicit shardings is enough — no shard_map."""
        from veneur_tpu.core.worker import _histo_fold_staged

        comp = self.compression

        def _fold(*args):
            return _histo_fold_staged.__wrapped__(*args, compression=comp)

        in_sh = tuple([self.sh2] * 2 + [self.sh1] * 12 + [self.sh2] * 2)
        out_sh = tuple([self.sh2] * 2 + [self.sh1] * 12)
        return jax.jit(_fold, in_shardings=in_sh, out_shardings=out_sh,
                       donate_argnums=tuple(range(14)))

    @functools.cached_property
    def flush_extract(self):
        from veneur_tpu.core.worker import _histo_flush_extract

        in_sh = tuple([self.sh2] * 2 + [self.sh1] * 12 + [self.rep])
        out_sh = tuple([self.sh2] + [self.sh1] * 10)
        return jax.jit(_histo_flush_extract.__wrapped__,
                       in_shardings=in_sh, out_shardings=out_sh)

    @functools.cached_property
    def ingest_step(self):
        """Sharded spill ingest. `active` carries PHYSICAL slots; each
        shard rebases to local and maps foreign entries out of range so
        the (replicated, bit-identical) batch folds everywhere but only
        the owner's writes land. See module docstring for why the batch
        must not be filtered per shard."""
        from veneur_tpu.core.worker import _histo_ingest_step

        comp = self.compression

        def _local(*args):
            fields = args[:14]
            act, lids, vals, wts = args[14:]
            cap = fields[0].shape[0]
            d = jax.lax.axis_index("series")
            la = act - d * cap
            la = jnp.where((la >= 0) & (la < cap), la, cap).astype(jnp.int32)
            return _histo_ingest_step.__wrapped__(
                *fields, la, lids, vals, wts, compression=comp)

        sm = shard_map(
            _local, mesh=self.mesh,
            in_specs=tuple([P("series", None)] * 2 + [P("series")] * 12
                           + [P(None)] * 4),
            out_specs=tuple([P("series", None)] * 2 + [P("series")] * 12),
            check_vma=False)
        return jax.jit(sm, donate_argnums=tuple(range(14)))

    @functools.cached_property
    def import_step(self):
        """Sharded `_histo_import_step` (global tier merge): per-row
        independent, but the row ids are data — same local-rebase +
        out-of-range-foreign remap as ingest."""
        from veneur_tpu.core.worker import _histo_import_step

        comp = self.compression

        def _local(*args):
            fields = args[:6]
            rows, im, iw, imn, imx, irc = args[6:]
            cap = fields[0].shape[0]
            d = jax.lax.axis_index("series")
            lr = rows - d * cap
            lr = jnp.where((lr >= 0) & (lr < cap), lr, cap).astype(jnp.int32)
            return _histo_import_step.__wrapped__(
                *fields, lr, im, iw, imn, imx, irc, compression=comp)

        sm = shard_map(
            _local, mesh=self.mesh,
            in_specs=tuple([P("series", None)] * 2 + [P("series")] * 4
                           + [P(None)] * 6),
            out_specs=tuple([P("series", None)] * 2 + [P("series")] * 4),
            check_vma=False)
        return jax.jit(sm, donate_argnums=tuple(range(6)))

    # -- staged-plane upload ------------------------------------------------

    def expand_flat(self, flat_v, flat_w, counts_phys, depth: int,
                    unit: bool):
        """Sharded `_expand_flat_planes`: the host pre-splits the flat
        compacted samples into per-shard segments padded to a common
        length ([D, Lmax], see worker._fold_one_plane), counts arrive in
        phys order, and each shard rebuilds its own [cap, depth] dense
        planes locally. Keeps the upload O(samples) per shard."""
        fn = self._expand_cache.get((depth, unit))
        if fn is None:
            from veneur_tpu.core.worker import _expand_flat_planes

            def _local(fv, fw, cnt):
                return _expand_flat_planes.__wrapped__(
                    fv[0], fw[0], cnt, depth, unit)

            fn = jax.jit(shard_map(
                _local, mesh=self.mesh,
                in_specs=(P("series", None), P("series", None), P("series")),
                out_specs=(P("series", None), P("series", None)),
                check_vma=False))
            self._expand_cache[(depth, unit)] = fn
        return fn(flat_v, flat_w, counts_phys)

    # -- slicing / growth ---------------------------------------------------

    def slice_field(self, a, s_eff: int):
        """Shrink one pool array [S, ...] -> [s_eff, ...]: each shard
        keeps its local prefix (the interleave closure property)."""
        ecap = s_eff // self.shards
        fn = self._slice_cache.get(ecap)
        if fn is None:
            def _local(x):
                return x[:ecap]

            fn = jax.jit(shard_map(_local, mesh=self.mesh,
                                   in_specs=P("series"),
                                   out_specs=P("series"), check_vma=False))
            self._slice_cache[ecap] = fn
        return fn(a)

    def slice_chunk(self, a, start: int, rows: int):
        """One extraction chunk: global rows [start, start+rows), both
        D-aligned (pow2 chunks >= 1024 over pow2 D <= 1024), are local
        rows [start//D, ...+rows//D) on EVERY shard — a lockstep
        dynamic slice, no resharding."""
        lc = rows // self.shards
        fn = self._chunk_cache.get(lc)
        if fn is None:
            def _local(x, s):
                return jax.lax.dynamic_slice_in_dim(x, s, lc, 0)

            fn = jax.jit(shard_map(_local, mesh=self.mesh,
                                   in_specs=(P("series"), P()),
                                   out_specs=P("series"), check_vma=False))
            self._chunk_cache[lc] = fn
        return fn(a, jnp.int32(start // self.shards))

    def grow_2d(self, old, new_rows: int):
        """Sharded pool growth: each shard zero-pads its local block.
        Because r % D is unchanged by growth (D fixed), every existing
        logical row keeps its shard AND its local index — growth moves
        no data between devices."""
        ncap = new_rows // self.shards
        fn = self._grow2_cache.get(ncap)
        if fn is None:
            def _local(x):
                cap, c = x.shape
                return jnp.zeros((ncap, c), x.dtype).at[:cap].set(x)

            fn = jax.jit(shard_map(_local, mesh=self.mesh,
                                   in_specs=P("series", None),
                                   out_specs=P("series", None),
                                   check_vma=False),
                         donate_argnums=(0,))
            self._grow2_cache[ncap] = fn
        return fn(old)

    def grow_1d(self, old, new_rows: int, fill: float):
        ncap = new_rows // self.shards
        key = (ncap, float(fill))
        fn = self._grow1_cache.get(key)
        if fn is None:
            def _local(x):
                cap = x.shape[0]
                return jnp.full((ncap,), fill, x.dtype).at[:cap].set(x)

            fn = jax.jit(shard_map(_local, mesh=self.mesh,
                                   in_specs=P("series"),
                                   out_specs=P("series"), check_vma=False),
                         donate_argnums=(0,))
            self._grow1_cache[key] = fn
        return fn(old)

    # -- micro-fold mirror --------------------------------------------------

    @functools.cached_property
    def scatter_chunk(self):
        """Sharded microfold scatter: rows carry PHYSICAL slots (the
        mirror's carry buffers stay logical; translation happens at
        dispatch). DROP_ROW padding is >= pool rows, hence out of range
        on every shard — dropped, same as the unsharded mode="drop"."""

        def _local(dv, dw, rows, slots, vals, wts):
            cap = dv.shape[0]
            d = jax.lax.axis_index("series")
            lr = rows - d * cap
            lr = jnp.where((lr >= 0) & (lr < cap), lr, cap).astype(jnp.int32)
            dv = dv.at[lr, slots].set(vals, mode="drop")
            dw = dw.at[lr, slots].set(wts, mode="drop")
            return dv, dw

        sm = shard_map(
            _local, mesh=self.mesh,
            in_specs=(P("series", None), P("series", None),
                      P(None), P(None), P(None), P(None)),
            out_specs=(P("series", None), P("series", None)),
            check_vma=False)
        return jax.jit(sm, donate_argnums=(0, 1))

    def mirror_dense(self, arr, s_eff: int):
        """Align a mirror plane [mirror_rows, depth] to the fold's
        [s_eff, depth] phys layout: per shard, slice or zero-pad the
        local block to s_eff // D rows."""
        ecap = s_eff // self.shards
        fn = self._mirror_cache.get(ecap)
        if fn is None:
            def _local(x):
                mcap, depth = x.shape
                if mcap >= ecap:
                    return x[:ecap]
                return jnp.zeros((ecap, depth), x.dtype).at[:mcap].set(x)

            fn = jax.jit(shard_map(_local, mesh=self.mesh,
                                   in_specs=P("series", None),
                                   out_specs=P("series", None),
                                   check_vma=False))
            self._mirror_cache[ecap] = fn
        return fn(arr)

    # -- HLL programs -------------------------------------------------------

    @functools.cached_property
    def hll_insert(self):
        """Sharded HLL register scatter-max. int8 max is order- and
        placement-independent, so only the row rebase matters: foreign
        rows map past the local register plane and drop."""

        def _local(regs, rows, reg_idx, rank):
            cap = regs.shape[0]
            d = jax.lax.axis_index("series")
            lr = rows - d * cap
            lr = jnp.where((lr >= 0) & (lr < cap), lr, cap).astype(jnp.int32)
            return hll_ops.insert_batch(regs, lr, reg_idx, rank)

        sm = shard_map(
            _local, mesh=self.mesh,
            in_specs=(P("series", None), P(None), P(None), P(None)),
            out_specs=P("series", None), check_vma=False)
        return jax.jit(sm, donate_argnums=(0,))

    @functools.cached_property
    def hll_max_rows(self):
        """Sharded register max-merge at explicit rows (import path)."""

        def _local(regs, rows, imp):
            cap = regs.shape[0]
            d = jax.lax.axis_index("series")
            lr = rows - d * cap
            lr = jnp.where((lr >= 0) & (lr < cap), lr, cap).astype(jnp.int32)
            return regs.at[lr].max(imp, mode="drop")

        sm = shard_map(
            _local, mesh=self.mesh,
            in_specs=(P("series", None), P(None), P(None, None)),
            out_specs=P("series", None), check_vma=False)
        return jax.jit(sm, donate_argnums=(0,))

    def hll_estimate(self, registers, precision: int):
        """Per-row HLL estimation over the sharded register plane
        (per-row independent: plain GSPMD jit, precision baked in)."""
        fn = self._est_cache.get(precision)
        if fn is None:
            def _e(regs):
                return hll_ops.estimate(regs, precision)

            fn = jax.jit(_e, in_shardings=(self.sh2,),
                         out_shardings=self.sh1)
            self._est_cache[precision] = fn
        return fn(registers)

    # -- scalar segment ops -------------------------------------------------

    def segment_counter_sum(self, rows, contributions, num_rows: int):
        """Sharded device counter reduction (ops/scalars device path):
        each shard segment-sums the replicated COO into its local rows.
        Host f64 pools remain the exactness-critical default; this is
        the device-resident variant for sharded deployments."""
        fn = getattr(self, "_seg_sum_fn", None)
        if fn is None:
            def _local(r, c, out):
                cap = out.shape[0]
                d = jax.lax.axis_index("series")
                lr = r - d * cap
                lr = jnp.where((lr >= 0) & (lr < cap), lr,
                               cap).astype(jnp.int32)
                return out.at[lr].add(c, mode="drop")

            sm = shard_map(_local, mesh=self.mesh,
                           in_specs=(P(None), P(None), P("series")),
                           out_specs=P("series"), check_vma=False)
            fn = jax.jit(sm, donate_argnums=(2,))
            self._seg_sum_fn = fn
        out = jax.device_put(jnp.zeros(num_rows, jnp.float32), self.sh1)
        return fn(jnp.asarray(rows, jnp.int32),
                  jnp.asarray(contributions, jnp.float32), out)

    def segment_gauge_last(self, rows, values, num_rows: int):
        """Sharded last-write-wins gauge plane. Mirrors
        ops/scalars.segment_gauge_last's (values, present) contract: the
        winner per row is the highest arrival position; each shard
        resolves its own rows from the replicated batch."""
        fn = getattr(self, "_seg_last_fn", None)
        if fn is None:
            def _local(r, v, seq, out_v, out_s):
                cap = out_v.shape[0]
                d = jax.lax.axis_index("series")
                lr = r - d * cap
                lr = jnp.where((lr >= 0) & (lr < cap), lr,
                               cap).astype(jnp.int32)
                # newest sequence number wins per row (seq starts at 1;
                # a row left at 0 had no sample -> present False)
                ns = out_s.at[lr].max(seq, mode="drop")
                win = ns[lr] == seq
                lr_w = jnp.where(win, lr, cap).astype(jnp.int32)
                nv = out_v.at[lr_w].set(v, mode="drop")
                return nv, ns

            sm = shard_map(_local, mesh=self.mesh,
                           in_specs=(P(None), P(None), P(None),
                                     P("series"), P("series")),
                           out_specs=(P("series"), P("series")),
                           check_vma=False)
            fn = jax.jit(sm, donate_argnums=(3, 4))
            self._seg_last_fn = fn
        n = len(np.asarray(rows))
        seq = jnp.arange(1, n + 1, dtype=jnp.int32)
        out_v = jax.device_put(jnp.zeros(num_rows, jnp.float32), self.sh1)
        out_s = jax.device_put(jnp.zeros(num_rows, jnp.int32), self.sh1)
        nv, ns = fn(jnp.asarray(rows, jnp.int32),
                    jnp.asarray(values, jnp.float32), seq, out_v, out_s)
        return nv, ns > 0
