"""Two-tier (sparse host / dense device) HyperLogLog set store.

The dense pool in ops/hll.py costs 2^p bytes per series (16KB at p=14):
at 1M set series that is 16GB of HBM — past a v5e chip. The reference
avoids the same cliff with the vendored sketch's sparse mode
(vendor/github.com/axiomhq/hyperloglog/hyperloglog.go:31-39: small sets
live as encoded-hash lists, converting to registers past a size bound).

Here the staging is columnar and batched instead of per-sketch:

* Sparse tier (host): inserts accumulate as (row, register-index, rank)
  triples; compaction lexsorts by (row, idx) and keeps the max rank per
  pair — exactly the register content, stored at ~9 bytes per *distinct*
  register instead of 2^p bytes per series.
* Dense tier (device): a row crossing ``promote_entries`` distinct
  registers replays its triples into a dense device row via the same
  scatter-max insert as always; later inserts route straight to the
  device. Imported full-register rows (the global tier's merge) are
  dense by nature and promote immediately.

Crossover: a sparse register costs ~9B host-side, a dense row 2^p bytes
of HBM; the default threshold 2^p/8 (2048 at p=14) promotes when the
sparse form reaches ~18KB — past the dense cost — so memory is within
~2x of optimal on both sides of the boundary.

Estimates use the same harmonic-mean + linear-counting estimator as the
device kernel (ops/hll.py estimate), so a series reports identically on
either side of promotion.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from veneur_tpu.ops import hll as hll_ops
from veneur_tpu.ops import host_engine as he
from veneur_tpu.ops.device_guard import DeviceFaultError


class StagedSetStore:
    """Per-epoch set-sketch state for one worker (staged representation).

    All rows are identified by the worker directory's set-row index.

    Device fault domain (ops/device_guard): every dense-tier device op
    routes through the worker's guard under op "sets". Register updates
    are max-merges — idempotent and order-independent — so the failover
    story is the simplest in the system: on a classified device fault
    the dense tier converts to host numpy registers (``to_host``) and
    the faulted update re-applies there; a partially-applied device
    update before the fault can only have asserted ranks the host redo
    asserts again. ``to_device`` re-uploads at probe re-admission.
    """

    def __init__(self, precision: int = hll_ops.DEFAULT_PRECISION,
                 promote_entries: Optional[int] = None,
                 compact_every: int = 1 << 16, shard=None,
                 guard=None, host: bool = False) -> None:
        self.precision = precision
        # series-sharded dense tier (ops/series_shard.SeriesSharding):
        # the [slots, m] register plane partitions over the shard mesh
        # with the same row interleave as the sketch pools — slots are
        # promotion-order, so the interleave spreads hot promoted rows
        # round-robin. The sparse host tier is unaffected.
        self._shard = shard
        self.m = hll_ops.num_registers(precision)
        self.promote_entries = promote_entries or max(self.m // 8, 64)
        self.compact_every = compact_every
        # sparse tier: compacted sorted-unique keys row*m+idx with max rank
        self._ckeys = np.empty(0, np.int64)
        self._crank = np.empty(0, np.int8)
        # pending (uncompacted) triples
        self._p_keys: list[np.ndarray] = []
        self._p_rank: list[np.ndarray] = []
        self._pend = 0
        # dense tier
        self._slot_of_row: dict[int, int] = {}
        # vectorized row→slot lookup (-1 = sparse); grows with max row
        self._slot_lut = np.full(64, -1, np.int32)
        self._guard = guard
        # host mode: _dense is np int8 [slots, m] in LOGICAL slot order
        # (quarantined worker, or failover after a dense-tier fault)
        self._host = bool(host)
        self._dense = None  # jax int8 [slots, m] (np int8 in host mode)
        # imported full-register rows max-merge host-side and batch onto
        # the device once per flush (a per-import device update would
        # copy the whole dense pool each call)
        self._imp_dense: dict[int, np.ndarray] = {}

    # -- device fault domain ------------------------------------------------

    @property
    def host_mode(self) -> bool:
        return self._host

    def _dev_call(self, fn, *args, retryable: bool = False):
        """One dense-tier device op through the worker's guard. The
        sharded register programs donate the plane (retryable=False);
        the unsharded inserts and all estimates do not."""
        if self._guard is None:
            return fn(*args)
        return self._guard.call("sets", fn, *args, retryable=retryable)

    def to_host(self) -> None:
        """Fail the dense tier over to host numpy registers (logical
        slot order). Safe after a partially-applied faulted update:
        max-merges re-applied host-side only re-assert existing ranks."""
        if self._host:
            return
        self._host = True
        if self._dense is None:
            return
        d = np.asarray(self._dense)
        if self._shard is not None:
            d = d[self._shard.perm_l2p(d.shape[0])]
        self._dense = d

    def to_device(self) -> None:
        """Re-admit the dense tier to the device (probe succeeded)."""
        if not self._host:
            return
        self._host = False
        if self._dense is None:
            return
        d = self._dense
        if self._shard is not None:
            self._dense = self._shard.place(
                jnp.asarray(d[self._shard.perm_p2l(d.shape[0])]))
        else:
            self._dense = jnp.asarray(d)

    # -- ingest -------------------------------------------------------------

    def insert(self, rows: np.ndarray, idx: np.ndarray,
               rank: np.ndarray) -> None:
        """Batch of (row, register, rank) updates (host arrays)."""
        rows = np.asarray(rows, np.int64)
        if rows.size == 0:
            return
        idx = np.asarray(idx, np.int64)
        rank = np.asarray(rank, np.int8)
        if self._slot_of_row:
            dense_slot = self._slot_lut[
                np.minimum(rows, self._slot_lut.size - 1)]
            dense_slot = np.where(rows < self._slot_lut.size, dense_slot, -1)
            dmask = dense_slot >= 0
            if dmask.any():
                self._dense_insert(dense_slot[dmask], idx[dmask],
                                   rank[dmask])
            smask = ~dmask
            rows, idx, rank = rows[smask], idx[smask], rank[smask]
            if rows.size == 0:
                return
        self._p_keys.append(rows * self.m + idx)
        self._p_rank.append(rank)
        self._pend += rows.size
        if self._pend >= self.compact_every:
            self._compact()

    def import_dense(self, row: int, registers: np.ndarray) -> None:
        """Merge a full register row (wire import) — dense by nature.
        Max-merged host-side; promoted to the device in one batched
        update at flush (_apply_imports)."""
        row = int(row)
        regs = np.asarray(registers, np.int8)
        prev = self._imp_dense.get(row)
        self._imp_dense[row] = (regs.copy() if prev is None
                                else np.maximum(prev, regs))

    def _apply_imports(self) -> None:
        if not self._imp_dense:
            return
        rows = sorted(self._imp_dense)
        slots = np.asarray([self._promote(r) for r in rows], np.int32)
        stacked = np.stack([self._imp_dense[r] for r in rows])
        self._imp_dense = {}
        assert self._dense is not None
        if self._host:
            np.maximum.at(self._dense, slots.astype(np.int64), stacked)
            return
        sh = self._shard
        try:
            if sh is not None:
                self._dense = self._dev_call(
                    sh.hll_max_rows, self._dense,
                    sh.replicate(sh.phys_rows(slots, self._dense.shape[0])),
                    sh.replicate(stacked))
            else:
                self._dense = self._dev_call(
                    lambda d, s, v: d.at[s].max(v), self._dense,
                    jnp.asarray(slots), jnp.asarray(stacked),
                    retryable=True)
        except DeviceFaultError:
            self.to_host()
            np.maximum.at(self._dense, slots.astype(np.int64), stacked)

    # -- internals ----------------------------------------------------------

    def _dense_insert(self, slots: np.ndarray, idx: np.ndarray,
                      rank: np.ndarray) -> None:
        assert self._dense is not None
        if self._host:
            self._dense = he.np_hll_insert_batch(
                self._dense, slots.astype(np.int64), idx.astype(np.int64),
                rank.astype(np.int8))
            return
        sh = self._shard
        try:
            if sh is not None:
                self._dense = self._dev_call(
                    sh.hll_insert, self._dense,
                    sh.replicate(sh.phys_rows(slots.astype(np.int32),
                                              self._dense.shape[0])),
                    sh.replicate(idx.astype(np.int32)),
                    sh.replicate(rank.astype(np.int8)))
            else:
                self._dense = self._dev_call(
                    hll_ops.insert_batch,
                    self._dense, jnp.asarray(slots.astype(np.int32)),
                    jnp.asarray(idx.astype(np.int32)),
                    jnp.asarray(rank.astype(np.int8)), retryable=True)
        except DeviceFaultError:
            self.to_host()
            self._dense = he.np_hll_insert_batch(
                self._dense, slots.astype(np.int64), idx.astype(np.int64),
                rank.astype(np.int8))

    def _compact(self) -> None:
        self._compact_no_promote()
        self._maybe_promote()

    def _maybe_promote(self) -> None:
        rows = self._ckeys // self.m
        # distinct-register count per row (keys are sorted ⇒ rows grouped)
        urows, counts = np.unique(rows, return_counts=True)
        for r in urows[counts >= self.promote_entries]:
            self._promote(int(r))

    def _promote(self, row: int) -> int:
        """Move one row's sparse entries into a dense device row."""
        if row in self._slot_of_row:
            return self._slot_of_row[row]
        self._compact_pending_row(row)
        slot = len(self._slot_of_row)
        self._slot_of_row[row] = slot
        if row >= self._slot_lut.size:
            grown = np.full(max(self._slot_lut.size * 2, row + 1), -1,
                            np.int32)
            grown[:self._slot_lut.size] = self._slot_lut
            self._slot_lut = grown
        self._slot_lut[row] = slot
        if self._dense is None or slot >= self._dense.shape[0]:
            grown = max(16, (slot + 1) * 2)
            sh = self._shard
            if self._host:
                fresh = np.zeros((grown, self.m), np.int8)
                if self._dense is not None:
                    fresh[:self._dense.shape[0]] = self._dense
                self._dense = fresh
            elif sh is not None:
                # pow2 multiple of the shard count so the slot-axis
                # interleave stays divisible; per-shard local pad keeps
                # every promoted slot on its shard across growth
                g = sh.shards
                while g < grown:
                    g *= 2
                grown = g
                try:
                    if self._dense is None:
                        self._dense = self._dev_call(
                            sh.place, jnp.zeros((grown, self.m), jnp.int8))
                    else:
                        self._dense = self._dev_call(
                            sh.grow_2d, self._dense, grown)
                except DeviceFaultError:
                    self.to_host()
                    fresh = np.zeros((grown, self.m), np.int8)
                    if self._dense is not None:
                        fresh[:self._dense.shape[0]] = self._dense
                    self._dense = fresh
            else:
                try:
                    def _grow(old, n):
                        fresh = jnp.zeros((n, self.m), jnp.int8)
                        return (fresh if old is None
                                else fresh.at[:old.shape[0]].set(old))

                    self._dense = self._dev_call(
                        _grow, self._dense, grown, retryable=True)
                except DeviceFaultError:
                    self.to_host()
                    fresh = np.zeros((grown, self.m), np.int8)
                    if self._dense is not None:
                        fresh[:self._dense.shape[0]] = self._dense
                    self._dense = fresh
        mask = (self._ckeys // self.m) == row
        if mask.any():
            idx = (self._ckeys[mask] % self.m).astype(np.int32)
            rank = self._crank[mask]
            self._dense_insert(np.full(idx.shape, slot, np.int32), idx, rank)
            keep = ~mask
            self._ckeys, self._crank = self._ckeys[keep], self._crank[keep]
        return slot

    def _compact_pending_row(self, row: int) -> None:
        # promotion needs the row's full sparse content; cheapest correct
        # move is a full compaction (amortized by compact_every)
        if self._p_keys:
            self._compact_no_promote()

    def _compact_no_promote(self) -> None:
        if not self._p_keys:
            return
        keys = np.concatenate([self._ckeys] + self._p_keys)
        rank = np.concatenate([self._crank] + self._p_rank)
        self._p_keys, self._p_rank, self._pend = [], [], 0
        if keys.size == 0:
            self._ckeys, self._crank = keys, rank
            return
        order = np.lexsort((rank, keys))
        keys, rank = keys[order], rank[order]
        # last element of each equal-key run holds the max rank
        is_end = np.r_[keys[1:] != keys[:-1], True]
        self._ckeys, self._crank = keys[is_end], rank[is_end]

    # -- flush --------------------------------------------------------------

    def estimates(self, num_rows: int) -> np.ndarray:
        """Cardinality estimate per directory set row [num_rows] (f32).

        Sparse rows evaluate the same estimator as the device kernel
        (harmonic mean + linear counting) over their distinct registers;
        dense rows read the device result.
        """
        self._apply_imports()
        self._compact_no_promote()
        m = float(self.m)
        alpha = 0.7213 / (1.0 + 1.079 / m)
        out = np.zeros(num_rows, np.float32)
        rows = self._ckeys // self.m
        inv = np.power(2.0, -self._crank.astype(np.float64))
        # segmented sums per row over the sorted keys
        urows, starts = np.unique(rows, return_index=True)
        ends = np.r_[starts[1:], rows.size]
        csum = np.r_[0.0, np.cumsum(inv)]
        for r, a, b in zip(urows, starts, ends):
            if r >= num_rows:
                continue
            d = b - a  # distinct registers
            zeros = m - d
            inv_sum = zeros + (csum[b] - csum[a])
            raw = alpha * m * m / inv_sum
            if raw <= 2.5 * m and zeros > 0:
                out[r] = m * np.log(m / zeros)
            else:
                out[r] = raw
        if self._slot_of_row and self._dense is not None:
            dense_est = None
            if not self._host:
                try:
                    if self._shard is not None:
                        sh = self._shard
                        dense_est = np.asarray(self._dev_call(
                            sh.hll_estimate, self._dense, self.precision,
                            retryable=True
                        ))[sh.perm_l2p(self._dense.shape[0])]
                    else:
                        dense_est = np.asarray(self._dev_call(
                            hll_ops.estimate, self._dense, self.precision,
                            retryable=True))
                except DeviceFaultError:
                    self.to_host()
            if dense_est is None:
                # host mode: the bitwise f32 twin of the device
                # estimator (ops/host_engine parity contract)
                dense_est = he.np_hll_estimate_exact(
                    self._dense, self.precision)
            for r, s in self._slot_of_row.items():
                if r < num_rows:
                    out[r] = dense_est[s]
        return out

    def registers(self, num_rows: int) -> np.ndarray:
        """Materialize dense int8 register rows [num_rows, m] (the
        forwarding codec's wire form). Transient — only built at flush
        for rows that actually forward."""
        self._apply_imports()
        self._compact_no_promote()
        out = np.zeros((num_rows, self.m), np.int8)
        rows = (self._ckeys // self.m).astype(np.int64)
        idx = (self._ckeys % self.m).astype(np.int64)
        mask = rows < num_rows
        out[rows[mask], idx[mask]] = self._crank[mask]
        if self._slot_of_row and self._dense is not None:
            if self._host:
                dense_np = self._dense
            else:
                dense_np = np.asarray(self._dense)
                if self._shard is not None:
                    dense_np = dense_np[
                        self._shard.perm_l2p(self._dense.shape[0])]
            for r, s in self._slot_of_row.items():
                if r < num_rows:
                    out[r] = dense_np[s]
        return out

    @property
    def sparse_entries(self) -> int:
        return int(self._ckeys.size) + self._pend

    @property
    def dense_rows(self) -> int:
        return len(self._slot_of_row)
