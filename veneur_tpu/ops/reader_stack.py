"""Reader-shard plane stacking: fold N per-reader staging planes into
ONE flat batch in canonical row space.

Shared-nothing ingest (core/worker.attach_reader_shards) gives every
reader its own C++ context — private directory, staging plane, SoA
spill epoch — so the commit hot path takes no shared lock. The price is
paid here, once per flush: each context's detached [rows, B] plane
carries CONTEXT-LOCAL rows, and the flush needs one batch in the
worker's canonical row space.

The merge is a host-side stacked concatenation, NOT a new device
kernel: per context the filled slots compact to a 1-D row-major flat
array (exactly what the legacy single-context fold uploads, see
DeviceWorker._fold_one_plane), local rows translate through the
reconciliation map built at series sync, and a stable sort by canonical
row groups every series' samples in context order. The result — flat
values (+ weights) and per-row counts — feeds the EXACT legacy device
program (_expand_flat_planes → _histo_fold_staged), which is what makes
reader-sharded == legacy bit-identical: same slot order, same values,
same fold.

Rows whose stacked total exceeds the staging depth B keep their first B
samples in the plane (the same membership the legacy path produces:
each context's plane caps at B and per-context overflow rode that
context's SoA spill) and route the excess to the spill fold, so
conservation stays exact — committed == folded + shed.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def merge_reader_planes(planes: list, s_eff: int):
    """Merge per-context detached staging planes into one canonical
    flat batch.

    planes: [(stage, rowmap), ...] in context order, where stage is the
    NativeIngest.detach_stage tuple (vals[rows, B], wts[rows, B],
    counts[rows], unit, free) — vals/wts/counts may alias C++ memory;
    this function copies out of them and does NOT free (the caller owns
    the release hooks) — and rowmap is an int32 array mapping context-
    local histo row → canonical directory row.

    Returns (flat_v, flat_w_or_None, counts, spill, per_ctx_samples):
      flat_v    f32 [total_kept] — kept samples, canonical-row-major,
                context order within each row
      flat_w    f32 [total_kept] or None when every weight is 1.0
      counts    i32 [s_eff] — kept samples per canonical row (≤ B)
      spill     (rows, vals, wts) SoA of the over-depth excess, or None
      per_ctx_samples  [int] — staged samples contributed per context
                (transfer-ledger attribution)
    Returns (None, None, None, None, per_ctx) when nothing is staged.
    """
    unit_all = all(st[3] for st, _m in planes)
    crows_parts = []
    vals_parts = []
    wts_parts = []
    per_ctx = []
    depth = 0
    for st, rowmap in planes:
        sv, sw, counts, unit, _free = st
        B = sv.shape[1]
        depth = max(depth, B)
        rows_avail = min(sv.shape[0], len(rowmap))
        counts_k = np.minimum(counts[:rows_avail], B).astype(np.int64)
        n_k = int(counts_k.sum())
        per_ctx.append(n_k)
        if not n_k:
            continue
        mask = (np.arange(B, dtype=np.int64)[None, :]
                < counts_k[:, None])
        vals_parts.append(sv[:rows_avail][mask])  # copies out of C++
        if unit_all:
            pass  # weights rebuilt on device from counts
        elif unit:
            wts_parts.append(np.ones(n_k, np.float32))
        else:
            wts_parts.append(sw[:rows_avail][mask])
        crows_parts.append(
            np.repeat(np.asarray(rowmap[:rows_avail], np.int64), counts_k))
    if not vals_parts:
        return None, None, None, None, per_ctx

    crows = np.concatenate(crows_parts)
    flat_v = np.concatenate(vals_parts)
    flat_w = None if unit_all else np.concatenate(wts_parts)
    # stable sort: per canonical row, samples stay in context-concat
    # order — the serialized-reader-order ground truth the parity tests
    # pin against
    order = np.argsort(crows, kind="stable")
    srows = crows[order]
    flat_v = flat_v[order]
    if flat_w is not None:
        flat_w = flat_w[order]
    totals = np.bincount(srows, minlength=s_eff)
    offs = np.cumsum(totals) - totals
    within = np.arange(len(srows), dtype=np.int64) - offs[srows]
    keep = within < depth
    counts_out = np.minimum(totals[:s_eff], depth).astype(np.int32)
    spill = None
    if not keep.all():
        ex = ~keep
        sp_v = flat_v[ex]
        sp_w = (np.ones(len(sp_v), np.float32) if flat_w is None
                else flat_w[ex])
        spill = (srows[ex].astype(np.int32), sp_v, sp_w)
        flat_v = flat_v[keep]
        if flat_w is not None:
            flat_w = flat_w[keep]
    return flat_v, flat_w, counts_out, spill, per_ctx
