"""Order-pinned exact numerics: paired device (jnp) / host (np) kernels.

The device fault domain (ops/device_guard.py) fails a quarantined worker
over to a host NumPy sketch engine (ops/host_engine.py) whose flushes
must stay BYTE-identical to the device path — a degraded interval that
silently shifts every quantile would defeat the whole point of an
escape hatch. f32 arithmetic only delivers that when both sides execute
the *same sequence of IEEE-754 operations*, and three things normally
break it:

1. **Reductions/scans reassociate.** `jnp.sum`/`jnp.cumsum` lower to
   whatever tree XLA picks; NumPy runs strict left folds (with its own
   pairwise blocking). Fix: express every float reduction as an explicit
   Hillis-Steele scan (`cumsum`) or pairwise halving tree (`tsum`) whose
   loop structure is identical in both twins — then both sides perform
   literally the same adds in the same order.
2. **FMA contraction.** XLA/LLVM fuse `a*b + c` into one fused
   multiply-add; NumPy rounds the product first. `lax.optimization_barrier`
   does NOT stop it (verified: the barrier is stripped before fusion).
   Fix: `block(x) = where(x == x, x, 0)` — a NaN-semantics select the
   compiler cannot constant-fold or look through, so the product is
   rounded to f32 before it meets the add. The NumPy twin applies the
   same select (an identity for non-NaN values).
3. **Transcendentals differ per libm.** `arcsin`, `log`, `exp2` have no
   cross-implementation bit contract. Fix: precompute them on the host
   in f64, round once to f32, and ship the results as *tables* both
   sides read with exact integer gathers / comparison-exact
   searchsorted (`kscale_boundaries` for the t-digest k-function,
   `EXP2_NEG_TABLE` / `hll_linear_table` for the HLL estimator).

Division, sqrt, min/max, comparisons, sorts (`lax.sort` is stable, like
`np.argsort(kind="stable")`), searchsorted, selects, and single add/sub
ops are IEEE-correctly-rounded on both sides and need no treatment.

A welcome side effect: with the transcendentals gone and every
reduction order pinned, the *device* path itself becomes reproducible
across backends (TPU f32 mul/add/div are IEEE) instead of merely within
one compiled executable.
"""

from __future__ import annotations

import functools
import math

import jax.numpy as jnp
import numpy as np


def next_pow2(n: int, floor: int = 1) -> int:
    v = max(int(n), floor)
    return 1 << (v - 1).bit_length()


# ---------------------------------------------------------------------------
# FMA contraction blocker


def block(x):
    """Round a product to f32 before it can contract into an add.

    `where(x == x, x, 0)` is an identity for every non-NaN value, but its
    NaN semantics stop XLA from folding it away — the multiply's result
    must materialize, so `block(a*b) + c` performs a rounded multiply
    then a rounded add on both device and host."""
    return jnp.where(x == x, x, jnp.zeros_like(x))


def np_block(x):
    x = np.asarray(x)
    return np.where(x == x, x, np.zeros_like(x))


# ---------------------------------------------------------------------------
# Order-pinned scans and reductions (last axis)


def cumsum(x):
    """Inclusive prefix sum along the last axis as a Hillis-Steele
    doubling scan: log2(n) vectorized adds in a fixed order. The np twin
    runs the identical loop, so results are bitwise equal."""
    n = x.shape[-1]
    pad = [(0, 0)] * (x.ndim - 1)
    shift = 1
    while shift < n:
        x = x + jnp.pad(x, pad + [(shift, 0)])[..., :n]
        shift *= 2
    return x


def np_cumsum(x):
    x = np.asarray(x)
    n = x.shape[-1]
    pad = [(0, 0)] * (x.ndim - 1)
    shift = 1
    while shift < n:
        x = x + np.pad(x, pad + [(shift, 0)])[..., :n]
        shift *= 2
    return x


def tsum(x):
    """Sum along the last axis as a pairwise halving tree (zero-padded
    to a power of two): the one fixed association both twins share."""
    n = x.shape[-1]
    p = next_pow2(n)
    if p != n:
        pad = [(0, 0)] * (x.ndim - 1) + [(0, p - n)]
        x = jnp.pad(x, pad)
    while p > 1:
        x = x[..., 0::2] + x[..., 1::2]
        p //= 2
    return x[..., 0]


def np_tsum(x):
    x = np.asarray(x)
    n = x.shape[-1]
    p = next_pow2(n)
    if p != n:
        pad = [(0, 0)] * (x.ndim - 1) + [(0, p - n)]
        x = np.pad(x, pad)
    while p > 1:
        x = x[..., 0::2] + x[..., 1::2]
        p //= 2
    return x[..., 0]


def tsum0(x):
    """Tree sum over axis 0 (stacked-pool merges)."""
    return tsum(jnp.moveaxis(x, 0, -1))


def np_tsum0(x):
    return np_tsum(np.moveaxis(np.asarray(x), 0, -1))


# ---------------------------------------------------------------------------
# t-digest k-function bucketing, table form
#
# The scale function k(q) = δ·(asin(2q−1)/π + ½) is only ever used as
# floor(k(q)) — a bucket id. Inverting it once on the host turns the
# device-side arcsin into a searchsorted against the δ bucket
# boundaries q_j = (sin(π(j/δ − ½)) + 1)/2, j = 1..⌊δ⌋: bucket(q) is
# the number of boundaries ≤ q, i.e. searchsorted(side="right").
# Comparisons are exact, so both twins agree bitwise — and the device
# trades a transcendental for a log2(δ)-step binary search.


@functools.lru_cache(maxsize=None)
def kscale_boundaries(compression: float) -> np.ndarray:
    """f32[⌊δ⌋] ascending bucket boundaries for floor(k1_δ(q)),
    computed in f64 and rounded once."""
    delta = float(compression)
    j = np.arange(1, int(math.floor(delta)) + 1, dtype=np.float64)
    q = (np.sin(np.pi * (j / delta - 0.5)) + 1.0) / 2.0
    return np.clip(q, 0.0, 1.0).astype(np.float32)


def kscale_bucket(q, compression: float):
    """floor(k1_δ(q)) for f32 q in [0, 1], table form (device)."""
    btab = jnp.asarray(kscale_boundaries(compression))
    return jnp.searchsorted(btab, q, side="right").astype(jnp.int32)


def np_kscale_bucket(q, compression: float):
    btab = kscale_boundaries(compression)
    return np.searchsorted(
        btab, np.asarray(q, np.float32), side="right").astype(np.int32)


# ---------------------------------------------------------------------------
# HLL estimator tables
#
# exp2(-rank) over int8 ranks 0..64 is a 65-entry gather; the linear-
# counting branch m·ln(m/z) is a (m+1)-entry gather by the integer
# zero-register count. Both tables are f64-computed, f32-rounded once.

_EXP2_NEG_TABLE = np.exp2(-np.arange(65, dtype=np.float64)).astype(np.float32)


def exp2_neg_table() -> np.ndarray:
    """f32[65]: exp2(-r) for register ranks r = 0..64."""
    return _EXP2_NEG_TABLE


@functools.lru_cache(maxsize=None)
def hll_linear_table(precision: int) -> np.ndarray:
    """f32[m+1]: m·ln(m / max(z, 1)) by zero-register count z."""
    m = float(1 << precision)
    z = np.maximum(np.arange((1 << precision) + 1, dtype=np.float64), 1.0)
    return (m * np.log(m / z)).astype(np.float32)


@functools.lru_cache(maxsize=None)
def hll_alpha_m2(precision: int) -> np.float32:
    """f32: α_m · m² for the harmonic-mean estimator, rounded once."""
    m = float(1 << precision)
    alpha = 0.7213 / (1.0 + 1.079 / m)
    return np.float32(alpha * m * m)
