"""Scatter-free segmented-scan primitives for TPU.

XLA lowers `segment_sum`/`segment_min` on TPU to scatters and large
`searchsorted` calls to gather-chain binary searches; both run far below
VPU peak (measured ~9ns/element on v5e). These primitives keep segmented
reductions in cumsum/select territory instead:

* `segmented_cumsum` — chunked Hillis-Steele scan with an affine
  cross-chunk carry stitch; no scatter, no per-segment loop.
* `last_marked_carry` — exclusive "value at the last marked position"
  scan, the building block that turns per-run sums into differences of
  prefix sums at run boundaries (ops/tdigest.py uses it for t-digest
  bucket accumulation).

Used by the t-digest batch ingest (ops/tdigest.py); the reference's
equivalent inner loop is the per-centroid Go walk in
tdigest/merging_digest.go:140-224, which has no batched analog.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

CHUNK = 128  # one TPU lane tile


def _pad_to_chunks(x: jax.Array, fill) -> jax.Array:
    n = x.shape[0]
    pad = (-n) % CHUNK
    if pad:
        x = jnp.concatenate(
            [x, jnp.full((pad,), fill, dtype=x.dtype)])
    return x.reshape(-1, CHUNK)


def _affine_carry(a: jax.Array, *bs: jax.Array) -> tuple[jax.Array, ...]:
    """Solve open[g] = a[g]*open[g-1] + b[g] for each payload b via an
    associative scan of affine maps; returns each open[] (inclusive)."""

    def combine(x, y):
        ax, *bx = x
        ay, *by = y
        return (ax * ay, *[bxi * ay + byi for bxi, byi in zip(bx, by)])

    out = jax.lax.associative_scan(combine, (a, *bs))
    return out[1:]


def _shift_right(x: jax.Array, fill) -> jax.Array:
    return jnp.concatenate(
        [jnp.full((1,), fill, dtype=x.dtype), x[:-1]])


def segmented_cumsum(values: jax.Array, starts: jax.Array) -> jax.Array:
    """Inclusive cumulative sum of `values` that restarts wherever
    `starts` is True (position 0 is implicitly a start).

    values: f32[N]; starts: bool[N]. Returns f32[N].
    """
    n = values.shape[0]
    v2 = _pad_to_chunks(values, 0.0)
    s2 = _pad_to_chunks(starts, False)
    s2 = s2.at[0, 0].set(True)
    g, l = v2.shape

    # Per-chunk segmented Hillis-Steele scan (col 0 treated as a reset;
    # the true cross-chunk carry is stitched below).
    v = v2
    f = s2.at[:, 0].set(True)
    shift = 1
    while shift < l:
        vs = jnp.pad(v, ((0, 0), (shift, 0)))[:, :l]
        fs = jnp.pad(f, ((0, 0), (shift, 0)), constant_values=True)[:, :l]
        v = jnp.where(f, v, v + vs)
        f = f | fs
        shift *= 2

    # Cross-chunk carry: open[g] = a*open[g-1] + last, a = "no real start
    # in chunk" (the chunk's whole run continues through it).
    no_start = ~jnp.any(s2, axis=1)
    (open_w,) = _affine_carry(
        no_start.astype(values.dtype), v[:, -1])
    carry_in = _shift_right(open_w, jnp.zeros((), values.dtype))
    # carry applies to the head run only: elements before the first real
    # start of the chunk.
    before_first = jnp.cumsum(s2.astype(jnp.int32), axis=1) == 0
    out = v + carry_in[:, None] * before_first.astype(values.dtype)
    return out.reshape(-1)[:n]


def last_marked_carry(mask: jax.Array, *values: jax.Array
                      ) -> tuple[jax.Array, ...]:
    """Along the last axis, carry each payload forward from the most
    recent *strictly earlier* position where ``mask`` is True (exclusive
    scan; positions before any mark carry 0).

    mask: bool[..., L]; values: f32[..., L] each. Returns one array per
    payload. log2(L) elementwise select steps — no gathers, no scatters.
    (Hand-rolled Hillis-Steele jumps rather than lax.associative_scan:
    the scan's recursive slicing stalls the TPU compiler when fused into
    a larger program — observed >30min on v5e for _compress_rows — while
    this loop, the same shape as segmented_cumsum's, compiles in
    seconds.)
    """
    pad = [(0, 0)] * (mask.ndim - 1) + [(1, 0)]
    m = jnp.pad(mask, pad)[..., :-1]
    vs = [jnp.pad(v, pad)[..., :-1] for v in values]
    n = m.shape[-1]

    def shift_right(x, k, fill=False):
        p = [(0, 0)] * (x.ndim - 1) + [(k, 0)]
        return jnp.pad(x, p, constant_values=fill)[..., :n]

    shift = 1
    while shift < n:
        # invariant: (m, vs) at i reflect the last mark in (i-2^k, i]
        m_s = shift_right(m, shift)
        vs = [jnp.where(m, v, shift_right(v, shift, 0))
              for v in vs]
        m = m | m_s
        shift *= 2
    return tuple(vs)
