"""Scatter-free sorted-segment primitives for TPU.

XLA lowers `segment_sum`/`segment_min` on TPU to scatters and large
`searchsorted` calls to gather-chain binary searches; both run far below VPU
peak (measured ~9ns/element on v5e — the dominant cost of sketch ingest).
The primitives here reformulate sorted-segment reductions as:

    reshape to [G, L=128] chunks → per-chunk run ranks (cumsum of boundary
    flags) → per-run partial sums as a fused compare+select+reduce over
    [G, L, L] (streams through the VPU; XLA fuses without materializing)
    → cross-chunk run stitching with tiny affine scans over [G]
    → results addressed by *global run index*, resolved by gathers.

Everything is gathers, cumsums and elementwise ops — no scatter anywhere.
Used by the t-digest batch ingest (ops/tdigest.py); the reference's
equivalent inner loop is the per-centroid Go walk in
tdigest/merging_digest.go:140-224, which has no batched analog.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

CHUNK = 128  # one TPU lane tile


def _pad_to_chunks(x: jax.Array, fill) -> jax.Array:
    n = x.shape[0]
    pad = (-n) % CHUNK
    if pad:
        x = jnp.concatenate(
            [x, jnp.full((pad,), fill, dtype=x.dtype)])
    return x.reshape(-1, CHUNK)


def _affine_carry(a: jax.Array, *bs: jax.Array) -> tuple[jax.Array, ...]:
    """Solve open[g] = a[g]*open[g-1] + b[g] for each payload b via an
    associative scan of affine maps; returns each open[] (inclusive)."""

    def combine(x, y):
        ax, *bx = x
        ay, *by = y
        return (ax * ay, *[bxi * ay + byi for bxi, byi in zip(bx, by)])

    out = jax.lax.associative_scan(combine, (a, *bs))
    return out[1:]


def _shift_right(x: jax.Array, fill) -> jax.Array:
    return jnp.concatenate(
        [jnp.full((1,), fill, dtype=x.dtype), x[:-1]])


def segmented_cumsum(values: jax.Array, starts: jax.Array) -> jax.Array:
    """Inclusive cumulative sum of `values` that restarts wherever
    `starts` is True (position 0 is implicitly a start).

    values: f32[N]; starts: bool[N]. Returns f32[N].
    """
    n = values.shape[0]
    v2 = _pad_to_chunks(values, 0.0)
    s2 = _pad_to_chunks(starts, False)
    s2 = s2.at[0, 0].set(True)
    g, l = v2.shape

    # Per-chunk segmented Hillis-Steele scan (col 0 treated as a reset;
    # the true cross-chunk carry is stitched below).
    v = v2
    f = s2.at[:, 0].set(True)
    shift = 1
    while shift < l:
        vs = jnp.pad(v, ((0, 0), (shift, 0)))[:, :l]
        fs = jnp.pad(f, ((0, 0), (shift, 0)), constant_values=True)[:, :l]
        v = jnp.where(f, v, v + vs)
        f = f | fs
        shift *= 2

    # Cross-chunk carry: open[g] = a*open[g-1] + last, a = "no real start
    # in chunk" (the chunk's whole run continues through it).
    no_start = ~jnp.any(s2, axis=1)
    (open_w,) = _affine_carry(
        no_start.astype(values.dtype), v[:, -1])
    carry_in = _shift_right(open_w, jnp.zeros((), values.dtype))
    # carry applies to the head run only: elements before the first real
    # start of the chunk.
    before_first = jnp.cumsum(s2.astype(jnp.int32), axis=1) == 0
    out = v + carry_in[:, None] * before_first.astype(values.dtype)
    return out.reshape(-1)[:n]


def last_marked_carry(mask: jax.Array, *values: jax.Array
                      ) -> tuple[jax.Array, ...]:
    """Along the last axis, carry each payload forward from the most
    recent *strictly earlier* position where ``mask`` is True (exclusive
    scan; positions before any mark carry 0).

    mask: bool[..., L]; values: f32[..., L] each. Returns one array per
    payload. log2(L) elementwise select steps — no gathers, no scatters.
    (Hand-rolled Hillis-Steele jumps rather than lax.associative_scan:
    the scan's recursive slicing stalls the TPU compiler when fused into
    a larger program — observed >30min on v5e for _compress_rows — while
    this loop, the same shape as segmented_cumsum's, compiles in
    seconds.)
    """
    pad = [(0, 0)] * (mask.ndim - 1) + [(1, 0)]
    m = jnp.pad(mask, pad)[..., :-1]
    vs = [jnp.pad(v, pad)[..., :-1] for v in values]
    n = m.shape[-1]

    def shift_right(x, k, fill=False):
        p = [(0, 0)] * (x.ndim - 1) + [(k, 0)]
        return jnp.pad(x, p, constant_values=fill)[..., :n]

    shift = 1
    while shift < n:
        # invariant: (m, vs) at i reflect the last mark in (i-2^k, i]
        m_s = shift_right(m, shift)
        vs = [jnp.where(m, v, shift_right(v, shift, 0))
              for v in vs]
        m = m | m_s
        shift *= 2
    return tuple(vs)


class RunSums(NamedTuple):
    """Per-run sums of a sorted id array, addressed by global run index.

    val_w/val_v: f32[G*L] — finalized payload sums laid out per
        (chunk, local-run) slot; slots not resolved by `gather_runs`
        addressing contain partial garbage.
    offset: i32[G] — global run index of each chunk's local run 0.
    grank:  i32[N] — global run index of each element.
    num_runs: i32[] — total number of distinct runs.
    """

    val_w: jax.Array
    val_v: jax.Array
    offset: jax.Array
    grank: jax.Array
    num_runs: jax.Array


def sorted_run_sums(seg_id: jax.Array, w: jax.Array,
                    v: jax.Array) -> RunSums:
    """Sum `w` and `v` over each run of equal ids in the sorted i32[N]
    `seg_id`. Scatter-free; see module docstring for the scheme."""
    n = seg_id.shape[0]
    ids2 = _pad_to_chunks(seg_id, -1)
    # pad joins the final run (id -1 can't equal a real id? it can't — pad
    # uses the last real id instead so it merges with zero contribution).
    if ids2.size != n:
        last = seg_id[-1]
        flat = ids2.reshape(-1)
        flat = jnp.where(jnp.arange(flat.shape[0]) < n, flat, last)
        ids2 = flat.reshape(ids2.shape)
    w2 = _pad_to_chunks(w, 0.0)
    v2 = _pad_to_chunks(v, 0.0)
    g, l = ids2.shape

    prev = jnp.pad(ids2.reshape(-1), (1, 0))[:-1].reshape(g, l)
    starts = ids2 != prev  # [G, L]; element (0,0) False — forced below
    starts_forced = starts.at[:, 0].set(True)

    r_local = jnp.cumsum(starts_forced.astype(jnp.int32), axis=1) - 1
    n_runs = r_local[:, -1] + 1  # [G]
    # head of chunk g continues the tail run of g-1
    continues = jnp.concatenate(
        [jnp.zeros((1,), bool),
         ids2[1:, 0] == ids2[:-1, -1]])

    # per-(chunk, local run) partial sums: fused masked broadcast-reduce
    rbins = jnp.arange(l, dtype=jnp.int32)
    eq = r_local[:, :, None] == rbins[None, None, :]  # [G, L, L]
    pw = jnp.sum(jnp.where(eq, w2[:, :, None], 0.0), axis=1)  # [G, L]
    pv = jnp.sum(jnp.where(eq, v2[:, :, None], 0.0), axis=1)

    # stitch runs spanning chunk boundaries: open[g] is the accumulated
    # tail-run value at the end of chunk g.
    tail_idx = n_runs - 1
    tw = jnp.take_along_axis(pw, tail_idx[:, None], axis=1)[:, 0]
    tv = jnp.take_along_axis(pv, tail_idx[:, None], axis=1)[:, 0]
    a = (continues & (n_runs == 1)).astype(w.dtype)
    open_w, open_v = _affine_carry(a, tw, tv)
    carry_w = jnp.where(continues, _shift_right(open_w, 0.0), 0.0)
    carry_v = jnp.where(continues, _shift_right(open_v, 0.0), 0.0)
    pw = pw.at[:, 0].add(carry_w)
    pv = pv.at[:, 0].add(carry_v)

    # global run index of each chunk's local run 0: runs before it minus
    # boundary merges
    cont_i = continues.astype(jnp.int32)
    offset = (jnp.cumsum(n_runs) - n_runs
              - jnp.cumsum(cont_i)).astype(jnp.int32)
    total = (jnp.sum(n_runs) - jnp.sum(cont_i)).astype(jnp.int32)

    grank2 = offset[:, None] + r_local
    return RunSums(
        val_w=pw.reshape(-1),
        val_v=pv.reshape(-1),
        offset=offset,
        grank=grank2.reshape(-1)[:n],
        num_runs=total,
    )


def gather_runs(rs: RunSums, m: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Fetch the finalized (w, v) sums of global run indices `m` (i32[...]).
    Out-of-range m returns arbitrary values — mask at the call site.

    For a run spanning several chunks the finalized value lives in the
    *last* chunk of the span (earlier partials were folded forward), which
    is exactly the last chunk whose offset ≤ m.
    """
    l = CHUNK
    g = jnp.searchsorted(rs.offset, m, side="right").astype(jnp.int32) - 1
    g = jnp.maximum(g, 0)
    slot = g * l + (m - jnp.take(rs.offset, g))
    slot = jnp.clip(slot, 0, rs.val_w.shape[0] - 1)
    return jnp.take(rs.val_w, slot), jnp.take(rs.val_v, slot)
