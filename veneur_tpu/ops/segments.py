"""Scatter-free segmented-scan primitives for TPU.

XLA lowers `segment_sum`/`segment_min` on TPU to scatters and large
`searchsorted` calls to gather-chain binary searches; both run far below
VPU peak (measured ~9ns/element on v5e). These primitives keep segmented
reductions in cumsum/select territory instead:

* `segmented_cumsum` — chunked Hillis-Steele scan with a segmented
  cross-chunk carry stitch; no scatter, no per-segment loop.
* `last_marked_carry` — exclusive "value at the last marked position"
  scan, the building block that turns per-run sums into differences of
  prefix sums at run boundaries (ops/tdigest.py uses it for t-digest
  bucket accumulation).

Every float add here happens in a fixed, explicitly-coded order (the
doubling-shift loops), and each primitive has a NumPy twin running the
IDENTICAL loop — the bit-parity contract the host fallback engine
(ops/host_engine.py) is built on; see ops/exactnum.py for why. The
earlier cross-chunk stitch used `lax.associative_scan` over affine
maps, whose recursive association XLA owns and NumPy cannot mirror; the
carry is itself just a segmented scan over chunk totals, so it now runs
the same Hillis loop at the chunk level.

Used by the t-digest batch ingest (ops/tdigest.py); the reference's
equivalent inner loop is the per-centroid Go walk in
tdigest/merging_digest.go:140-224, which has no batched analog.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

CHUNK = 128  # one TPU lane tile


def _pad_to_chunks(x: jax.Array, fill) -> jax.Array:
    n = x.shape[0]
    pad = (-n) % CHUNK
    if pad:
        x = jnp.concatenate(
            [x, jnp.full((pad,), fill, dtype=x.dtype)])
    return x.reshape(-1, CHUNK)


def _np_pad_to_chunks(x: np.ndarray, fill) -> np.ndarray:
    n = x.shape[0]
    pad = (-n) % CHUNK
    if pad:
        x = np.concatenate(
            [x, np.full((pad,), fill, dtype=x.dtype)])
    return x.reshape(-1, CHUNK)


def _shift_right(x: jax.Array, fill) -> jax.Array:
    return jnp.concatenate(
        [jnp.full((1,), fill, dtype=x.dtype), x[:-1]])


def segmented_cumsum(values: jax.Array, starts: jax.Array) -> jax.Array:
    """Inclusive cumulative sum of `values` that restarts wherever
    `starts` is True (position 0 is implicitly a start).

    values: f32[N]; starts: bool[N]. Returns f32[N].
    """
    n = values.shape[0]
    v2 = _pad_to_chunks(values, 0.0)
    s2 = _pad_to_chunks(starts, False)
    s2 = s2.at[0, 0].set(True)
    g, l = v2.shape

    # Per-chunk segmented Hillis-Steele scan (col 0 treated as a reset;
    # the true cross-chunk carry is stitched below).
    v = v2
    f = s2.at[:, 0].set(True)
    shift = 1
    while shift < l:
        vs = jnp.pad(v, ((0, 0), (shift, 0)))[:, :l]
        fs = jnp.pad(f, ((0, 0), (shift, 0)), constant_values=True)[:, :l]
        v = jnp.where(f, v, v + vs)
        f = f | fs
        shift *= 2

    # Cross-chunk carry: the open-run total entering chunk g is itself a
    # segmented inclusive cumsum of the chunks' last-column values,
    # restarting at any chunk that contains a real start — the SAME
    # Hillis loop as above, run once at the chunk level.
    has_start = jnp.any(s2, axis=1)
    cv = v[:, -1]
    cf = has_start.at[0].set(True)
    shift = 1
    while shift < g:
        cvs = jnp.pad(cv, (shift, 0))[:g]
        cfs = jnp.pad(cf, (shift, 0), constant_values=True)[:g]
        cv = jnp.where(cf, cv, cv + cvs)
        cf = cf | cfs
        shift *= 2
    carry_in = _shift_right(cv, jnp.zeros((), values.dtype))
    # carry applies to the head run only: elements before the first real
    # start of the chunk. (Select, not multiply-by-mask: the add order
    # stays pinned and nothing invites contraction.)
    before_first = jnp.cumsum(s2.astype(jnp.int32), axis=1) == 0
    out = jnp.where(before_first, v + carry_in[:, None], v)
    return out.reshape(-1)[:n]


def np_segmented_cumsum(values: np.ndarray, starts: np.ndarray) -> np.ndarray:
    """NumPy twin of `segmented_cumsum`: the identical shift loops, so
    the result is bitwise equal to the device kernel's."""
    values = np.asarray(values)
    n = values.shape[0]
    v = _np_pad_to_chunks(values, values.dtype.type(0))
    s2 = _np_pad_to_chunks(np.asarray(starts, bool), False).copy()
    s2[0, 0] = True
    g, l = v.shape

    f = s2.copy()
    f[:, 0] = True
    shift = 1
    while shift < l:
        vs = np.pad(v, ((0, 0), (shift, 0)))[:, :l]
        fs = np.pad(f, ((0, 0), (shift, 0)), constant_values=True)[:, :l]
        v = np.where(f, v, v + vs)
        f = f | fs
        shift *= 2

    has_start = np.any(s2, axis=1)
    cv = v[:, -1]
    cf = has_start.copy()
    cf[0] = True
    shift = 1
    while shift < g:
        cvs = np.pad(cv, (shift, 0))[:g]
        cfs = np.pad(cf, (shift, 0), constant_values=True)[:g]
        cv = np.where(cf, cv, cv + cvs)
        cf = cf | cfs
        shift *= 2
    carry_in = np.concatenate(
        [np.zeros((1,), values.dtype), cv[:-1]])
    before_first = np.cumsum(s2.astype(np.int32), axis=1) == 0
    out = np.where(before_first, v + carry_in[:, None], v)
    return out.reshape(-1)[:n].astype(values.dtype)


def last_marked_carry(mask: jax.Array, *values: jax.Array
                      ) -> tuple[jax.Array, ...]:
    """Along the last axis, carry each payload forward from the most
    recent *strictly earlier* position where ``mask`` is True (exclusive
    scan; positions before any mark carry 0).

    mask: bool[..., L]; values: f32[..., L] each. Returns one array per
    payload. log2(L) elementwise select steps — no gathers, no scatters.
    (Hand-rolled Hillis-Steele jumps rather than lax.associative_scan:
    the scan's recursive slicing stalls the TPU compiler when fused into
    a larger program — observed >30min on v5e for _compress_rows — while
    this loop, the same shape as segmented_cumsum's, compiles in
    seconds.)
    """
    pad = [(0, 0)] * (mask.ndim - 1) + [(1, 0)]
    m = jnp.pad(mask, pad)[..., :-1]
    vs = [jnp.pad(v, pad)[..., :-1] for v in values]
    n = m.shape[-1]

    def shift_right(x, k, fill=False):
        p = [(0, 0)] * (x.ndim - 1) + [(k, 0)]
        return jnp.pad(x, p, constant_values=fill)[..., :n]

    shift = 1
    while shift < n:
        # invariant: (m, vs) at i reflect the last mark in (i-2^k, i]
        m_s = shift_right(m, shift)
        vs = [jnp.where(m, v, shift_right(v, shift, 0))
              for v in vs]
        m = m | m_s
        shift *= 2
    return tuple(vs)


def np_last_marked_carry(mask: np.ndarray, *values: np.ndarray
                         ) -> tuple[np.ndarray, ...]:
    """NumPy twin of `last_marked_carry` (selects and shifts only, in
    the identical order — bitwise equal by construction)."""
    mask = np.asarray(mask, bool)
    pad = [(0, 0)] * (mask.ndim - 1) + [(1, 0)]
    m = np.pad(mask, pad)[..., :-1]
    vs = [np.pad(np.asarray(v), pad)[..., :-1] for v in values]
    n = m.shape[-1]

    def shift_right(x, k, fill=False):
        p = [(0, 0)] * (x.ndim - 1) + [(k, 0)]
        return np.pad(x, p, constant_values=fill)[..., :n]

    shift = 1
    while shift < n:
        m_s = shift_right(m, shift)
        vs = [np.where(m, v, shift_right(v, shift, 0))
              for v in vs]
        m = m | m_s
        shift *= 2
    return tuple(vs)
