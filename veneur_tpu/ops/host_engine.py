"""Host (NumPy) sketch engine: the device fault domain's failover target.

When ops/device_guard.py trips a worker's breaker, the worker's histogram
pool, set pool, and flush extraction move onto these kernels until a
probe re-admits the device. The contract is BIT-EQUALITY: a degraded
interval must flush byte-identically to what the device path would have
produced over the same inputs, for every metric class — otherwise
failover silently shifts quantiles/estimates and the "graceful" in
graceful degradation is a lie the dashboards can't see.

That contract is only possible because the device kernels are written
against ops/exactnum.py: every float reduction is an explicitly-coded
Hillis-Steele scan or pairwise halving tree, every product that feeds an
add is select-blocked against FMA contraction, and every transcendental
is a host-precomputed f32 table read by exact integer gathers or
comparison-exact searchsorted. Each function here replays the SAME
IEEE-754 operation sequence with NumPy ops:

* ``jax.lax.sort`` (stable)            → ``np.argsort(kind="stable")`` /
                                          ``np.lexsort`` (both stable)
* ``exn.cumsum`` / ``exn.tsum``        → ``exn.np_cumsum`` / ``np_tsum``
                                          (identical shift loops)
* ``segments.*``                       → their ``np_*`` twins
* searchsorted / min / max / select /
  single add / sub / mul / div / sqrt  → IEEE-correctly-rounded on both
                                          sides; used directly

Mirrors are kept line-for-line parallel with their device source
(ops/tdigest.py, ops/hll.py, core/worker.py jitted steps) — when editing
one side, edit the other; tests/test_device_guard.py pins the parity
matrix and tools/fuzz_differential.py --op device_fallback fuzzes it.

NumPy dtype discipline: every float constant is spelled ``np.float32``
so no op silently promotes to f64 (JAX's weak-typing keeps the device
side in f32; NumPy 1.x promotes f32 op python-float to f32 by value but
an explicit cast removes the footgun). Integer index math may widen to
int64 host-side — value-exact, so parity is unaffected.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from veneur_tpu.ops import exactnum as exn
from veneur_tpu.ops import hll as hll_ops
from veneur_tpu.ops import segments
from veneur_tpu.ops import tdigest as td

_INF = np.float32(np.inf)
_F0 = np.float32(0.0)
_TINY = np.float32(1e-30)
_NAN = np.float32(np.nan)

# ---------------------------------------------------------------------------
# t-digest (ops/tdigest.py twins)


def _stable_sort_pair(keys: np.ndarray, payload: np.ndarray):
    """Twin of jax.lax.sort((keys, payload), num_keys=1) along the last
    axis (stable)."""
    order = np.argsort(keys, axis=-1, kind="stable")
    return (np.take_along_axis(keys, order, axis=-1),
            np.take_along_axis(payload, order, axis=-1))


def np_compress_rows(means: np.ndarray, weights: np.ndarray,
                     compression: float, capacity: int):
    """Twin of ops/tdigest._compress_rows."""
    s, m = means.shape
    with np.errstate(invalid="ignore", divide="ignore"):
        sort_keys = np.where(weights > 0, means, _INF)
        sorted_means, sorted_w = _stable_sort_pair(sort_keys, weights)
        w_cum = exn.np_cumsum(sorted_w)
        total = w_cum[:, -1:]
        q_left = (w_cum - sorted_w) / np.maximum(total, _TINY)
        bucket = np.clip(exn.np_kscale_bucket(q_left, compression),
                         0, capacity - 1)
        mw_cum = exn.np_cumsum(
            np.where(sorted_w > 0, sorted_means * sorted_w, _F0))
        nxt = np.concatenate(
            [bucket[:, 1:], np.full((s, 1), -1, np.int32)], axis=-1)
        is_end = bucket != nxt
        w_before, mw_before = segments.np_last_marked_carry(
            is_end, w_cum, mw_cum)
        seg_w = w_cum - w_before
        seg_mw = mw_cum - mw_before
        live = is_end & (seg_w > 0)
        new_means = np.where(live, seg_mw / np.maximum(seg_w, _TINY), _INF)
        new_w = np.where(live, seg_w, _F0)
        new_means, new_w = _stable_sort_pair(new_means, new_w)
    return new_means[:, :capacity], new_w[:, :capacity]


def _np_prefix_scans(srows, svals, sw, n):
    """Twin of ops/tdigest._prefix_scans_xla."""
    zero1 = np.zeros((1,), sw.dtype)
    pre_w = np.concatenate([zero1, exn.np_cumsum(sw)])
    pre_vw = np.concatenate(
        [zero1, exn.np_cumsum(exn.np_block(svals * sw))])
    with np.errstate(divide="ignore", invalid="ignore"):
        pre_recip = np.concatenate(
            [zero1, exn.np_cumsum(np.where(sw > 0, sw / svals, _F0))])
    row_starts = np.concatenate(
        [np.ones((1,), bool), srows[1:] != srows[:-1]])
    seg_cum = segments.np_segmented_cumsum(sw, row_starts)
    row_ends = np.concatenate([row_starts[1:], np.ones((1,), bool)])
    suffix = segments.np_segmented_cumsum(
        sw[::-1], row_ends[::-1])[::-1]
    return pre_w, pre_vw, pre_recip, seg_cum, suffix


def np_add_batch(means, weights, dmin, dmax, drecip, rows, values,
                 sample_weights, compression: float = td.DEFAULT_COMPRESSION):
    """Twin of ops/tdigest.add_batch (same return contract)."""
    k, c = means.shape
    n = rows.shape[0]
    rows = np.asarray(rows, np.int32)
    values = np.asarray(values, np.float32)
    sample_weights = np.asarray(sample_weights, np.float32)
    live = sample_weights > 0
    rows = np.where(live, rows, np.int32(k))
    safe_vals = np.where(live, values, np.float32(1.0))

    # lax.sort((rows, safe_vals, sw), num_keys=2) — lexsort's last key is
    # primary, and both sorts are stable
    order = np.lexsort((safe_vals, rows))
    srows, svals, sw = rows[order], safe_vals[order], sample_weights[order]

    pre_w, pre_vw, pre_recip, seg_cum, suffix = _np_prefix_scans(
        srows, svals, sw, n)

    kbins = np.arange(k, dtype=np.int32)
    row_upper = np.searchsorted(srows, kbins, side="right").astype(np.int32)
    row_lower = np.concatenate(
        [np.zeros((1,), np.int32), row_upper[:-1]])

    # zero-valued samples put inf in the reciprocal prefix sums; the
    # inf-inf nan for empty rows is masked by `has` below
    with np.errstate(invalid="ignore"):
        seg_w = pre_w[row_upper] - pre_w[row_lower]
        seg_sum = pre_vw[row_upper] - pre_vw[row_lower]
        seg_recip = pre_recip[row_upper] - pre_recip[row_lower]
    has = seg_w > 0
    seg_min = np.where(has, svals[row_lower], _INF)
    seg_max = np.where(has, svals[np.maximum(row_upper - 1, 0)], -_INF)
    stats = td.BatchStats(seg_w, seg_min, seg_max, seg_sum, seg_recip)

    with np.errstate(invalid="ignore", divide="ignore"):
        row_total = seg_cum + suffix - sw
        q_left = (seg_cum - sw) / np.maximum(row_total, _TINY)
    bucket = np.clip(exn.np_kscale_bucket(q_left, compression), 0, c - 1)
    seg_id = srows * np.int32(c) + bucket
    starts = np.concatenate(
        [np.ones((1,), bool), seg_id[1:] != seg_id[:-1]])
    grank = (np.cumsum(starts.astype(np.int32)) - 1).astype(np.int32)
    pos = np.where(starts, np.arange(n, dtype=np.int32), np.int32(n))
    pos_ext = np.concatenate(
        [np.sort(pos), np.full((1,), n, np.int32)])
    run_lo = grank[np.clip(row_lower, 0, n - 1)]
    run_hi = grank[np.maximum(row_upper - 1, 0)] + 1
    n_runs_row = np.where(has, run_hi - run_lo, 0)
    j = np.arange(c, dtype=np.int32)
    runs = np.clip(run_lo[:, None] + j[None, :], 0, n - 1)
    valid = j[None, :] < n_runs_row[:, None]
    r_start = pos_ext[runs]
    last = j[None, :] == (n_runs_row - 1)[:, None]
    pre = np.stack([pre_w, pre_vw], axis=-1)  # [N+1, 2]
    at_start = pre[r_start]  # [K, C, 2]
    at_row_end = pre[row_upper]  # [K, 2]
    at_next = np.concatenate(
        [at_start[:, 1:, :], np.zeros((k, 1, 2), at_start.dtype)], axis=1)
    at_end = np.where(last[:, :, None], at_row_end[:, None, :], at_next)
    diff = at_end - at_start
    bd_w = np.where(valid, diff[..., 0], _F0)
    bd_mw = np.where(valid, diff[..., 1], _F0)
    with np.errstate(invalid="ignore", divide="ignore"):
        bd_means = np.where(
            bd_w > 0, bd_mw / np.maximum(bd_w, _TINY), _INF)

    cat_means = np.concatenate([means, bd_means], axis=-1)
    cat_w = np.concatenate([weights, bd_w], axis=-1)
    new_means, new_w = np_compress_rows(cat_means, cat_w, compression, c)

    new_min = np.minimum(dmin, seg_min)
    new_max = np.maximum(dmax, seg_max)
    new_recip = drecip + seg_recip
    return new_means, new_w, new_min, new_max, new_recip, stats


def _np_row_bounds(means, weights, dmax):
    """Twin of ops/tdigest._row_bounds."""
    s, c = means.shape
    nonempty = weights > 0
    count = np.sum(nonempty, axis=-1)
    idx = np.arange(c)
    next_means = np.concatenate(
        [means[:, 1:], np.full((s, 1), _INF, means.dtype)], axis=-1)
    mid = (means + next_means) / np.float32(2.0)
    is_last = idx[None, :] == (count - 1)[:, None]
    ub = np.where(is_last, dmax[:, None], mid)
    return ub, count


# row-chunk size for the [chunk, C, P] comparison in np_quantile: bounds
# peak memory without changing any arithmetic (comparisons only)
_Q_CHUNK = 4096


def np_quantile(means, weights, dmin, dmax, qs):
    """Twin of ops/tdigest.quantile (gather form; the mask form is
    pinned bit-identical to it by test_tdigest)."""
    s, c = means.shape
    qs = np.asarray(qs, np.float32)
    ub, count = _np_row_bounds(means, weights, dmax)
    w_cum = exn.np_cumsum(weights)
    total = w_cum[:, -1]
    lb = np.concatenate([dmin[:, None], ub[:, :-1]], axis=-1)

    with np.errstate(invalid="ignore", divide="ignore"):
        target = exn.np_block(qs[None, :] * total[:, None])  # [S, P]
        first_idx = np.empty((s, qs.shape[0]), np.int64)
        for lo in range(0, s, _Q_CHUNK):
            hi = min(lo + _Q_CHUNK, s)
            # searchsorted(cw, t, side="left") == #elements < t; exact
            first_idx[lo:hi] = np.sum(
                w_cum[lo:hi, :, None] < target[lo:hi, None, :], axis=1)
        first_idx = np.minimum(first_idx, c - 1)

        def _at(x):
            return np.take_along_axis(x, first_idx, axis=1)

        w_at = _at(weights)
        w_before = _at(w_cum) - w_at
        lb_at = _at(lb)
        ub_at = _at(ub)
        proportion = (target - w_before) / np.maximum(w_at, _TINY)
        out = lb_at + exn.np_block(proportion * (ub_at - lb_at))
    return np.where(
        (total[:, None] > 0) & (count[:, None] > 0), out, _NAN)


def np_row_sum(means, weights):
    """Twin of ops/tdigest.row_sum."""
    with np.errstate(invalid="ignore"):
        return exn.np_tsum(np.where(weights > 0, means * weights, _F0))


def np_row_count(weights):
    """Twin of ops/tdigest.row_count."""
    return exn.np_tsum(weights)


# ---------------------------------------------------------------------------
# HLL (ops/hll.py twins)


def np_hll_insert_batch(registers, rows, reg_idx, rank):
    """Twin of ops/hll.insert_batch. Integer scatter-max is
    order-independent, so a direct np.maximum.at over in-range entries
    reproduces the device's sorted run-end scatter bitwise."""
    registers = np.asarray(registers)
    s, m = registers.shape
    rows = np.asarray(rows, np.int64)
    reg_idx = np.asarray(reg_idx, np.int64)
    rank = np.asarray(rank, registers.dtype)
    flat = rows * m + reg_idx
    ok = (flat >= 0) & (flat < s * m)  # mode="drop"
    out = registers.reshape(-1).copy()
    np.maximum.at(out, flat[ok], rank[ok])
    return out.reshape(s, m)


def np_hll_merge(a, b):
    """Twin of ops/hll.merge."""
    return np.maximum(a, b)


def np_hll_estimate_exact(registers, precision: int = hll_ops.
                          DEFAULT_PRECISION):
    """Bitwise twin of ops/hll.estimate (the f64 tolerance reference for
    the fuzzer lives in ops/query.np_hll_estimate; this one must agree
    with the device kernel to the bit)."""
    registers = np.asarray(registers)
    m = float(1 << precision)
    ranks = registers.astype(np.int32)
    ept = exn.exp2_neg_table()
    inv_sum = exn.np_tsum(ept[ranks])
    zeros = np.sum(registers == 0, axis=-1).astype(np.int32)
    raw = exn.hll_alpha_m2(precision) / inv_sum
    linear = exn.hll_linear_table(precision)[zeros]
    use_linear = (raw <= np.float32(2.5 * m)) & (zeros > 0)
    return np.where(use_linear, linear, raw)


# ---------------------------------------------------------------------------
# Worker jitted-step twins (core/worker.py)


def np_comp_add(s, c, x):
    """Twin of core/worker._comp_add (Neumaier compensated add)."""
    t = s + x
    with np.errstate(invalid="ignore"):
        resid = np.where(np.abs(s) >= np.abs(x), (s - t) + x, (x - t) + s)
        resid = np.where(np.isfinite(t), resid, _F0)
    return t, c + resid


def np_unit_wts_plane(counts, depth: int):
    """Twin of core/worker._unit_wts_plane."""
    return (np.arange(depth, dtype=np.int32)[None, :]
            < np.asarray(counts)[:, None]).astype(np.float32)


def np_expand_flat_planes(flat_v, flat_w, counts, depth: int, unit: bool):
    """Twin of core/worker._expand_flat_planes."""
    flat_v = np.asarray(flat_v, np.float32)
    counts = np.asarray(counts, np.int32)
    b = np.arange(depth, dtype=np.int32)[None, :]
    offsets = np.concatenate(
        [np.zeros((1,), np.int32),
         np.cumsum(counts, dtype=np.int32)[:-1]])
    idx = np.clip(offsets[:, None] + b, 0, flat_v.shape[0] - 1)
    valid = b < counts[:, None]
    sv = np.where(valid, flat_v[idx], _F0)
    if unit:
        sw = valid.astype(np.float32)
    else:
        sw = np.where(valid, np.asarray(flat_w, np.float32)[idx], _F0)
    return sv, sw


def np_fold_staged(means, weights, dmin, dmax, drecip, drecip_c,
                   lmin, lmax, lsum, lsum_c, lweight, lweight_c,
                   lrecip, lrecip_c, svals, swts,
                   compression: float = td.DEFAULT_COMPRESSION):
    """Twin of core/worker._histo_fold_staged."""
    c = means.shape[1]
    svals = np.asarray(svals, np.float32)
    swts = np.asarray(swts, np.float32)
    live = swts > 0
    with np.errstate(invalid="ignore", divide="ignore"):
        s_w = exn.np_tsum(swts)
        s_sum = exn.np_tsum(np.where(live, svals * swts, _F0))
        s_recip = exn.np_tsum(np.where(live, swts / svals, _F0))
        s_min = np.min(np.where(live, svals, _INF), axis=-1)
        s_max = np.max(np.where(live, svals, -_INF), axis=-1)

    cat_means = np.concatenate([means, svals], axis=-1)
    cat_w = np.concatenate([weights, swts], axis=-1)
    means, weights = np_compress_rows(cat_means, cat_w, compression, c)

    dmin = np.minimum(dmin, s_min)
    dmax = np.maximum(dmax, s_max)
    drecip, drecip_c = np_comp_add(drecip, drecip_c, s_recip)
    lmin = np.minimum(lmin, s_min)
    lmax = np.maximum(lmax, s_max)
    lsum, lsum_c = np_comp_add(lsum, lsum_c, s_sum)
    lweight, lweight_c = np_comp_add(lweight, lweight_c, s_w)
    lrecip, lrecip_c = np_comp_add(lrecip, lrecip_c, s_recip)
    return (means, weights, dmin, dmax, drecip, drecip_c,
            lmin, lmax, lsum, lsum_c, lweight, lweight_c, lrecip, lrecip_c)


def np_ingest_step(means, weights, dmin, dmax, drecip, drecip_c,
                   lmin, lmax, lsum, lsum_c, lweight, lweight_c,
                   lrecip, lrecip_c, active, lids, values, wts,
                   compression: float = td.DEFAULT_COMPRESSION):
    """Twin of core/worker._histo_ingest_step. `active` may contain
    duplicates (scratch-row padding); every duplicate writes an
    identical value (gather→compute→scatter of the same inputs), so
    plain fancy-index assignment matches the device scatter, and the
    accumulate-min/max scatters use ufunc.at."""
    active = np.asarray(active, np.int64)
    g_means = means[active]
    g_w = weights[active]
    g_min = dmin[active]
    g_max = dmax[active]
    g_recip = drecip[active]

    n_means, n_w, n_min, n_max, _, stats = np_add_batch(
        g_means, g_w, g_min, g_max, g_recip, lids, values, wts,
        compression=compression)

    means = means.copy()
    weights = weights.copy()
    dmin, dmax = dmin.copy(), dmax.copy()
    drecip, drecip_c = drecip.copy(), drecip_c.copy()
    lmin, lmax = lmin.copy(), lmax.copy()
    lsum, lsum_c = lsum.copy(), lsum_c.copy()
    lweight, lweight_c = lweight.copy(), lweight_c.copy()
    lrecip, lrecip_c = lrecip.copy(), lrecip_c.copy()

    means[active] = n_means
    weights[active] = n_w
    dmin[active] = n_min
    dmax[active] = n_max
    n_recip, n_recip_c = np_comp_add(g_recip, drecip_c[active], stats.recip)
    drecip[active] = n_recip
    drecip_c[active] = n_recip_c

    np.minimum.at(lmin, active, stats.min)
    np.maximum.at(lmax, active, stats.max)
    n_lsum, n_lsum_c = np_comp_add(lsum[active], lsum_c[active], stats.sum)
    lsum[active] = n_lsum
    lsum_c[active] = n_lsum_c
    n_lw, n_lw_c = np_comp_add(lweight[active], lweight_c[active],
                               stats.weight)
    lweight[active] = n_lw
    lweight_c[active] = n_lw_c
    n_lr, n_lr_c = np_comp_add(lrecip[active], lrecip_c[active], stats.recip)
    lrecip[active] = n_lr
    lrecip_c[active] = n_lr_c
    return (means, weights, dmin, dmax, drecip, drecip_c,
            lmin, lmax, lsum, lsum_c, lweight, lweight_c, lrecip, lrecip_c)


def np_import_step(means, weights, dmin, dmax, drecip, drecip_c,
                   rows, imp_means, imp_w, imp_min, imp_max, imp_recip,
                   compression: float = td.DEFAULT_COMPRESSION):
    """Twin of core/worker._histo_import_step."""
    c = means.shape[1]
    rows = np.asarray(rows, np.int64)
    g_means = means[rows]
    g_w = weights[rows]
    cat_means = np.concatenate(
        [g_means, np.asarray(imp_means, np.float32)], axis=-1)
    cat_w = np.concatenate([g_w, np.asarray(imp_w, np.float32)], axis=-1)
    n_means, n_w = np_compress_rows(cat_means, cat_w, compression, c)
    means = means.copy()
    weights = weights.copy()
    dmin, dmax = dmin.copy(), dmax.copy()
    drecip, drecip_c = drecip.copy(), drecip_c.copy()
    means[rows] = n_means
    weights[rows] = n_w
    np.minimum.at(dmin, rows, np.asarray(imp_min, np.float32))
    np.maximum.at(dmax, rows, np.asarray(imp_max, np.float32))
    n_recip, n_recip_c = np_comp_add(
        drecip[rows], drecip_c[rows], np.asarray(imp_recip, np.float32))
    drecip[rows] = n_recip
    drecip_c[rows] = n_recip_c
    return means, weights, dmin, dmax, drecip, drecip_c


def np_flush_extract(means, weights, dmin, dmax, drecip, drecip_c,
                     lmin, lmax, lsum, lsum_c, lweight, lweight_c,
                     lrecip, lrecip_c, qs):
    """Twin of core/worker._histo_flush_extract."""
    quantiles = np_quantile(means, weights, dmin, dmax, qs)
    dsum = np_row_sum(means, weights)
    dcount = np_row_count(weights)
    return (quantiles, dmin, dmax, dsum, dcount, drecip + drecip_c,
            lmin, lmax, lsum + lsum_c, lweight + lweight_c,
            lrecip + lrecip_c)


def np_pack_extract_columns(qv, *cols):
    """Twin of core/worker._pack_extract_columns."""
    return np.concatenate(
        [np.asarray(qv, np.float32)]
        + [np.asarray(col)[:, None].astype(np.float32) for col in cols],
        axis=1)


# ---------------------------------------------------------------------------
# Host histogram pool state


@dataclass
class HostHistoState:
    """NumPy mirror of core/worker.HistoDeviceState — same 14 fields in
    the same kernel argument order, so a quarantined worker swaps one
    state class for the other and every call site that only touches
    `.fields()` / `.num_rows` keeps working."""

    means: np.ndarray
    weights: np.ndarray
    dmin: np.ndarray
    dmax: np.ndarray
    drecip: np.ndarray
    drecip_c: np.ndarray
    lmin: np.ndarray
    lmax: np.ndarray
    lsum: np.ndarray
    lsum_c: np.ndarray
    lweight: np.ndarray
    lweight_c: np.ndarray
    lrecip: np.ndarray
    lrecip_c: np.ndarray

    @classmethod
    def create(cls, rows: int, capacity: int) -> "HostHistoState":
        def _full(v):
            return np.full((rows,), v, np.float32)

        return cls(
            means=np.full((rows, capacity), _INF, np.float32),
            weights=np.zeros((rows, capacity), np.float32),
            dmin=_full(np.inf), dmax=_full(-np.inf), drecip=_full(0.0),
            drecip_c=_full(0.0), lmin=_full(np.inf), lmax=_full(-np.inf),
            lsum=_full(0.0), lsum_c=_full(0.0), lweight=_full(0.0),
            lweight_c=_full(0.0), lrecip=_full(0.0), lrecip_c=_full(0.0),
        )

    @classmethod
    def from_fields(cls, fields, perm=None) -> "HostHistoState":
        """Snapshot device fields to host (the failover d2h). `perm` is
        the physical→logical row permutation for series-sharded pools
        (ops/series_shard.perm_l2p output); the host engine always works
        in logical row order."""
        host = []
        for f in fields:
            a = np.asarray(f)
            if perm is not None:
                a = a[perm]
            host.append(np.array(a, copy=True))
        return cls(*host)

    @property
    def num_rows(self) -> int:
        return self.means.shape[0]

    def fields(self) -> tuple:
        return (self.means, self.weights, self.dmin, self.dmax,
                self.drecip, self.drecip_c, self.lmin, self.lmax,
                self.lsum, self.lsum_c, self.lweight, self.lweight_c,
                self.lrecip, self.lrecip_c)

    def grow(self, new_rows: int) -> "HostHistoState":
        def g2(old):
            s, c = old.shape
            out = np.zeros((new_rows, c), old.dtype)
            out[:s] = old
            return out

        def g1(old, fill):
            out = np.full((new_rows,), fill, old.dtype)
            out[:old.shape[0]] = old
            return out

        inf = np.float32(np.inf)
        return HostHistoState(
            means=g2(self.means), weights=g2(self.weights),
            dmin=g1(self.dmin, inf), dmax=g1(self.dmax, -inf),
            drecip=g1(self.drecip, 0.0), drecip_c=g1(self.drecip_c, 0.0),
            lmin=g1(self.lmin, inf), lmax=g1(self.lmax, -inf),
            lsum=g1(self.lsum, 0.0), lsum_c=g1(self.lsum_c, 0.0),
            lweight=g1(self.lweight, 0.0), lweight_c=g1(self.lweight_c, 0.0),
            lrecip=g1(self.lrecip, 0.0), lrecip_c=g1(self.lrecip_c, 0.0),
        )
