"""Batched HyperLogLog on TPU.

Semantics spec: the reference's vendored axiomhq/hyperloglog sketch
(precision p=14 → 2^14 registers, used by samplers.Set,
samplers/samplers.go:367-463). Re-designed for SIMD execution:

* A pool of S sketches is one dense `int8[S, 2^p]` register array (p=14 ⇒
  16384 = 128×128 registers per row, one TPU tile-aligned panel). The
  reference's sparse representation is intentionally dropped — dense rows
  are what makes insert a single scatter and merge a single elementwise max
  (documented deviation; memory is 2^p bytes/series, configurable via p).

* Values are hashed host-side (strings never touch the device); the 64-bit
  hash splits into a p-bit register index and the leading-zero rank of the
  remaining bits — see `split_hashes`.

* insert = one `scatter-max` per batch over the whole pool; cross-host
  merge = elementwise `maximum` (the associative reduce the global tier
  runs over ICI); estimate = one vectorized harmonic-mean reduction per
  flush with linear counting for the small-cardinality regime.

The estimator is classic HLL with linear counting below 2.5m (the 64-bit
hash needs no large-range correction). The reference's axiomhq sketch uses
the LogLog-Beta estimator; both sit within the same ~1.04/√m error envelope,
which is what the tests assert.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from veneur_tpu.ops import exactnum as exn

DEFAULT_PRECISION = 14  # matches reference (axiomhq) precision


def num_registers(precision: int = DEFAULT_PRECISION) -> int:
    return 1 << precision


def init_pool(num_rows: int, precision: int = DEFAULT_PRECISION) -> jax.Array:
    return jnp.zeros((num_rows, num_registers(precision)), dtype=jnp.int8)


def split_hashes(
    hashes: np.ndarray, precision: int = DEFAULT_PRECISION
) -> tuple[np.ndarray, np.ndarray]:
    """Split 64-bit hashes into (register index, rank) host-side.

    index = top p bits; rank = #leading zeros of the remaining 64-p bits,
    plus one (capped at 64-p+1 when those bits are all zero).
    """
    h = hashes.astype(np.uint64)
    idx = (h >> np.uint64(64 - precision)).astype(np.int32)
    w = (h << np.uint64(precision)) & np.uint64(0xFFFFFFFFFFFFFFFF)
    # clz via float64 exponent: highest set bit of w is frexp-exponent - 1.
    # w == 0 → rank = 64-p+1. Values within 2^-52 of a power of two can
    # round the exponent up by one; that's a 1-in-2^40 rank-off-by-one on a
    # random hash — far below HLL's intrinsic error.
    nonzero = w != 0
    _, exp = np.frexp(w.astype(np.float64))
    clz = 64 - exp
    rank = np.where(nonzero, clz + 1, 64 - precision + 1).astype(np.int8)
    rank = np.minimum(rank, np.int8(64 - precision + 1))
    return idx, rank


@jax.jit
def insert_batch(
    registers: jax.Array,
    rows: jax.Array,
    reg_idx: jax.Array,
    rank: jax.Array,
) -> jax.Array:
    """Batch-max a set of (row, register, rank) updates into the pool.

    rows: i32[N] sketch row per sample (padding: rank 0 — a no-op since
    registers are >= 0).

    TPU-first formulation: a raw scatter-max with duplicate (row, register)
    indices serializes on TPU. Instead, sort by (flat register slot, rank);
    the LAST element of each equal-slot run then holds that slot's max, so
    a scatter against the sorted index vector applies the whole batch.
    Non-run-end elements keep their (sorted, duplicate) index but have
    their rank zeroed — max with 0 is a no-op since registers are >= 0 —
    so the indices_are_sorted=True promise to XLA holds exactly
    (duplicates allowed, hence unique_indices=False).
    """
    s, m = registers.shape
    flat = rows * m + reg_idx  # fits i32 for s·m < 2^31 (s ≤ 2^17 at p=14)
    rank32 = rank.astype(jnp.int32)
    sflat, srank = jax.lax.sort((flat, rank32), dimension=0, num_keys=2)
    is_end = jnp.concatenate(
        [sflat[1:] != sflat[:-1], jnp.ones((1,), bool)])
    vals = jnp.where(is_end, srank, 0)  # non-run-end → no-op max(·, 0)
    out = registers.reshape(-1).at[sflat].max(
        vals.astype(registers.dtype), mode="drop",
        indices_are_sorted=True, unique_indices=False)
    return out.reshape(s, m)


@jax.jit
def insert_batch_scatter(
    registers: jax.Array,
    rows: jax.Array,
    reg_idx: jax.Array,
    rank: jax.Array,
) -> jax.Array:
    """Plain duplicate-index scatter-max variant (kept for A/B against
    `insert_batch` on hardware)."""
    return registers.at[rows, reg_idx].max(rank, mode="drop")


@jax.jit
def merge(a: jax.Array, b: jax.Array) -> jax.Array:
    """Register-wise max — the associative cross-host reduce
    (reference Set.Combine, samplers/samplers.go:423-435)."""
    return jnp.maximum(a, b)


@functools.partial(jax.jit, static_argnames=("precision",))
def estimate(registers: jax.Array, precision: int = DEFAULT_PRECISION
             ) -> jax.Array:
    """Cardinality estimate per row: int8[S, m] → f32[S].

    Harmonic-mean estimator with linear counting below 2.5m.

    Order-pinned form (host fallback parity, see ops/exactnum.py): the
    transcendentals become host-precomputed f32 tables read by integer
    gathers (exp2(-rank) is 65 entries; the linear-counting m·ln(m/z)
    is indexed by the integer zero count), and the Σ 2^-reg reduction is
    a pairwise halving tree — so ops/host_engine.py reproduces every
    estimate bitwise.
    """
    m = float(num_registers(precision))
    ranks = registers.astype(jnp.int32)
    ept = jnp.asarray(exn.exp2_neg_table())
    inv_sum = exn.tsum(ept[ranks])  # Σ 2^-reg, fixed association
    zeros = jnp.sum((registers == 0).astype(jnp.int32), axis=-1)
    raw = jnp.asarray(exn.hll_alpha_m2(precision)) / inv_sum
    linear = jnp.asarray(exn.hll_linear_table(precision))[zeros]
    use_linear = (raw <= jnp.float32(2.5 * m)) & (zeros > 0)
    return jnp.where(use_linear, linear, raw)


# ---------------------------------------------------------------------------
# Host-side helpers (codec / single-sketch use)


def registers_to_bytes(row: np.ndarray) -> bytes:
    """Dense register row → wire bytes (see distributed/codec.py)."""
    return np.asarray(row, dtype=np.int8).tobytes()


def registers_from_bytes(data: bytes, precision: int = DEFAULT_PRECISION
                         ) -> np.ndarray:
    arr = np.frombuffer(data, dtype=np.int8)
    if arr.shape[0] != num_registers(precision):
        raise ValueError(
            f"HLL payload has {arr.shape[0]} registers, expected"
            f" {num_registers(precision)}"
        )
    return arr
