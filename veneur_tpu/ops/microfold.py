"""Streaming micro-fold mirror: always-hot device staging.

The once-per-interval flush pays a synchronous upload+fold burst at the
deadline (SUSTAINED_PIPELINE.json: tick_block_ms ~1100 with chip compute
in the milliseconds — the device is cold between flushes). This module
keeps a device-side mirror of the staging plane warm DURING the
interval: every micro-fold drains the staged samples accumulated since
the last drain as COO deltas (row, absolute slot, value, weight) and
scatters them into a persistent [M, B] mirror with donated dispatches,
so by flush time the staged state is already resident on device and the
tick's fold collapses to a drain.

Bit-identity by construction: slots are ABSOLUTE positions in the host
staging plane, so after the final drain the mirror holds exactly the
dense [S, B] array the batch path would have uploaded (values/weights at
filled slots, zeros elsewhere — including unit weights, which both paths
materialize as exact 1.0f). The flush then runs the SAME single
``_histo_fold_staged`` program over the mirror sliced to ``s_eff`` that
the batch path runs over its uploaded plane, so micro-folded ==
batch-folded is bitwise, not approximate (tests/test_microfold.py pins
all three metric classes).

Transfer accounting stays O(samples) and partition-invariant: uploads go
out in fixed MICRO_CHUNK-entry COO chunks (16 bytes/entry), the carry
remainder is buffered host-side across drains, and the final partial
chunk is padded with drop-sentinel rows (scatter ``mode="drop"``).
Total bytes = ceil(samples / MICRO_CHUNK) x MICRO_CHUNK x 16 no matter
how many micro-folds the scheduler ran — the ledger-equality contract
(tests assert +-0 against a single-drain run) and a single jit
specialization (no per-size compile ladder).

Overlap discipline (double buffering): each chunk's four COO arrays are
device_put first (async), then the scatter is dispatched; with at most
two unsynced scatters in the queue the upload of chunk N+1 overlaps the
scatter of chunk N, and the fence (block on the latest mirror) bounds
the dispatch queue so a fast producer cannot run the host arbitrarily
far ahead of the device.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

# COO entries per upload chunk. 65536 x 16B = 1 MB per dispatch: large
# enough to amortize dispatch overhead (and, on backends that cannot
# honor the scatter's donation — XLA-CPU copies the whole [M, B] mirror
# per dispatch — to keep the per-interval dispatch count in the single
# digits), small enough that the carry buffer and the padded final
# chunk stay trivial and uploads still interleave with compute.
MICRO_CHUNK = 65536

# Sentinel row for padding the final partial chunk: out of bounds for
# any mirror, so the donated scatter's mode="drop" discards it.
DROP_ROW = np.int32(np.iinfo(np.int32).max)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _scatter_chunk(dvals, dwts, rows, slots, vals, wts):
    """Scatter one COO chunk into the mirror (padding rows dropped)."""
    dvals = dvals.at[rows, slots].set(vals, mode="drop")
    dwts = dwts.at[rows, slots].set(wts, mode="drop")
    return dvals, dwts


@functools.partial(jax.jit, static_argnames=("new_rows",),
                   donate_argnums=(0,))
def _grow_mirror(old, new_rows: int):
    s, b = old.shape
    return jnp.zeros((new_rows, b), old.dtype).at[:s].set(old)


def mirror_dense(arr, s_eff: int):
    """The mirror as a dense [s_eff, B] plane: slice when the mirror is
    larger, zero-pad when the directory outgrew it. Either way the
    result is bitwise the array the batch path would have built."""
    m = arr.shape[0]
    if m == s_eff:
        return arr
    if m > s_eff:
        return arr[:s_eff]
    return jnp.zeros((s_eff, arr.shape[1]), arr.dtype).at[:m].set(arr)


class MirrorState(NamedTuple):
    """A finished epoch's mirror, handed to the swapped-epoch extract."""

    vals: jax.Array
    wts: jax.Array
    rows_hi: int
    samples: int
    chunks: int


class MicroFoldMirror:
    """Device-side [M, B] mirror of one epoch's staging plane.

    Single-threaded by contract: the worker's ingest lock serializes
    feed() (micro-fold scheduler) against finish() (swap). The ledger
    (optional) books uploads into its epoch accumulator, so the flush
    that extracts this epoch reports them.
    """

    def __init__(self, depth: int, ledger=None,
                 initial_rows: int = 1024,
                 chunk: int = MICRO_CHUNK, shard=None,
                 guard=None) -> None:
        self.depth = int(depth)
        self.chunk = int(chunk)
        self._ledger = ledger
        # device guard (ops/device_guard.DeviceGuard): the scatter is the
        # mirror's one donating device dispatch, so it routes through the
        # guard's fault seam. A fault here surfaces as DeviceFaultError
        # to the caller (worker.micro_fold_once), which drops the mirror
        # and falls back to the retained staging plane — the mirror is a
        # CACHE of staged state, never the only copy.
        self._guard = guard
        # series-sharded mirror (ops/series_shard.SeriesSharding): the
        # carry buffers keep LOGICAL rows — translation to physical slots
        # happens at dispatch, against the mirror size current THEN, so
        # growth between drains never strands a buffered row. Growth and
        # the dense view go through the shard's per-local-block programs
        # (append-at-end growth would break the interleave).
        self._shard = shard
        # False while the epoch is live (uploads book into the ledger's
        # epoch accumulator, surfaced by the flush that extracts it);
        # the swap rotation flips it True so the deferred residual feeds
        # — which run inside extract_snapshot, after begin_flush() popped
        # this epoch's tally as the open window — book into that same
        # window directly.
        self.book_in_flush = False
        self._rows0 = max(1, int(initial_rows))
        if shard is not None:
            # mirror rows must stay pow2 multiples of the shard count so
            # local blocks are equal-sized
            r = 1
            while r < max(self._rows0, shard.shards):
                r *= 2
            self._rows0 = r
        self._dvals: Optional[jax.Array] = None
        self._dwts: Optional[jax.Array] = None
        self._m = 0
        self.rows_hi = 0   # 1 + highest real row scattered this epoch
        self.samples = 0   # real COO entries fed (padding excluded)
        self.chunks = 0    # fixed-size scatter dispatches
        self._unsynced = 0
        # carry buffer: the partial-chunk remainder persists across
        # drains so upload totals are partition-invariant
        self._c_rows = np.empty(self.chunk, np.int32)
        self._c_slots = np.empty(self.chunk, np.int32)
        self._c_vals = np.empty(self.chunk, np.float32)
        self._c_wts = np.empty(self.chunk, np.float32)
        self._c_n = 0

    def feed(self, rows, slots, vals, wts) -> None:
        """Buffer one drained COO delta; dispatch every full chunk."""
        n = len(rows)
        if n == 0:
            return
        self.samples += n
        hi = int(rows.max()) + 1
        if hi > self.rows_hi:
            self.rows_hi = hi
        i = 0
        while i < n:
            take = min(self.chunk - self._c_n, n - i)
            s = slice(self._c_n, self._c_n + take)
            self._c_rows[s] = rows[i:i + take]
            self._c_slots[s] = slots[i:i + take]
            self._c_vals[s] = vals[i:i + take]
            self._c_wts[s] = wts[i:i + take]
            self._c_n += take
            i += take
            if self._c_n == self.chunk:
                self._dispatch()
                self._c_n = 0

    def finish(self) -> Optional[MirrorState]:
        """Flush the carry (padded to a full chunk with drop-sentinel
        rows), detach the mirror for the swapped epoch, and reset.
        None when nothing was staged this epoch."""
        if self.samples == 0:
            self._c_n = 0
            return None
        if self._c_n > 0:
            self._c_rows[self._c_n:] = DROP_ROW
            self._c_slots[self._c_n:] = 0
            self._c_vals[self._c_n:] = 0.0
            self._c_wts[self._c_n:] = 0.0
            self._dispatch()
            self._c_n = 0
        state = MirrorState(self._dvals, self._dwts, self.rows_hi,
                            self.samples, self.chunks)
        self._dvals = None
        self._dwts = None
        self._m = 0
        self.rows_hi = 0
        self.samples = 0
        self.chunks = 0
        self._unsynced = 0
        return state

    # -- internals --------------------------------------------------------

    def _dispatch(self) -> None:
        sh = self._shard
        # sharded: the physical-slot translation needs the mirror's
        # CURRENT row count, so sizing runs before the upload; unsharded
        # keeps the upload-first order (it overlaps the in-flight scatter)
        if sh is not None:
            self._ensure_rows(self.rows_hi)
            rows_np = sh.phys_rows(self._c_rows, self._m)
        else:
            rows_np = self._c_rows
        reps = sh.shards if sh is not None else 1
        put = sh.replicate if sh is not None else None
        if self._ledger is not None:
            up = (self._ledger.h2d if self.book_in_flush
                  else self._ledger.epoch_h2d)
            drows = up(rows_np, "micro_fold", replicas=reps, put=put)
            dslots = up(self._c_slots, "micro_fold", replicas=reps, put=put)
            dvals = up(self._c_vals, "micro_fold", replicas=reps, put=put)
            dwts = up(self._c_wts, "micro_fold", replicas=reps, put=put)
        elif sh is not None:
            drows = sh.replicate(rows_np)
            dslots = sh.replicate(self._c_slots)
            dvals = sh.replicate(self._c_vals)
            dwts = sh.replicate(self._c_wts)
        else:
            drows = jnp.asarray(rows_np)
            dslots = jnp.asarray(self._c_slots)
            dvals = jnp.asarray(self._c_vals)
            dwts = jnp.asarray(self._c_wts)
        self._ensure_rows(self.rows_hi)
        # double-buffer fence: at most two unsynced scatters queued
        self._unsynced += 1
        if self._unsynced > 2:
            jax.block_until_ready(self._dvals)
            self._unsynced = 1
        scatter = _scatter_chunk if sh is None else sh.scatter_chunk
        if self._guard is not None:
            # donated operands — never retryable
            self._dvals, self._dwts = self._guard.call(
                "micro", scatter,
                self._dvals, self._dwts, drows, dslots, dvals, dwts)
        else:
            self._dvals, self._dwts = scatter(
                self._dvals, self._dwts, drows, dslots, dvals, dwts)
        self.chunks += 1

    def _ensure_rows(self, needed: int) -> None:
        if self._dvals is None:
            m = self._rows0
            while m < needed:
                m *= 2
            dv = jnp.zeros((m, self.depth), jnp.float32)
            dw = jnp.zeros((m, self.depth), jnp.float32)
            if self._shard is not None:
                dv = self._shard.place(dv)
                dw = self._shard.place(dw)
            self._dvals = dv
            self._dwts = dw
            self._m = m
            return
        if needed <= self._m:
            return
        m = self._m
        while m < needed:
            m *= 2
        if self._shard is not None:
            self._dvals = self._shard.grow_2d(self._dvals, m)
            self._dwts = self._shard.grow_2d(self._dwts, m)
        else:
            self._dvals = _grow_mirror(self._dvals, m)
            self._dwts = _grow_mirror(self._dwts, m)
        self._m = m
