"""Scalar aggregators: counters, gauges, and their batched reductions.

Semantics spec: reference samplers/samplers.go:130-304 — Counter.Sample
truncates both the sample and the rate reciprocal to integers
(`value += int64(sample) * int64(1/rate)`, :142-144); Gauge is
last-write-wins (:225-227).

Counters and gauges are not sketches: their per-batch reduction is a
segment-sum / segment-last, and their running state must be *exact*
(counters count bytes and requests — f32 would saturate at 2^24). The
running accumulation therefore lives host-side in float64 numpy (exact up
to 2^53, matching the practical range of the reference's int64), while the
device versions below exist for the fused flush/mesh paths where counter
shards ride the same program as the sketches.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def counter_contribution(value: float, sample_rate: float) -> int:
    """One counter sample's contribution, with the reference's double
    truncation (samplers/samplers.go:142-144)."""
    return int(value) * int(1.0 / sample_rate)


def accumulate_counters(
    state: np.ndarray, rows: np.ndarray, contributions: np.ndarray
) -> None:
    """In-place exact segment-sum of a batch into f64 counter state."""
    if len(rows):
        np.add.at(state, rows, contributions)


def apply_gauges(
    state: np.ndarray, present: np.ndarray, rows: np.ndarray,
    values: np.ndarray,
) -> None:
    """In-place last-write-wins gauge update for a batch (arrival order).

    numpy fancy assignment applies duplicate indices in order, so the last
    sample for a row wins — the reference's Gauge.Sample semantics.
    """
    if len(rows):
        state[rows] = values
        present[rows] = True


# ---------------------------------------------------------------------------
# Device-side segment reductions (used by the fused mesh/flush programs)


@functools.partial(jax.jit, static_argnames=("num_rows",))
def segment_counter_sum(
    rows: jax.Array, contributions: jax.Array, num_rows: int
) -> jax.Array:
    return jax.ops.segment_sum(contributions, rows, num_segments=num_rows)


def segment_gauge_last(
    rows: jax.Array, values: jax.Array, num_rows: int
) -> tuple[jax.Array, jax.Array]:
    """Last-write-wins per row on device: returns (values[num_rows],
    present[num_rows]). The winner is the sample with the highest arrival
    position per row."""
    n = rows.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    last_pos = jax.ops.segment_max(pos, rows, num_segments=num_rows)
    present = last_pos >= 0
    safe = jnp.clip(last_pos, 0, n - 1)
    return values[safe], present
