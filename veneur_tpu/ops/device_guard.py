"""Device fault domain: guarded execution, breaker, and the dispatch seam.

Every device entry point on the worker's hot path — batch fold,
micro-fold scatter, spill fold, staged-plane fold, flush extract, set
insert, import merge, pool growth, ad-hoc query eval — goes through
``DeviceGuard.call``, which:

1. routes the actual invocation through the module-level ``dispatch``
   seam (the ONE chokepoint seeded fault injection monkeypatches —
   utils/faults.DeviceFaultPlan);
2. classifies any device-side exception into the ``device.fault.*``
   taxonomy (oom / compile / lost / other) and counts it;
3. retries ONCE when the call site declared itself retry-safe (no
   donated operands — retrying a donating jit call would replay against
   invalidated buffers);
4. trips a per-worker breaker after ``streak_limit`` CONSECUTIVE
   failures, after which the worker quarantines its device path and
   fails over to the host engine (ops/host_engine.py) — see
   core/worker.DeviceWorker._quarantine_live;
5. while quarantined, gates re-admission behind a probe
   (compile+fold+extract of a tiny pool, run by the worker once per
   ``probe_interval_s`` — the half-open breaker pattern the health gate
   (PR 14) and delivery manager (PR 5) already use).

Python-level errors (TypeError, ValueError, assertion failures in host
code) are NOT device faults: ``classify`` returns None for them and
``call`` re-raises untouched — a code bug must stay loud, not trip a
failover that masks it.

Escape hatch: ``VENEUR_DEVICE_GUARD=0`` (or config device_guard: false)
constructs the guard disabled — ``call`` invokes the function directly,
no seam, no classification, no breaker — restoring the exact pre-guard
behavior for bisection.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional

log = logging.getLogger("veneur_tpu.ops.device_guard")

FAULT_KINDS = ("oom", "compile", "lost", "other")

#: default consecutive-failure streak that trips the breaker
DEFAULT_STREAK_LIMIT = 3
#: default seconds between re-admission probes while quarantined
DEFAULT_PROBE_INTERVAL_S = 30.0


def guard_enabled_default() -> bool:
    """Process-wide escape hatch (checked at worker construction)."""
    return os.environ.get("VENEUR_DEVICE_GUARD", "1") not in ("0", "false")


class DeviceFaultError(RuntimeError):
    """A classified device failure, raised by DeviceGuard.call after
    counting (and after the retry, when one was allowed). Carries the
    taxonomy kind and the original exception."""

    def __init__(self, kind: str, op: str, original: BaseException):
        super().__init__(f"device fault [{kind}] in {op}: {original}")
        self.kind = kind
        self.op = op
        self.original = original


# message markers per kind, matched against the exception text. XLA's
# runtime errors carry gRPC-style status prefixes (RESOURCE_EXHAUSTED,
# UNAVAILABLE, ...); PJRT OOMs say "Out of memory"; Mosaic/XLA compile
# failures name the compiler. Matched in this order — an OOM message
# that also mentions compilation is still an OOM.
_OOM_MARKS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
              "Resource exhausted", "Allocation failure", "OOM")
_COMPILE_MARKS = ("Mosaic", "compilation", "Compilation", "compile",
                  "lowering", "XLA translation")
_LOST_MARKS = ("UNAVAILABLE", "FAILED_PRECONDITION", "DATA_LOSS",
               "device lost", "Device lost", "ABORTED", "INTERNAL",
               "device is in an invalid state", "halted")
# exception class names (anywhere in the MRO) that mark a device-side
# runtime error; matched by name so no jaxlib import is needed here
_XLA_CLASS_NAMES = {"XlaRuntimeError", "JaxRuntimeError"}


def classify(exc: BaseException) -> Optional[str]:
    """Map an exception to a fault kind, or None for "not a device
    error — re-raise untouched"."""
    if isinstance(exc, DeviceFaultError):
        return exc.kind
    # injected faults (utils/faults.DeviceFaultPlan) tag themselves so
    # the taxonomy works without faking jaxlib exception classes
    kind = getattr(exc, "device_fault_kind", None)
    if kind is not None:
        return kind if kind in FAULT_KINDS else "other"
    names = {c.__name__ for c in type(exc).__mro__}
    if not (names & _XLA_CLASS_NAMES):
        return None
    msg = str(exc)
    if any(m in msg for m in _OOM_MARKS):
        return "oom"
    if any(m in msg for m in _COMPILE_MARKS):
        return "compile"
    if any(m in msg for m in _LOST_MARKS):
        return "lost"
    return "other"


def dispatch(op: str, fn: Callable, *args, **kwargs):
    """The device dispatch seam — every guarded call funnels through
    this trivial function so seeded fault injection has exactly one
    surface to monkeypatch (utils/faults.install_device_faults). `op`
    names the call site (fold/spill/staged/micro/extract/sets/import/
    grow/probe/query) for per-kind fault scripting."""
    return fn(*args, **kwargs)


class DeviceGuard:
    """Per-worker breaker over the guarded device path."""

    def __init__(self, streak_limit: int = DEFAULT_STREAK_LIMIT,
                 probe_interval_s: float = DEFAULT_PROBE_INTERVAL_S,
                 enabled: bool = True,
                 clock: Callable[[], float] = time.monotonic):
        self.enabled = enabled
        self.streak_limit = max(1, int(streak_limit))
        self.probe_interval_s = float(probe_interval_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._streak = 0
        self._quarantined = False
        self._trip_reason: Optional[str] = None
        self._last_probe_t: Optional[float] = None
        self._counters: dict[str, int] = {}
        # last classified fault, for the governor's panic verdict
        self.last_fault: Optional[str] = None

    # -- state reads ------------------------------------------------------

    @property
    def quarantined(self) -> bool:
        return self._quarantined

    @property
    def trip_reason(self) -> Optional[str]:
        return self._trip_reason

    def counters(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def _bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def bump(self, key: str, n: int = 1) -> None:
        """Public counter hook for guard-adjacent events that happen
        outside call() — e.g. the HBM valve's grow-OOM degradation."""
        self._bump(key, n)

    # -- the guarded call -------------------------------------------------

    def call(self, op: str, fn: Callable, *args, retryable: bool = False,
             **kwargs):
        """Run one device operation under the guard.

        retryable=True only at call sites whose operands are NOT donated
        (extract, set inserts, query evals, allocation pre-flights): a
        transient fault there retries once against the same still-valid
        inputs. Donating folds must not retry — their inputs may already
        be invalidated — so their faults surface immediately and the
        worker replays the retained HOST inputs through the fallback
        engine instead (the no-epoch-lost contract).
        """
        if not self.enabled:
            return fn(*args, **kwargs)
        try:
            out = dispatch(op, fn, *args, **kwargs)
        except Exception as exc:
            kind = classify(exc)
            if kind is None:
                raise
            self._note_fault(op, kind)
            if retryable and not self._quarantined:
                self._bump("device.fault.retries")
                try:
                    out = dispatch(op, fn, *args, **kwargs)
                except Exception as exc2:
                    kind2 = classify(exc2)
                    if kind2 is None:
                        raise
                    self._note_fault(op, kind2)
                    raise DeviceFaultError(kind2, op, exc2) from exc2
                self._bump("device.fault.retry_success")
                self._note_success()
                return out
            raise DeviceFaultError(kind, op, exc) from exc
        self._note_success()
        return out

    def _note_fault(self, op: str, kind: str) -> None:
        with self._lock:
            self._counters[f"device.fault.{kind}"] = (
                self._counters.get(f"device.fault.{kind}", 0) + 1)
            self.last_fault = f"{kind}:{op}"
            self._streak += 1
            tripped = (not self._quarantined
                       and self._streak >= self.streak_limit)
            if tripped:
                self._quarantined = True
                self._trip_reason = (
                    f"{self._streak} consecutive device faults,"
                    f" last [{kind}] in {op}")
                self._counters["device.guard.trips"] = (
                    self._counters.get("device.guard.trips", 0) + 1)
                # first probe waits a full interval — the device just
                # proved itself unhealthy
                self._last_probe_t = self._clock()
        if tripped:
            log.error("device breaker OPEN: %s — failing over to host"
                      " engine", self._trip_reason)

    def _note_success(self) -> None:
        # lock-free fast path: this runs after EVERY successful device
        # dispatch, so the healthy path must not pay a lock round trip.
        # The unlocked read is safe — _streak only matters as "nonzero
        # after a fault", and faults serialize through _note_fault's
        # locked section before the next success can observe them.
        if self._streak:
            with self._lock:
                self._streak = 0

    # -- explicit breaker control ----------------------------------------

    def trip(self, reason: str) -> None:
        """Force the breaker open (used when a single fault is already
        proof the device path can't continue, e.g. OOM on pool growth
        after the pre-flight — waiting out a streak would just fault
        the same grow N more times)."""
        with self._lock:
            if self._quarantined:
                return
            self._quarantined = True
            self._trip_reason = reason
            self._counters["device.guard.trips"] = (
                self._counters.get("device.guard.trips", 0) + 1)
            self._last_probe_t = self._clock()
        log.error("device breaker OPEN: %s — failing over to host engine",
                  reason)

    def probe_due(self, now: Optional[float] = None) -> bool:
        """Half-open check: quarantined and a probe interval has passed
        since the trip / last failed probe."""
        with self._lock:
            if not self._quarantined:
                return False
            now = self._clock() if now is None else now
            return (self._last_probe_t is None
                    or now - self._last_probe_t >= self.probe_interval_s)

    def note_probe(self, ok: bool) -> None:
        with self._lock:
            self._counters["device.guard.probes"] = (
                self._counters.get("device.guard.probes", 0) + 1)
            if not ok:
                self._counters["device.guard.probe_failures"] = (
                    self._counters.get("device.guard.probe_failures", 0) + 1)
                self._last_probe_t = self._clock()

    def readmit(self) -> None:
        with self._lock:
            if not self._quarantined:
                return
            self._quarantined = False
            self._trip_reason = None
            self._streak = 0
            self._last_probe_t = None
            self._counters["device.guard.readmissions"] = (
                self._counters.get("device.guard.readmissions", 0) + 1)
        log.warning("device breaker CLOSED: probe succeeded, device path"
                    " re-admitted")
