"""Mergeable heavy-hitter sketches on TPU: count-min + space-saving top-k.

Semantics spec: Cormode & Muthukrishnan's count-min sketch (2005) and
Metwally et al.'s space-saving top-k (2005) — the two classic mergeable
heavy-hitter summaries. The reference has no analog (its only cardinality
defense is coarse worker shedding); this module is the device half of the
per-tenant QoS layer (core/tenancy.py holds the budgets it informs).

Design, mirroring ops/hll.py:

* A pool of T per-tenant sketches is one dense `int32[T, D, W]` counter
  array (depth D rows of width W each). int32 — NOT float — so the
  scatter-add is order-invariant and chunked inserts under the PR 1 pow2
  ladder are bit-identical to a single shot (f32 accumulation would not
  commute). W is required to be a power of two so the column index is a
  mask, and D·W at the defaults (4×2048 = 32 KiB/tenant) stays trivially
  small next to the t-digest and HLL pools.

* Keys are hashed host-side (strings never touch the device): one
  fmix64(fnv1a64) digest per key splits into D column indices by classic
  double hashing — `col_d = (h1 + d·h2) mod W` with h2 forced odd so the
  probe sequence covers the row for any pow2 W. See `split_hashes`.

* insert = one flattened `scatter-add` per batch over the whole pool
  (duplicates allowed — adds commute); cross-epoch / cross-host merge =
  elementwise `+` (the associative reduce, same shape as hll.merge's
  maximum); query = min over D of the addressed counters, the classic CMS
  point estimate (overestimates by at most ε·N with probability 1-δ,
  ε = e/W, δ = e^-D — what tests/test_heavyhitter.py asserts).

* The top-k half is host-side: `SpaceSavingTopK`, a small mergeable
  stream-summary fed per flush from the already-folded per-row counts.
  It never touches the device — k is tiny (default 8) and the candidate
  stream is one entry per live series per interval, not per sample.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from veneur_tpu.utils.hashing import fmix64, fnv1a_64

DEFAULT_DEPTH = 4
DEFAULT_WIDTH = 2048
DEFAULT_TOPK = 8


def init_pool(
    num_tenants: int,
    depth: int = DEFAULT_DEPTH,
    width: int = DEFAULT_WIDTH,
) -> jax.Array:
    if width & (width - 1):
        raise ValueError(f"count-min width must be a power of two, got {width}")
    return jnp.zeros((num_tenants, depth, width), dtype=jnp.int32)


def hash_keys(keys: list[str]) -> np.ndarray:
    """One 64-bit digest per key, host-side: fmix64(fnv1a64(utf-8)).

    fmix64 on top of fnv1a matches the ring's hashing idiom
    (distributed/ring.py) and breaks fnv's low-bit correlation before the
    double-hash split below.
    """
    out = np.empty(len(keys), dtype=np.uint64)
    for i, k in enumerate(keys):
        out[i] = fmix64(fnv1a_64(k.encode("utf-8")))
    return out


def split_hashes(
    hashes: np.ndarray,
    depth: int = DEFAULT_DEPTH,
    width: int = DEFAULT_WIDTH,
) -> np.ndarray:
    """64-bit digests → i32[D, N] column indices via double hashing.

    h1 = low 32 bits, h2 = high 32 bits forced odd (odd stride is coprime
    with any pow2 width, so the D probes are distinct mod W for D ≤ W).
    """
    h = hashes.astype(np.uint64)
    h1 = (h & np.uint64(0xFFFFFFFF)).astype(np.int64)
    h2 = ((h >> np.uint64(32)) | np.uint64(1)).astype(np.int64)
    d = np.arange(depth, dtype=np.int64)[:, None]
    return ((h1[None, :] + d * h2[None, :]) & (width - 1)).astype(np.int32)


@jax.jit
def insert_batch(
    pool: jax.Array,
    rows: jax.Array,
    col_idx: jax.Array,
    counts: jax.Array,
) -> jax.Array:
    """Scatter-add a batch of (tenant row, key columns, count) into the pool.

    rows: i32[N] tenant sketch row per sample; col_idx: i32[D, N] from
    `split_hashes`; counts: i32[N] (padding: count 0 — add is a no-op).
    Integer adds commute, so duplicate slots and any chunking of the batch
    produce bit-identical pools (pinned by tests/test_heavyhitter.py).
    """
    t, d, w = pool.shape
    flat = (rows[None, :] * d + jnp.arange(d, dtype=jnp.int32)[:, None]) * w \
        + col_idx
    vals = jnp.broadcast_to(counts[None, :], col_idx.shape)
    out = pool.reshape(-1).at[flat.reshape(-1)].add(
        vals.reshape(-1).astype(pool.dtype), mode="drop")
    return out.reshape(t, d, w)


def insert_chunked(
    pool: jax.Array,
    rows: np.ndarray,
    col_idx: np.ndarray,
    counts: np.ndarray,
    chunk: int,
) -> jax.Array:
    """Feed a large batch through `insert_batch` in fixed-size chunks.

    The tail chunk is zero-padded to `chunk` (count 0 → no-op) so XLA only
    ever sees one batch shape per chunk size — the same compile-cache
    discipline as the PR 1 pow2 extract ladder. Bit-identical to a single
    `insert_batch` over the whole batch because int32 adds commute.
    """
    n = len(counts)
    for lo in range(0, n, chunk):
        hi = min(lo + chunk, n)
        r = np.zeros(chunk, dtype=np.int32)
        c = np.zeros((col_idx.shape[0], chunk), dtype=np.int32)
        v = np.zeros(chunk, dtype=np.int32)
        r[: hi - lo] = rows[lo:hi]
        c[:, : hi - lo] = col_idx[:, lo:hi]
        v[: hi - lo] = counts[lo:hi]
        pool = insert_batch(pool, jnp.asarray(r), jnp.asarray(c),
                            jnp.asarray(v))
    return pool


@jax.jit
def merge(a: jax.Array, b: jax.Array) -> jax.Array:
    """Counter-wise add — the associative cross-epoch/cross-host reduce."""
    return a + b


@jax.jit
def query(pool: jax.Array, rows: jax.Array, col_idx: jax.Array) -> jax.Array:
    """CMS point estimate per sample: min over depth of the addressed
    counters. i32[T,D,W] × i32[N] × i32[D,N] → i32[N]."""
    d = pool.shape[1]
    picked = pool[rows[None, :], jnp.arange(d, dtype=jnp.int32)[:, None],
                  col_idx]
    return jnp.min(picked, axis=0)


@functools.partial(jax.jit, static_argnames=())
def tenant_totals(pool: jax.Array) -> jax.Array:
    """Total inserted count per tenant row: any single depth row sums to
    the exact insert total (every insert adds `count` to each depth)."""
    return jnp.sum(pool[:, 0, :], axis=-1)


# ---------------------------------------------------------------------------
# Fenced reads (the live query path, veneur_tpu/query/)
#
# `query` / `tenant_totals` above were written for the flush path, where
# the caller owns the pool lifecycle. The entry points below are for
# reads OUTSIDE the flush — they take the pool reference captured at the
# epoch fence, hash keys host-side, run only the pure (non-donating)
# jitted programs, and read the result back to host. Nothing here can
# mutate pool state: every mutation in this module goes through
# insert_batch/insert_chunked, which RETURN new arrays rather than
# writing in place — pinned by the bit-identity regression in
# tests/test_query.py.


def read_query(pool: jax.Array, tenant_row: int,
               keys: list[str]) -> np.ndarray:
    """Fenced CMS point estimates for `keys` against one tenant's sketch
    row: i64[len(keys)], pool state untouched."""
    if not keys:
        return np.zeros(0, dtype=np.int64)
    _t, d, w = pool.shape
    rows = np.full(len(keys), int(tenant_row), dtype=np.int32)
    cols = split_hashes(hash_keys(keys), d, w)
    est = query(pool, jnp.asarray(rows), jnp.asarray(cols))
    return np.asarray(est).astype(np.int64)


def read_totals(pool: jax.Array) -> np.ndarray:
    """Fenced per-tenant exact insert totals: i64[T], pool untouched."""
    return np.asarray(tenant_totals(pool)).astype(np.int64)


# ---------------------------------------------------------------------------
# Host-side mergeable top-k (space-saving / stream-summary)


class SpaceSavingTopK:
    """Metwally-style space-saving summary over (key, count) offers.

    Holds at most ``capacity`` keys. A new key arriving into a full summary
    evicts the current minimum and inherits its count as error bound —
    the classic guarantee: stored_count - error <= true_count <=
    stored_count, and any key with true count > min_count is present.

    ``merge`` is the standard summary merge: counts add for shared keys;
    a key present on one side only is credited the other side's min-count
    as its possible undercount (added to the error bound, not the count),
    then the union is re-truncated to capacity. Merge is commutative in
    the reported counts (tests pin top-k stability under merge).
    """

    __slots__ = ("capacity", "counts", "errors")

    def __init__(self, capacity: int = DEFAULT_TOPK):
        if capacity < 1:
            raise ValueError("top-k capacity must be >= 1")
        self.capacity = capacity
        self.counts: dict[str, int] = {}
        self.errors: dict[str, int] = {}

    def _min_key(self) -> str:
        return min(self.counts, key=lambda k: (self.counts[k], k))

    def offer(self, key: str, count: int = 1) -> None:
        if count <= 0:
            return
        if key in self.counts:
            self.counts[key] += count
            return
        if len(self.counts) < self.capacity:
            self.counts[key] = count
            self.errors[key] = 0
            return
        victim = self._min_key()
        floor = self.counts.pop(victim)
        self.errors.pop(victim)
        self.counts[key] = floor + count
        self.errors[key] = floor

    def merge(self, other: "SpaceSavingTopK") -> None:
        if not other.counts:
            return
        self_floor = min(self.counts.values()) if (
            len(self.counts) >= self.capacity) else 0
        other_floor = min(other.counts.values()) if (
            len(other.counts) >= other.capacity) else 0
        merged_counts: dict[str, int] = {}
        merged_errors: dict[str, int] = {}
        for key in set(self.counts) | set(other.counts):
            a = self.counts.get(key)
            b = other.counts.get(key)
            if a is not None and b is not None:
                merged_counts[key] = a + b
                merged_errors[key] = self.errors[key] + other.errors[key]
            elif a is not None:
                merged_counts[key] = a + other_floor
                merged_errors[key] = self.errors[key] + other_floor
            else:
                merged_counts[key] = b + self_floor
                merged_errors[key] = other.errors[key] + self_floor
        keep = sorted(merged_counts, key=lambda k: (-merged_counts[k], k))
        keep = keep[: self.capacity]
        self.counts = {k: merged_counts[k] for k in keep}
        self.errors = {k: merged_errors[k] for k in keep}

    def items(self) -> list[tuple[str, int, int]]:
        """(key, count, error) descending by count, ties by key — the
        deterministic order telemetry and tests rely on."""
        return [
            (k, self.counts[k], self.errors[k])
            for k in sorted(self.counts, key=lambda k: (-self.counts[k], k))
        ]
