"""Fused prefix/segmented scans for the t-digest ingest path.

ops/tdigest.add_batch needs, over the (row, value)-sorted sample stream:

* three plain inclusive prefix sums — weight, value*weight,
  weight/value,
* a row-segmented inclusive prefix sum of weight (restarting at row
  changes), and
* the same segmented sum taken from the row's other end (the suffix),
  which yields each sample's row total.

As separate XLA ops these are five multi-pass scans, each re-reading
its [N] inputs from HBM (segments.segmented_cumsum alone is ~7
shift+select sweeps). This module computes all of them in TWO linear
HBM passes — one forward, one reverse — as Pallas TPU kernels: a grid
of row-major [R, 128] tiles walked sequentially (TPU grids are
sequential), lane-level scans done as lower-triangular matmuls (MXU)
and log-step shift+max sweeps, with the cross-tile running state
carried in SMEM scratch.

Correctness is pinned against the XLA formulations in
tests/test_pallas_scan.py (interpret mode off-TPU); add_batch switches
to this path on TPU via VENEUR_FUSED_SCANS (see ops/tdigest.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
DEFAULT_ROWS = 64  # tile = [64, 128] = 8192 elements

_NEG = -3.0e38  # "-inf" stand-in that survives f32 arithmetic


def _tril(n: int) -> jnp.ndarray:
    col = jax.lax.broadcasted_iota(jnp.float32, (n, n), 0)
    row = jax.lax.broadcasted_iota(jnp.float32, (n, n), 1)
    return (col <= row).astype(jnp.float32)


def _lane_cumsum(x, tril):
    """Inclusive cumsum along the 128-lane axis via MXU matmul."""
    return jnp.dot(x, tril, preferred_element_type=jnp.float32)


def _lane_cummax(x):
    """Inclusive running max along the lane axis (log2(128) = 7 steps)."""
    r, l = x.shape
    shift = 1
    while shift < l:
        shifted = jnp.pad(x, ((0, 0), (shift, 0)),
                          constant_values=_NEG)[:, :l]
        x = jnp.maximum(x, shifted)
        shift *= 2
    return x


def _row_exclusive(x_last, neutral, combine):
    """Exclusive scan down the sublane axis of a [R, 1] column via
    log-step shifts (R is small: 64)."""
    r = x_last.shape[0]
    inc = x_last
    shift = 1
    while shift < r:
        shifted = jnp.pad(inc, ((shift, 0), (0, 0)),
                          constant_values=neutral)[:r]
        inc = combine(inc, shifted)
        shift *= 2
    # exclusive = inclusive shifted down one row
    return jnp.pad(inc, ((1, 0), (0, 0)), constant_values=neutral)[:r]


def _scan_fwd_kernel(rows_ref, w_ref, vw_ref, recip_ref,
                     cw_ref, cvw_ref, crecip_ref, seg_ref,
                     carry_ref, rowcarry_ref):
    """One [R, 128] tile of the forward pass.

    carry_ref: SMEM f32[4] = running (w, vw, recip, seg) totals.
    rowcarry_ref: SMEM i32[1] = row id of the previous element.
    """
    step = pl.program_id(0)
    rows = rows_ref[...]
    w = w_ref[...]
    vw = vw_ref[...]
    recip = recip_ref[...]
    r, l = w.shape
    tril = _tril(l)

    @pl.when(step == 0)
    def _init():
        carry_ref[0] = 0.0
        carry_ref[1] = 0.0
        carry_ref[2] = 0.0
        carry_ref[3] = 0.0
        rowcarry_ref[0] = rows[0, 0]  # element -1 joins the first run

    # --- plain prefix sums: lane cumsum + exclusive row offsets + carry
    cw_l = _lane_cumsum(w, tril)
    cvw_l = _lane_cumsum(vw, tril)
    crec_l = _lane_cumsum(recip, tril)

    def _tot(c):  # [R, 1] per-tile-row totals
        return c[:, l - 1:l]

    add = lambda a, b: a + b  # noqa: E731
    cw = cw_l + _row_exclusive(_tot(cw_l), 0.0, add) + carry_ref[0]
    cvw = cvw_l + _row_exclusive(_tot(cvw_l), 0.0, add) + carry_ref[1]
    crec = crec_l + _row_exclusive(_tot(crec_l), 0.0, add) + carry_ref[2]
    cw_ref[...] = cw
    cvw_ref[...] = cvw
    crecip_ref[...] = crec

    # --- segmented prefix sum of w, restarting at row changes ---------
    # previous element's row id, across the flattened row-major order
    prev_last = jnp.concatenate(
        [jnp.full((1, 1), rowcarry_ref[0], rows.dtype), rows[:-1, l - 1:l]],
        axis=0)
    prev = jnp.concatenate([prev_last, rows[:, :l - 1]], axis=1)
    starts = rows != prev

    # within each tile row: value of cw_excl at the latest start
    cw_excl = cw - w
    marked = jnp.where(starts, cw_excl, _NEG)
    lane_start = _lane_cummax(marked)  # [R, L]
    # carry the latest start value down tile rows (rows with no start
    # pass the previous rows' value through)
    row_best = lane_start[:, l - 1:l]  # [R, 1]
    row_carry = _row_exclusive(row_best, _NEG, jnp.maximum)
    start_val = jnp.maximum(lane_start, row_carry)
    # elements before ANY start in the whole array continue the carry run
    base = jnp.where(start_val > _NEG / 2, start_val,
                     carry_ref[0] - carry_ref[3])
    seg = cw - base
    seg_ref[...] = seg

    carry_ref[0] = cw[r - 1, l - 1]
    carry_ref[1] = cvw[r - 1, l - 1]
    carry_ref[2] = crec[r - 1, l - 1]
    carry_ref[3] = seg[r - 1, l - 1]
    rowcarry_ref[0] = rows[r - 1, l - 1]


def _scan_rev_kernel(rows_ref, w_ref, suf_ref, carry_ref, rowcarry_ref):
    """One tile of the reverse pass: row-segmented suffix sum of w.
    The grid walks tiles back to front; within a tile the scan runs
    right to left (implemented by flipping, scanning, flipping back)."""
    step = pl.program_id(0)
    rows = rows_ref[...]
    w = w_ref[...]
    r, l = w.shape

    @pl.when(step == 0)
    def _init():
        carry_ref[0] = 0.0
        rowcarry_ref[0] = rows[r - 1, l - 1]

    # flip both axes: suffix scan becomes prefix scan on the flipped tile
    fr = rows[::-1, ::-1]
    fw = w[::-1, ::-1]
    tril = _tril(l)
    cw_l = _lane_cumsum(fw, tril)
    add = lambda a, b: a + b  # noqa: E731
    cw = cw_l + _row_exclusive(cw_l[:, l - 1:l], 0.0, add)

    prev_last = jnp.concatenate(
        [jnp.full((1, 1), rowcarry_ref[0], fr.dtype), fr[:-1, l - 1:l]],
        axis=0)
    prev = jnp.concatenate([prev_last, fr[:, :l - 1]], axis=1)
    starts = fr != prev

    cw_excl = cw - fw
    marked = jnp.where(starts, cw_excl, _NEG)
    lane_start = _lane_cummax(marked)
    row_best = lane_start[:, l - 1:l]
    row_carry = _row_exclusive(row_best, _NEG, jnp.maximum)
    start_val = jnp.maximum(lane_start, row_carry)
    base = jnp.where(start_val > _NEG / 2, start_val, -carry_ref[0])
    seg = cw - base
    suf_ref[...] = seg[::-1, ::-1]

    carry_ref[0] = seg[r - 1, l - 1]
    rowcarry_ref[0] = fr[r - 1, l - 1]


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def fused_prefix_scans(srows, svals, sw, block_rows: int = DEFAULT_ROWS,
                       interpret: bool = False):
    """All ingest scans in two HBM passes.

    srows: i32[N] sorted row ids; svals/sw: f32[N] (value, weight) in
    the same order. N must be a multiple of 128; the caller pads (pad
    with w=0 and the last row id, which extends the final run
    harmlessly).

    Returns (cw, cvw, crecip, seg, suffix): all f32[N], inclusive;
    `seg` restarts at row changes, `suffix` is the same from the row's
    other end (so row_total = seg + suffix - sw).
    """
    n = srows.shape[0]
    assert n % LANES == 0, "caller pads to a lane multiple"
    rows_needed = n // LANES
    while rows_needed % block_rows:
        block_rows //= 2
    grid = (rows_needed // block_rows,)
    shape2 = (rows_needed, LANES)
    rows2 = srows.reshape(shape2)
    vw2 = (jnp.where(sw > 0, svals * sw, 0.0)).reshape(shape2)
    recip2 = jnp.where(sw > 0, sw / svals, 0.0).reshape(shape2)
    w2 = sw.reshape(shape2)

    spec = pl.BlockSpec((block_rows, LANES), lambda i: (i, 0))
    out4 = [jax.ShapeDtypeStruct(shape2, jnp.float32)] * 4
    cw, cvw, crecip, seg = pl.pallas_call(
        _scan_fwd_kernel,
        grid=grid,
        in_specs=[spec, spec, spec, spec],
        out_specs=[spec, spec, spec, spec],
        out_shape=out4,
        scratch_shapes=[pltpu.SMEM((4,), jnp.float32),
                        pltpu.SMEM((1,), jnp.int32)],
        interpret=interpret,
    )(rows2, w2, vw2, recip2)

    nblocks = grid[0]
    rev_spec = pl.BlockSpec((block_rows, LANES),
                            lambda i, nb=nblocks: (nb - 1 - i, 0))
    (suffix,) = pl.pallas_call(
        _scan_rev_kernel,
        grid=grid,
        in_specs=[rev_spec, rev_spec],
        out_specs=[rev_spec],
        out_shape=[jax.ShapeDtypeStruct(shape2, jnp.float32)],
        scratch_shapes=[pltpu.SMEM((1,), jnp.float32),
                        pltpu.SMEM((1,), jnp.int32)],
        interpret=interpret,
    )(rows2, w2)

    flat = lambda a: a.reshape(-1)  # noqa: E731
    return (flat(cw), flat(cvw), flat(crecip), flat(seg), flat(suffix))


def supported() -> bool:
    return jax.default_backend() == "tpu"
