"""veneur_tpu: a TPU-native observability-aggregation framework.

A brand-new framework with the capabilities of Veneur (the reference
implementation lives at github.com/stripe/veneur): a DogStatsD/SSF-compatible
aggregation server whose numeric core — per-flush t-digest histogram
compression, HyperLogLog set cardinality, and cross-host global sketch
merging — executes as batched JAX/XLA programs on TPU instead of per-series
CPU loops.

Package layout:
  ops/          batched sketch kernels (t-digest, HLL, scalar reductions)
  core/         metric types, parser-facing model, series directory,
                device worker, flusher, server
  protocol/     DogStatsD and SSF wire parsing
  ssf/          SSF sample/span schema
  distributed/  forwarding, import server, proxy, consistent hashing,
                discovery, device-mesh collectives
  sinks/        egress sinks (datadog, prometheus, kafka, ...)
  trace/        tracing client library
  cli/          command-line entry points
  utils/        hashing and helpers
"""

__version__ = "0.1.0"
