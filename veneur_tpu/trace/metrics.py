"""Metrics-only reporting through the trace client.

Parity: reference trace/metrics/client.go:21-51 — ReportOne/ReportBatch
wrap SSF samples in a metrics-only span (no trace identity) and record it;
used for all internal self-telemetry that flows through SSF.
"""

from __future__ import annotations

from veneur_tpu import ssf
from veneur_tpu.trace.client import Client, ErrWouldBlock


def report_batch(client: Client, samples: list[ssf.SSFSample]) -> bool:
    """Submit samples on a metrics-only span; returns False if dropped."""
    if client is None or not samples:
        return False
    span = ssf.SSFSpan(metrics=list(samples))
    try:
        client.record(span)
    except ErrWouldBlock:
        return False
    return True


def report_one(client: Client, sample: ssf.SSFSample) -> bool:
    return report_batch(client, [sample])


class Samples:
    """Accumulate samples across a code path, then report once
    (reference ssf.Samples + metrics.Report pattern)."""

    def __init__(self) -> None:
        self.samples: list[ssf.SSFSample] = []

    def add(self, *samples: ssf.SSFSample) -> None:
        self.samples.extend(samples)

    def report(self, client: Client) -> bool:
        return report_batch(client, self.samples)
