"""Span model and in-process tracer.

Parity: reference trace/trace.go:52-95 (Trace/Span model: ids, parent
lineage, error flag, attached samples) with the inject/extract HTTP-header
propagation of trace/opentracing.go (TraceID/SpanID/ParentID headers).
"""

from __future__ import annotations

import random
import time
from typing import Optional

from veneur_tpu import ssf

# HTTP propagation headers (the reference's opentracing text-map carrier
# uses these names for cross-hop propagation, handlers_global.go:81)
HEADER_TRACE_ID = "Trace-Id"
HEADER_SPAN_ID = "Span-Id"
HEADER_PARENT_ID = "Parent-Span-Id"


def _new_id() -> int:
    return random.getrandbits(62) + 1


class Span:
    """One timed operation; finishes into an SSFSpan."""

    def __init__(self, name: str, service: str = "",
                 trace_id: Optional[int] = None,
                 parent_id: Optional[int] = None,
                 indicator: bool = False,
                 tags: Optional[dict[str, str]] = None) -> None:
        self.id = _new_id()
        self.trace_id = trace_id or self.id
        self.parent_id = parent_id or 0
        self.name = name
        self.service = service
        self.indicator = indicator
        self.tags = dict(tags or {})
        self.error = False
        self.start_ns = time.time_ns()
        self.end_ns = 0
        self.samples: list[ssf.SSFSample] = []

    def child(self, name: str, **kw) -> "Span":
        return Span(
            name, service=self.service, trace_id=self.trace_id,
            parent_id=self.id, **kw,
        )

    def add(self, *samples: ssf.SSFSample) -> None:
        self.samples.extend(samples)

    def set_error(self) -> None:
        self.error = True

    def finish(self) -> ssf.SSFSpan:
        self.end_ns = time.time_ns()
        return ssf.SSFSpan(
            trace_id=self.trace_id,
            id=self.id,
            parent_id=self.parent_id,
            start_timestamp=self.start_ns,
            end_timestamp=self.end_ns,
            error=self.error,
            service=self.service,
            tags=dict(self.tags),
            indicator=self.indicator,
            name=self.name,
            metrics=list(self.samples),
        )

    def client_finish(self, client=None) -> ssf.SSFSpan:
        """Finish and best-effort record to a trace client
        (reference Span.ClientFinish)."""
        span = self.finish()
        if client is not None:
            try:
                client.record(span)
            except Exception:
                pass
        return span

    # -- context manager ----------------------------------------------------

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.error = True
        self.finish()

    # -- propagation --------------------------------------------------------

    def inject_headers(self, headers: dict[str, str]) -> None:
        headers[HEADER_TRACE_ID] = str(self.trace_id)
        headers[HEADER_SPAN_ID] = str(self.id)
        if self.parent_id:
            headers[HEADER_PARENT_ID] = str(self.parent_id)


def start_span(name: str, service: str = "", **kw) -> Span:
    return Span(name, service=service, **kw)


def extract_request_child(headers: dict[str, str], name: str,
                          service: str = "") -> Span:
    """Create a child span continuing a trace from HTTP headers
    (reference ExtractRequestChild, handlers_global.go:81)."""
    trace_id = int(headers.get(HEADER_TRACE_ID, 0) or 0)
    parent_id = int(headers.get(HEADER_SPAN_ID, 0) or 0)
    span = Span(name, service=service)
    if trace_id:
        span.trace_id = trace_id
        span.parent_id = parent_id
    return span
