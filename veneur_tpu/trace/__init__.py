"""Tracing client library.

Parity: reference trace/ — the backpressure-managed span client
(trace/client.go:56-575), network backends (trace/backend.go:46-240), span
model (trace/trace.go), and the metrics helpers (trace/metrics/client.go).
"""

from veneur_tpu.trace.client import (  # noqa: F401
    Client,
    ErrWouldBlock,
    NoOpBackend,
    ChannelBackend,
    UDPBackend,
    UnixBackend,
    neutralize_client,
)
from veneur_tpu.trace.span import Span, start_span  # noqa: F401
