"""Span-submission client with backpressure.

Parity: reference trace/client.go:56-575 — Record pushes spans into a
bounded channel and DROPS (ErrWouldBlock) instead of blocking when the
pipeline is saturated; N backend threads drain the channel to the network.
Backends (trace/backend.go:46-240): UDP packet backend (one datagram per
span) and buffered unix-stream backend (framed SSF, flushed on demand),
both reconnecting with linear backoff and discarding the poison span.
"""

from __future__ import annotations

import queue
import socket
import threading
import time
from typing import Optional

from veneur_tpu import ssf
from veneur_tpu.protocol import ssf_wire


class ErrWouldBlock(Exception):
    """The client's buffer is full; the span was dropped."""


class Backend:
    def send(self, span: ssf.SSFSpan) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class NoOpBackend(Backend):
    def send(self, span: ssf.SSFSpan) -> None:
        pass


class ChannelBackend(Backend):
    """Delivers spans to an in-process queue (reference trace/testbackend
    and NewChannelClient, used for a server's internal telemetry loop)."""

    def __init__(self, out: "queue.Queue[ssf.SSFSpan]",
                 send_error: Optional[Exception] = None) -> None:
        self.out = out
        self.send_error = send_error

    def send(self, span: ssf.SSFSpan) -> None:
        if self.send_error is not None:
            raise self.send_error
        self.out.put(span)


class _ReconnectingBackend(Backend):
    """Shared reconnect-with-linear-backoff behavior
    (reference trace/backend.go:71-91)."""

    def __init__(self, backoff_s: float = 0.2, max_backoff_s: float = 5.0
                 ) -> None:
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self._failures = 0

    def _connect(self):
        raise NotImplementedError

    def _ensure_connected(self):
        while True:
            try:
                self._connect()
                self._failures = 0
                return
            except OSError:
                self._failures += 1
                delay = min(self.backoff_s * self._failures,
                            self.max_backoff_s)
                time.sleep(delay)


class UDPBackend(Backend):
    """One datagram per span; no connection state to speak of."""

    def __init__(self, address: tuple[str, int]) -> None:
        self.address = address
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def send(self, span: ssf.SSFSpan) -> None:
        self.sock.sendto(ssf_wire.encode_datagram(span), self.address)

    def close(self) -> None:
        self.sock.close()


class UnixBackend(_ReconnectingBackend):
    """Buffered framed-SSF unix-stream backend; a failed write discards
    the poison span and reconnects (reference trace/backend.go:150-240)."""

    def __init__(self, path: str, **kw) -> None:
        super().__init__(**kw)
        self.path = path
        self._sock: Optional[socket.socket] = None
        self._file = None

    def _connect(self):
        if self._sock is not None:
            return
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.connect(self.path)
        self._sock = sock
        self._file = sock.makefile("wb")

    def send(self, span: ssf.SSFSpan) -> None:
        self._ensure_connected()
        try:
            ssf_wire.write_ssf(self._file, span)
        except OSError:
            # discard the poison span, force a reconnect for the next one
            self.close()
            self._ensure_connected()

    def flush(self) -> None:
        if self._file is not None:
            try:
                self._file.flush()
            except OSError:
                self.close()

    def close(self) -> None:
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._file = None


class Client:
    """Buffered span pump: Record → channel → backend threads."""

    def __init__(self, backend: Backend, capacity: int = 1024,
                 num_backends: int = 1) -> None:
        self.backend = backend
        self.chan: "queue.Queue[Optional[ssf.SSFSpan]]" = queue.Queue(capacity)
        self.records_dropped = 0
        self.records_sent = 0
        self._threads = []
        self._closed = False
        for i in range(num_backends):
            t = threading.Thread(target=self._drain, daemon=True,
                                 name=f"trace-backend-{i}")
            t.start()
            self._threads.append(t)

    def _drain(self) -> None:
        while True:
            span = self.chan.get()
            if span is None:
                return
            try:
                self.backend.send(span)
                self.records_sent += 1
            except Exception:
                self.records_dropped += 1

    def record(self, span: ssf.SSFSpan) -> None:
        """Enqueue a span; raises ErrWouldBlock (after counting the drop)
        when the buffer is full (reference Record, trace/client.go:484-511).
        """
        if self._closed:
            raise ErrWouldBlock("client closed")
        try:
            self.chan.put_nowait(span)
        except queue.Full:
            self.records_dropped += 1
            raise ErrWouldBlock("trace client buffer full") from None

    def flush(self) -> None:
        """Drain-and-flush barrier (reference Flush, trace/client.go:521).
        Waits for the queue to empty, then flushes the backend."""
        deadline = time.time() + 5.0
        while not self.chan.empty() and time.time() < deadline:
            time.sleep(0.005)
        self.backend.flush()

    def close(self) -> None:
        self._closed = True
        for _ in self._threads:
            self.chan.put(None)
        for t in self._threads:
            t.join(timeout=2)
        self.backend.close()


def neutralize_client(client: Client) -> None:
    """Disarm a client so tests produce no telemetry
    (reference NeutralizeClient, trace/client.go:422-427)."""
    client.backend = NoOpBackend()
