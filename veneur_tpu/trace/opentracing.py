"""OpenTracing-compatible Tracer.

Parity: reference trace/opentracing.go:1-659 — Tracer with StartSpan
options (child-of / follows-from references, explicit start time, tags),
Inject/Extract over TextMap, HTTPHeaders and Binary carriers, span-context
baggage, and the multi-format header negotiation the proxy/import HTTP
hops use for cross-hop propagation (handlers_global.go:81,125).

The opentracing-python package is not a dependency; the surface mirrors
its API shapes (format constants, method names) so instrumented code
ports directly, while spans finish into this framework's SSF model
(trace/span.py → ssf.SSFSpan).
"""

from __future__ import annotations

import io
import time
from typing import Iterable, Optional, Union

from veneur_tpu.gen import ssf_pb2
from veneur_tpu.trace.span import Span

# Carrier formats (opentracing.Format analogs)
TEXT_MAP = "text_map"
HTTP_HEADERS = "http_headers"
BINARY = "binary"

RESOURCE_KEY = "resource"

# Reference reserved baggage keys (spanContext.Init): the context's ids
# ride in its baggage under these names.
_TRACE_ID_KEY = "traceid"
_PARENT_ID_KEY = "parentid"
_SPAN_ID_KEY = "spanid"


class HeaderGroup:
    """One supported tracing-header naming scheme
    (reference HeaderFormats, opentracing.go:38-67)."""

    def __init__(self, trace_id: str, span_id: str, hexfmt: bool = False,
                 outgoing: Optional[dict[str, str]] = None) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.hexfmt = hexfmt
        self.outgoing = outgoing or {}


# Tried in order on extract; the Envoy/Lightstep scheme first since an
# Envoy sidecar is most likely the nearest parent (reference comment).
HEADER_FORMATS = [
    HeaderGroup("ot-tracer-traceid", "ot-tracer-spanid", hexfmt=True,
                outgoing={"ot-tracer-sampled": "true"}),
    HeaderGroup("Trace-Id", "Span-Id"),
    HeaderGroup("X-Trace-Id", "X-Span-Id"),
    HeaderGroup("Traceid", "Spanid"),
]

DEFAULT_HEADER_FORMAT = HEADER_FORMATS[0]


class UnsupportedFormatError(ValueError):
    pass


class SpanExtractionError(ValueError):
    pass


class SpanContext:
    """Propagation state of one span: ids + resource + baggage
    (reference spanContext, opentracing.go:126-211)."""

    def __init__(self, trace_id: int = 0, span_id: int = 0,
                 parent_id: int = 0, resource: str = "",
                 baggage: Optional[dict[str, str]] = None) -> None:
        self.baggage = dict(baggage or {})
        self.baggage.setdefault(_TRACE_ID_KEY, str(trace_id))
        self.baggage.setdefault(_SPAN_ID_KEY, str(span_id))
        self.baggage.setdefault(_PARENT_ID_KEY, str(parent_id))
        if resource:
            self.baggage.setdefault(RESOURCE_KEY, resource)

    def _int(self, key: str) -> int:
        try:
            return int(self.baggage.get(key, "0") or "0")
        except ValueError:
            return 0

    @property
    def trace_id(self) -> int:
        return self._int(_TRACE_ID_KEY)

    @property
    def span_id(self) -> int:
        return self._int(_SPAN_ID_KEY)

    @property
    def parent_id(self) -> int:
        return self._int(_PARENT_ID_KEY)

    @property
    def resource(self) -> str:
        return self.baggage.get(RESOURCE_KEY, "")

    def foreach_baggage_item(self, handler) -> None:
        for k, v in self.baggage.items():
            if not handler(k, v):
                return


class OTSpan:
    """OpenTracing-API span wrapping the SSF span model."""

    def __init__(self, tracer: "Tracer", span: Span,
                 resource: str = "") -> None:
        self._tracer = tracer
        self.span = span
        self.resource = resource or span.name
        self._baggage: dict[str, str] = {}
        self._recorded = False

    # -- opentracing.Span surface -------------------------------------------

    def context(self) -> SpanContext:
        return SpanContext(
            trace_id=self.span.trace_id, span_id=self.span.id,
            parent_id=self.span.parent_id, resource=self.resource,
            baggage=dict(self._baggage))

    def tracer(self) -> "Tracer":
        return self._tracer

    def set_operation_name(self, name: str) -> "OTSpan":
        self.span.name = name
        return self

    def set_tag(self, key: str, value) -> "OTSpan":
        # reference stringifies non-string values (opentracing.go:284-304)
        self.span.tags[key] = value if isinstance(value, str) else str(value)
        if key == "name":
            self.span.name = str(value)
        return self

    def set_baggage_item(self, key: str, value: str) -> "OTSpan":
        self._baggage[key] = value
        return self

    def baggage_item(self, key: str) -> str:
        return self._baggage.get(key, "")

    def log_kv(self, *alternating_key_values) -> None:
        """reference LogKV is an intentional no-op pending sink support
        (opentracing.go:317-322)."""

    def set_error(self) -> None:
        self.span.set_error()

    def finish(self, finish_time: Optional[float] = None,
               client=None) -> None:
        if self._recorded:
            return
        self._recorded = True
        out = self.span.finish()
        if finish_time is not None:
            out.end_timestamp = int(finish_time * 1e9)
        cl = client or self._tracer.client
        if cl is not None:
            try:
                cl.record(out)
            except Exception:
                pass

    def __enter__(self) -> "OTSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.set_error()
        self.finish()


def child_of(parent: Union[OTSpan, SpanContext, Span]) -> tuple:
    return ("child_of", parent)


def follows_from(parent: Union[OTSpan, SpanContext, Span]) -> tuple:
    """The reference treats follows-from like child-of
    (opentracing.go:424-427)."""
    return ("follows_from", parent)


def _as_context(ref) -> Optional[SpanContext]:
    if isinstance(ref, SpanContext):
        return ref
    if isinstance(ref, OTSpan):
        return ref.context()
    if isinstance(ref, Span):
        return SpanContext(trace_id=ref.trace_id, span_id=ref.id,
                           parent_id=ref.parent_id)
    return None


class Tracer:
    """reference Tracer (opentracing.go:399-647). `client` is the trace
    client spans record to on finish (None = discard)."""

    def __init__(self, client=None, service: str = "") -> None:
        self.client = client
        self.service = service

    # -- span creation ------------------------------------------------------

    def start_span(self, operation_name: str = "", *,
                   child_of=None,
                   references: Iterable[tuple] = (),
                   start_time: Optional[float] = None,
                   tags: Optional[dict] = None) -> OTSpan:
        refs = list(references)
        if child_of is not None:
            refs.insert(0, ("child_of", child_of))
        parent: Optional[SpanContext] = None
        for _kind, ref in refs:
            ctx = _as_context(ref)
            if ctx is not None:
                parent = ctx
                break
        if parent is None:
            span = Span(operation_name, service=self.service)
            resource = operation_name
        else:
            span = Span(operation_name, service=self.service,
                        trace_id=parent.trace_id or None,
                        parent_id=parent.span_id or None)
            resource = parent.resource or operation_name
        if start_time is not None:
            span.start_ns = int(start_time * 1e9)
        ot = OTSpan(self, span, resource=resource)
        for k, v in (tags or {}).items():
            ot.set_tag(k, v)
        return ot

    # -- inject -------------------------------------------------------------

    def inject(self, span_context: SpanContext, fmt: str, carrier) -> None:
        if not isinstance(span_context, SpanContext):
            raise UnsupportedFormatError("unsupported SpanContext")
        if fmt == BINARY:
            # SSFSpan proto bytes (reference trace.ProtoMarshalTo)
            pb = ssf_pb2.SSFSpan()
            pb.trace_id = span_context.trace_id
            pb.id = span_context.span_id
            pb.parent_id = span_context.parent_id
            if span_context.resource:
                pb.tags[RESOURCE_KEY] = span_context.resource
            carrier.write(pb.SerializeToString())
            return
        if fmt == HTTP_HEADERS:
            base_hex = DEFAULT_HEADER_FORMAT.hexfmt
            sid = span_context.span_id
            tid = span_context.trace_id
            carrier[DEFAULT_HEADER_FORMAT.span_id] = (
                format(sid, "x") if base_hex else str(sid))
            carrier[DEFAULT_HEADER_FORMAT.trace_id] = (
                format(tid, "x") if base_hex else str(tid))
            for k, v in DEFAULT_HEADER_FORMAT.outgoing.items():
                carrier[k] = v
            return
        if fmt == TEXT_MAP or hasattr(carrier, "__setitem__"):
            # text maps carry the whole baggage (ids included)
            for k, v in span_context.baggage.items():
                carrier[k] = v
            return
        raise UnsupportedFormatError(fmt)

    # -- extract ------------------------------------------------------------

    def extract(self, fmt: str, carrier) -> SpanContext:
        if fmt == BINARY:
            data = carrier.read() if hasattr(carrier, "read") else bytes(
                carrier)
            pb = ssf_pb2.SSFSpan()
            pb.ParseFromString(data)
            return SpanContext(trace_id=pb.trace_id, span_id=pb.id,
                               resource=pb.tags.get(RESOURCE_KEY, ""))
        if hasattr(carrier, "items"):
            lowered = {str(k).lower(): str(v) for k, v in carrier.items()}
            trace_id = span_id = 0
            for group in HEADER_FORMATS:
                base = 16 if group.hexfmt else 10
                try:
                    trace_id = int(
                        lowered.get(group.trace_id.lower(), "") or "0", base)
                    span_id = int(
                        lowered.get(group.span_id.lower(), "") or "0", base)
                except ValueError:
                    trace_id = span_id = 0
                if trace_id and span_id:
                    break
            if not trace_id and not span_id:
                raise SpanExtractionError(
                    "no tracing headers found in carrier")
            # the reference restores only ids+resource; text maps here
            # also restore baggage (a compatible superset)
            baggage = lowered if fmt == TEXT_MAP else None
            return SpanContext(trace_id=trace_id, span_id=span_id,
                               resource=lowered.get(RESOURCE_KEY, ""),
                               baggage=baggage)
        raise UnsupportedFormatError(fmt)

    # -- HTTP convenience (the cross-hop propagation surface) ---------------

    def inject_header(self, span_context: SpanContext, headers) -> None:
        """reference InjectHeader (opentracing.go:492-497)."""
        self.inject(span_context, HTTP_HEADERS, headers)

    def extract_request_child(self, resource: str, headers,
                              name: str) -> OTSpan:
        """Continue a trace from incoming HTTP headers
        (reference ExtractRequestChild, opentracing.go:499-523; used by
        the proxy/import handlers, handlers_global.go:81,125)."""
        parent = self.extract(HTTP_HEADERS, headers)
        ot = self.start_span(name, child_of=parent)
        ot.resource = resource
        ot.set_tag(RESOURCE_KEY, resource)
        return ot


GLOBAL_TRACER = Tracer()


def start_span_from_headers(headers, name: str, resource: str = "",
                            tracer: Optional[Tracer] = None
                            ) -> Optional[OTSpan]:
    """Best-effort child-span start for server hops: returns None when the
    request carries no recognizable tracing headers."""
    t = tracer or GLOBAL_TRACER
    try:
        return t.extract_request_child(resource or name, headers, name)
    except (SpanExtractionError, UnsupportedFormatError):
        return None


class traced_server_hop:
    """Context manager for an HTTP handler continuing an incoming trace:
    starts a child span from the request headers (None when untraced),
    marks it errored on exception, finishes it either way. Shared by the
    import and proxy /import handlers (reference ExtractRequestChild
    call sites, handlers_global.go:28-58,60-72)."""

    def __init__(self, headers, name: str, resource: str = "",
                 tracer: Optional[Tracer] = None) -> None:
        self.span = start_span_from_headers(headers, name,
                                            resource=resource, tracer=tracer)

    def __enter__(self) -> Optional[OTSpan]:
        return self.span

    def __exit__(self, exc_type, exc, tb) -> None:
        if self.span is not None:
            if exc_type is not None:
                self.span.set_error()
            self.span.finish()
