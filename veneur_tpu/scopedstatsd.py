"""Scoped statsd client for self-telemetry.

TPU-native equivalent of the reference's ``scopedstatsd/client.go:13-119``:
a DogStatsD client wrapper that force-appends per-metric-type scope tags
(``veneurlocalonly:true`` / ``veneurglobalonly:true``) as configured by
``veneur_metrics_scopes`` (reference config.go / README), so the server's
own metrics are aggregated at the intended tier without write-amplification.

The underlying transport is pluggable: UDP to ``stats_address`` (the
reference points datadog-go at veneur's own listen address), or a loopback
sender that feeds the server's packet handler directly (used for tests and
for zero-copy self-ingestion on the same process).
"""

from __future__ import annotations

import logging
import socket
from typing import Callable, Iterable, Optional

from veneur_tpu.core.config import MetricsScopes

log = logging.getLogger(__name__)

# scope strings accepted in veneur_metrics_scopes (reference ssf.SSFSample_Scope)
SCOPE_LOCAL = "local"
SCOPE_GLOBAL = "global"

_SCOPE_TAG = {
    SCOPE_LOCAL: "veneurlocalonly:true",
    SCOPE_GLOBAL: "veneurglobalonly:true",
}


_INJECT = str.maketrans({"|": "_", "\n": "_"})


def _clean(s: str) -> str:
    """Strip statsd framing bytes from untrusted name/tag content —
    without this, a hostile tag value (e.g. an SSF service name) forges
    extra metric lines in the outgoing stats stream."""
    return s.translate(_INJECT) if ("|" in s or "\n" in s) else s


def _format_line(name: str, value, mtype: str, tags: Iterable[str],
                 rate: float) -> str:
    """Render one DogStatsD line: ``name:value|type[|@rate][|#t1,t2]``."""
    parts = [f"{_clean(name)}:{value}|{mtype}"]
    if rate != 1.0:
        parts.append(f"@{rate}")
    tags = [_clean(t) for t in tags if t]
    if tags:
        parts.append("#" + ",".join(tags))
    return "|".join(parts)


class Sender:
    """Transport for rendered statsd lines."""

    def send(self, line: str) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def close(self) -> None:
        pass


class NullSender(Sender):
    def send(self, line: str) -> None:
        pass


class UDPSender(Sender):
    """Fire-and-forget UDP datagrams to ``stats_address``."""

    def __init__(self, address: str) -> None:
        raw = address
        if address.startswith("udp://"):
            address = address[len("udp://"):]
        host, port = "127.0.0.1", 8125
        try:
            if address.startswith("["):  # [::1]:8125
                host, _, rest = address[1:].partition("]")
                if rest.startswith(":"):
                    port = int(rest[1:])
            elif ":" in address:
                host, _, p = address.rpartition(":")
                port = int(p)
            elif address.isdigit():  # bare port, e.g. "8125"
                port = int(address)
            elif address:
                host = address
            info = socket.getaddrinfo(host, port, socket.AF_UNSPEC,
                                      socket.SOCK_DGRAM)[0]
        except (OSError, ValueError) as e:
            raise ValueError(f"invalid stats_address {raw!r}: {e}") from e
        self._addr = info[4]
        self._sock = socket.socket(info[0], socket.SOCK_DGRAM)

    def send(self, line: str) -> None:
        try:
            self._sock.sendto(line.encode("utf-8"), self._addr)
        except OSError as e:  # self-telemetry is expendable, like the reference
            log.debug("statsd send failed: %s", e)

    def close(self) -> None:
        self._sock.close()


class LoopbackSender(Sender):
    """Feeds lines straight into a packet handler (a ``Server`` on the same
    process), skipping the kernel round-trip the reference pays when it
    points its statsd client at its own UDP listener."""

    def __init__(self, handle_packet: Callable[[bytes], None]) -> None:
        self._handle = handle_packet

    def send(self, line: str) -> None:
        try:
            self._handle(line.encode("utf-8"))
        except Exception as e:
            log.debug("loopback statsd send failed: %s", e)


class CaptureSender(Sender):
    """Test sender that records every rendered line."""

    def __init__(self) -> None:
        self.lines: list[str] = []

    def send(self, line: str) -> None:
        self.lines.append(line)


class ScopedClient:
    """DogStatsD client with per-type scope tags
    (reference scopedstatsd.ScopedClient, scopedstatsd/client.go:40-111)."""

    def __init__(self, sender: Optional[Sender] = None,
                 add_tags: Optional[list[str]] = None,
                 scopes: Optional[MetricsScopes] = None,
                 namespace: str = "") -> None:
        self._sender = sender or NullSender()
        self._add_tags = list(add_tags or [])
        self._scopes = scopes or MetricsScopes()
        self._namespace = namespace

    def _emit(self, name: str, value, mtype: str,
              tags: Optional[list[str]], rate: float, scope: str) -> None:
        name = self._namespace + name
        all_tags = list(tags or []) + self._add_tags
        scope_tag = _SCOPE_TAG.get(scope)
        if scope_tag:
            all_tags.append(scope_tag)
        self._sender.send(_format_line(name, value, mtype, all_tags, rate))

    # the reference Client interface (scopedstatsd/client.go:13-20)
    def gauge(self, name: str, value: float,
              tags: Optional[list[str]] = None, rate: float = 1.0) -> None:
        self._emit(name, value, "g", tags, rate, self._scopes.gauge)

    def count(self, name: str, value: int,
              tags: Optional[list[str]] = None, rate: float = 1.0) -> None:
        self._emit(name, value, "c", tags, rate, self._scopes.counter)

    def incr(self, name: str,
             tags: Optional[list[str]] = None, rate: float = 1.0) -> None:
        self.count(name, 1, tags, rate)

    def histogram(self, name: str, value: float,
                  tags: Optional[list[str]] = None, rate: float = 1.0) -> None:
        self._emit(name, value, "h", tags, rate, self._scopes.histogram)

    def timing(self, name: str, seconds: float,
               tags: Optional[list[str]] = None, rate: float = 1.0) -> None:
        """Reports in milliseconds, like datadog-go's Timing."""
        self._emit(name, seconds * 1000.0, "ms", tags, rate,
                   self._scopes.histogram)

    def time_in_nanoseconds(self, name: str, ns: float,
                            tags: Optional[list[str]] = None,
                            rate: float = 1.0) -> None:
        self._emit(name, ns, "ms", tags, rate, self._scopes.histogram)

    def set(self, name: str, value: str,
            tags: Optional[list[str]] = None, rate: float = 1.0) -> None:
        self._emit(name, value, "s", tags, rate, self._scopes.set)

    def close(self) -> None:
        self._sender.close()


def ensure(client: Optional[ScopedClient]) -> ScopedClient:
    """Nil-safe accessor (reference scopedstatsd.Ensure)."""
    return client if client is not None else ScopedClient()
