"""VMB1: the self-contained columnar metric flush-frame format.

One frame per flushed interval, in the journal's checksummed-record
discipline (VSB1, spans/wire.py): magic, a CRC-32 over the payload, then
the payload — a small header (flush timestamp, hostname) and a list of
sections. Two section kinds:

* ``SECTION_COLUMNAR`` (0) — one ColumnGroup, dense: a local
  first-appearance string table (per-row name then tags, then family
  suffixes), the row metadata table, the family table, and the raw f64
  value / u8 mask planes memcpy'd straight out of the flush arrays. This
  is the zero-copy body the native serializer
  (native/emit.cpp vn_encode_archive_section) builds GIL-free; the
  Python encoder here produces byte-identical sections (pinned by
  tests/test_archive.py).
* ``SECTION_SAMPLES`` (1) — per-sample rows (name, tags, type, value,
  message, hostname) for everything the dense layout can't carry:
  status-check extras, per-row ``veneursinkonly`` routed groups, and
  the legacy object-path ``flush(list)`` surface.

All integers little-endian; values are raw IEEE-754 f64 bits, so a
decoded sample reproduces the flushed value exactly (the bit-identical
replay contract). Decode refuses a bad magic, CRC, truncation, or
trailing bytes rather than guessing — torn tails surface as errors, not
garbage metrics (the corruption matrix in tests/test_archive.py).
"""

from __future__ import annotations

import struct
from typing import Optional
from zlib import crc32

import numpy as np

from veneur_tpu import native
from veneur_tpu.core.metrics import InterMetric

MAGIC = b"VMB1"
SECTION_COLUMNAR = 0
SECTION_SAMPLES = 1


class _Interner:
    """First-appearance local string table (the VSB1 sid() discipline —
    and the exact order vn_encode_archive_section interns in, which is
    what makes native and Python sections byte-identical)."""

    def __init__(self) -> None:
        self.strings: list[bytes] = []
        self._ids: dict[str, int] = {}

    def sid(self, s: str) -> int:
        i = self._ids.get(s)
        if i is None:
            i = len(self.strings)
            self.strings.append(s.encode("utf-8"))
            self._ids[s] = i
        return i

    def table(self) -> bytes:
        out = bytearray(struct.pack("<I", len(self.strings)))
        for raw in self.strings:
            out += struct.pack("<I", len(raw))
            out += raw
        return bytes(out)


def _filter_tags(tags, excluded_tags):
    if not excluded_tags:
        return tags
    return [t for t in tags if t.split(":", 1)[0] not in excluded_tags]


def _columnar_section_py(group, excluded_tags=None) -> tuple[bytes, int]:
    """(section body, emitted sample count) for one ColumnGroup, dense.
    The pure-Python twin of vn_encode_archive_section — identical bytes
    when no tags are excluded (exclusion rewrites the row table, which
    only this path supports)."""
    intern = _Interner()
    sid = intern.sid
    rows = bytearray()
    meta_at = group.meta_at
    for i in range(group.nrows):
        name, tags, _sinks = meta_at(i)
        tags = _filter_tags(tags, excluded_tags)
        rows += struct.pack("<IH", sid(name), len(tags))
        for t in tags:
            rows += struct.pack("<I", sid(t))
    fams = bytearray(struct.pack("<I", len(group.families)))
    planes = bytearray()
    count = 0
    values = np.ascontiguousarray(
        np.stack([f.values for f in group.families]), np.float64)
    masks = np.ascontiguousarray(
        np.stack([f.mask.astype(np.uint8) if f.mask is not None
                  else np.ones(group.nrows, np.uint8)
                  for f in group.families]), np.uint8)
    for f in group.families:
        fams += struct.pack("<BI", int(f.type), sid(f.suffix))
    count = int(masks.sum())
    planes += values.tobytes()
    planes += masks.tobytes()
    body = (intern.table() + struct.pack("<I", group.nrows) + bytes(rows)
            + bytes(fams) + bytes(planes))
    return body, count


def _columnar_section_native(plan) -> Optional[bytes]:
    """The GIL-released body build over one EmitGroupPlan; None when the
    library (or the symbol) is unavailable."""
    return native.encode_archive_section(
        plan.meta_blob, plan.nrows, plan.suffixes, plan.family_types,
        plan.values, plan.masks)


def _samples_section(samples) -> tuple[bytes, int]:
    """Per-sample section body from (name, tags, type, value, message,
    hostname) tuples."""
    intern = _Interner()
    sid = intern.sid
    rows = bytearray()
    count = 0
    for name, tags, mtype, value, message, hostname in samples:
        rows += struct.pack("<IH", sid(name), len(tags))
        for t in tags:
            rows += struct.pack("<I", sid(t))
        rows += struct.pack("<BdII", int(mtype) & 0xFF, float(value),
                            sid(message or ""), sid(hostname or ""))
        count += 1
    body = intern.table() + struct.pack("<I", count) + bytes(rows)
    return body, count


def _routed_samples(group, sink_name, excluded_tags):
    """Samples of a veneursinkonly-routed group, filtered the way the
    base MetricSink.flush_columnar would route them to ``sink_name``."""
    meta_at = group.meta_at
    for fam in group.families:
        suffix = fam.suffix
        mtype = int(fam.type)
        vals = fam.values.tolist()
        for i in group.rows_for(fam).tolist():
            name, tags, sinks = meta_at(i)
            if sink_name is not None and sinks is not None \
                    and sink_name not in sinks:
                continue
            yield (name + suffix if suffix else name,
                   _filter_tags(tags, excluded_tags), mtype, vals[i],
                   "", "")


def _frame(timestamp: int, hostname: str,
           sections: list[tuple[int, bytes]]) -> bytes:
    host = hostname.encode("utf-8")
    out = bytearray(struct.pack("<qI", int(timestamp), len(host)))
    out += host
    out += struct.pack("<I", len(sections))
    for kind, body in sections:
        out += struct.pack("<BI", kind, len(body))
        out += body
    payload = bytes(out)
    return MAGIC + struct.pack("<I", crc32(payload)) + payload


def encode_flush(batch, hostname: str = "", *,
                 sink_name: Optional[str] = None,
                 excluded_tags: Optional[set] = None,
                 use_native: Optional[bool] = None) -> tuple[bytes, int]:
    """One VMB1 frame for a ColumnarMetrics flush; returns
    ``(frame, archived sample count)``.

    Plan-capable groups (emit_plan) serialize dense — through the
    native tier when ``use_native`` (default: availability) and no tags
    are excluded, byte-identically in Python otherwise. Routed groups
    and extras go per-sample, honoring ``sink_name`` routing exactly as
    the base flush_columnar does."""
    if use_native is None:
        use_native = native.emit_available()
    sections: list[tuple[int, bytes]] = []
    total = 0
    plans = batch.emit_plan()
    for g, plan in zip(batch.groups, plans):
        if not g.families or g.nrows == 0:
            continue
        if g.has_routing and sink_name is not None:
            body, n = _samples_section(
                _routed_samples(g, sink_name, excluded_tags))
            sections.append((SECTION_SAMPLES, body))
            total += n
            continue
        body = None
        if plan is not None and use_native and not excluded_tags:
            body = _columnar_section_native(plan)
            if body is not None:
                n = sum(f.count(g.nrows) for f in g.families)
        if body is None:
            body, n = _columnar_section_py(g, excluded_tags)
        sections.append((SECTION_COLUMNAR, body))
        total += n
    extras = [
        (m.name, _filter_tags(m.tags, excluded_tags), int(m.type),
         m.value, m.message, m.hostname)
        for m in batch.extras
        if sink_name is None or m.sinks is None or sink_name in m.sinks]
    if extras:
        body, n = _samples_section(extras)
        sections.append((SECTION_SAMPLES, body))
        total += n
    return _frame(batch.timestamp, hostname, sections), total


def encode_metrics(metrics: list[InterMetric], timestamp: int = 0,
                   hostname: str = "") -> tuple[bytes, int]:
    """Object-path frame: one per-sample section over an InterMetric
    list (the legacy ``flush(list)`` sink surface and the plugins'
    metrics argument when the columnar path is off)."""
    if timestamp == 0 and metrics:
        timestamp = metrics[0].timestamp
    body, n = _samples_section(
        (m.name, m.tags, int(m.type), m.value, m.message, m.hostname)
        for m in metrics)
    return _frame(timestamp, hostname, [(SECTION_SAMPLES, body)]), n


def decode_flush(frame: bytes) -> dict:
    """Inverse of encode_flush/encode_metrics: the frame header plus the
    flat sample list (family-major within a columnar section, mirroring
    ColumnarMetrics.materialize order). Raises ValueError on bad
    magic/CRC/truncation/trailing bytes."""
    if frame[:4] != MAGIC:
        raise ValueError("bad VMB1 magic")
    if len(frame) < 8:
        raise ValueError("truncated VMB1 frame")
    (crc,) = struct.unpack_from("<I", frame, 4)
    payload = frame[8:]
    if crc32(payload) != crc:
        raise ValueError("VMB1 CRC mismatch")
    off = 0

    def take(n: int) -> bytes:
        nonlocal off
        if off + n > len(payload):
            raise ValueError("truncated VMB1 frame")
        chunk = payload[off:off + n]
        off += n
        return chunk

    ts, host_len = struct.unpack("<qI", take(12))
    hostname = take(host_len).decode("utf-8")
    (nsections,) = struct.unpack("<I", take(4))
    samples: list[dict] = []
    for _ in range(nsections):
        kind, body_len = struct.unpack("<BI", take(5))
        body = take(body_len)
        if kind == SECTION_COLUMNAR:
            samples.extend(_decode_columnar(body))
        elif kind == SECTION_SAMPLES:
            samples.extend(_decode_samples(body))
        else:
            raise ValueError(f"unknown VMB1 section kind {kind}")
    if off != len(payload):
        raise ValueError("trailing bytes in VMB1 frame")
    return {"timestamp": ts, "hostname": hostname,
            "nsections": nsections, "samples": samples}


def _take_strings(body: bytes, off: int) -> tuple[list[str], int]:
    if off + 4 > len(body):
        raise ValueError("truncated VMB1 section")
    (nstrings,) = struct.unpack_from("<I", body, off)
    off += 4
    strings = []
    for _ in range(nstrings):
        if off + 4 > len(body):
            raise ValueError("truncated VMB1 section")
        (slen,) = struct.unpack_from("<I", body, off)
        off += 4
        if off + slen > len(body):
            raise ValueError("truncated VMB1 section")
        strings.append(body[off:off + slen].decode("utf-8"))
        off += slen
    return strings, off


def _decode_columnar(body: bytes):
    strings, off = _take_strings(body, 0)
    if off + 4 > len(body):
        raise ValueError("truncated VMB1 section")
    (nrows,) = struct.unpack_from("<I", body, off)
    off += 4
    names: list[str] = []
    tags: list[list[str]] = []
    for _ in range(nrows):
        if off + 6 > len(body):
            raise ValueError("truncated VMB1 section")
        nsid, ntags = struct.unpack_from("<IH", body, off)
        off += 6
        if off + 4 * ntags > len(body):
            raise ValueError("truncated VMB1 section")
        names.append(strings[nsid])
        tags.append([strings[t] for t in
                     struct.unpack_from(f"<{ntags}I", body, off)])
        off += 4 * ntags
    if off + 4 > len(body):
        raise ValueError("truncated VMB1 section")
    (nfam,) = struct.unpack_from("<I", body, off)
    off += 4
    fams = []
    for _ in range(nfam):
        if off + 5 > len(body):
            raise ValueError("truncated VMB1 section")
        ftype, ssid = struct.unpack_from("<BI", body, off)
        off += 5
        fams.append((ftype, strings[ssid]))
    need = nfam * nrows * 9
    if off + need != len(body):
        raise ValueError("VMB1 columnar plane size mismatch")
    values = np.frombuffer(body, "<f8", nfam * nrows, off)
    values = values.reshape(nfam, nrows)
    off += nfam * nrows * 8
    masks = np.frombuffer(body, np.uint8, nfam * nrows, off)
    masks = masks.reshape(nfam, nrows)
    for f, (ftype, suffix) in enumerate(fams):
        vals = values[f].tolist()
        mask = masks[f]
        for i in range(nrows):
            if not mask[i]:
                continue
            yield {"name": names[i] + suffix if suffix else names[i],
                   "tags": tags[i], "type": ftype, "value": vals[i],
                   "message": "", "hostname": ""}


def _decode_samples(body: bytes):
    strings, off = _take_strings(body, 0)
    if off + 4 > len(body):
        raise ValueError("truncated VMB1 section")
    (nrows,) = struct.unpack_from("<I", body, off)
    off += 4
    for _ in range(nrows):
        if off + 6 > len(body):
            raise ValueError("truncated VMB1 section")
        nsid, ntags = struct.unpack_from("<IH", body, off)
        off += 6
        if off + 4 * ntags > len(body):
            raise ValueError("truncated VMB1 section")
        tag_sids = struct.unpack_from(f"<{ntags}I", body, off)
        off += 4 * ntags
        if off + 17 > len(body):
            raise ValueError("truncated VMB1 section")
        mtype, value, msid, hsid = struct.unpack_from("<BdII", body, off)
        off += 17
        yield {"name": strings[nsid], "tags": [strings[t] for t in tag_sids],
               "type": mtype, "value": value, "message": strings[msid],
               "hostname": strings[hsid]}
    if off != len(body):
        raise ValueError("trailing bytes in VMB1 section")
