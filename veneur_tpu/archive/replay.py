"""Re-ingest a VMB1 archive through the global tier's import path.

A replayed archive flows through the exact merge entrypoint forwarded
sketches use — ``ImportServer.handle_batch`` in-process, or a
ForwardClient RPC against a remote global — so backfill lands in the
same worker shards, under the same locks and tenant budgets, as live
traffic. Archived counter and gauge samples carry raw IEEE-754 flush
values (archive/wire.py), and the import path merges scalars exactly
(worker.import_counter / import_gauge), so a replayed flush re-emits the
archived series bit-for-bit (pinned by tools/soak_archive_replay.py).

With ``dedup=True`` every frame's batch is wrapped in a PR 11 VDE1
idempotency envelope keyed by a stable, archive-derived (sender, id)
pair — the sender token hashes the archive's frame CRCs, the id is the
frame's position + CRC — so replaying the same archive twice merges
once: the second pass is absorbed by the receiver's DedupWindow with
honest ``metrics_deduped`` counters.

Status-check extras can't ride the import path (no pb representation)
and non-integral counter values can't merge exactly; both are counted,
never silently dropped.
"""

from __future__ import annotations

import logging
from zlib import crc32

from veneur_tpu.archive.wire import decode_flush
from veneur_tpu.core.metrics import MetricType
from veneur_tpu.distributed import codec
from veneur_tpu.gen import veneur_tpu_pb2 as pb

log = logging.getLogger("veneur_tpu.archive.replay")


def samples_to_batch(samples) -> tuple["pb.MetricBatch", dict]:
    """Decoded VMB1 samples → one importable MetricBatch. Returns the
    batch plus the skip tally: ``status`` (extras the import path can't
    represent) and ``inexact`` (counters whose archived value isn't
    integral — int() would silently change the replayed bits)."""
    batch = pb.MetricBatch()
    skipped = {"status": 0, "inexact": 0}
    for s in samples:
        mtype = s["type"]
        value = s["value"]
        if mtype == int(MetricType.COUNTER):
            if value != int(value):
                skipped["inexact"] += 1
                continue
            m = batch.metrics.add()
            m.name = s["name"]
            m.tags.extend(s["tags"])
            m.kind = pb.KIND_COUNTER
            m.scope = pb.SCOPE_GLOBAL
            m.counter.value = int(value)
        elif mtype == int(MetricType.GAUGE):
            m = batch.metrics.add()
            m.name = s["name"]
            m.tags.extend(s["tags"])
            m.kind = pb.KIND_GAUGE
            m.scope = pb.SCOPE_GLOBAL
            m.gauge.value = float(value)
        else:
            skipped["status"] += 1
    return batch, skipped


def archive_sender_token(frames: list[bytes]) -> str:
    """Stable dedup sender token derived from the archive's content
    (the frame CRCs chained), so two replay runs of the same archive
    present as the SAME sender and absorb each other's duplicates."""
    acc = 0
    for frame in frames:
        acc = crc32(frame, acc)
    return f"archive:{acc:08x}"


def replay_frames(frames: list[bytes], apply_batch=None, apply_wire=None,
                  dedup: bool = False, sender: str = "") -> dict:
    """Drive every frame through one of the import entrypoints.

    ``apply_batch(pb.MetricBatch)`` is ImportServer.handle_batch (or a
    ForwardClient send); with ``dedup`` the frames go through
    ``apply_wire(blob)`` (ImportServer.handle_wire or send_raw) wrapped
    in VDE1 envelopes instead. Undecodable frames (corruption that beat
    both CRC layers, or a newer format) are counted, not fatal — a
    partial archive still backfills."""
    if dedup and apply_wire is None:
        raise ValueError("dedup replay needs an apply_wire entrypoint")
    if dedup and not sender:
        sender = archive_sender_token(frames)
    stats = {"frames": len(frames), "frames_applied": 0,
             "frames_undecodable": 0, "samples": 0, "imported": 0,
             "skipped_status": 0, "skipped_inexact": 0, "sender": sender}
    for idx, frame in enumerate(frames):
        try:
            decoded = decode_flush(frame)
        except ValueError as e:
            stats["frames_undecodable"] += 1
            log.warning("archive frame %d undecodable: %s", idx, e)
            continue
        stats["samples"] += len(decoded["samples"])
        batch, skipped = samples_to_batch(decoded["samples"])
        stats["skipped_status"] += skipped["status"]
        stats["skipped_inexact"] += skipped["inexact"]
        n = len(batch.metrics)
        if n:
            if dedup:
                # position + content keyed: stable across replay runs,
                # unique across the archive's frames
                dedup_id = (idx << 32) | crc32(frame)
                blob = codec.encode_dedup_envelope(
                    sender, dedup_id, n, batch.SerializeToString())
                apply_wire(blob)
            else:
                apply_batch(batch)
            stats["imported"] += n
        stats["frames_applied"] += 1
    return stats
