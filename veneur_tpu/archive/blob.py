"""ArchiveBlobPlugin: VMB1 flush frames PUT to S3-compatible storage.

The reference's s3 plugin uploads row-at-a-time gzipped TSV with a
single log-and-count on failure. This plugin ships the same checksummed
columnar frames the local archive writes (archive/wire.py) — encoded
once, natively when the emit tier is loaded — and drives every PUT
through a DeliveryManager, so blob egress gets retry / breaker /
bounded-spill semantics and exact payload conservation instead of
drop-on-first-503. Objects land under
``archive/<hostname>/<timestamp>-<seq>.vmb``; SigV4 signing reuses
plugins/s3.sigv4_headers (the headers are minted inside the send
closure, so a spilled payload retried next interval re-signs with a
fresh date).
"""

from __future__ import annotations

import logging
import urllib.request

from veneur_tpu.archive.wire import encode_flush, encode_metrics
from veneur_tpu.plugins import Plugin
from veneur_tpu.plugins.s3 import sigv4_headers
from veneur_tpu.sinks.delivery import make_manager
from veneur_tpu.utils.http import default_opener

log = logging.getLogger("veneur_tpu.archive.blob")


class ArchiveBlobPlugin(Plugin):
    def __init__(self, bucket: str, region: str, access_key: str,
                 secret_key: str, delivery=None,
                 opener=default_opener) -> None:
        self.bucket = bucket
        self.region = region
        self.access_key = access_key
        self.secret_key = secret_key
        self.opener = opener
        self.delivery = make_manager("archive_blob", delivery)
        self.uploads = 0
        self.flush_errors = 0
        self.frames_encoded = 0
        self.bytes_encoded = 0
        self._seq = 0

    def name(self) -> str:
        return "archive_blob"

    def flush(self, metrics, hostname: str) -> None:
        """``metrics`` is the ColumnarMetrics batch on the columnar
        flush path, or an InterMetric list on the legacy object path —
        either way, one frame, one PUT."""
        if hasattr(metrics, "emit_plan"):
            frame, count = encode_flush(metrics, hostname)
            ts = metrics.timestamp
        else:
            frame, count = encode_metrics(list(metrics), hostname=hostname)
            ts = metrics[0].timestamp if metrics else 0
        man = self.delivery
        man.begin_flush()
        man.retry_spill()
        if count == 0:
            return
        self.frames_encoded += 1
        self.bytes_encoded += len(frame)
        self._seq += 1
        key = f"archive/{hostname}/{int(ts)}-{self._seq:06d}.vmb"
        status = man.deliver(self._send_fn(key, frame), len(frame),
                             payload=frame)
        if status == "delivered":
            self.uploads += 1
        elif status == "dropped":
            self.flush_errors += 1

    def _send_fn(self, key: str, frame: bytes):
        host = f"{self.bucket}.s3.{self.region}.amazonaws.com"
        path = f"/{key}"

        def send(timeout_s: float) -> None:
            headers = sigv4_headers(
                "PUT", host, path, self.region, self.access_key,
                self.secret_key, frame)
            headers["Content-Type"] = "application/octet-stream"
            req = urllib.request.Request(
                f"https://{host}{path}", data=frame, method="PUT",
                headers=headers)
            self.opener(req, timeout_s)

        return send
