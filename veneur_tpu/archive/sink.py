"""MetricArchiveSink: the local segmented VMB1 archive.

One frame per flush interval, appended to a rotated, size-and-count-
bounded segment log (the PR 12 SegmentedLogWriter discipline, under
``metrics-*.vmb``) through the DeliveryManager — so archival gets the
same retry / breaker / bounded-spill / exact-conservation contract
every network sink has, and a full disk degrades to honest drop
counters instead of a wedged flush.

The sink is native-emit capable: ``flush_columnar_native`` serializes
each plan-capable ColumnGroup GIL-free (native/emit.cpp
vn_encode_archive_section), while routed groups, extras, and excluded
tags take the byte-compatible Python path inside the same frame.
``read_archive`` yields the frames back in write order, torn-tail
tolerant — the replay corpus surface (archive/replay.py).
"""

from __future__ import annotations

import logging
import threading

from veneur_tpu.archive.wire import encode_flush, encode_metrics
from veneur_tpu.sinks import MetricSink
from veneur_tpu.sinks.delivery import make_manager
from veneur_tpu.spans.sink import SegmentedLogWriter, read_segmented_log

log = logging.getLogger("veneur_tpu.archive.sink")

ARCHIVE_PREFIX = "metrics-"
ARCHIVE_SUFFIX = ".vmb"


class SegmentedArchiveWriter(SegmentedLogWriter):
    """The span log's rotation/bounding/torn-tail discipline, applied to
    VMB1 flush frames (``metrics-%08d.vmb`` segments)."""

    def __init__(self, directory: str, max_segment_bytes: int = 64 << 20,
                 max_segments: int = 8) -> None:
        super().__init__(directory, max_segment_bytes, max_segments,
                         prefix=ARCHIVE_PREFIX, suffix=ARCHIVE_SUFFIX)


def read_archive(directory: str) -> list[bytes]:
    """Every VMB1 frame across the archive's segments in write order;
    stops at a torn tail instead of raising (decode_flush then rejects
    any frame whose own CRC fails — two independent checksum layers)."""
    return read_segmented_log(directory, prefix=ARCHIVE_PREFIX,
                              suffix=ARCHIVE_SUFFIX)


class MetricArchiveSink(MetricSink):
    """Flush archival as a first-class metric sink.

    Counter contract (the conservation the A/B artifact pins):
    ``metrics_flushed + metrics_dropped + metrics_deferred`` equals
    every sample accepted into a frame, and the delivery manager's own
    payload ledger (``accepted == delivered + dropped + spilled``) holds
    exactly underneath it."""

    supports_columnar = True
    supports_native_emit = True

    def __init__(self, writer, hostname: str = "", delivery=None,
                 name: str = "archive") -> None:
        self._name = name
        self.writer = writer
        self.hostname = hostname
        self.delivery = make_manager(name, delivery)
        self._stats_lock = threading.Lock()
        self.metrics_flushed = 0
        self.metrics_dropped = 0
        self.metrics_deferred = 0
        self.frames_encoded = 0
        self.bytes_encoded = 0

    def name(self) -> str:
        return self._name

    def start(self, trace_client=None) -> None:
        pass

    # -- flush surfaces (all three negotiate down to one frame) --------

    def flush_columnar_native(self, batch, excluded_tags=None) -> bool:
        frame, count = encode_flush(
            batch, self.hostname, sink_name=self._name,
            excluded_tags=excluded_tags, use_native=True)
        self._flush_frame(frame, count)
        return True

    def flush_columnar(self, batch, excluded_tags=None) -> None:
        frame, count = encode_flush(
            batch, self.hostname, sink_name=self._name,
            excluded_tags=excluded_tags, use_native=False)
        self._flush_frame(frame, count)

    def flush(self, metrics) -> None:
        metrics = list(metrics)
        frame, count = encode_metrics(metrics, hostname=self.hostname)
        self._flush_frame(frame, count)

    # -- delivery ------------------------------------------------------

    def _flush_frame(self, frame: bytes, count: int) -> None:
        man = self.delivery
        man.begin_flush()
        man.retry_spill()
        if count == 0:
            return  # nothing flushed this interval; spill still drained
        with self._stats_lock:
            self.frames_encoded += 1
            self.bytes_encoded += len(frame)
        writer = self.writer

        def send(timeout_s: float, _p=frame) -> None:
            writer.write(_p, timeout_s)

        status = man.deliver(send, len(frame), payload=frame)
        with self._stats_lock:
            if status == "delivered":
                self.metrics_flushed += count
            elif status == "dropped":
                self.metrics_dropped += count
            else:
                # parked in the bounded spill; payload-level
                # conservation is the manager's ledger from here on
                self.metrics_deferred += count

    def stop(self) -> None:
        close = getattr(self.writer, "close", None)
        if close is not None:
            try:
                close()
            except Exception:  # noqa: BLE001
                log.exception("archive writer close failed")
