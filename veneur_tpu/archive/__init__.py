"""Flush archival & replay: the VMB1 segmented metric archive.

The reference ships an s3 plugin that archives every flush as TSV —
unbounded, row-at-a-time, and write-only (nothing reads it back). This
package closes the capture→replay loop at the flush level:

* ``wire``   — VMB1, a checksummed columnar flush-frame format (magic +
  local string table + dense sample columns + CRC), serialized zero-copy
  from the ColumnarMetrics flush arrays — natively (GIL-released,
  native/emit.cpp) when the emit tier is loaded, byte-identically in
  Python otherwise.
* ``sink``   — MetricArchiveSink: a rotated, size-and-count-bounded
  append-only local archive behind the DeliveryManager (retry / breaker
  / bounded spill, exact payload conservation).
* ``blob``   — ArchiveBlobPlugin: the same frames PUT to S3-compatible
  blob storage through the existing SigV4 machinery (plugins/s3.py).
* ``replay`` — decoded frames re-ingested bit-identically through the
  global tier's import path (distributed/import_server.py), optionally
  under VDE1 dedup envelopes so a twice-replayed archive double-counts
  nothing.
"""

from veneur_tpu.archive.blob import ArchiveBlobPlugin
from veneur_tpu.archive.sink import (MetricArchiveSink,
                                     SegmentedArchiveWriter, read_archive)
from veneur_tpu.archive.wire import (decode_flush, encode_flush,
                                     encode_metrics)

__all__ = [
    "ArchiveBlobPlugin", "MetricArchiveSink", "SegmentedArchiveWriter",
    "read_archive", "encode_flush", "encode_metrics", "decode_flush",
]
