"""Local-file plugin: append each flush as TSV to a file.

Parity: reference plugins/localfile/localfile.go (the flush_file config).
"""

from __future__ import annotations

import logging

from veneur_tpu.plugins import Plugin, encode_inter_metrics_tsv

log = logging.getLogger("veneur_tpu.plugins.localfile")


class LocalFilePlugin(Plugin):
    def __init__(self, path: str, interval_s: float = 10.0) -> None:
        self.path = path
        self.interval_s = interval_s
        self.flush_errors = 0

    def name(self) -> str:
        return "localfile"

    def flush(self, metrics, hostname: str) -> None:
        try:
            data = encode_inter_metrics_tsv(metrics, hostname,
                                            self.interval_s)
            with open(self.path, "ab") as f:
                f.write(data)
        except OSError as e:
            self.flush_errors += 1
            log.warning("localfile flush failed: %s", e)
