"""Local-file plugin: append each flush as TSV to a file.

Parity: reference plugins/localfile/localfile.go (the flush_file config),
plus size-bounded rotation the reference lacks — an append that would
push the file past ``max_bytes`` first rotates it aside to ``<path>.1``
(one generation, the previous one replaced), so a long-lived process
never grows the flush file without bound.
"""

from __future__ import annotations

import logging
import os

from veneur_tpu.plugins import Plugin, encode_inter_metrics_tsv

log = logging.getLogger("veneur_tpu.plugins.localfile")


class LocalFilePlugin(Plugin):
    def __init__(self, path: str, interval_s: float = 10.0,
                 max_bytes: int = 0) -> None:
        self.path = path
        self.interval_s = interval_s
        self.max_bytes = max(0, int(max_bytes))
        self.flush_errors = 0
        self.rotations = 0

    def name(self) -> str:
        return "localfile"

    def _maybe_rotate(self, incoming: int) -> None:
        if not self.max_bytes:
            return
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return  # no file yet: nothing to rotate
        if size and size + incoming > self.max_bytes:
            os.replace(self.path, self.path + ".1")
            self.rotations += 1

    def flush(self, metrics, hostname: str) -> None:
        try:
            data = encode_inter_metrics_tsv(metrics, hostname,
                                            self.interval_s)
            self._maybe_rotate(len(data))
            with open(self.path, "ab") as f:
                f.write(data)
        except OSError as e:
            self.flush_errors += 1
            log.warning("localfile flush failed: %s", e)
