"""Flush-time archival plugins.

Parity: reference plugins/plugins.go:16-19 — a Plugin receives every
flush's final InterMetrics (after the sinks) and archives them; shipped
implementations are localfile and s3 (registered in server.go:737-785).
"""

from __future__ import annotations

import abc
import csv
import io
import time

from veneur_tpu.core.metrics import InterMetric


class Plugin(abc.ABC):
    @abc.abstractmethod
    def name(self) -> str: ...

    @abc.abstractmethod
    def flush(self, metrics: list[InterMetric], hostname: str) -> None: ...


def encode_inter_metrics_tsv(metrics: list[InterMetric], hostname: str,
                             interval_s: float) -> bytes:
    """TSV encoding of a flush (the reference's CSV/TSV flush-file format:
    name, tags, type, veneur hostname, interval, timestamp, value, and a
    date partition column)."""
    buf = io.StringIO()
    w = csv.writer(buf, delimiter="\t", lineterminator="\n")
    for m in metrics:
        ts = time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime(m.timestamp))
        partition = time.strftime("%Y%m%d", time.gmtime(m.timestamp))
        w.writerow([
            m.name,
            ",".join(m.tags),
            m.type.name.lower(),
            hostname,
            int(interval_s),
            ts,
            repr(m.value),
            partition,
        ])
    return buf.getvalue().encode("utf-8")
