"""S3 plugin: upload each flush as a gzipped TSV object.

Parity: reference plugins/s3/s3.go — per-flush PUT of the TSV under
<hostname>/<timestamp>.tsv.gz. AWS SigV4 request signing is implemented
directly over stdlib (no SDK in this environment); the HTTP opener is
injectable for tests.
"""

from __future__ import annotations

import datetime
import gzip
import hashlib
import hmac
import logging
import urllib.request

from veneur_tpu.plugins import Plugin, encode_inter_metrics_tsv
from veneur_tpu.utils.http import default_opener

log = logging.getLogger("veneur_tpu.plugins.s3")


def _sign(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode("utf-8"), hashlib.sha256).digest()


def sigv4_headers(method: str, host: str, path: str, region: str,
                  access_key: str, secret_key: str, payload: bytes,
                  now: datetime.datetime | None = None) -> dict[str, str]:
    """Minimal AWS Signature Version 4 for S3 PUT/GET."""
    t = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = t.strftime("%Y%m%dT%H%M%SZ")
    datestamp = t.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(payload).hexdigest()

    canonical_headers = (
        f"host:{host}\n"
        f"x-amz-content-sha256:{payload_hash}\n"
        f"x-amz-date:{amz_date}\n"
    )
    signed_headers = "host;x-amz-content-sha256;x-amz-date"
    canonical_request = "\n".join([
        method, path, "", canonical_headers, signed_headers, payload_hash,
    ])
    scope = f"{datestamp}/{region}/s3/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical_request.encode()).hexdigest(),
    ])
    k = _sign(("AWS4" + secret_key).encode(), datestamp)
    k = _sign(k, region)
    k = _sign(k, "s3")
    k = _sign(k, "aws4_request")
    signature = hmac.new(k, string_to_sign.encode(),
                         hashlib.sha256).hexdigest()
    return {
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_hash,
        "Authorization": (
            f"AWS4-HMAC-SHA256 Credential={access_key}/{scope},"
            f" SignedHeaders={signed_headers}, Signature={signature}"
        ),
    }


class S3Plugin(Plugin):
    def __init__(self, bucket: str, region: str, access_key: str,
                 secret_key: str, interval_s: float = 10.0,
                 opener=default_opener) -> None:
        self.bucket = bucket
        self.region = region
        self.access_key = access_key
        self.secret_key = secret_key
        self.interval_s = interval_s
        self.opener = opener
        self.flush_errors = 0
        self.uploads = 0

    def name(self) -> str:
        return "s3"

    def flush(self, metrics, hostname: str) -> None:
        data = gzip.compress(
            encode_inter_metrics_tsv(metrics, hostname, self.interval_s))
        now = datetime.datetime.now(datetime.timezone.utc)
        key = f"{hostname}/{now.strftime('%Y%m%d%H%M%S')}.tsv.gz"
        host = f"{self.bucket}.s3.{self.region}.amazonaws.com"
        path = f"/{key}"
        headers = sigv4_headers("PUT", host, path, self.region,
                                self.access_key, self.secret_key, data, now)
        headers["Content-Type"] = "application/gzip"
        req = urllib.request.Request(
            f"https://{host}{path}", data=data, method="PUT",
            headers=headers)
        try:
            self.opener(req, 30.0)
            self.uploads += 1
        except Exception as e:
            self.flush_errors += 1
            log.warning("s3 upload failed: %s", e)
