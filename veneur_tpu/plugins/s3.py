"""S3 plugin: upload each flush as a gzipped TSV object.

Parity: reference plugins/s3/s3.go — per-flush PUT of the TSV under
<hostname>/<timestamp>.tsv.gz. AWS SigV4 request signing is implemented
directly over stdlib (no SDK in this environment); the HTTP opener is
injectable for tests.
"""

from __future__ import annotations

import datetime
import gzip
import hashlib
import hmac
import logging
import urllib.parse
import urllib.request

from veneur_tpu.plugins import Plugin, encode_inter_metrics_tsv
from veneur_tpu.utils.http import default_opener

log = logging.getLogger("veneur_tpu.plugins.s3")


def _sign(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode("utf-8"), hashlib.sha256).digest()


def _canonical_query(query: str) -> str:
    """RFC-3986 canonical query string: each name and value URI-encoded
    (unreserved chars kept), pairs sorted by name then value, valueless
    params rendered ``name=`` (the documented GET-bucket-lifecycle
    example)."""
    if not query:
        return ""
    pairs = []
    for part in query.split("&"):
        name, _, value = part.partition("=")
        pairs.append((urllib.parse.quote(name, safe="-_.~"),
                      urllib.parse.quote(value, safe="-_.~")))
    return "&".join(f"{n}={v}" for n, v in sorted(pairs))


def sigv4_headers(method: str, host: str, path: str, region: str,
                  access_key: str, secret_key: str, payload: bytes,
                  now: datetime.datetime | None = None, query: str = "",
                  extra_headers: dict[str, str] | None = None
                  ) -> dict[str, str]:
    """AWS Signature Version 4 for S3, pinned against the documented AWS
    signing examples (tests/test_plugins.py): canonical URI encoding,
    canonical query strings, and arbitrary extra signed headers. The
    returned dict carries everything the request must send (including
    the extra headers), minus Host — the transport sets that."""
    t = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = t.strftime("%Y%m%dT%H%M%SZ")
    datestamp = t.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(payload).hexdigest()

    headers = {
        "host": host,
        "x-amz-content-sha256": payload_hash,
        "x-amz-date": amz_date,
    }
    for k, v in (extra_headers or {}).items():
        headers[k.lower()] = str(v).strip()
    names = sorted(headers)
    canonical_headers = "".join(f"{n}:{headers[n]}\n" for n in names)
    signed_headers = ";".join(names)
    canonical_uri = urllib.parse.quote(path, safe="/-_.~")
    canonical_request = "\n".join([
        method, canonical_uri, _canonical_query(query),
        canonical_headers, signed_headers, payload_hash,
    ])
    scope = f"{datestamp}/{region}/s3/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical_request.encode()).hexdigest(),
    ])
    k = _sign(("AWS4" + secret_key).encode(), datestamp)
    k = _sign(k, region)
    k = _sign(k, "s3")
    k = _sign(k, "aws4_request")
    signature = hmac.new(k, string_to_sign.encode(),
                         hashlib.sha256).hexdigest()
    out = {
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_hash,
        "Authorization": (
            f"AWS4-HMAC-SHA256 Credential={access_key}/{scope},"
            f" SignedHeaders={signed_headers}, Signature={signature}"
        ),
    }
    for k, v in (extra_headers or {}).items():
        out.setdefault(k, str(v).strip())
    return out


class S3Plugin(Plugin):
    def __init__(self, bucket: str, region: str, access_key: str,
                 secret_key: str, interval_s: float = 10.0,
                 opener=default_opener) -> None:
        self.bucket = bucket
        self.region = region
        self.access_key = access_key
        self.secret_key = secret_key
        self.interval_s = interval_s
        self.opener = opener
        self.flush_errors = 0
        self.uploads = 0

    def name(self) -> str:
        return "s3"

    def flush(self, metrics, hostname: str) -> None:
        data = gzip.compress(
            encode_inter_metrics_tsv(metrics, hostname, self.interval_s))
        now = datetime.datetime.now(datetime.timezone.utc)
        key = f"{hostname}/{now.strftime('%Y%m%d%H%M%S')}.tsv.gz"
        host = f"{self.bucket}.s3.{self.region}.amazonaws.com"
        path = f"/{key}"
        headers = sigv4_headers("PUT", host, path, self.region,
                                self.access_key, self.secret_key, data, now)
        headers["Content-Type"] = "application/gzip"
        req = urllib.request.Request(
            f"https://{host}{path}", data=data, method="PUT",
            headers=headers)
        try:
            self.opener(req, 30.0)
            self.uploads += 1
        except Exception as e:
            self.flush_errors += 1
            log.warning("s3 upload failed: %s", e)
