"""VSB1: the self-contained columnar span-batch wire format.

One frame per sealed batch, in the journal's checksummed-record
discipline (utils/journal.py): magic, a CRC-32 over the payload, then
the payload — a local string table (arena ids remapped to a compact
per-batch table, so a frame never references process-local state), the
flat row arrays, the referenced sample templates, and the flattened
samples. All integers little-endian; decode refuses a bad magic or CRC
rather than guessing (torn tails surface as errors, not garbage spans).

This is what SpanBatchSink ships — one Kafka message or one segmented-
log record per batch — replacing the per-span protobuf/JSON encode of
the drop-only kafka span lane with an O(distinct strings) columnar
serialization.
"""

from __future__ import annotations

import struct
import sys
import zlib
from array import array

from veneur_tpu.spans.batch import SealedBatch

MAGIC = b"VSB1"
_NO_STRING = 0xFFFFFFFF


def _le(a: array) -> bytes:
    if sys.byteorder != "little":  # pragma: no cover - LE-only CI
        a = array(a.typecode, a)
        a.byteswap()
    return a.tobytes()

def _from_le(typecode: str, buf: bytes) -> array:
    a = array(typecode)
    a.frombytes(buf)
    if sys.byteorder != "little":  # pragma: no cover - LE-only CI
        a.byteswap()
    return a


def encode_batch(sealed: SealedBatch) -> bytes:
    b, arena, store = sealed
    lstrings: list[bytes] = []
    lids: dict[str, int] = {}

    def sid(s: str) -> int:
        i = lids.get(s)
        if i is None:
            i = len(lstrings)
            lstrings.append(s.encode("utf-8"))
            lids[s] = i
        return i

    strings = arena.strings
    rows = b.rows
    service = array("I", (sid(strings[i]) for i in b.service_id))
    name = array("I", (sid(strings[i]) for i in b.name_id))
    objective = array("I", (sid(strings[i]) for i in b.objective_id))
    tags = array("I", (sid(strings[i]) for i in b.tags_id))

    # only the templates this batch references, remapped densely
    tpl_local: dict[int, int] = {}
    tpl_entries: list[tuple[int, int, int, int]] = []
    s_row = array("I")
    s_tpl = array("I")
    s_num = array("d")
    s_rate = array("d")
    s_msg = array("I")
    for j in range(b.samples):
        t = b.sample_tpl[j]
        lt = tpl_local.get(t)
        if lt is None:
            kind, tpl = store.templates[t]
            lt = len(tpl_entries)
            tpl_local[t] = lt
            tpl_entries.append((kind, int(tpl.scope), sid(tpl.key.name),
                                sid(tpl.key.joined_tags)))
        v = b.sample_value[j]
        if isinstance(v, str):
            s_num.append(0.0)
            s_msg.append(sid(v))
        else:
            s_num.append(float(v))
            s_msg.append(_NO_STRING)
        s_row.append(b.sample_row[j])
        s_tpl.append(lt)
        s_rate.append(b.sample_rate[j])

    out = bytearray()
    out += struct.pack("<IIII", rows, b.samples, len(lstrings),
                       len(tpl_entries))
    for raw in lstrings:
        out += struct.pack("<I", len(raw))
        out += raw
    for col in (b.trace_id, b.span_id, b.parent_id, b.start_ns, b.end_ns):
        out += _le(col)
    out += bytes(b.error)
    out += bytes(b.indicator)
    for col in (service, name, objective, tags):
        out += _le(col)
    for kind, scope, nsid, tsid in tpl_entries:
        out += struct.pack("<BBII", kind, scope, nsid, tsid)
    out += _le(s_row)
    out += _le(s_tpl)
    out += _le(s_num)
    out += _le(s_rate)
    out += _le(s_msg)
    payload = bytes(out)
    return MAGIC + struct.pack("<I", zlib.crc32(payload)) + payload


def decode_batch(frame: bytes) -> dict:
    """Inverse of encode_batch: a plain dict of columns + the local
    string/template tables (replay tooling and the roundtrip tests).
    Raises ValueError on bad magic/CRC/truncation."""
    if frame[:4] != MAGIC:
        raise ValueError("bad VSB1 magic")
    (crc,) = struct.unpack_from("<I", frame, 4)
    payload = frame[8:]
    if zlib.crc32(payload) != crc:
        raise ValueError("VSB1 CRC mismatch")
    off = 0

    def take(n: int) -> bytes:
        nonlocal off
        if off + n > len(payload):
            raise ValueError("truncated VSB1 frame")
        chunk = payload[off:off + n]
        off += n
        return chunk

    rows, nsamples, nstrings, ntpls = struct.unpack("<IIII", take(16))
    strings = []
    for _ in range(nstrings):
        (slen,) = struct.unpack("<I", take(4))
        strings.append(take(slen).decode("utf-8"))
    cols = {}
    for key in ("trace_id", "span_id", "parent_id", "start_ns", "end_ns"):
        cols[key] = _from_le("q", take(8 * rows))
    cols["error"] = bytearray(take(rows))
    cols["indicator"] = bytearray(take(rows))
    for key in ("service", "name", "objective", "tags"):
        cols[key] = _from_le("I", take(4 * rows))
    templates = []
    for _ in range(ntpls):
        kind, scope, nsid, tsid = struct.unpack("<BBII", take(10))
        templates.append({"kind": kind, "scope": scope,
                          "name": strings[nsid],
                          "joined_tags": strings[tsid]})
    s_row = _from_le("I", take(4 * nsamples))
    s_tpl = _from_le("I", take(4 * nsamples))
    s_num = _from_le("d", take(8 * nsamples))
    s_rate = _from_le("d", take(8 * nsamples))
    s_msg = _from_le("I", take(4 * nsamples))
    if off != len(payload):
        raise ValueError("trailing bytes in VSB1 frame")
    samples = []
    for j in range(nsamples):
        value = (strings[s_msg[j]] if s_msg[j] != _NO_STRING
                 else s_num[j])
        samples.append({"row": s_row[j], "template": s_tpl[j],
                        "value": value, "sample_rate": s_rate[j]})
    return {"rows": rows, "strings": strings, "columns": cols,
            "templates": templates, "samples": samples}
