"""Batched span→metric derivation, bit-identical to the per-span path.

The per-span reference behavior lives in core/spans.py
(``convert_metrics`` / ``convert_indicator_metrics`` /
``convert_span_uniqueness_metrics``, themselves pinned to the Go
reference). This module reproduces it over a sealed columnar batch by
construction rather than by reimplementation: every distinct key
combination — an attached sample's (type, name, tags), an indicator
timer's (service, error), an objective timer's (service, objective,
error), a uniqueness set's (indicator, service, root_span) — is parsed
exactly once through ``protocol.dogstatsd.parse_metric_ssf`` (the same
fnv1a-32 digest chain, magic-tag scope extraction, and tag
canonicalization the per-span path runs per metric) and cached as a
``UDPMetric`` template. Each row then emits a copy of its template
varying only in ``value`` / ``sample_rate``, in exactly the per-span
emission order: attached samples first (span order), then the indicator
timer, the objective timer, and the uniqueness set. Identical inputs to
``DeviceWorker.process_metric`` in identical per-worker order ⇒
identical sketches, micro-fold and series_shards included — that is the
whole parity argument, and tests/test_spans_columnar.py pins it per
metric class.
"""

from __future__ import annotations

import random
from typing import Callable, Optional

from veneur_tpu import ssf
from veneur_tpu.core.metrics import UDPMetric
from veneur_tpu.protocol.dogstatsd import ParseError, parse_metric_ssf

from veneur_tpu.spans.batch import SealedBatch, StringArena, frag_tags

_SET = int(ssf.SSFMetricType.SET)
_STATUS = int(ssf.SSFMetricType.STATUS)


def _clone(tpl: UDPMetric, value, sample_rate: float) -> UDPMetric:
    """A template copy varying only in value/sample_rate. key, digest,
    tags and scope are immutable downstream (the worker only reads
    them), so sharing is safe and keeps emission allocation-light."""
    return UDPMetric(key=tpl.key, digest=tpl.digest, value=value,
                     sample_rate=sample_rate, tags=tpl.tags,
                     scope=tpl.scope)


class TemplateStore:
    """Per-key-combination UDPMetric templates, all minted through
    parse_metric_ssf so the digest/scope/tag semantics cannot drift from
    the per-span path."""

    def __init__(self, arena: StringArena,
                 indicator_timer_name: str = "",
                 objective_timer_name: str = "") -> None:
        self.arena = arena
        self.indicator_timer_name = indicator_timer_name
        self.objective_timer_name = objective_timer_name
        # attached-sample templates; the batch's sample_tpl column
        # indexes self.templates, so this list is part of the wire model
        self.templates: list[tuple[int, UDPMetric]] = []
        self._sample_ids: dict[tuple, Optional[tuple[int, int]]] = {}
        self._indicator: dict[tuple[int, int], UDPMetric] = {}
        self._objective: dict[tuple[int, int, int], UDPMetric] = {}
        self._uniq: dict[tuple[int, int, int], UDPMetric] = {}

    # -- attached samples ----------------------------------------------

    def sample_template(self, sample) -> Optional[tuple[int, int]]:
        """(template id, metric kind) for an SSF sample, or None when
        the per-span path would count it invalid (unknown metric enum,
        empty metric name). One parse per distinct key combination."""
        try:
            kind = int(sample.metric)
        except (TypeError, ValueError):
            return None
        key = (kind, sample.name,
               tuple(sorted(sample.tags.items())) if sample.tags else ())
        hit = self._sample_ids.get(key, False)
        if hit is not False:
            return hit
        resolved: Optional[tuple[int, int]]
        try:
            tpl = parse_metric_ssf(ssf.SSFSample(
                metric=sample.metric, name=sample.name,
                tags=dict(sample.tags)))
        except ParseError:
            resolved = None
        else:
            if not tpl.key.name:
                resolved = None
            else:
                resolved = (len(self.templates), kind)
                self.templates.append((kind, tpl))
        self._sample_ids[key] = resolved
        return resolved

    @staticmethod
    def sample_value(sample, kind: int):
        """The value parse_metric_ssf would put on the UDPMetric: the
        message for sets, the raw status for status checks, float()
        otherwise. None ⇒ the per-span path's valid-metric check drops
        it."""
        if kind == _SET:
            return sample.message
        if kind == _STATUS:
            return sample.status
        return float(sample.value)

    # -- derived timers / sets -----------------------------------------

    def indicator_template(self, service_sid: int, error: int) -> UDPMetric:
        key = (service_sid, error)
        tpl = self._indicator.get(key)
        if tpl is None:
            tpl = parse_metric_ssf(ssf.timing_ns(
                self.indicator_timer_name, 0,
                {"service": self.arena.strings[service_sid],
                 "error": "true" if error else "false"}))
            self._indicator[key] = tpl
        return tpl

    def objective_template(self, service_sid: int, objective_sid: int,
                           error: int) -> UDPMetric:
        key = (service_sid, objective_sid, error)
        tpl = self._objective.get(key)
        if tpl is None:
            tpl = parse_metric_ssf(ssf.timing_ns(
                self.objective_timer_name, 0,
                {"service": self.arena.strings[service_sid],
                 "objective": self.arena.strings[objective_sid],
                 "error": "true" if error else "false",
                 "veneurglobalonly": "true"}))
            self._objective[key] = tpl
        return tpl

    def uniqueness_template(self, indicator: int, service_sid: int,
                            root: int) -> UDPMetric:
        key = (indicator, service_sid, root)
        tpl = self._uniq.get(key)
        if tpl is None:
            tpl = parse_metric_ssf(ssf.set_sample(
                "ssf.names_unique", "",
                {"indicator": "true" if indicator else "false",
                 "service": self.arena.strings[service_sid],
                 "root_span": "true" if root else "false"}))
            self._uniq[key] = tpl
        return tpl


def derive_batch(sealed: SealedBatch, uniqueness_rate: float,
                 emit: Callable[[UDPMetric], None]) -> int:
    """Emit every UDPMetric the per-span path would derive from this
    batch, in the per-span path's exact order (rows FIFO; within a row:
    attached samples, indicator timer, objective timer, uniqueness set).
    Returns the number of metrics emitted."""
    b, arena, store = sealed
    strings = arena.strings
    templates = store.templates
    ind_name = store.indicator_timer_name
    obj_name = store.objective_timer_name
    emitted = 0
    sp = 0
    nsamples = b.samples
    for row in range(b.rows):
        # 1) attached SSF samples (convert_metrics)
        while sp < nsamples and b.sample_row[sp] == row:
            kind, tpl = templates[b.sample_tpl[sp]]
            emit(_clone(tpl, b.sample_value[sp], b.sample_rate[sp]))
            emitted += 1
            sp += 1
        service_sid = b.service_id[row]
        name_sid = b.name_id[row]
        error = b.error[row]
        # 2) indicator/objective duration timers
        # (convert_indicator_metrics gate: indicator && valid_trace_span)
        if (b.indicator[row]
                and b.span_id[row] != 0 and b.trace_id[row] != 0
                and b.start_ns[row] != 0 and b.end_ns[row] != 0
                and strings[name_sid] != ""):
            duration = float(b.end_ns[row] - b.start_ns[row])
            if ind_name:
                emit(_clone(store.indicator_template(service_sid, error),
                            duration, 1.0))
                emitted += 1
            if obj_name:
                emit(_clone(store.objective_template(
                    service_sid, b.objective_id[row], error),
                    duration, 1.0))
                emitted += 1
        # 3) span-name uniqueness set (convert_span_uniqueness_metrics:
        # gated on a nonempty service, sampled through the same
        # module-global RNG contract as ssf.randomly_sample)
        if uniqueness_rate > 0 and strings[service_sid]:
            if uniqueness_rate >= 1.0:
                rate = 1.0
            elif random.random() < uniqueness_rate:
                rate = uniqueness_rate
            else:
                continue
            root = 1 if b.span_id[row] == b.trace_id[row] else 0
            emit(_clone(
                store.uniqueness_template(b.indicator[row], service_sid,
                                          root),
                strings[name_sid], rate))
            emitted += 1
    return emitted


def batch_tags(sealed: SealedBatch, row: int) -> dict:
    """The row's tag dict, reconstructed from the interned frag (egress
    and debugging helper)."""
    return frag_tags(sealed.arena.strings[sealed.batch.tags_id[row]])
