"""ColumnarSpanPipeline: the flush-driven span path of the server.

Ingest appends spans into columnar batches (spans/batch.py); the flush
edge derives every pending batch's metrics (spans/derive.py) straight
into the device workers — grouped per worker so each worker lock is
taken once per flush instead of once per derived metric — and hands the
sealed batches to the batch-capable span sinks for egress. Derivation
runs at the flush edge *before* the epoch swap, so an interval's spans
land in the same epoch its statsd samples do, and the derived key space
flows through the existing staged-plane path: micro-fold, series_shards,
tenant budgets and QoS all apply unchanged.

Conservation is exact and cheap to assert (the SPAN_SUSTAINED soak
does): spans_ingested == spans_derived + spans_dropped + pending, all
monotonic for the life of the process.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

from veneur_tpu.spans.batch import SpanColumnizer, StringArena
from veneur_tpu.spans.derive import TemplateStore, derive_batch

log = logging.getLogger("veneur_tpu.spans.pipeline")


class ColumnarSpanPipeline:
    def __init__(self, route_many: Callable[[list], None],
                 batch_sinks: Optional[list] = None,
                 common_tags: Optional[dict] = None,
                 indicator_timer_name: str = "",
                 objective_timer_name: str = "",
                 uniqueness_rate: float = 0.0,
                 batch_rows: int = 512,
                 pending_cap: int = 1 << 20) -> None:
        self.route_many = route_many
        self.batch_sinks = list(batch_sinks or [])
        self.uniqueness_rate = uniqueness_rate
        self.arena = StringArena()
        self.store = TemplateStore(
            self.arena,
            indicator_timer_name=indicator_timer_name,
            objective_timer_name=objective_timer_name)
        self.columnizer = SpanColumnizer(
            self.arena, self.store, common_tags=common_tags,
            batch_rows=batch_rows, pending_cap=pending_cap)
        # lifetime tallies (columnizer owns ingest-side ones)
        self.spans_derived = 0
        self.derived_rows = 0
        self.sink_errors = 0

    # -- ingest side ---------------------------------------------------

    def ingest(self, span) -> None:
        """Non-blocking columnar append; sheds at the pending cap
        (loss-over-stall, counted)."""
        self.columnizer.append(span)

    @property
    def spans_ingested(self) -> int:
        return self.columnizer.spans_appended

    @property
    def spans_dropped(self) -> int:
        return self.columnizer.spans_dropped

    @property
    def invalid_samples(self) -> int:
        return self.columnizer.invalid_samples

    @property
    def pending(self) -> int:
        return self.columnizer.pending

    # -- flush edge ----------------------------------------------------

    def flush(self) -> tuple[int, int]:
        """Derive and route every pending batch, then hand the sealed
        batches to the batch span sinks. Returns (spans, derived rows)
        this call processed. Runs on the flush tick before the epoch
        swap; ingest keeps appending into a fresh open batch meanwhile."""
        sealed = self.columnizer.take_sealed()
        if not sealed:
            return 0, 0
        spans = 0
        rows = 0
        for sb in sealed:
            derived: list = []
            rows += derive_batch(sb, self.uniqueness_rate, derived.append)
            if derived:
                self.route_many(derived)
            spans += sb.batch.rows
        self.spans_derived += spans
        self.derived_rows += rows
        for sink in self.batch_sinks:
            for sb in sealed:
                try:
                    sink.ingest_batch(sb)
                except Exception:
                    self.sink_errors += 1
                    log.exception("batch span sink %s ingest_batch failed",
                                  sink.name())
        return spans, rows

    def stats(self) -> dict:
        return {
            "spans_ingested": self.spans_ingested,
            "spans_derived": self.spans_derived,
            "spans_dropped": self.spans_dropped,
            "derived_rows": self.derived_rows,
            "invalid_samples": self.invalid_samples,
            "pending": self.pending,
            "sink_errors": self.sink_errors,
        }
