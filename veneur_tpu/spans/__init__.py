"""Columnar SSF trace-span pipeline (ROADMAP item 3: the second workload).

The per-span path (core/spans.py) walks Python ``SSFSpan`` objects through
``SpanWorker`` lanes and derives indicator/objective/uniqueness metrics one
span at a time via per-span callbacks. This package is the batched twin:

* ``batch``    — columnar span batches: service/operation/tag strings
  interned once into an append-only arena (the PR 4 frag discipline),
  start/end/error/indicator as flat arrays.
* ``derive``   — span→metric derivation over a sealed batch, bit-identical
  to the per-span ``convert_metrics`` / ``convert_indicator_metrics`` /
  ``convert_span_uniqueness_metrics`` path by construction: every distinct
  key combination parses ONCE through ``parse_metric_ssf`` (same fnv1a
  digest chain, same magic-tag scope extraction) and is cached as a
  template; rows emit copies varying only in value/sample_rate.
* ``pipeline`` — the flush-driven ``ColumnarSpanPipeline`` the server
  ingests into when every configured span sink is batch-capable.
* ``wire``     — the self-contained VSB1 batch serialization (checksummed,
  local string table) span egress ships.
* ``sink``     — ``SpanBatchSink``: batch egress through the PR 5
  ``DeliveryManager`` (retry/breaker/spill/journal) over a pluggable
  writer (Kafka wire producer or segmented local log).

``VENEUR_SPAN_COLUMNAR=0`` is the env escape hatch (the CI parity lane
runs the suite once per side), mirroring VENEUR_MICRO_FOLD /
VENEUR_SERIES_SHARDS.
"""

from __future__ import annotations

import os

_ENV_KEY = "VENEUR_SPAN_COLUMNAR"


def columnar_enabled(cfg_value: bool) -> bool:
    """Config value with the env escape hatch applied."""
    env = os.environ.get(_ENV_KEY)
    if env is not None:
        return env.strip().lower() not in ("0", "false", "no", "off")
    return bool(cfg_value)


from veneur_tpu.spans.batch import (  # noqa: E402
    SpanBatch, SpanColumnizer, StringArena, SealedBatch,
    frag_tags, tags_frag,
)
from veneur_tpu.spans.derive import TemplateStore, derive_batch  # noqa: E402
from veneur_tpu.spans.pipeline import ColumnarSpanPipeline  # noqa: E402
from veneur_tpu.spans.wire import decode_batch, encode_batch  # noqa: E402
from veneur_tpu.spans.sink import (  # noqa: E402
    DiscardWriter, KafkaBatchWriter, SegmentedLogWriter, SpanBatchSink,
)

__all__ = [
    "ColumnarSpanPipeline", "DiscardWriter", "KafkaBatchWriter",
    "SealedBatch", "SegmentedLogWriter", "SpanBatch", "SpanBatchSink",
    "SpanColumnizer", "StringArena", "TemplateStore", "columnar_enabled",
    "decode_batch", "derive_batch", "encode_batch", "frag_tags",
    "tags_frag",
]
