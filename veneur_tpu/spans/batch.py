"""Columnar span batches: interned string columns + flat scalar arrays.

The PR 4 frag-arena discipline applied to spans: every string a span
carries (service, operation name, the canonical tag frag, the objective
override) interns exactly once into an append-only ``StringArena``; a
batch row is then a handful of int32 arena ids plus flat int64/byte
scalars. A 10k-span interval with 4 services and ~200 operations costs
~200 interned strings and zero per-span dict/object churn on the flush
path.

Attached SSF samples flatten the same way: the (type, name, tags) key
combination of each sample resolves once through the derivation template
cache (spans/derive.py) and the batch stores only (row, template id,
value, sample_rate) — the parse work the per-span path redoes per sample
happens once per distinct key.
"""

from __future__ import annotations

import threading
from array import array
from typing import NamedTuple, Optional

# The frag separators of the PR 4 intern discipline: \x1f joins key and
# value inside one tag, \x1e joins tags inside the canonical frag. Both
# are illegal in DogStatsD/SSF tag material, so the mapping is bijective.
FRAG_KV = "\x1f"
FRAG_SEP = "\x1e"


def tags_frag(tags: dict) -> str:
    """Canonical frag for a span's tag dict (sorted, so equal dicts
    intern to one arena entry regardless of insertion order)."""
    if not tags:
        return ""
    return FRAG_SEP.join(
        k + FRAG_KV + v for k, v in sorted(tags.items()))


def frag_tags(frag: str) -> dict:
    """Inverse of tags_frag."""
    if not frag:
        return {}
    out = {}
    for part in frag.split(FRAG_SEP):
        k, _, v = part.partition(FRAG_KV)
        out[k] = v
    return out


class StringArena:
    """Append-only string intern pool; id == insertion index. Lookups by
    id are plain list indexing, safe against concurrent appends."""

    __slots__ = ("_ids", "strings")

    def __init__(self) -> None:
        self._ids: dict[str, int] = {}
        self.strings: list[str] = []

    def intern(self, s: str) -> int:
        i = self._ids.get(s)
        if i is None:
            i = len(self.strings)
            self.strings.append(s)
            self._ids[s] = i
        return i

    def __len__(self) -> int:
        return len(self.strings)


class SpanBatch:
    """One unit of columnar span rows plus their flattened samples.

    Parallel arrays only — no per-span objects survive ingest. ``error``
    / ``indicator`` are 0/1 bytes; ids are int64 (SSF ids are uint64-ish
    randoms below 2^62); string columns are int32 arena ids."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "start_ns", "end_ns",
        "error", "indicator", "service_id", "name_id", "objective_id",
        "tags_id", "sample_row", "sample_tpl", "sample_rate",
        "sample_value",
    )

    def __init__(self) -> None:
        self.trace_id = array("q")
        self.span_id = array("q")
        self.parent_id = array("q")
        self.start_ns = array("q")
        self.end_ns = array("q")
        self.error = bytearray()
        self.indicator = bytearray()
        self.service_id = array("i")
        self.name_id = array("i")
        # span.tags["ssf_objective"] or span.name, resolved at append so
        # derivation never touches a tag dict
        self.objective_id = array("i")
        self.tags_id = array("i")
        # attached samples, flattened across rows (sample_row ascending)
        self.sample_row = array("i")
        self.sample_tpl = array("i")
        self.sample_rate = array("d")
        # float for counter/gauge/histogram, str for set, raw status for
        # status checks — exactly what parse_metric_ssf would produce
        self.sample_value: list = []

    @property
    def rows(self) -> int:
        return len(self.span_id)

    @property
    def samples(self) -> int:
        return len(self.sample_row)


class SealedBatch(NamedTuple):
    """A sealed batch plus the (append-only, shared) arena and template
    store its ids index into — everything egress needs to serialize it."""

    batch: SpanBatch
    arena: StringArena
    store: "TemplateStore"  # noqa: F821 - duck-typed, spans/derive.py


class SpanColumnizer:
    """Thread-safe span→columns appender with bounded pending memory.

    Shared by the server's ColumnarSpanPipeline and by SpanBatchSink's
    per-span fallback path (columnar disabled): both need the same
    intern + template-resolution discipline."""

    def __init__(self, arena: StringArena, store,
                 common_tags: Optional[dict] = None,
                 batch_rows: int = 512,
                 pending_cap: int = 1 << 20) -> None:
        self.arena = arena
        self.store = store
        self.common_tags = dict(common_tags or {})
        self.batch_rows = max(1, int(batch_rows))
        self.pending_cap = max(1, int(pending_cap))
        self._open = SpanBatch()
        self._sealed: list[SealedBatch] = []
        self._sealed_rows = 0
        self._lock = threading.Lock()
        self.spans_appended = 0
        self.spans_dropped = 0
        self.invalid_samples = 0

    @property
    def pending(self) -> int:
        with self._lock:
            return self._open.rows + self._sealed_rows

    def append(self, span) -> bool:
        """Columnarize one span; False when the pending cap sheds it
        (loss-over-stall, same policy as the SpanWorker channel)."""
        # common tags fill in missing span tags before anything reads
        # them (same setdefault the SpanWorker applies, worker.go:627-634)
        for k, v in self.common_tags.items():
            span.tags.setdefault(k, v)
        arena = self.arena
        store = self.store
        with self._lock:
            if self._open.rows + self._sealed_rows >= self.pending_cap:
                self.spans_dropped += 1
                return False
            b = self._open
            row = b.rows
            b.trace_id.append(span.trace_id)
            b.span_id.append(span.id)
            b.parent_id.append(span.parent_id)
            b.start_ns.append(span.start_timestamp)
            b.end_ns.append(span.end_timestamp)
            b.error.append(1 if span.error else 0)
            b.indicator.append(1 if span.indicator else 0)
            b.service_id.append(arena.intern(span.service))
            b.name_id.append(arena.intern(span.name))
            b.objective_id.append(arena.intern(
                span.tags.get("ssf_objective") or span.name))
            b.tags_id.append(arena.intern(tags_frag(span.tags)))
            for sample in span.metrics:
                resolved = store.sample_template(sample)
                if resolved is None:
                    # ParseError or empty metric name — the per-span
                    # path's convert_metrics skip-and-count
                    self.invalid_samples += 1
                    continue
                tpl_id, kind = resolved
                value = store.sample_value(sample, kind)
                if value is None:
                    self.invalid_samples += 1
                    continue
                b.sample_row.append(row)
                b.sample_tpl.append(tpl_id)
                b.sample_rate.append(sample.sample_rate)
                b.sample_value.append(value)
            self.spans_appended += 1
            if b.rows >= self.batch_rows:
                self._seal_locked()
        return True

    def _seal_locked(self) -> None:
        if self._open.rows:
            self._sealed.append(
                SealedBatch(self._open, self.arena, self.store))
            self._sealed_rows += self._open.rows
            self._open = SpanBatch()

    def take_sealed(self) -> list[SealedBatch]:
        """Seal the open batch and hand back everything pending (FIFO —
        derivation preserves span arrival order)."""
        with self._lock:
            self._seal_locked()
            out, self._sealed = self._sealed, []
            self._sealed_rows = 0
        return out
