"""SpanBatchSink: columnar span egress through the DeliveryManager.

The reference's kafka span sink (and this repo's port of it,
sinks/kafka.py) is drop-only: a failed produce is a silent counter. This
sink replaces that lane for batch egress — each sealed columnar batch
serializes once (spans/wire.py VSB1 frames) and ships through the PR 5
``DeliveryManager``: retry with jittered backoff, circuit breaker,
bounded spill retried ahead of fresh data next interval, optional
write-ahead journal — so span egress gets the same
accepted == delivered + dropped + spilled conservation contract metric
sinks have.

The wire itself is pluggable:

* ``KafkaBatchWriter``   — one Kafka message per batch over the
  from-scratch wire producer (sinks/kafka_wire.py), surfacing the
  producer's internal drop counter as a raising failure so the
  DeliveryManager owns the loss accounting.
* ``SegmentedLogWriter`` — a local segmented append-only log (size-
  bounded, rotated) for brokerless deployments and replay tooling.
* ``DiscardWriter``      — serialize-only (the loadgen harness: full
  encode cost, zero network variance).

On the columnar path the server's span pipeline hands sealed batches to
``ingest_batch``; with columnar derivation disabled
(VENEUR_SPAN_COLUMNAR=0) the sink still works — the per-span ``ingest``
fallback columnarizes locally through the same SpanColumnizer.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import zlib

from veneur_tpu.sinks.delivery import make_manager
from veneur_tpu.spans.batch import SealedBatch, SpanColumnizer, StringArena
from veneur_tpu.spans.derive import TemplateStore
from veneur_tpu.spans.wire import encode_batch

log = logging.getLogger("veneur_tpu.spans.sink")


class _TransientWriteError(RuntimeError):
    """A write failure worth retrying (delivery.retryable honors the
    transient attribute)."""

    transient = True


class DiscardWriter:
    """Serialize-only writer: accepts every frame, writes nowhere."""

    def write(self, payload: bytes, timeout_s: float) -> None:
        pass


class KafkaBatchWriter:
    """One Kafka message per VSB1 frame through KafkaWireProducer.

    The producer buffers internally and folds failures into its own
    dropped counter; this wrapper flushes synchronously per write and
    raises when the drop counter moved, so the DeliveryManager — not the
    producer — owns retry/spill/loss accounting."""

    def __init__(self, producer, topic: str) -> None:
        self.producer = producer
        self.topic = topic
        self._lock = threading.Lock()

    def write(self, payload: bytes, timeout_s: float) -> None:
        with self._lock:
            before = self.producer.dropped
            self.producer.send(self.topic, None, payload)
            self.producer.flush()
            lost = self.producer.dropped - before
        if lost:
            raise _TransientWriteError(
                f"kafka producer dropped {lost} batch message(s)")

    def close(self) -> None:
        self.producer.close()


class SegmentedLogWriter:
    """Append-only local span-batch log, journal-style framed records
    (u32 length + u32 CRC + frame), size-rotated and segment-bounded:
    oldest segment unlinked first, never unbounded disk.

    ``prefix``/``suffix`` name the segment files — the metric archive
    (veneur_tpu/archive/sink.py) reuses this exact discipline for VMB1
    frames under ``metrics-*.vmb``."""

    def __init__(self, directory: str, max_segment_bytes: int = 16 << 20,
                 max_segments: int = 8, prefix: str = "spans-",
                 suffix: str = ".vsb") -> None:
        self.directory = directory
        self.max_segment_bytes = max(1, int(max_segment_bytes))
        self.max_segments = max(1, int(max_segments))
        self.prefix = prefix
        self.suffix = suffix
        self._lock = threading.Lock()
        self._fh = None
        self._seq = 0
        self._written = 0
        os.makedirs(directory, exist_ok=True)
        for name in sorted(os.listdir(directory)):
            if name.startswith(prefix) and name.endswith(suffix):
                try:
                    self._seq = max(
                        self._seq,
                        int(name[len(prefix):-len(suffix)]) + 1)
                except ValueError:
                    continue

    def _segments(self) -> list[str]:
        return sorted(
            n for n in os.listdir(self.directory)
            if n.startswith(self.prefix) and n.endswith(self.suffix))

    def _rotate_locked(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        path = os.path.join(
            self.directory, f"{self.prefix}{self._seq:08d}{self.suffix}")
        self._seq += 1
        self._fh = open(path, "ab")
        self._written = 0
        segs = self._segments()
        while len(segs) > self.max_segments:
            os.unlink(os.path.join(self.directory, segs.pop(0)))

    def write(self, payload: bytes, timeout_s: float) -> None:
        record = struct.pack("<II", len(payload),
                             zlib.crc32(payload)) + payload
        with self._lock:
            if self._fh is None or self._written >= self.max_segment_bytes:
                self._rotate_locked()
            self._fh.write(record)
            self._fh.flush()
            self._written += len(record)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def read_segmented_log(directory: str, prefix: str = "spans-",
                       suffix: str = ".vsb") -> list[bytes]:
    """Yield every frame across the log's segments in write order
    (replay tooling + tests); stops at a torn tail instead of raising."""
    frames: list[bytes] = []
    for name in sorted(os.listdir(directory)):
        if not (name.startswith(prefix) and name.endswith(suffix)):
            continue
        with open(os.path.join(directory, name), "rb") as fh:
            data = fh.read()
        off = 0
        while off + 8 <= len(data):
            size, crc = struct.unpack_from("<II", data, off)
            if off + 8 + size > len(data):
                break  # torn tail
            frame = data[off + 8:off + 8 + size]
            if zlib.crc32(frame) != crc:
                break
            frames.append(frame)
            off += 8 + size
    return frames


class SpanBatchSink:
    """Batch-capable span sink (SpanSink surface + ``ingest_batch``)."""

    # bound on sealed batches parked between flushes (each ≤ batch_rows
    # spans); beyond it new batches shed with honest spans_dropped
    MAX_PENDING_BATCHES = 256

    def __init__(self, writer, name: str = "span_batch",
                 delivery=None, batch_rows: int = 512,
                 pending_cap: int = 1 << 20) -> None:
        self._name = name
        self.writer = writer
        self.delivery = make_manager(name + "_spans", delivery)
        self._pending: list[SealedBatch] = []
        self._pending_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self.spans_flushed = 0
        self.spans_dropped = 0
        self.spans_deferred = 0
        self.batches_encoded = 0
        # per-span fallback path (columnar derivation disabled): local
        # columnizer with the same intern/template discipline
        arena = StringArena()
        self._columnizer = SpanColumnizer(
            arena, TemplateStore(arena), batch_rows=batch_rows,
            pending_cap=pending_cap)

    def name(self) -> str:
        return self._name

    def start(self, trace_client=None) -> None:
        pass

    # -- ingest (both granularities) -----------------------------------

    def ingest(self, span) -> None:
        """Per-span fallback: columnarize locally; sealed batches are
        adopted at flush."""
        if not self._columnizer.append(span):
            with self._stats_lock:
                self.spans_dropped += 1

    def ingest_batch(self, sealed: SealedBatch) -> None:
        """Columnar path: adopt a sealed batch for the next flush."""
        with self._pending_lock:
            if len(self._pending) >= self.MAX_PENDING_BATCHES:
                with self._stats_lock:
                    self.spans_dropped += sealed.batch.rows
                return
            self._pending.append(sealed)

    # -- flush ---------------------------------------------------------

    def flush(self) -> None:
        for sb in self._columnizer.take_sealed():
            self.ingest_batch(sb)
        with self._pending_lock:
            pending, self._pending = self._pending, []
        if not pending and not len(self.delivery.spill):
            return
        self.delivery.begin_flush()
        self.delivery.retry_spill()
        writer = self.writer
        for sb in pending:
            payload = encode_batch(sb)
            rows = sb.batch.rows
            with self._stats_lock:
                self.batches_encoded += 1

            def send(timeout_s: float, _p=payload) -> None:
                writer.write(_p, timeout_s)

            status = self.delivery.deliver(send, len(payload),
                                           payload=payload)
            with self._stats_lock:
                if status == "delivered":
                    self.spans_flushed += rows
                elif status == "dropped":
                    self.spans_dropped += rows
                else:
                    # parked in the spill; payload-level conservation
                    # (accepted == delivered + dropped + spilled) is the
                    # manager's ledger from here on
                    self.spans_deferred += rows
        wflush = getattr(writer, "flush", None)
        if wflush is not None:
            try:
                wflush()
            except Exception:  # noqa: BLE001 - telemetry-only path
                log.exception("span batch writer flush failed")

    def stop(self) -> None:
        close = getattr(self.writer, "close", None)
        if close is not None:
            try:
                close()
            except Exception:  # noqa: BLE001
                log.exception("span batch writer close failed")
