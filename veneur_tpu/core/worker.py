"""DeviceWorker: the batched aggregation engine.

Replaces the reference's worker goroutines (worker.go:265-517): instead of N
workers each holding Go maps of per-series sampler objects and processing
one metric at a time, one DeviceWorker owns dense device pools —

  t-digest rows   f32[S_h, C]×2 + scalars   (histogram & timer series)
  HLL registers   int8[S_s, 2^p]            (set series)
  local stats     f32[S_h] × 5              (the Histo sampler's host-local
                                             aggregates, samplers.go:467-494)

— and ingests *batches*: samples buffer host-side into SoA pending arrays,
and one jitted program per batch gathers the active rows, runs the digest
compression / HLL scatter, and scatters the rows back. Counters and gauges
are not sketches; their running state stays host-side in exact float64
(np.bincount-style segment adds), since f32 device accumulators would lose
counts past 2^24 — see ops/scalars.py.

Scope handling: the reference splits state across 13 maps by (type, scope)
(worker.go:60-103); here scope is a per-row *label* (directory.ScopeClass)
and the device programs are scope-oblivious — flush/forward select rows by
label (core/flusher.py).

Flush is a buffer swap (the map-swap of worker.go:498-517): the directory
and pools are handed to the flusher wholesale and replaced with fresh ones,
so next-interval ingest proceeds while extraction runs on the old buffers.

The import path (global tier) merges serialized sketches from downstream
instances: digests buffer host-side per row and merge in one concat+compress
program at flush; HLLs fold with np.maximum and one scatter-max
(reference Worker.ImportMetric/ImportMetricGRPC, worker.go:394-495).
"""

from __future__ import annotations

import functools
import logging
import time
from array import array
from dataclasses import dataclass, field
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from veneur_tpu.core import columnar
from veneur_tpu.core.directory import ScopeClass, SeriesDirectory, classify
from veneur_tpu.core.metrics import (DEFAULT_TENANT, MetricKey, UDPMetric,
                                     route_info, tenant_of)
from veneur_tpu.core.tenancy import TenantTallies
from veneur_tpu.health.ledger import TransferLedger
from veneur_tpu.ops import device_guard as dg
from veneur_tpu.ops import exactnum as exn
from veneur_tpu.ops import hll as hll_ops
from veneur_tpu.ops import host_engine as he
from veneur_tpu.ops import microfold as mf
from veneur_tpu.ops import reader_stack as rstack
from veneur_tpu.ops import series_shard as ss
from veneur_tpu.ops import tdigest as td
from veneur_tpu.ops.scalars import counter_contribution
from veneur_tpu.utils.hashing import hll_hash, fmix64, metric_digest

log = logging.getLogger("veneur_tpu.core.worker")


# max spilled samples per direct-fold dispatch (see _apply_native_raw);
# bounds drain memory to O(chunk) x the in-flight window, not O(backlog)
_FOLD_CHUNK = 1 << 18

# HBM valve threshold (see _ensure_histo): pool growths whose estimated
# device footprint stays under this skip the allocation pre-flight — a
# kB-scale grow cannot exhaust HBM, and pre-flighting it would put an
# extra device dispatch on every interval's early-growth ladder
_GROW_PREFLIGHT_MIN_BYTES = 4 << 20


def _next_pow2(n: int, floor: int = 1) -> int:
    v = floor
    while v < n:
        v *= 2
    return v


def _series_budget_id(scope_class: ScopeClass, key: MetricKey) -> str:
    """The tenant ledger's series identity: distinct (key, scope_class)
    pairs occupy distinct rows (see SeriesDirectory), so each consumes
    budget separately."""
    return f"{int(scope_class)}\x1f{key.key_string()}"


# ---------------------------------------------------------------------------
# Jitted device steps


def _comp_add(s, c, x):
    """Neumaier compensated add: (sum, compensation) += x, in f32.

    Long-running scalar accumulators (sum/count/reciprocal-sum) see 10^8+
    samples per series; a bare f32 add loses increments once the running
    value passes 2^24. The reference keeps these in float64
    (tdigest/merging_digest.go scalars); TPUs have no fast f64, so a
    two-float compensated sum carries the residual instead — the true
    value is s + c, reconstructed at flush extraction."""
    t = s + x
    # pick the larger-magnitude operand as the base of the residual;
    # on overflow (t = ±inf) the residual is inf-inf = NaN — drop it so
    # the accumulator saturates at inf like a bare f32 add would
    resid = jnp.where(jnp.abs(s) >= jnp.abs(x), (s - t) + x, (x - t) + s)
    resid = jnp.where(jnp.isfinite(t), resid, 0.0)
    return t, c + resid


@functools.partial(jax.jit, static_argnames=("compression",), donate_argnums=tuple(range(14)))
def _histo_ingest_step(
    means, weights, dmin, dmax, drecip, drecip_c,
    lmin, lmax, lsum, lsum_c, lweight, lweight_c, lrecip, lrecip_c,
    active, lids, values, wts,
    compression: float = td.DEFAULT_COMPRESSION,
):
    """Gather active digest rows, fold one sample batch in, scatter back.

    active: i32[K] pool rows (padded with a scratch row); lids index into
    `active`. Also updates the sampler-local scalar arrays for those rows.
    Scalar accumulators use compensated f32 (see _comp_add); `active`'s
    padding duplicates all point at the scratch row with zero-weight
    stats, so the gather→compensate→set round trip writes identical
    values at every duplicate.
    """
    g_means = means[active]
    g_w = weights[active]
    g_min = dmin[active]
    g_max = dmax[active]
    g_recip = drecip[active]

    n_means, n_w, n_min, n_max, _, stats = td.add_batch(
        g_means, g_w, g_min, g_max, g_recip, lids, values, wts,
        compression=compression,
    )

    means = means.at[active].set(n_means, mode="drop")
    weights = weights.at[active].set(n_w, mode="drop")
    dmin = dmin.at[active].set(n_min, mode="drop")
    dmax = dmax.at[active].set(n_max, mode="drop")
    n_recip, n_recip_c = _comp_add(g_recip, drecip_c[active], stats.recip)
    drecip = drecip.at[active].set(n_recip, mode="drop")
    drecip_c = drecip_c.at[active].set(n_recip_c, mode="drop")

    lmin = lmin.at[active].min(stats.min, mode="drop")
    lmax = lmax.at[active].max(stats.max, mode="drop")
    n_lsum, n_lsum_c = _comp_add(lsum[active], lsum_c[active], stats.sum)
    lsum = lsum.at[active].set(n_lsum, mode="drop")
    lsum_c = lsum_c.at[active].set(n_lsum_c, mode="drop")
    n_lw, n_lw_c = _comp_add(lweight[active], lweight_c[active], stats.weight)
    lweight = lweight.at[active].set(n_lw, mode="drop")
    lweight_c = lweight_c.at[active].set(n_lw_c, mode="drop")
    n_lr, n_lr_c = _comp_add(lrecip[active], lrecip_c[active], stats.recip)
    lrecip = lrecip.at[active].set(n_lr, mode="drop")
    lrecip_c = lrecip_c.at[active].set(n_lr_c, mode="drop")
    return (means, weights, dmin, dmax, drecip, drecip_c,
            lmin, lmax, lsum, lsum_c, lweight, lweight_c, lrecip, lrecip_c)


class StagedPlane(NamedTuple):
    """One raw-sample staging plane handed to the flush: host arrays
    (vals/wts [S, B], counts [S]) plus the native-memory release hook.
    wts is None when every weight is 1.0 (rebuilt on device from counts);
    free is None for Python-owned planes."""

    vals: np.ndarray
    wts: Optional[np.ndarray]
    counts: Optional[np.ndarray]
    free: Optional[object]


def _free_staged_planes(planes) -> None:
    """Release the native memory of any not-yet-freed planes."""
    for p in planes or ():
        if p.free is not None:
            try:
                p.free()
            except Exception:  # pragma: no cover
                log.exception("staged plane free failed")


def _staged_plane_to_host(plane: StagedPlane) -> StagedPlane:
    """Copy a native plane's content out of C++ memory (flat compaction,
    same layout _fold_one_plane uploads) and release it, so a device
    failover can replay the plane through the host engine. Host-owned
    planes pass through untouched."""
    if plane.free is None:
        return plane
    B = plane.vals.shape[1]
    counts_np = np.minimum(plane.counts, B).astype(np.int32)
    mask = (np.arange(B, dtype=np.int32)[None, :] < counts_np[:, None])
    flat_v = plane.vals[mask]
    flat_w = None if plane.wts is None else plane.wts[mask]
    try:
        plane.free()
    except Exception:  # pragma: no cover
        log.exception("staged plane free failed")
    return StagedPlane(flat_v, flat_w, counts_np, None)


@functools.partial(jax.jit, static_argnames=("depth",))
def _unit_wts_plane(counts, depth: int):
    """Rebuild a unit-weights staging plane from per-row staged counts:
    slot j of row r weighs 1.0 iff j < counts[r]. Uploading [S] i32
    instead of [S, B] f32 halves the flush's host→device bytes when no
    sampled (@rate) metric arrived — the common case."""
    return (jnp.arange(depth, dtype=jnp.int32)[None, :]
            < counts[:, None]).astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("depth", "unit"))
def _expand_flat_planes(flat_v, flat_w, counts, depth: int, unit: bool):
    """Rebuild the dense [S, depth] value+weight staging planes on
    DEVICE from their row-major compacted form (filled slots only) +
    per-row counts, in ONE dispatch sharing the offset/validity index.

    The dense plane is O(S × depth) bytes regardless of fill — at 1M
    series × depth 64 that is a 268 MB host→device transfer for ~17 MB
    of actual samples, and on a transfer-bound link (the dev rig's
    ~11 MB/s relay) the dense upload alone blows the 10s flush budget.
    Uploading the compacted samples + counts and paying one gather here
    makes the transfer O(samples), like the readback diet did for the
    extract direction. unit=True ignores flat_w (pass flat_v; XLA DCEs
    it) and uses the validity mask as the weights plane."""
    b = jnp.arange(depth, dtype=jnp.int32)[None, :]
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(counts, dtype=jnp.int32)[:-1]])
    idx = jnp.clip(offsets[:, None] + b, 0, flat_v.shape[0] - 1)
    valid = b < counts[:, None]
    sv = jnp.where(valid, flat_v[idx], jnp.float32(0))
    if unit:
        sw = valid.astype(jnp.float32)
    else:
        sw = jnp.where(valid, flat_w[idx], jnp.float32(0))
    return sv, sw


@functools.partial(jax.jit, static_argnames=("compression",),
                   donate_argnums=tuple(range(14)))
def _histo_fold_staged(
    means, weights, dmin, dmax, drecip, drecip_c,
    lmin, lmax, lsum, lsum_c, lweight, lweight_c, lrecip, lrecip_c,
    svals, swts,
    compression: float = td.DEFAULT_COMPRESSION,
):
    """Fold the staged raw-sample plane [S, B] into the digest pool.

    The TPU-first half of staged ingest: samples land in a host-side
    [S, B] plane at O(1) numpy-store cost per sample, and this ONE
    program pays the digest compress once per row per interval — the
    batched analog of the reference's deferred tempCentroids merge
    (tdigest/merging_digest.go:115-137 buffers raw samples, :140-224
    merges on overflow). Replaces per-batch gather→add_batch→scatter,
    whose [K, 2C] sort per batch dominated ingest compute.

    The staged plane is already row-dense, so no batch sort, run
    detection, or prefix-sum gathers are needed: per-row scalar stats
    are masked [S, B] reductions and the merge is one compress over
    [S, C+B]. Empty slots carry weight 0 (value ignored).
    """
    c = means.shape[1]
    live = swts > 0
    # Order-pinned tree sums (ops/exactnum.py): the host fallback engine
    # replays this fold over the same staged plane bitwise.
    s_w = exn.tsum(swts)
    s_sum = exn.tsum(jnp.where(live, svals * swts, 0.0))
    s_recip = exn.tsum(jnp.where(live, swts / svals, 0.0))
    s_min = jnp.min(jnp.where(live, svals, jnp.inf), axis=-1)
    s_max = jnp.max(jnp.where(live, svals, -jnp.inf), axis=-1)

    cat_means = jnp.concatenate([means, svals], axis=-1)
    cat_w = jnp.concatenate([weights, swts], axis=-1)
    means, weights = td._compress_rows(cat_means, cat_w, compression, c)

    dmin = jnp.minimum(dmin, s_min)
    dmax = jnp.maximum(dmax, s_max)
    drecip, drecip_c = _comp_add(drecip, drecip_c, s_recip)
    lmin = jnp.minimum(lmin, s_min)
    lmax = jnp.maximum(lmax, s_max)
    lsum, lsum_c = _comp_add(lsum, lsum_c, s_sum)
    lweight, lweight_c = _comp_add(lweight, lweight_c, s_w)
    lrecip, lrecip_c = _comp_add(lrecip, lrecip_c, s_recip)
    return (means, weights, dmin, dmax, drecip, drecip_c,
            lmin, lmax, lsum, lsum_c, lweight, lweight_c, lrecip, lrecip_c)


@functools.partial(jax.jit, static_argnames=("compression",), donate_argnums=(0, 1, 2, 3, 4, 5))
def _histo_import_step(
    means, weights, dmin, dmax, drecip, drecip_c,
    rows, imp_means, imp_w, imp_min, imp_max, imp_recip,
    compression: float = td.DEFAULT_COMPRESSION,
):
    """Merge imported digest rows [K, W] into pool rows (global tier)."""
    c = means.shape[1]
    g_means = means[rows]
    g_w = weights[rows]
    cat_means = jnp.concatenate([g_means, imp_means], axis=-1)
    cat_w = jnp.concatenate([g_w, imp_w], axis=-1)
    n_means, n_w = td.compress_rows(cat_means, cat_w, compression, c)
    means = means.at[rows].set(n_means, mode="drop")
    weights = weights.at[rows].set(n_w, mode="drop")
    dmin = dmin.at[rows].min(imp_min, mode="drop")
    dmax = dmax.at[rows].max(imp_max, mode="drop")
    n_recip, n_recip_c = _comp_add(drecip[rows], drecip_c[rows], imp_recip)
    drecip = drecip.at[rows].set(n_recip, mode="drop")
    drecip_c = drecip_c.at[rows].set(n_recip_c, mode="drop")
    return means, weights, dmin, dmax, drecip, drecip_c


@jax.jit
def _histo_flush_extract(means, weights, dmin, dmax, drecip, drecip_c,
                         lmin, lmax, lsum, lsum_c, lweight, lweight_c,
                         lrecip, lrecip_c, qs):
    """One program extracting everything the flusher needs from all rows.

    Compensated accumulators resolve to their true value (s + c) here."""
    quantiles = td.quantile(means, weights, dmin, dmax, qs)
    dsum = td.row_sum(means, weights)
    dcount = td.row_count(weights)
    return (quantiles, dmin, dmax, dsum, dcount, drecip + drecip_c,
            lmin, lmax, lsum + lsum_c, lweight + lweight_c,
            lrecip + lrecip_c)


@jax.jit
def _pack_extract_columns(qv, *cols):
    """[S,P] quantiles + ten [S] aggregates → one [S,P+10] f32 array, so
    extract_snapshot pays a single device→host transfer instead of
    eleven synchronous ones (the round-trips, not the bytes, dominate on
    a remote-device link).

    The f32 cast is a deliberate precision bound: sums/weights
    ACCUMULATE in compensated f64 on device (error does not grow with
    sample count), and the single final cast caps the REPORTED value at
    f32's 2^-24 relative error (~7 significant digits) — ample for
    observability data, and half the readback bytes of f64 at 1M
    series. Counters are unaffected (host-side exact f64 pools);
    integer-valued digest counts are exact below 2^24 per series per
    interval."""
    return jnp.concatenate(
        [qv] + [c[:, None].astype(jnp.float32) for c in cols], axis=1)


@functools.partial(jax.jit, static_argnames=("new_rows",), donate_argnums=(0,))
def _grow_2d(old, new_rows: int):
    s, c = old.shape
    return jnp.zeros((new_rows, c), old.dtype).at[:s].set(old)


@functools.partial(
    jax.jit, static_argnames=("new_rows", "fill"), donate_argnums=(0,)
)
def _grow_1d(old, new_rows: int, fill: float):
    s = old.shape[0]
    return jnp.full((new_rows,), fill, old.dtype).at[:s].set(old)


# ---------------------------------------------------------------------------
# Host-side state containers


class ScalarPool:
    """Growable f64 value array + per-row metadata; row ids are
    append-ordered so the Python dict path and the native directory agree
    on assignment."""

    def __init__(self, initial: int = 256) -> None:
        self.index: dict = {}  # (key, class) → row (python path only)
        self.meta: list = []  # (key, tags, scope_class, sinks)
        # packed per-row scope codes + routed-row count, maintained
        # incrementally for the columnar flush (see directory._Pool)
        from array import array as _array

        self.scope_codes = _array("b")
        self.routed_rows = 0
        # per-row admission codes + rejected-row count (per-tenant QoS,
        # see directory._Pool): only the native path can produce a
        # rejected scalar row (C++ assigns rows before the ledger runs)
        self.admit_codes = _array("b")
        self.rejected_rows = 0
        # incremental \x1e-joined wire-frag arena (see directory._Pool):
        # the native emit tier reads this buffer zero-copy at flush
        self.frag_arena = bytearray()
        self.frag_clean = True
        self.values = np.zeros(initial, np.float64)
        self.present = np.zeros(initial, bool)
        self.used = 0

    def frag_blob(self):
        return self.frag_arena if self.frag_clean else None

    def ensure(self, rows: int) -> None:
        if rows > len(self.values):
            cap = len(self.values)
            while cap < rows:
                cap *= 2
            self.values = np.resize(self.values, cap)
            self.values[self.used:] = 0.0
            newp = np.zeros(cap, bool)
            newp[: self.used] = self.present[: self.used]
            self.present = newp

    def upsert(self, key, scope_class, tags, sinks) -> int:
        k = (key, scope_class)
        row = self.index.get(k)
        if row is None:
            row = self.used
            self.index[k] = row
            self.adopt_row(row, key, tags, scope_class, sinks)
        return row

    def adopt_row(self, row: int, key, tags, scope_class, sinks,
                  frag=False, admitted=True) -> None:
        """Register metadata for a row assigned externally (native path).
        ``frag`` carries a prebuilt wire_frag (the worker's cross-epoch
        RowMeta cache); False = build here (the Python upsert path)."""
        assert row == len(self.meta), "rows must be adopted in order"
        self.meta.append((key, tags, scope_class, sinks))
        self.scope_codes.append(int(scope_class))
        self.admit_codes.append(1 if admitted else 0)
        if not admitted:
            self.rejected_rows += 1
        if sinks is not None:
            self.routed_rows += 1
        if self.frag_clean:
            if frag is False:
                from veneur_tpu.core.directory import build_frag

                frag = build_frag(getattr(key, "name", key), list(tags))
            if frag is None:
                self.frag_clean = False
            else:
                if row:
                    self.frag_arena += b"\x1e"
                self.frag_arena += frag
        # grow BEFORE bumping used: ensure() copies/zeroes relative to
        # self.used, and with used already including the new row it
        # copies one element past the old arrays (crash at a capacity
        # boundary) and leaves np.resize's recycled junk in the new row
        self.ensure(row + 1)
        self.used = row + 1


@dataclass
class HostScalars:
    """Exact host-side counter/gauge/status state for one interval."""

    counters: ScalarPool = field(default_factory=ScalarPool)
    gauges: ScalarPool = field(default_factory=ScalarPool)

    status_index: dict = field(default_factory=dict)
    status_meta: list = field(default_factory=list)
    status_values: list = field(default_factory=list)  # (value, message, host)

    # compatibility iteration helpers used by the flusher/codec
    @property
    def counter_meta(self):
        return self.counters.meta

    @property
    def counter_values(self):
        return self.counters.values[: self.counters.used]

    @property
    def gauge_meta(self):
        return self.gauges.meta

    @property
    def gauge_values(self):
        return self.gauges.values[: self.gauges.used]


@dataclass
class HistoDeviceState:
    means: jax.Array
    weights: jax.Array
    dmin: jax.Array
    dmax: jax.Array
    drecip: jax.Array
    # compensation halves of the compensated-f32 scalar accumulators
    # (see _comp_add); true value = base + _c, resolved at flush extract
    drecip_c: jax.Array
    lmin: jax.Array
    lmax: jax.Array
    lsum: jax.Array
    lsum_c: jax.Array
    lweight: jax.Array
    lweight_c: jax.Array
    lrecip: jax.Array
    lrecip_c: jax.Array

    @classmethod
    def create(cls, rows: int, capacity: int) -> "HistoDeviceState":
        # every field gets its own buffer — the ingest step donates all of
        # them, and donating one buffer twice is an error
        pool = td.init_pool(rows, capacity)

        def _full(v):
            return jnp.full((rows,), v, jnp.float32)

        return cls(
            means=pool.means, weights=pool.weights, dmin=pool.min,
            dmax=pool.max, drecip=pool.recip, drecip_c=_full(0.0),
            lmin=_full(jnp.inf), lmax=_full(-jnp.inf), lsum=_full(0.0),
            lsum_c=_full(0.0), lweight=_full(0.0), lweight_c=_full(0.0),
            lrecip=_full(0.0), lrecip_c=_full(0.0),
        )

    @property
    def num_rows(self) -> int:
        return self.means.shape[0]

    def fields(self) -> tuple:
        """The 14 device arrays in the kernel argument order."""
        return (self.means, self.weights, self.dmin, self.dmax,
                self.drecip, self.drecip_c, self.lmin, self.lmax,
                self.lsum, self.lsum_c, self.lweight, self.lweight_c,
                self.lrecip, self.lrecip_c)

    def placed(self, shard) -> "HistoDeviceState":
        """Commit every pool array to a SeriesSharding's mesh (fresh
        pools are all-constant, so the initial resharding copy is the
        only cross-device move the sharded pool ever makes)."""
        return HistoDeviceState(*(shard.place(a) for a in self.fields()))

    def grow(self, new_rows: int, shard=None) -> "HistoDeviceState":
        # zero-filled new mean rows are safe: every kernel keys empty slots
        # off weight==0, never the stored mean. Sharded pools pad each
        # shard's local block instead of appending at the end, which keeps
        # every existing logical row on its shard at its local index
        # (ops/series_shard.grow_2d) — growth moves no data between devices.
        inf = float("inf")
        g2 = _grow_2d if shard is None else shard.grow_2d
        g1 = _grow_1d if shard is None else shard.grow_1d
        return HistoDeviceState(
            means=g2(self.means, new_rows),
            weights=g2(self.weights, new_rows),
            dmin=g1(self.dmin, new_rows, inf),
            dmax=g1(self.dmax, new_rows, -inf),
            drecip=g1(self.drecip, new_rows, 0.0),
            drecip_c=g1(self.drecip_c, new_rows, 0.0),
            lmin=g1(self.lmin, new_rows, inf),
            lmax=g1(self.lmax, new_rows, -inf),
            lsum=g1(self.lsum, new_rows, 0.0),
            lsum_c=g1(self.lsum_c, new_rows, 0.0),
            lweight=g1(self.lweight, new_rows, 0.0),
            lweight_c=g1(self.lweight_c, new_rows, 0.0),
            lrecip=g1(self.lrecip, new_rows, 0.0),
            lrecip_c=g1(self.lrecip_c, new_rows, 0.0),
        )


@dataclass
class FlushSnapshot:
    """Everything one interval produced, in host memory: the input to
    InterMetric generation (core/flusher.py) and to forwarding
    (distributed/forward.py)."""

    directory: SeriesDirectory
    scalars: HostScalars
    interval_s: float
    # histogram/timer extraction [rows in directory.histo order]:
    quantile_values: Optional[np.ndarray] = None  # [S, P]
    quantile_qs: Optional[np.ndarray] = None  # [P]
    dmin: Optional[np.ndarray] = None
    dmax: Optional[np.ndarray] = None
    dsum: Optional[np.ndarray] = None
    dcount: Optional[np.ndarray] = None
    drecip: Optional[np.ndarray] = None
    lmin: Optional[np.ndarray] = None
    lmax: Optional[np.ndarray] = None
    lsum: Optional[np.ndarray] = None
    lweight: Optional[np.ndarray] = None
    lrecip: Optional[np.ndarray] = None
    # raw digest rows (for forwarding):
    digest_means: Optional[np.ndarray] = None
    digest_weights: Optional[np.ndarray] = None
    # sets:
    set_estimates: Optional[np.ndarray] = None  # [S_sets]
    set_registers: Optional[np.ndarray] = None  # [S_sets, m] (forwarding)
    # unique-timeseries count for this worker (None if disabled):
    unique_timeseries_registers: Optional[np.ndarray] = None
    # True when this interval's extraction finished on the HOST engine
    # after a device fault or while the device path was quarantined
    # (ops/device_guard); surfaced by the live query layer so readers
    # know the numbers came from the fallback path (still bit-identical
    # by the host-engine parity contract, but worth flagging)
    degraded: bool = False


@dataclass
class SwappedEpoch:
    """A closed interval's state, detached from the live worker by
    DeviceWorker.swap(). Holds device arrays (histo/sets) plus host
    directories; extract_snapshot() turns it into a FlushSnapshot without
    touching the worker's new epoch."""

    directory: SeriesDirectory
    scalars: HostScalars
    histo: Optional["HistoDeviceState"]
    sets: Optional[jax.Array]
    staged_sets: object
    umts: Optional[np.ndarray]
    mesh_out: Optional[dict]
    # raw-sample staging planes still unfolded at swap, each a
    # (vals[S, B], wts[S, B], free_or_None) tuple — the Python plane
    # and/or the detached native C++ plane (whose memory `free` releases
    # once uploaded); extract_snapshot folds them into `histo` off the
    # ingest lock
    staged_histo: Optional[list] = None
    # hot-row spill batch (rows, vals, wts numpy SoA) drained from the
    # C++ context at epoch close but NOT yet folded: under overload the
    # backlog fold is tens of seconds of device work, and running it in
    # swap() held the ingest lock for the whole of it (round-5 overload
    # measurement: swap 42s of a 44s flush, all in the spill fold).
    # extract_snapshot folds it off the lock, like the staged planes.
    spill_histo: Optional[tuple] = None
    # micro-fold device mirror of the epoch's staging plane
    # (ops/microfold.MirrorState): already resident on device at swap, so
    # extract folds it without an upload. Replaces the plane it mirrored
    # in staged_histo — exactly one of the two carries a given sample.
    device_stage: Optional[object] = None
    # the epoch's rotated MicroFoldMirror plus the residual COO deltas
    # collected under the swap fence but NOT yet fed: the device feeds
    # are deferred to extract_snapshot (off the tick) — a starved
    # scheduler must not turn the swap into the upload burst micro-folds
    # exist to remove. extract feeds these, finish()es the mirror, and
    # populates device_stage.
    micro_residual: Optional[tuple] = None
    # shared-nothing reader shards (DeviceWorker.attach_reader_shards):
    # each context's detached staging plane paired with a COPY of its
    # local-row → canonical-row map, in context order. extract_snapshot
    # merges them into ONE flat batch (ops/reader_stack.py) feeding the
    # legacy staged fold; the native memory is released right after the
    # merge copies out of it.
    reader_planes: Optional[list] = None
    # conservation insurance for the micro-fold mirror (device fault
    # domain): the staging plane the mirror fully covered, RETAINED
    # (host-side) instead of freed at swap. If a device fault voids the
    # mirror before or during extract, the flush folds this plane on
    # the host engine — no epoch lost. Freed once the mirror's fold
    # lands. A StagedPlane (native path, flat host copies) or a dense
    # (vals, wts) pair (python path).
    micro_replay: Optional[object] = None


class DeviceWorker:
    """Batched aggregation engine for one shard of the metric space.

    The reference routes each metric to one of N workers by Digest%N
    (server.go:1028,1039) to keep every series in exactly one histogram;
    here a single DeviceWorker typically owns the whole space (the TPU *is*
    the parallelism), but sharding across workers/devices composes the same
    way — see distributed/mesh.py.
    """

    def __init__(
        self,
        batch_size: int = 16384,
        compression: float = td.DEFAULT_COMPRESSION,
        capacity: int = td.DEFAULT_CAPACITY,
        hll_precision: int = hll_ops.DEFAULT_PRECISION,
        initial_histo_rows: int = 1024,
        initial_set_rows: int = 256,
        count_unique_timeseries: bool = False,
        is_local: bool = True,
        set_hash: str = "fnv",
        set_store: str = "staged",
        stage_depth: int = 64,
        spill_cap: int = 1 << 22,
        micro_fold: bool = False,
        micro_fold_rows: int = 8192,
        micro_fold_max_age_s: float = 0.25,
        series_shards: int = 0,
        device_guard: bool = True,
        device_fault_streak: int = dg.DEFAULT_STREAK_LIMIT,
        device_probe_interval_s: float = dg.DEFAULT_PROBE_INTERVAL_S,
    ) -> None:
        self.batch_size = batch_size
        # native pending-batch bound; beyond it samples shed, counted in
        # overload_dropped (drop-don't-block under overload)
        self.spill_cap = spill_cap
        # raw-sample staging slots per digest row (B in _histo_fold_staged);
        # rows whose staged count hits B spill through the direct per-batch
        # device fold — cheap there, since hot rows make K small
        self.stage_depth = stage_depth
        self.compression = compression
        self.capacity = capacity
        self.hll_precision = hll_precision
        self.set_hash = set_hash
        if set_hash == "metro":
            from veneur_tpu.utils.hashing import metro_hash64

            self._set_hash64 = metro_hash64
        else:
            self._set_hash64 = hll_hash
        self._initial_histo_rows = initial_histo_rows
        self._initial_set_rows = initial_set_rows
        # device-sharded series axis (ops/series_shard.py): partition the
        # sketch pools over a 1-D device mesh. Resolved through the
        # VENEUR_SERIES_SHARDS escape hatch; an unusable request (not a
        # pow2, more shards than devices) degrades to the legacy
        # single-device path with a warning rather than failing ingest.
        shards = ss.resolve_series_shards(series_shards)
        self._shard: Optional[ss.SeriesSharding] = None
        if shards > 1:
            if ss.shards_usable(shards):
                self._shard = ss.SeriesSharding(shards, compression)
                # pool row counts must stay pow2 multiples of the shard
                # count so every growth/slice divides evenly
                self._initial_histo_rows = _next_pow2(
                    max(initial_histo_rows, shards))
                self._initial_set_rows = _next_pow2(
                    max(initial_set_rows, shards))
            else:
                log.warning(
                    "series_shards=%d unusable (need a power of two <= "
                    "visible device count); using the single-device pool",
                    shards)
        self.series_shards = self._shard.shards if self._shard else 1
        self.count_unique_timeseries = count_unique_timeseries
        self.is_local = is_local
        self.set_store = set_store
        self._processed_py = 0
        self._native_proc_seen = 0
        # lifetime samples accepted across epochs (accumulated at swap;
        # per-epoch `processed` resets there)
        self.processed_total = 0
        self.imported = 0
        # overload-shedding tallies: per-interval (consumed + reset by
        # the server's flush telemetry) and lifetime (soaks/operators)
        self.overload_dropped = 0
        self.overload_dropped_total = 0
        self._inflight_folds = 0
        # per-flush spill-fold budget: seconds of fold work one flush may
        # inherit (the server sets this to a fraction of its interval)
        # and the measured fold throughput that converts it to samples.
        # Backlog beyond budget sheds AT SWAP, counted — bounding flush
        # wall time is what keeps the cadence under overload.
        self.fold_budget_s: float = 5.0
        self._fold_rate_ewma: float = 1e6  # samples/s, refined by extract
        # flush-path transfer byte accounting (health/ledger.py); reset
        # each swap, read by the server's flush telemetry and pinned by
        # the O(samples)-transfer regression test
        self.ledger = TransferLedger()
        # flush-deadline governor (health/governor.py), installed by the
        # server; None (or disabled) keeps single-shot extraction
        self.governor = None
        self._native = None
        # shared-nothing reader shards (attach_reader_shards): extra
        # native contexts, one per C++ reader thread, each with its own
        # directory/staging plane/spill epoch so the commit hot path
        # takes no shared mutex. Empty list == legacy single-context
        # mode everywhere (the checks below are `if self._reader_ctxs`).
        self._reader_ctxs: list = []
        # per-reader-context rebasing baselines (the home context keeps
        # its historical scalar fields — the server reads those directly)
        self._reader_errs_seen: list[int] = []
        self._reader_proc_seen: list[int] = []
        self._reader_drop_seen: list[int] = []
        # lifetime per-context conservation attribution, [home] + one
        # per reader shard: samples committed (counted at the flush-edge
        # detach fence) and samples shed at that context's spill caps
        self.reader_committed: list[int] = []
        self.reader_dropped: list[int] = []
        self._mesh_pool = None
        # always-hot flush (ops/microfold.py): when enabled, a scheduler
        # calls micro_fold_once() every time the staged-sample backlog
        # crosses micro_fold_rows or ages past micro_fold_max_age_s, so
        # the staging plane streams to a device mirror DURING the
        # interval and swap's fold shrinks to a residual drain
        self.micro_fold = bool(micro_fold)
        self.micro_fold_rows = int(micro_fold_rows)
        self.micro_fold_max_age_s = float(micro_fold_max_age_s)
        self._micro: Optional[mf.MicroFoldMirror] = None
        self._micro_last_drain = time.monotonic()
        # lifetime / per-epoch micro-fold drains, and the seconds swap
        # spent on the final residual drain + mirror fence (the server's
        # per-flush drain_ms telemetry; captured at swap like
        # staged_samples_swapped)
        self.micro_folds_total = 0
        self.micro_folds_epoch = 0
        self.micro_folds_swapped = 0
        self.micro_drain_swapped_s = 0.0
        # cross-epoch series-metadata cache (see _sync_native_series);
        # deliberately NOT in _reset_epoch — surviving the per-flush
        # directory swap is its whole purpose
        self._adopt_cache: dict = {}
        # per-tenant QoS (core/tenancy.py), installed by the server when
        # tenancy is configured; None keeps every tenant path dormant.
        # The ledger is SHARED across workers (admission is a host-global
        # decision — one tenant's series spread across workers by digest)
        self.tenancy = None
        self.tenant_sketch = None
        # live query subsystem (veneur_tpu/query/): when the server wires
        # a publisher, extract_snapshot hands it this epoch's read view —
        # the FlushSnapshot, a device evaluator closed over the retained
        # post-fold field arrays, and a fenced tenant-sketch view — right
        # before returning. The engine stages per-worker views and the
        # server commits them as ONE epoch after every worker extracted,
        # so queries never see a torn cross-worker state. None keeps the
        # whole path dormant (no retained device memory).
        self.query_publisher = None
        self.query_epoch_seq = 0
        # per-epoch / lifetime sample accounting per tenant; the epoch
        # tallies fold into the totals at swap, the processed_total
        # pattern (see swap())
        self.tenant_tallies = TenantTallies()
        self.tenant_tallies_total = TenantTallies()
        # device fault domain (ops/device_guard.py): one breaker per
        # worker over every device entry point. While quarantined
        # (_host_live) the live pools are host numpy state driven by the
        # host engine (ops/host_engine.py, bit-identical per metric
        # class); device_guard_tick() — run by the server after each
        # extraction, under the ingest lock — handles quarantine of the
        # live epoch, probing, and re-admission.
        self.guard = dg.DeviceGuard(
            streak_limit=device_fault_streak,
            probe_interval_s=device_probe_interval_s,
            enabled=bool(device_guard) and dg.guard_enabled_default())
        # live pools are host-side (HostHistoState / np registers)
        self._host_live = False
        # a device fault voided this epoch's micro-fold mirror: the
        # staging plane retains every sample, micro-folding pauses until
        # the next epoch, and the swap folds the plane as if micro-fold
        # were off
        self._micro_fault_epoch = False
        # lifetime count of flushes whose extraction completed on the
        # host engine (mirrors ledger.host_fallbacks; kept on the worker
        # for the soak's conservation accounting)
        self.host_fallback_flushes = 0
        self._reset_epoch()

    def attach_mesh_pool(self, pool) -> None:
        """Shard histogram state over a device mesh
        (distributed/mesh.MeshHistoPool): raw samples and imported
        centroids route to mesh shards instead of the single-device
        pool; the cross-host merge rides ICI collectives at flush.
        Intended for the global tier (config tpu_mesh_devices); local
        scalar aggregates (.min/.max of mixed-scope rows emitted by
        locals) are not tracked on the mesh path."""
        if self._shard is not None:
            # the mesh pool owns its own device layout; routing rows into
            # BOTH layouts would split series state. Config validation
            # rejects the combination up front; this guard covers direct
            # construction (tools/tests).
            log.warning("series sharding disabled: mesh pool attached "
                        "(tpu_mesh_devices and series_shards are exclusive)")
            self._shard = None
            self.series_shards = 1
        self._mesh_pool = pool
        if self._native is not None:
            # staging would divert samples from the mesh pool: mesh rows
            # route through add_samples_bulk, not the staged fold
            try:
                self._native.set_stage_depth(0)
            except AttributeError:
                pass

    @property
    def processed(self) -> int:
        """Samples accepted this epoch. In native mode the router commits
        into the C++ context off the Python path, so the native counter's
        delta since the last rebase is folded in live."""
        n = self._processed_py
        if self._native is not None:
            n += int(self._native.processed) - self._native_proc_seen
        for i, ctx in enumerate(self._reader_ctxs):
            n += int(ctx.processed) - self._reader_proc_seen[i]
        return n

    @processed.setter
    def processed(self, v: int) -> None:
        # preserves `self.processed += k` semantics: the native delta read
        # by the getter is subtracted back out so it isn't double-counted
        nd = 0
        if self._native is not None:
            nd = int(self._native.processed) - self._native_proc_seen
        for i, ctx in enumerate(self._reader_ctxs):
            nd += int(ctx.processed) - self._reader_proc_seen[i]
        self._processed_py = v - nd

    # -- native front-end ----------------------------------------------------

    def attach_native(self) -> bool:
        """Attach the C++ ingest pipeline (native/dogstatsd.cpp): parsing,
        tag normalization, row assignment AND raw-sample staging move off
        the Python path; this worker's Python-side paths (SSF-derived
        metrics, imports) share the native directory through upsert."""
        try:
            from veneur_tpu.native import NativeIngest

            self._native = NativeIngest(self.hll_precision,
                                        set_hash=self.set_hash)
        except (RuntimeError, OSError):
            return False
        if self._mesh_pool is None and self.stage_depth > 0:
            try:
                self._native.set_stage_depth(self.stage_depth)
            except AttributeError:  # stale .so without the staging API
                pass
        if self.spill_cap:
            try:
                self._native.set_spill_cap(self.spill_cap)
            except AttributeError:  # stale .so without the cap API
                pass
        return True

    def attach_reader_shards(self, n: int) -> bool:
        """Shared-nothing multi-reader ingest: give each of n reader
        threads its OWN native context — private directory, staging
        plane, SoA spill epoch — so the commit hot path takes no shared
        mutex (the per-context lock survives only at the flush-edge
        detach fence and the periodic drains, where it is uncontended).

        Series identity becomes (reader, local row), reconciled into
        this worker's canonical Python directory at the series sync
        (_sync_native_series appends each context's local row to a
        local→canonical map); the flush folds all staging planes
        on-device as ONE stacked batch (ops/reader_stack.py), so every
        downstream consumer sees the same output as the legacy
        digest-routed path. Requires an attached home context, no mesh
        pool, and a reader-shard-capable .so. Returns False (legacy
        path keeps working) when any precondition fails."""
        if n < 1 or self._reader_ctxs:
            return bool(self._reader_ctxs)
        if self._native is None or self._mesh_pool is not None:
            return False
        if not hasattr(self._native._lib, "vn_ingest_home"):
            return False  # stale .so: no home-aware commit entry point
        from veneur_tpu.native import NativeIngest

        ctxs = []
        try:
            for _ in range(n):
                ctx = NativeIngest(self.hll_precision,
                                   set_hash=self.set_hash)
                if self.stage_depth > 0:
                    ctx.set_stage_depth(self.stage_depth)
                if self.spill_cap:
                    ctx.set_spill_cap(self.spill_cap)
                ctxs.append(ctx)
        except (RuntimeError, OSError, AttributeError):
            for ctx in ctxs:
                ctx.close()
            return False
        self._reader_ctxs = ctxs
        self._reader_errs_seen = [0] * n
        self._reader_proc_seen = [0] * n
        self._reader_drop_seen = [0] * n
        self.reader_committed = [0] * (n + 1)
        self.reader_dropped = [0] * (n + 1)
        # the maps were sized for zero reader contexts at construction
        self._ctx_maps = [tuple(array("i") for _ in range(4))
                          for _ in range(n + 1)]
        return True

    def _all_ctxs(self) -> list:
        """[home context] + reader-shard contexts, in the context order
        every reconciliation structure is indexed by."""
        return [self._native] + self._reader_ctxs

    def ingest_datagram(self, datagram: bytes) -> int:
        """Native-path ingest of one (possibly multi-line) datagram.
        Returns leftover event/service-check lines via drain_other on the
        caller's schedule."""
        n = self._native.ingest(datagram)
        if (self._native.pending_histo >= self.batch_size
                or self._native.pending_set >= self.batch_size):
            self.drain_native()
        return n

    def ingest_ssf_packet(self, packet: bytes, indicator_name: bytes,
                          objective_name: bytes,
                          uniqueness_rate: float = 0.0) -> int:
        """Native-path SSF span ingest (decode + span→metric extraction in
        C++). Returns the vn_ingest_ssf rc: 1 ok, 0 decode error, -1 the
        caller must take the Python path (STATUS samples aboard)."""
        rc = self._native.ingest_ssf(packet, indicator_name, objective_name,
                                     uniqueness_rate)
        if rc == 1:
            self.processed += 1
            if (self._native.pending_histo >= self.batch_size
                    or self._native.pending_set >= self.batch_size):
                self.drain_native()
        return rc

    def _scalar_upsert_meta(self, pool, meta) -> int:
        """ScalarPool twin of _Pool.upsert_meta: dedup by (key, class),
        adopting a fresh row only for a genuinely new series (adopt_row
        leaves index maintenance to its caller)."""
        k = (meta.key, meta.scope_class)
        row = pool.index.get(k)
        if row is not None:
            return row
        row = len(pool.meta)
        pool.index[k] = row
        pool.adopt_row(row, meta.key, meta.tags, meta.scope_class,
                       meta.sinks, frag=meta.wire_frag(),
                       admitted=meta.admitted)
        return row

    def _sync_native_series(self, ctx=None, ctx_i: int = 0) -> None:
        from veneur_tpu.core.directory import RowMeta
        from veneur_tpu.native import NativeIngest

        if ctx is None:
            ctx = self._native
        if not ctx.pending_new_series:
            return
        # reader-shard mode: context rows are LOCAL — reconcile each into
        # the worker's canonical directory (dedup by series identity, so
        # the same series arriving via several readers shares one
        # canonical row) and append the translation to this context's
        # local→canonical map. The home context (ctx_i 0) reconciles the
        # same way so every native row space is treated uniformly.
        shard_maps = self._ctx_maps[ctx_i] if self._reader_ctxs else None
        # cross-epoch adopt cache: every flush resets the directory and
        # the same series re-register next interval; their RowMeta
        # (key, tags, routing) is identical every time, so build it once
        # per series lifetime instead of per epoch — the dominant cost
        # of the global tier's steady-state import before this cache
        cache = self._adopt_cache
        for pool, row, kind, scope, name, joined in (
            ctx.drain_new_series()
        ):
            ck = (pool, kind, scope, name, joined)
            meta = cache.get(ck)
            if meta is None:
                mtype = NativeIngest.TYPE_BY_KIND[kind]
                key = MetricKey(name=name, type=mtype, joined_tags=joined)
                tags = joined.split(",") if joined else []
                tenant = ""
                admitted = True
                if self.tenancy is not None:
                    # native-path budget gate: C++ already assigned the
                    # row, so a rejected series keeps its row but is
                    # marked admitted=False — the flusher skips it on
                    # both emit paths. The decision caches with the
                    # RowMeta (admission is per series lifetime).
                    tenant = tenant_of(tags, self.tenancy.tag_key)
                    admitted = self.tenancy.admit(
                        tenant, _series_budget_id(ScopeClass(scope), key))
                meta = RowMeta(key=key, tags=tags,
                               scope_class=ScopeClass(scope),
                               sinks=route_info(tags),
                               tenant=tenant, admitted=admitted)
                if len(cache) >= 4_000_000:
                    # unbounded series churn: drop the cache rather than
                    # grow without limit (steady workloads never hit it)
                    cache.clear()
                cache[ck] = meta
            if self.count_unique_timeseries:
                # feed the unique-timeseries HLL once per new series; the
                # HLL insert is idempotent so per-sample feeding (the Python
                # path, worker.go:300-341) and per-series feeding agree
                self._sample_timeseries_key(name, meta.key.type, joined,
                                            meta.scope_class)
            if shard_maps is not None:
                arr = shard_maps[pool]
                assert row == len(arr), \
                    "reader-shard series must drain in row order"
                if pool == 0:
                    crow, _ = self.directory.histo.upsert_meta(meta)
                elif pool == 1:
                    crow, _ = self.directory.sets.upsert_meta(meta)
                elif pool == 2:
                    crow = self._scalar_upsert_meta(
                        self.scalars.counters, meta)
                else:
                    crow = self._scalar_upsert_meta(
                        self.scalars.gauges, meta)
                arr.append(crow)
            elif pool == 0:
                self.directory.histo.adopt_meta(row, meta)
            elif pool == 1:
                self.directory.sets.adopt_meta(row, meta)
            elif pool == 2:
                self.scalars.counters.adopt_row(
                    row, meta.key, meta.tags, meta.scope_class, meta.sinks,
                    frag=meta.wire_frag(), admitted=meta.admitted)
            else:
                self.scalars.gauges.adopt_row(
                    row, meta.key, meta.tags, meta.scope_class, meta.sinks,
                    frag=meta.wire_frag(), admitted=meta.admitted)

    def sync_native_series(self) -> None:
        """Adopt pending new-series registrations mid-epoch.

        Directory adoption is per-series Python work — ~0.9s per 131k
        fresh series — and every interval re-registers every series
        (metrics expire at flush, reference README.md:135-137). Left to
        epoch close it all lands in swap(), UNDER the server's ingest
        lock; called periodically (Server._series_sync_loop) it spreads
        across the interval and swap only adopts the last cadence
        window's tail. Caller holds the worker lock; takes the native
        context lock itself."""
        if self._native is None:
            return
        for i, ctx in enumerate(self._all_ctxs()
                                if self._reader_ctxs else [self._native]):
            ctx.lock()
            try:
                self._sync_native_series(ctx, i)
            finally:
                ctx.unlock()

    def native_series_pending(self) -> bool:
        """Lock-free pending-new-series probe across every native
        context (the server's sync-sweep early-out)."""
        if self._native is None:
            return False
        if any(ctx.pending_new_series for ctx in self._reader_ctxs):
            return True
        return bool(self._native.pending_new_series)

    def drain_native(self) -> None:
        """Move everything pending in the native pipeline into device/host
        state. Holds the context lock across the whole raw-drain so routed
        commits from reader threads can't interleave between calls."""
        if self._native is None:
            return
        if self._reader_ctxs:
            # shard mode: per-context drain → local→canonical row
            # translation → apply. Each context's lock is held only for
            # its own drain (shared-nothing extends to the drain path).
            for i, ctx in enumerate(self._all_ctxs()):
                ctx.lock()
                try:
                    raw = self._drain_native_raw_ctx(ctx, i)
                finally:
                    ctx.unlock()
                self._apply_native_raw(self._map_raw_rows(i, raw))
            return
        self._native.lock()
        try:
            raw = self._drain_native_raw()
        finally:
            self._native.unlock()
        self._apply_native_raw(raw)

    def _map_raw_rows(self, ctx_i: int, raw):
        """Translate one context's drained SoA batches from its LOCAL
        row space to canonical rows via the reconciliation maps built at
        series sync. Samples drain after their series registration (same
        C++ critical section ordering), so every row has a map entry —
        a miss is a bug and raises IndexError loudly."""
        h, s, c, g, st, others, ssf_fb = raw
        maps = self._ctx_maps[ctx_i]

        def translate(pool_i: int, rows):
            return np.frombuffer(maps[pool_i], dtype=np.int32)[rows]

        if h is not None and len(h[0]):
            h = (translate(0, h[0]), h[1], h[2])
        if s is not None and len(s[0]):
            s = (translate(1, s[0]), s[1], s[2])
        rows, contribs = c
        if len(rows):
            c = (translate(2, rows), contribs)
        rows, vals = g
        if len(rows):
            g = (translate(3, rows), vals)
        return h, s, c, g, st, others, ssf_fb

    def native_rows_canonical(self, rows, kinds, sel):
        """Translate rows handed back by the home context's batched
        upsert (native.upsert_many — the import wire path) to canonical
        rows. Identity on the legacy path; in reader-shard mode every
        native row space is local and maps through the home context's
        reconciliation map (the caller must have synced new series
        first, so the map covers every returned row)."""
        if not self._reader_ctxs:
            return rows
        maps = self._ctx_maps[0]
        out = np.asarray(rows).copy()
        for pool_i, kmask in ((0, (kinds == 2) | (kinds == 3)),
                              (1, kinds == 4),
                              (2, kinds == 0),
                              (3, kinds == 1)):
            m = sel & kmask
            if m.any():
                lookup = np.frombuffer(maps[pool_i], dtype=np.int32)
                out[m] = lookup[out[m]]
        return out

    def reader_stats(self, lock_stats: bool = False) -> dict:
        """Per-context ingest attribution for Server.ingress_stats /
        flush telemetry: context order is [home] + reader shards.
        lock_stats=True also reads each context's commit-mutex record
        (meaningful only while vn_set_lock_stats is on)."""
        out = {
            "shards": len(self._reader_ctxs),
            "committed": list(self.reader_committed),
            "dropped": list(self.reader_dropped),
        }
        if lock_stats and self._native is not None:
            locks = []
            for ctx in self._all_ctxs():
                st = ctx.lock_stats()
                acq = st["acquisitions"]
                waits = sorted(st["wait_ns_samples"])
                holds = sorted(st["hold_ns_samples"])

                def pct(sorted_ns, q):
                    if not sorted_ns:
                        return 0
                    return sorted_ns[min(len(sorted_ns) - 1,
                                         int(q * len(sorted_ns)))]

                locks.append({
                    "acquisitions": acq,
                    "contended": st["contended"],
                    "contended_fraction": (st["contended"] / acq
                                           if acq else 0.0),
                    "wait_ns_p50": pct(waits, 0.50),
                    "wait_ns_p99": pct(waits, 0.99),
                    "hold_ns_p50": pct(holds, 0.50),
                    "hold_ns_p99": pct(holds, 0.99),
                })
            out["lock"] = locks
        return out

    def _drain_native_raw(self, detach_stage: bool = False):
        return self._drain_native_raw_ctx(self._native, 0, detach_stage)

    def _drain_native_raw_ctx(self, ctx, ctx_i: int,
                              detach_stage: bool = False):
        """Pull raw sample buffers + bookkeeping out of the C++ context.
        Caller holds the context lock. Samples drain BEFORE the new-series
        sync: a sample's series record is committed at-or-before the
        sample itself (same C++ critical section), so syncing afterwards
        can only over-adopt rows with no samples yet — never leave a
        drained sample without directory metadata.

        detach_stage (flush only): also detach the C++ staging plane —
        must happen in the same critical section as the epoch close so no
        staged sample is destroyed by the reset."""
        errs = int(ctx.errors)
        dropped = int(ctx.overload_dropped)
        if ctx_i == 0:
            e_seen, d_seen = self._native_errs_seen, self._native_drop_seen
            self._native_errs_seen = errs
            self._native_drop_seen = dropped
        else:
            j = ctx_i - 1
            e_seen = self._reader_errs_seen[j]
            d_seen = self._reader_drop_seen[j]
            self._reader_errs_seen[j] = errs
            self._reader_drop_seen[j] = dropped
        self.parse_errors += errs - e_seen
        delta = dropped - d_seen
        self.overload_dropped += delta
        # lifetime tally (never reset): self-telemetry consumes the
        # per-interval field above; soaks/operators read this one
        self.overload_dropped_total += delta
        if self.reader_dropped:
            # per-context shed attribution (conservation: committed ==
            # folded + shed, per reader)
            self.reader_dropped[ctx_i] += delta
        n = ctx.pending_histo
        h = ctx.drain_histo(n) if n else None
        n = ctx.pending_set
        s = ctx.drain_set(n) if n else None
        # sized by the actual pending counts: a fixed 4M-entry drain both
        # allocated ~50MB of scratch per (100ms-cadence) pump call and
        # silently destroyed anything beyond it at the epoch reset when
        # tpu_spill_cap is raised above the old constant
        n = ctx.pending_counter
        c = ctx.drain_counter(n)
        n = ctx.pending_gauge
        g = ctx.drain_gauge(n)
        st = None
        others: list = []
        ssf_fb: list = []
        if detach_stage:
            try:
                st = ctx.detach_stage()
            except AttributeError:  # stale .so without the staging API
                st = None
            # epoch close: pull buffered event/service-check lines and
            # Python-fallback SSF payloads in the SAME critical section —
            # the reset right after this drain clears both buffers, and
            # anything landing between a separate drain and the reset
            # would be destroyed
            others = ctx.drain_other()
            try:
                ssf_fb = ctx.drain_ssf_fallback()
            except AttributeError:  # stale .so without the SSF reader API
                pass
        self._sync_native_series(ctx, ctx_i)
        return h, s, c, g, st, others, ssf_fb

    def _apply_native_raw(self, raw, defer_histo_spill: bool = False):
        """Apply drained buffers to device/host pools (no context lock —
        device dispatch must not stall reader commits). The detached
        staging plane (raw[4]) and event lines (raw[5], both flush only)
        are the caller's to hand to the swapped epoch.

        defer_histo_spill (swap only): skip the histo spill fold and
        return the (rows, vals, wts) SoA for the caller to attach to the
        SwappedEpoch — extract_snapshot runs the fold off the ingest
        lock. Only the direct-fold path defers (mesh and plane-staging
        paths are host-cheap); returns None when nothing was deferred."""
        h, s, c, g, _st, _others, _ssf_fb = raw
        deferred = None
        if h is not None and len(h[0]):
            if self._mesh_pool is not None:
                self._mesh_pool.add_samples_bulk(*h)
            else:
                self._ensure_histo(self.directory.num_histo_rows)
                if self._native is not None and self.stage_depth > 0:
                    # with native staging on, the SoA batch holds only
                    # hot-row spill: fold it directly (K is small there;
                    # re-staging it in the Python plane would just add a
                    # second fold). Chunked: a drain after a stall can
                    # hold millions of spilled samples, and one fold's
                    # padded [N] arrays at that size are ~100MB — eight
                    # in flight was most of the RSS in the overload
                    # soak. Bounded chunks × the in-flight window keeps
                    # drain memory O(chunk), not O(backlog).
                    if defer_histo_spill:
                        deferred = h
                    else:
                        rows, vals, wts = h
                        chunk = _FOLD_CHUNK
                        for i in range(0, len(rows), chunk):
                            self._fold_batch_direct(
                                rows[i:i + chunk], vals[i:i + chunk],
                                wts[i:i + chunk])
                else:
                    self._device_histo_step(*h)
        if s is not None and len(s[0]):
            self._ensure_sets(self.directory.num_set_rows)
            self._device_set_step(*s)
        rows, contribs = c
        if len(rows):
            pool = self.scalars.counters
            np.add.at(pool.values, rows, contribs)
            pool.present[rows] = True
        rows, vals = g
        if len(rows):
            pool = self.scalars.gauges
            pool.values[rows] = vals  # in-order: last write wins
            pool.present[rows] = True
        return deferred

    # -- micro-folds (always-hot flush) --------------------------------------

    def _micro_active(self) -> bool:
        """Micro-folds engage only where the staged fold exists: staging
        on and no mesh (mesh rows bypass the staging plane entirely).
        Reader-shard mode also opts out: the mirror would need N
        per-context COO streams re-keyed to canonical rows mid-interval;
        the stacked flush-edge merge (ops/reader_stack.py) covers the
        same work, so always-hot flush stays a legacy-path feature.

        The device fault domain pauses micro-folds too: quarantined (or
        live-failed-over) workers have no device to mirror into, and an
        epoch whose mirror already faulted keeps every sample in the
        retained staging plane instead (conservation over warmth)."""
        return (self.micro_fold and self.stage_depth > 0
                and self._mesh_pool is None and not self._reader_ctxs
                and not self._host_live and not self.guard.quarantined
                and not self._micro_fault_epoch)

    def _ensure_micro(self) -> "mf.MicroFoldMirror":
        if self._micro is None:
            self._micro = mf.MicroFoldMirror(
                self.stage_depth, ledger=self.ledger,
                initial_rows=self._initial_histo_rows,
                shard=self._shard, guard=self.guard)
        return self._micro

    def micro_fold_pending(self) -> int:
        """Staged samples not yet streamed to the device mirror (the
        scheduler's due check; caller holds the worker ingest lock)."""
        if not self._micro_active():
            return 0
        if self._native is not None:
            try:
                return int(self._native.stage_pending)
            except AttributeError:  # stale .so without the delta API
                return 0
        if self._stage_count is None:
            return 0
        total = int(self._stage_count.sum())
        mark = self._ustage_mark
        if mark is not None:
            total -= int(mark[:len(self._stage_count)].sum())
        return total

    def micro_fold_due(self) -> bool:
        pending = self.micro_fold_pending()
        if pending <= 0:
            return False
        if pending >= self.micro_fold_rows:
            return True
        return (time.monotonic() - self._micro_last_drain
                >= self.micro_fold_max_age_s)

    def micro_fold_once(self) -> int:
        """One micro-fold: stream the staged samples accumulated since
        the last drain into the device mirror (ops/microfold.py), and —
        native mode — drain the pending scalar/set/spill SoA batches so
        swap inherits none of them either. Caller holds the worker
        ingest lock. Returns samples streamed."""
        if not self._micro_active():
            return 0
        self._micro_last_drain = time.monotonic()
        try:
            if self._native is not None:
                # mid-interval SoA drain first: counters are np.add.at in
                # drain order and gauges last-write-wins, so draining more
                # often splits the stream into ordered deltas — the folded
                # result is bitwise what one deadline-time drain produces
                self.drain_native()
                fed = self._micro_drain_native()
            else:
                fed = self._micro_drain_python()
        except dg.DeviceFaultError as exc:
            # the mirror is a CACHE of the staging plane — the plane
            # retains every sample (watermarks advanced, counts did
            # not), so dropping the mirror loses nothing. Micro-folding
            # pauses for the rest of the epoch; the swap folds the
            # retained plane exactly as if micro-fold were off.
            log.warning("micro-fold device fault (%s); mirror dropped, "
                        "epoch falls back to the staged plane", exc)
            self._micro = None
            self._micro_fault_epoch = True
            return 0
        if fed:
            self.micro_folds_total += 1
            self.micro_folds_epoch += 1
            gov = self.governor
            if gov is not None:
                try:
                    gov.note_micro_fold(fed)
                except AttributeError:
                    pass
        return fed

    def _micro_drain_native(self) -> int:
        """COO-drain the C++ staging plane's undrained delta into the
        mirror. drain_stage_delta advances the plane's per-row watermark
        WITHOUT touching counts, so the per-epoch depth cap (and the
        spill partitioning) is identical to a run with no micro-folds."""
        try:
            if self._native.stage_pending <= 0:
                return 0
        except AttributeError:  # stale .so without the delta API
            return 0
        micro = self._ensure_micro()
        fed = 0
        cap = 1 << 18
        while True:
            rows, slots, vals, wts = self._native.drain_stage_delta(cap)
            n = len(rows)
            if n == 0:
                break
            micro.feed(rows, slots, vals, wts)
            fed += n
            if n < cap:
                break
        return fed

    def _python_stage_delta(self) -> Optional[tuple]:
        """The Python staging plane's [mark, count) delta per row as one
        COO tuple (rows, slots, vals, wts — all copies), advancing the
        watermark; None when nothing is undrained. Touches only what
        _device_histo_step already wrote — it never forces the pending
        SoA batches through, so the spill-fold batch boundaries stay
        exactly the batch path's."""
        counts = self._stage_count
        if counts is None:
            return None
        rows_n = len(counts)
        mark = self._ustage_mark
        if mark is None or len(mark) < rows_n:
            nm = np.zeros(rows_n, np.int32)
            if mark is not None:
                nm[:len(mark)] = mark
            mark = self._ustage_mark = nm
        delta = counts - mark[:rows_n]
        live = np.flatnonzero(delta > 0)
        if not len(live):
            return None
        reps = delta[live]
        total = int(reps.sum())
        rows = np.repeat(live.astype(np.int32), reps)
        run_starts = np.cumsum(reps) - reps
        intra = (np.arange(total, dtype=np.int32)
                 - np.repeat(run_starts, reps).astype(np.int32))
        slots = np.repeat(mark[live], reps).astype(np.int32) + intra
        coo = (rows, slots, self._stage_vals[rows, slots],
               self._stage_wts[rows, slots])
        mark[live] = counts[live]
        return coo

    def _micro_drain_python(self) -> int:
        coo = self._python_stage_delta()
        if coo is None:
            return 0
        self._ensure_micro().feed(*coo)
        return len(coo[0])

    # -- epoch lifecycle ----------------------------------------------------

    def _reset_epoch(self) -> None:
        if getattr(self, "_native_epoch_closed", False):
            # flush already reset the context(s) atomically with its
            # drain; resetting again here would destroy new-epoch commits
            # that routed readers landed in the meantime
            self._native_epoch_closed = False
        else:
            if self._native is not None:
                self._native.reset()
            self._native_errs_seen = 0
            self._native_proc_seen = 0
            self._native_drop_seen = 0
            for i, ctx in enumerate(self._reader_ctxs):
                ctx.reset()
                self._reader_errs_seen[i] = 0
                self._reader_proc_seen[i] = 0
                self._reader_drop_seen[i] = 0
        # per-context local-row → canonical-row reconciliation maps, one
        # int32 array per pool kind (histo/set/counter/gauge), [home] +
        # readers. Rebuilt every epoch: context resets restart local rows
        # at 0 and the canonical directory is fresh too.
        self._ctx_maps = [tuple(array("i") for _ in range(4))
                          for _ in range(1 + len(self._reader_ctxs))]
        self._processed_py = 0
        self.parse_errors = getattr(self, "parse_errors", 0)
        # the epoch's per-tenant tallies were accumulated into the
        # lifetime totals by swap() before this reset (never reset the
        # totals — they are the cross-epoch truth, like processed_total)
        self.tenant_tallies.reset()
        self.directory = SeriesDirectory()
        self.scalars = HostScalars()
        # fresh epoch, fresh mirror fault state (the voided mirror was
        # epoch-scoped; a new epoch may micro-fold again if the guard is
        # otherwise healthy)
        self._micro_fault_epoch = False
        self._histo: Optional[HistoDeviceState] = None
        self._sets: Optional[jax.Array] = None
        # staged (sparse-host / dense-device) set store — the scalable
        # default; tpu_set_store: dense keeps the all-dense pool
        if self.set_store == "staged":
            from veneur_tpu.ops.staged_sets import StagedSetStore

            self._staged_sets = StagedSetStore(self.hll_precision,
                                               shard=self._shard,
                                               guard=self.guard,
                                               host=self._host_live)
        else:
            self._staged_sets = None
        # host raw-sample staging planes (see _device_histo_step); created
        # lazily alongside _histo
        self._stage_vals: Optional[np.ndarray] = None
        self._stage_wts: Optional[np.ndarray] = None
        self._stage_count: Optional[np.ndarray] = None
        # micro-fold watermark for the Python plane: slots
        # [mark[r], count[r]) are staged but not yet mirrored
        self._ustage_mark: Optional[np.ndarray] = None
        self.micro_folds_epoch = 0
        self._micro_last_drain = time.monotonic()
        # pending SoA buffers (host)
        self._ph_rows: list[int] = []
        self._ph_vals: list[float] = []
        self._ph_wts: list[float] = []
        self._ps_rows: list[int] = []
        self._ps_idx: list[int] = []
        self._ps_rank: list[int] = []
        # import buffers (global tier)
        self._imp_digests: dict[int, list] = {}
        self._imp_hll: dict[int, np.ndarray] = {}
        # unique-timeseries HLL registers (host, tiny)
        m = hll_ops.num_registers(self.hll_precision)
        self._umts = (
            np.zeros(m, dtype=np.int8) if self.count_unique_timeseries else None
        )

    def _ensure_histo(self, needed_rows: int) -> None:
        # a tripped breaker fails the live epoch over right here, before
        # any pool is created or grown on the dying device — the server's
        # post-flush device_guard_tick() would do it anyway, but ingest
        # between the trip and the tick must not re-fault
        if self.guard.quarantined and not self._host_live:
            self._quarantine_live()
        # keep one scratch row free at the top for gather/scatter padding
        # (under sharding the scratch row — logical S-1 — maps to physical
        # S-1, shard D-1's last local row, so sizing is shard-oblivious)
        if self._host_live or isinstance(self._histo, he.HostHistoState):
            if self._histo is None:
                rows = _next_pow2(needed_rows + 1, self._initial_histo_rows)
                self._histo = he.HostHistoState.create(rows, self.capacity)
            elif needed_rows + 1 > self._histo.num_rows:
                self._flush_pending_histos()
                self._histo = self._histo.grow(
                    _next_pow2(needed_rows + 1, self._histo.num_rows * 2))
            return
        if self._histo is None:
            rows = _next_pow2(needed_rows + 1, self._initial_histo_rows)
            st = HistoDeviceState.create(rows, self.capacity)
            self._histo = (st if self._shard is None
                           else st.placed(self._shard))
        elif needed_rows + 1 > self._histo.num_rows:
            self._flush_pending_histos()  # pending lids reference old layout
            if isinstance(self._histo, he.HostHistoState):
                # the pending fold itself faulted and quarantined us
                self._ensure_histo(needed_rows)
                return
            new_rows = _next_pow2(needed_rows + 1, self._histo.num_rows * 2)
            # HBM pressure valve: growth doubles the pool's device
            # footprint and the donating grow programs free the OLD
            # buffers only after the new ones materialize. Pre-flight
            # the allocation with a throwaway (non-donated) buffer of
            # the target size: an OOM here is a clean fault — the old
            # pool is untouched — and degrades to the host engine
            # instead of faulting mid-grow. Only worth a dispatch when
            # the target is big enough to plausibly OOM: pools are
            # re-created per epoch, so an unconditional pre-flight would
            # tax every interval's early-growth ladder (~0.5ms/dispatch)
            # to guard kB-scale allocations that cannot exhaust HBM.
            try:
                if (self.guard.enabled and new_rows * self.capacity * 12
                        >= _GROW_PREFLIGHT_MIN_BYTES):
                    def _preflight():
                        probe = jnp.zeros((new_rows, 2 * self.capacity),
                                          jnp.float32)
                        if self._shard is not None:
                            probe = self._shard.place(probe)
                        jax.block_until_ready(probe)

                    self.guard.call("grow", _preflight, retryable=True)
                self._histo = self.guard.call(
                    "grow", self._histo.grow, new_rows, shard=self._shard)
            except dg.DeviceFaultError as exc:
                self.guard.bump("device.valve.grow_oom")
                self.guard.trip(f"pool growth to {new_rows} rows faulted "
                                f"[{exc.kind}] — HBM valve")
                self._quarantine_live()
                # _quarantine_live moved the (old-size) pool to host;
                # grow it there
                self._histo = self._histo.grow(new_rows)

    def _ensure_sets(self, needed_rows: int) -> None:
        if self.guard.quarantined and not self._host_live:
            self._quarantine_live()
        if self._staged_sets is not None:
            return  # the staged store sizes itself
        if self._host_live or isinstance(self._sets, np.ndarray):
            if self._sets is None:
                rows = _next_pow2(needed_rows + 1, self._initial_set_rows)
                m = hll_ops.num_registers(self.hll_precision)
                self._sets = np.zeros((rows, m), np.int8)
            elif needed_rows + 1 > self._sets.shape[0]:
                self._flush_pending_sets()
                new_rows = _next_pow2(needed_rows + 1,
                                      self._sets.shape[0] * 2)
                grown = np.zeros((new_rows, self._sets.shape[1]), np.int8)
                grown[:self._sets.shape[0]] = self._sets
                self._sets = grown
            return
        if self._sets is None:
            rows = _next_pow2(needed_rows + 1, self._initial_set_rows)
            pool = hll_ops.init_pool(rows, self.hll_precision)
            self._sets = (pool if self._shard is None
                          else self._shard.place(pool))
        elif needed_rows + 1 > self._sets.shape[0]:
            self._flush_pending_sets()
            if isinstance(self._sets, np.ndarray):
                self._ensure_sets(needed_rows)
                return
            new_rows = _next_pow2(needed_rows + 1, self._sets.shape[0] * 2)
            try:
                self._sets = self.guard.call(
                    "grow",
                    (_grow_2d if self._shard is None
                     else self._shard.grow_2d), self._sets, new_rows)
            except dg.DeviceFaultError as exc:
                self.guard.trip(f"set pool growth to {new_rows} rows "
                                f"faulted [{exc.kind}]")
                self._quarantine_live()
                self._ensure_sets(needed_rows)

    # -- ingest -------------------------------------------------------------

    def process_metric(self, m: UDPMetric) -> None:
        """Route one parsed sample into the right pool
        (reference Worker.ProcessMetric, worker.go:344-394)."""
        self.processed += 1
        mtype = m.key.type
        scope_class = classify(mtype, m.scope)
        tenant = ""
        if self.tenancy is not None:
            # budgeted admission (core/tenancy.py): a sample for a series
            # the tenant ledger refuses is rejected HERE, before any row
            # exists — already-admitted series always pass (the ledger is
            # idempotent), so innocent dashboards never flap. Status
            # checks are host-health plumbing, never budgeted.
            tenant = tenant_of(m.tags, self.tenancy.tag_key)
            tt = self.tenant_tallies
            tt.accepted[tenant] = tt.accepted.get(tenant, 0) + 1
            # reader-shard mode takes the Python branch too: its Python-
            # path series live in the Python pools (the canonical row
            # space), so admission happens here exactly like non-native
            if ((self._native is None or self._reader_ctxs)
                    and mtype != "status"):
                if not self._admit_sample(tenant, m.key, scope_class,
                                          mtype):
                    tt.rejected[tenant] = tt.rejected.get(tenant, 0) + 1
                    return
                tt.kept[tenant] = tt.kept.get(tenant, 0) + 1
        if self.count_unique_timeseries:
            self._sample_timeseries(m, mtype, scope_class)

        if mtype == "counter":
            self._host_counter(m.key, scope_class, m.tags,
                               counter_contribution(m.value, m.sample_rate))
        elif mtype == "gauge":
            self._host_gauge(m.key, scope_class, m.tags, float(m.value))
        elif mtype in ("histogram", "timer"):
            row = self._upsert_histo(m.key, scope_class, m.tags, tenant)
            if self._mesh_pool is not None:
                self._mesh_pool.add_sample(
                    row, float(m.value), 1.0 / m.sample_rate,
                    host_slot=m.digest)
                return
            self._ensure_histo(
                max(self.directory.num_histo_rows, row + 1))
            self._ph_rows.append(row)
            self._ph_vals.append(float(m.value))
            self._ph_wts.append(1.0 / m.sample_rate)
            if len(self._ph_rows) >= self.batch_size:
                self._flush_pending_histos()
        elif mtype == "set":
            row = self._upsert_set(m.key, scope_class, m.tags, tenant)
            self._ensure_sets(max(self.directory.num_set_rows, row + 1))
            h = self._set_hash64(str(m.value).encode("utf-8"))
            idx, rank = hll_ops.split_hashes(
                np.array([h], dtype=np.uint64), self.hll_precision
            )
            self._ps_rows.append(row)
            self._ps_idx.append(int(idx[0]))
            self._ps_rank.append(int(rank[0]))
            if len(self._ps_rows) >= self.batch_size:
                self._flush_pending_sets()
        elif mtype == "status":
            self._host_status(m)

    def _admit_sample(self, tenant: str, key: MetricKey,
                      scope_class: ScopeClass, mtype: str) -> bool:
        """Python-path budget gate: a series already rowed this epoch was
        admitted (rejected series never get rows here); otherwise ask the
        shared ledger — which is free for already-admitted series and
        only consumes budget for genuinely new ones."""
        if mtype in ("histogram", "timer"):
            index = self.directory.histo.index
        elif mtype == "set":
            index = self.directory.sets.index
        elif mtype == "counter":
            index = self.scalars.counters.index
        else:
            index = self.scalars.gauges.index
        if (key, scope_class) in index:
            return True
        return self.tenancy.admit(tenant, _series_budget_id(scope_class, key))

    def _upsert_histo(self, key: MetricKey, scope_class: ScopeClass,
                      tags: list[str], tenant: str = "") -> int:
        # reader-shard mode routes Python-path samples through the
        # Python pools: the canonical row space IS the Python directory
        # there, and a native upsert would hand back a context-LOCAL row
        if self._native is not None and not self._reader_ctxs:
            row = self._native.upsert(key.name, key.type, key.joined_tags,
                                      int(scope_class))
            # adoption is deferred and batched: metadata drains every
            # 1024 new series and always before extraction (swap's
            # native drain syncs) — a per-upsert drain dominated the
            # global tier's import cost
            if self._native.pending_new_series >= 1024:
                self._sync_native_series()
            return row
        row, _ = self.directory.upsert_histo(key, scope_class, tags,
                                             tenant=tenant)
        return row

    def _upsert_set(self, key: MetricKey, scope_class: ScopeClass,
                    tags: list[str], tenant: str = "") -> int:
        if self._native is not None and not self._reader_ctxs:
            row = self._native.upsert(key.name, "set", key.joined_tags,
                                      int(scope_class))
            if self._native.pending_new_series >= 1024:
                self._sync_native_series()
            return row
        row, _ = self.directory.upsert_set(key, scope_class, tags,
                                           tenant=tenant)
        return row

    def _should_count_timeseries(self, mtype: str, cls: ScopeClass) -> bool:
        """Forwarding-aware unique-timeseries gating (reference
        SampleTimeseries, worker.go:300-341): a local instance skips series
        it forwards upstream (the global instance counts those)."""
        if not self.is_local:
            return True
        if mtype in ("counter", "gauge"):
            return cls != ScopeClass.GLOBAL
        if mtype in ("histogram", "set", "timer"):
            return cls == ScopeClass.LOCAL
        return True

    def _insert_timeseries(self, digest: int) -> None:
        h = fmix64(digest)
        idx, rank = hll_ops.split_hashes(
            np.array([h], dtype=np.uint64), self.hll_precision
        )
        self._umts[idx[0]] = max(self._umts[idx[0]], rank[0])

    def _sample_timeseries_key(self, name: str, mtype: str, joined: str,
                               cls: ScopeClass) -> None:
        """Native-path unique-timeseries sampling, keyed by series identity
        (idempotent, so per-series feeding agrees with per-sample)."""
        if self._umts is not None and self._should_count_timeseries(mtype, cls):
            self._insert_timeseries(metric_digest(name, mtype, joined))

    def _sample_timeseries(self, m: UDPMetric, mtype: str,
                           cls: ScopeClass) -> None:
        """Python-path unique-timeseries sampling (one call per sample)."""
        if self._umts is not None and self._should_count_timeseries(mtype, cls):
            self._insert_timeseries(m.digest)

    # host scalar paths

    def _host_counter(self, key: MetricKey, scope_class: ScopeClass,
                      tags: list[str], contribution: int) -> None:
        pool = self.scalars.counters
        if self._native is not None and not self._reader_ctxs:
            row = self._native.upsert(key.name, "counter", key.joined_tags,
                                      int(scope_class))
            self._sync_native_series()
        else:
            row = pool.upsert(key, scope_class, tags, route_info(tags))
        pool.values[row] += contribution
        pool.present[row] = True

    def _host_gauge(self, key: MetricKey, scope_class: ScopeClass,
                    tags: list[str], value: float) -> None:
        pool = self.scalars.gauges
        if self._native is not None and not self._reader_ctxs:
            row = self._native.upsert(key.name, "gauge", key.joined_tags,
                                      int(scope_class))
            self._sync_native_series()
        else:
            row = pool.upsert(key, scope_class, tags, route_info(tags))
        pool.values[row] = value
        pool.present[row] = True

    def _host_status(self, m: UDPMetric) -> None:
        sc = self.scalars
        k = (m.key, ScopeClass.LOCAL)
        row = sc.status_index.get(k)
        if row is None:
            row = len(sc.status_values)
            sc.status_index[k] = row
            sc.status_meta.append(
                (m.key, m.tags, ScopeClass.LOCAL, route_info(m.tags))
            )
            sc.status_values.append(None)
        sc.status_values[row] = (float(m.value), m.message, m.hostname)

    # -- pending-batch device steps ----------------------------------------

    def _flush_pending_histos(self) -> None:
        if not self._ph_rows:
            return
        rows = np.asarray(self._ph_rows, dtype=np.int32)
        vals = np.asarray(self._ph_vals, dtype=np.float32)
        wts = np.asarray(self._ph_wts, dtype=np.float32)
        self._ph_rows, self._ph_vals, self._ph_wts = [], [], []
        self._device_histo_step(rows, vals, wts)

    def _ensure_stage(self) -> None:
        """Size the host staging planes to the digest pool's row count."""
        rows = self._histo.num_rows
        if self._stage_count is None:
            self._stage_vals = np.zeros((rows, self.stage_depth), np.float32)
            self._stage_wts = np.zeros((rows, self.stage_depth), np.float32)
            self._stage_count = np.zeros(rows, np.int32)
        elif len(self._stage_count) < rows:
            old = len(self._stage_count)
            nv = np.zeros((rows, self.stage_depth), np.float32)
            nw = np.zeros((rows, self.stage_depth), np.float32)
            nc = np.zeros(rows, np.int32)
            nv[:old] = self._stage_vals
            nw[:old] = self._stage_wts
            nc[:old] = self._stage_count
            self._stage_vals, self._stage_wts, self._stage_count = nv, nw, nc

    def _device_histo_step(self, rows: np.ndarray, vals: np.ndarray,
                           wts: np.ndarray) -> None:
        """Stage a raw-sample batch host-side; the digest compress is paid
        once per interval in _histo_fold_staged (see its docstring).

        Pure vectorized numpy — no device dispatch on the common path, so
        ingest throughput is bounded by parse + store, not by per-batch
        [K, 2C] sorts. Rows whose staging is full spill through the direct
        per-batch device fold; a row with sustained volume stays full, so
        its samples keep taking the spill path, where a hot batch's K
        (unique rows) is small and the gathered fold is cheap."""
        n = len(rows)
        if n == 0:
            return
        B = self.stage_depth
        self._ensure_stage()
        order = np.argsort(rows, kind="stable")
        srows = rows[order]
        svals = vals[order]
        swts = wts[order]
        newrun = np.empty(n, bool)
        newrun[0] = True
        np.not_equal(srows[1:], srows[:-1], out=newrun[1:])
        starts = np.flatnonzero(newrun)
        runid = np.cumsum(newrun) - 1
        # rank of each sample within its row's run → its staging slot
        slots = self._stage_count[srows] + (np.arange(n) - starts[runid])
        run_rows = srows[starts]
        run_len = np.diff(np.append(starts, n))
        fit = slots < B
        if fit.all():
            self._stage_vals[srows, slots] = svals
            self._stage_wts[srows, slots] = swts
            self._stage_count[run_rows] += run_len.astype(np.int32)
            return
        keep = fit
        self._stage_vals[srows[keep], slots[keep]] = svals[keep]
        self._stage_wts[srows[keep], slots[keep]] = swts[keep]
        self._stage_count[run_rows] = np.minimum(
            self._stage_count[run_rows] + run_len, B).astype(np.int32)
        spill = ~keep
        self._fold_batch_direct(srows[spill], svals[spill], swts[spill])

    @staticmethod
    def _pad_spill_batch(rows: np.ndarray, vals: np.ndarray,
                         wts: np.ndarray, scratch: int):
        """Pow2-pad one spill batch for the ingest step: padding sample
        slots point at `scratch` with weight 0, which the step treats
        as absent. Shared by the live-pool and swapped-epoch folds so
        their jit shapes (and semantics) cannot drift."""
        uniq, inverse = np.unique(rows, return_inverse=True)
        k = _next_pow2(len(uniq), 64)
        n = _next_pow2(len(vals), 256)
        active = np.full(k, scratch, dtype=np.int32)
        active[: len(uniq)] = uniq
        lids = np.full(n, k - 1, dtype=np.int32)
        lids[: len(vals)] = inverse
        v = np.zeros(n, dtype=np.float32)
        v[: len(vals)] = vals
        w = np.zeros(n, dtype=np.float32)
        w[: len(vals)] = wts
        return active, lids, v, w

    def _fold_batch_direct(self, rows: np.ndarray, vals: np.ndarray,
                           wts: np.ndarray) -> None:
        """Gather→add_batch→scatter device fold of one sample batch — the
        spill path for rows whose staging plane is full."""
        h = self._histo
        assert h is not None
        active, lids, v, w = self._pad_spill_batch(
            rows, vals, wts, h.num_rows - 1)

        if isinstance(h, he.HostHistoState):
            # quarantined: the host engine's bit-identical ingest twin
            out = he.np_ingest_step(*h.fields(), active, lids, v, w,
                                    compression=self.compression)
            (h.means, h.weights, h.dmin, h.dmax, h.drecip, h.drecip_c,
             h.lmin, h.lmax, h.lsum, h.lsum_c, h.lweight, h.lweight_c,
             h.lrecip, h.lrecip_c) = out
            return

        sh = self._shard
        try:
            if sh is not None:
                # replicated COO, physical `active`: every shard folds the
                # bit-identical batch and keeps only the writes it owns
                # (ops/series_shard.ingest_step — the OOB-foreign remap)
                out = self.guard.call(
                    "fold", sh.ingest_step,
                    *h.fields(),
                    sh.replicate(sh.phys_rows(active, h.num_rows)),
                    sh.replicate(lids), sh.replicate(v), sh.replicate(w),
                )
            else:
                out = self.guard.call(
                    "fold", _histo_ingest_step,
                    h.means, h.weights, h.dmin, h.dmax, h.drecip,
                    h.drecip_c, h.lmin, h.lmax, h.lsum, h.lsum_c,
                    h.lweight, h.lweight_c, h.lrecip, h.lrecip_c,
                    jnp.asarray(active), jnp.asarray(lids), jnp.asarray(v),
                    jnp.asarray(w), compression=self.compression,
                )
        except dg.DeviceFaultError:
            # the fold donates the pool, so no in-place retry. The host
            # inputs are still ours: if the breaker tripped, quarantine
            # the live epoch (pool → host) and fold this batch there;
            # otherwise re-stage the samples into the pending SoA — the
            # next flush (or next spill drain) replays them naturally,
            # and a still-sick device walks the streak to the breaker.
            if self.guard.quarantined:
                self._quarantine_live()
                self._fold_batch_direct(rows, vals, wts)
            else:
                self._ph_rows.extend(rows.tolist())
                self._ph_vals.extend(vals.tolist())
                self._ph_wts.extend(wts.tolist())
            return
        (h.means, h.weights, h.dmin, h.dmax, h.drecip, h.drecip_c,
         h.lmin, h.lmax, h.lsum, h.lsum_c, h.lweight, h.lweight_c,
         h.lrecip, h.lrecip_c) = out
        # bound the async dispatch queue: an un-executed fold holds its
        # input buffers, and a backend slower than the offered load
        # would otherwise queue folds without limit (observed: 2.7GB RSS
        # growth in a 10-min overload soak). Blocking the DRAINING
        # thread here throttles drain to device speed — readers are C++
        # and unaffected; backlog then accumulates in the C++ spill
        # batches, which cap and shed load (drop-don't-block, the same
        # policy as trace.Client backpressure).
        self._inflight_folds += 1
        if self._inflight_folds >= 8:
            h.means.block_until_ready()
            self._inflight_folds = 0

    def _fold_spill_chunk(self, fields: tuple, rows: np.ndarray,
                          vals: np.ndarray, wts: np.ndarray,
                          pool_rows: int) -> tuple:
        """_fold_batch_direct's twin for a SWAPPED epoch: folds one spill
        chunk into the detached full-pool `fields` tuple instead of the
        live self._histo — same shapes, same jit specialization, so the
        compile _fold_batch_direct paid mid-interval is reused here.
        Runs in extract_snapshot, off the ingest lock. Padding entries
        carry weight 0, which the ingest step treats as absent (same
        invariant _fold_batch_direct relies on for its scratch row)."""
        active, lids, v, w = self._pad_spill_batch(
            rows, vals, wts, pool_rows - 1)
        led = self.ledger
        sh = self._shard
        if sh is not None:
            # replication is a real per-device transfer: book the batch
            # once per shard (the transfer-diet pin stays honest), then
            # fold it everywhere with the OOB-foreign remap
            d = sh.shards
            act = sh.phys_rows(active, pool_rows)
            ups = []
            for a in (act, lids, v, w):
                led.count_h2d_shards([a.nbytes] * d, "spill")
                ups.append(sh.replicate(a))
            return self.guard.call("spill", sh.ingest_step, *fields, *ups)
        return self.guard.call(
            "spill", _histo_ingest_step,
            *fields,
            led.h2d(active, "spill"), led.h2d(lids, "spill"),
            led.h2d(v, "spill"), led.h2d(w, "spill"),
            compression=self.compression,
        )

    def _flush_pending_sets(self) -> None:
        if not self._ps_rows:
            return
        rows = np.asarray(self._ps_rows, dtype=np.int32)
        idx = np.asarray(self._ps_idx, dtype=np.int32)
        rank = np.asarray(self._ps_rank, dtype=np.int8)
        self._ps_rows, self._ps_idx, self._ps_rank = [], [], []
        self._device_set_step(rows, idx, rank)

    def _device_set_step(self, rows: np.ndarray, idx: np.ndarray,
                         rank: np.ndarray) -> None:
        if self._staged_sets is not None:
            self._staged_sets.insert(rows, idx, rank)
            return
        regs = self._sets
        assert regs is not None
        n = _next_pow2(len(rows), 256)
        scratch = regs.shape[0] - 1
        prow = np.full(n, scratch, dtype=np.int32)
        prow[: len(rows)] = rows
        pidx = np.zeros(n, dtype=np.int32)
        pidx[: len(rows)] = idx
        prank = np.zeros(n, dtype=np.int8)
        prank[: len(rows)] = rank
        if isinstance(regs, np.ndarray):
            # quarantined: host numpy registers, same scatter-max
            self._sets = he.np_hll_insert_batch(
                regs, prow.astype(np.int64), pidx.astype(np.int64), prank)
            return
        sh = self._shard
        try:
            if sh is not None:
                # int8 scatter-max is order- and placement-independent, so
                # the sharded insert is bit-identical by construction;
                # padding rows (scratch, rank 0) stay a no-op max on their
                # owner. The sharded program donates the plane — no retry.
                self._sets = self.guard.call(
                    "sets", sh.hll_insert,
                    regs, sh.replicate(sh.phys_rows(prow, regs.shape[0])),
                    sh.replicate(pidx), sh.replicate(prank))
            else:
                self._sets = self.guard.call(
                    "sets", hll_ops.insert_batch,
                    regs, jnp.asarray(prow), jnp.asarray(pidx),
                    jnp.asarray(prank), retryable=True)
        except dg.DeviceFaultError:
            # max-idempotent: re-applying on host after a partial device
            # write only re-asserts ranks. Pull the plane down and redo.
            if self.guard.quarantined:
                self._quarantine_live()
            else:
                self._sets = self._sets_to_host(regs)
            self._device_set_step(rows, idx, rank)

    # -- import path (global tier) ------------------------------------------

    def import_digest(
        self, key: MetricKey, tags: list[str], mtype: str,
        scope_class: ScopeClass, means: np.ndarray, weights: np.ndarray,
        dmin: float, dmax: float, drecip: float,
    ) -> None:
        """Buffer a downstream instance's digest for row-wise merge at flush
        (reference Histo.Merge path, worker.go:438-495)."""
        self.imported += 1
        row = self._upsert_histo(key, scope_class, tags)
        if self._mesh_pool is not None:
            # mesh path: centroids re-ingest as weighted samples — the
            # reference's own Merge semantics (merging_digest.go:374-389:
            # min/max evolve from centroid means, reciprocalSum carried
            # exactly)
            self._mesh_pool.add_centroids(
                row, np.asarray(means, np.float32),
                np.asarray(weights, np.float32), float(drecip))
            return
        self._ensure_histo(max(self.directory.num_histo_rows, row + 1))
        self._imp_digests.setdefault(row, []).append(
            (np.asarray(means, np.float32), np.asarray(weights, np.float32),
             float(dmin), float(dmax), float(drecip))
        )

    def import_digests_soa(self, rows: np.ndarray, lo: np.ndarray,
                           hi: np.ndarray, means_flat: np.ndarray,
                           weights_flat: np.ndarray, dmin: np.ndarray,
                           dmax: np.ndarray, drecip: np.ndarray) -> None:
        """Batched digest import from a decoded wire batch: rows were
        already assigned by the native batched upsert (vn_upsert_many),
        so no per-metric directory work remains — only buffering views
        of the flat centroid arrays for the flush-time merge."""
        k = len(rows)
        if not k:
            return
        self.imported += k
        if self._mesh_pool is not None:
            for i in range(k):
                self._mesh_pool.add_centroids(
                    int(rows[i]), means_flat[lo[i]:hi[i]],
                    weights_flat[lo[i]:hi[i]], float(drecip[i]))
            return
        self._ensure_histo(max(self.directory.num_histo_rows,
                               int(rows.max()) + 1))
        imp = self._imp_digests
        setdefault = imp.setdefault
        rl = rows.tolist()
        lol = lo.tolist()
        hil = hi.tolist()
        mnl = dmin.tolist()
        mxl = dmax.tolist()
        rcl = drecip.tolist()
        for i in range(k):
            setdefault(rl[i], []).append(
                (means_flat[lol[i]:hil[i]], weights_flat[lol[i]:hil[i]],
                 mnl[i], mxl[i], rcl[i]))

    def import_counter_rows(self, rows: np.ndarray,
                            values: np.ndarray) -> None:
        """Batched counter import by pre-assigned rows (forced-global
        semantics were applied at upsert)."""
        k = len(rows)
        if not k:
            return
        self.imported += k
        pool = self.scalars.counters
        pool.ensure(int(rows.max()) + 1)
        np.add.at(pool.values, rows, values.astype(np.int64))
        pool.present[rows] = True

    def import_gauge_rows(self, rows: np.ndarray,
                          values: np.ndarray) -> None:
        """Batched gauge import: duplicates resolve arbitrarily, which
        is the reference's own semantics for global gauges
        (random-write-wins, README.md:262)."""
        k = len(rows)
        if not k:
            return
        self.imported += k
        pool = self.scalars.gauges
        pool.ensure(int(rows.max()) + 1)
        pool.values[rows] = values
        pool.present[rows] = True

    def import_hll_row(self, row: int, registers: np.ndarray) -> None:
        """Register import by pre-assigned row."""
        self.imported += 1
        if len(registers) != (1 << self.hll_precision):
            raise ValueError(
                f"HLL payload has {len(registers)} registers, expected"
                f" {1 << self.hll_precision}")
        if self._staged_sets is not None:
            self._staged_sets.import_dense(row, registers)
            return
        self._ensure_sets(max(self.directory.num_set_rows, row + 1))
        prev = self._imp_hll.get(row)
        regs = np.asarray(registers, np.int8)
        self._imp_hll[row] = regs if prev is None else np.maximum(prev, regs)

    def import_hll(self, key: MetricKey, tags: list[str],
                   scope_class: ScopeClass, registers: np.ndarray) -> None:
        self.imported += 1
        row = self._upsert_set(key, scope_class, tags)
        if self._staged_sets is not None:
            self._staged_sets.import_dense(row, registers)
            return
        self._ensure_sets(max(self.directory.num_set_rows, row + 1))
        prev = self._imp_hll.get(row)
        regs = np.asarray(registers, np.int8)
        self._imp_hll[row] = regs if prev is None else np.maximum(prev, regs)

    def import_counter(self, key: MetricKey, tags: list[str],
                       value: int) -> None:
        """Imported counters are global by definition
        (reference worker.go:404-407, 449-451)."""
        self.imported += 1
        self._host_counter(key, ScopeClass.GLOBAL, tags, int(value))

    def import_gauge(self, key: MetricKey, tags: list[str],
                     value: float) -> None:
        self.imported += 1
        self._host_gauge(key, ScopeClass.GLOBAL, tags, float(value))

    def _merge_imports(self) -> None:
        if self._imp_digests:
            h = self._histo
            assert h is not None
            rows = sorted(self._imp_digests)
            c = self.capacity
            widths = {
                r: sum(len(m) for m, *_ in self._imp_digests[r])
                for r in rows
            }
            w_bucket = _next_pow2(max(widths.values()), c)
            k = _next_pow2(len(rows), 16)
            scratch = h.num_rows - 1
            arows = np.full(k, scratch, dtype=np.int32)
            imp_means = np.full((k, w_bucket), np.inf, dtype=np.float32)
            imp_w = np.zeros((k, w_bucket), dtype=np.float32)
            imp_min = np.full(k, np.inf, dtype=np.float32)
            imp_max = np.full(k, -np.inf, dtype=np.float32)
            imp_recip = np.zeros(k, dtype=np.float32)
            for i, r in enumerate(rows):
                arows[i] = r
                off = 0
                for m, wts, mn, mx, rc in self._imp_digests[r]:
                    nz = wts > 0
                    cnt = int(nz.sum())
                    imp_means[i, off:off + cnt] = m[nz]
                    imp_w[i, off:off + cnt] = wts[nz]
                    off += cnt
                    imp_min[i] = min(imp_min[i], mn)
                    imp_max[i] = max(imp_max[i], mx)
                    imp_recip[i] += rc
            self._imp_digests = {}

            def _host_digest_merge():
                hh = self._histo
                out = he.np_import_step(
                    hh.means, hh.weights, hh.dmin, hh.dmax, hh.drecip,
                    hh.drecip_c, arows, imp_means, imp_w, imp_min,
                    imp_max, imp_recip, compression=self.compression)
                (hh.means, hh.weights, hh.dmin, hh.dmax, hh.drecip,
                 hh.drecip_c) = out

            if isinstance(h, he.HostHistoState):
                _host_digest_merge()
            else:
                sh = self._shard
                try:
                    if sh is not None:
                        out = self.guard.call(
                            "import", sh.import_step,
                            h.means, h.weights, h.dmin, h.dmax, h.drecip,
                            h.drecip_c,
                            sh.replicate(sh.phys_rows(arows, h.num_rows)),
                            sh.replicate(imp_means), sh.replicate(imp_w),
                            sh.replicate(imp_min), sh.replicate(imp_max),
                            sh.replicate(imp_recip),
                        )
                    else:
                        out = self.guard.call(
                            "import", _histo_import_step,
                            h.means, h.weights, h.dmin, h.dmax, h.drecip,
                            h.drecip_c,
                            jnp.asarray(arows), jnp.asarray(imp_means),
                            jnp.asarray(imp_w), jnp.asarray(imp_min),
                            jnp.asarray(imp_max), jnp.asarray(imp_recip),
                            compression=self.compression,
                        )
                    (h.means, h.weights, h.dmin, h.dmax, h.drecip,
                     h.drecip_c) = out
                except dg.DeviceFaultError as exc:
                    # the merge runs at swap — there is no later retry
                    # point for this epoch, so one fault here forces the
                    # failover (the import buffers are already drained
                    # into locals; the host merge conserves them all)
                    self.guard.trip(f"import merge faulted [{exc.kind}]")
                    self._quarantine_live()
                    _host_digest_merge()

        if self._imp_hll:
            regs = self._sets
            assert regs is not None
            rows = sorted(self._imp_hll)
            k = len(rows)
            arows = np.asarray(rows, dtype=np.int32)
            imp = np.stack([self._imp_hll[r] for r in rows])
            self._imp_hll = {}

            def _host_hll_merge():
                np.maximum.at(self._sets, arows.astype(np.int64), imp)

            if isinstance(regs, np.ndarray):
                _host_hll_merge()
            else:
                sh = self._shard
                try:
                    if sh is not None:
                        self._sets = self.guard.call(
                            "import", sh.hll_max_rows,
                            regs,
                            sh.replicate(sh.phys_rows(arows,
                                                      regs.shape[0])),
                            sh.replicate(imp))
                    else:
                        self._sets = self.guard.call(
                            "import",
                            lambda r, a, m: r.at[a].max(m, mode="drop"),
                            regs, jnp.asarray(arows), jnp.asarray(imp),
                            retryable=True)
                except dg.DeviceFaultError as exc:
                    self.guard.trip(f"HLL import merge faulted "
                                    f"[{exc.kind}]")
                    self._quarantine_live()
                    _host_hll_merge()

    # -- device fault domain -------------------------------------------------

    def _sets_to_host(self, regs) -> np.ndarray:
        """d2h one dense register plane to logical row order; on a hard
        device loss the readback itself can fail, in which case the set
        state restarts empty (logged — honest degraded mode)."""
        try:
            d = np.array(np.asarray(regs), copy=True)
        except Exception:
            log.exception("set pool readback failed during quarantine;"
                          " restarting host registers empty")
            return np.zeros(regs.shape, np.int8)
        if self._shard is not None:
            d = d[self._shard.perm_l2p(d.shape[0])]
        return d

    def _quarantine_live(self) -> None:
        """Fail the LIVE epoch's device state over to the host engine
        (ops/host_engine.py, bit-identical per metric class). Caller
        holds the ingest lock. Idempotent. The d2h snapshots are the one
        device interaction left; on a hard-lost device they can fail
        too, and then the affected pool restarts empty — counted and
        logged, with the retained staging plane and pending SoA batches
        still replaying everything they hold."""
        if self._host_live:
            return
        self._host_live = True
        h = self._histo
        if h is not None and not isinstance(h, he.HostHistoState):
            try:
                perm = (self._shard.perm_l2p(h.num_rows)
                        if self._shard is not None else None)
                self._histo = he.HostHistoState.from_fields(
                    h.fields(), perm=perm)
            except Exception:
                log.exception("digest pool readback failed during "
                              "quarantine; restarting host pools empty")
                self._histo = he.HostHistoState.create(
                    h.num_rows, self.capacity)
        s = self._sets
        if s is not None and not isinstance(s, np.ndarray):
            self._sets = self._sets_to_host(s)
        if self._staged_sets is not None:
            self._staged_sets.to_host()
        # the mirror is device memory; the staging plane retained every
        # sample it mirrored (watermark drains never consumed counts),
        # so dropping it loses nothing and the swap folds the plane
        self._micro = None
        self._micro_fault_epoch = True
        self.guard.bump("device.guard.quarantines")
        log.warning("live epoch quarantined to the host engine (%s)",
                    self.guard.trip_reason)

    def _readmit_device(self) -> None:
        """Re-upload the host pools and leave host mode (the probe
        succeeded; caller holds the ingest lock)."""
        if not self._host_live:
            return
        sh = self._shard
        h = self._histo
        if isinstance(h, he.HostHistoState):
            if sh is not None:
                perm = sh.perm_p2l(h.num_rows)
                self._histo = HistoDeviceState(
                    *(sh.place(a[perm]) for a in h.fields()))
            else:
                self._histo = HistoDeviceState(
                    *(jnp.asarray(a) for a in h.fields()))
        s = self._sets
        if isinstance(s, np.ndarray):
            if sh is not None:
                self._sets = sh.place(s[sh.perm_p2l(s.shape[0])])
            else:
                self._sets = jnp.asarray(s)
        if self._staged_sets is not None:
            self._staged_sets.to_device()
        self._host_live = False
        self.guard.readmit()

    def _device_probe(self) -> bool:
        """Tiny compile+fold+extract round trip through the dispatch
        seam (op "probe") — the half-open breaker's health check. Runs
        on throwaway buffers so a failing probe cannot touch state."""
        def _probe():
            st = HistoDeviceState.create(64, self.capacity)
            rows = np.array([1, 2, 3], np.int32)
            vals = np.array([1.0, 2.0, 3.0], np.float32)
            wts = np.ones(3, np.float32)
            active, lids, v, w = self._pad_spill_batch(rows, vals, wts, 63)
            out = _histo_ingest_step(
                *st.fields(), jnp.asarray(active), jnp.asarray(lids),
                jnp.asarray(v), jnp.asarray(w),
                compression=self.compression)
            qs = jnp.asarray(np.array([0.25, 0.5, 0.75, 0.99], np.float32))
            ext = _histo_flush_extract(*out, qs)
            jax.block_until_ready(ext)
            return True

        try:
            return bool(self.guard.call("probe", _probe))
        except dg.DeviceFaultError:
            return False
        except Exception:
            log.exception("device probe raised a non-device error")
            return False

    def device_guard_tick(self) -> None:
        """Per-flush guard maintenance, run by the server after each
        extraction with this worker's ingest lock held (extraction
        itself must NOT mutate live state — it runs off the lock):
        quarantine the live epoch if the breaker tripped during the
        flush, and while quarantined run the re-admission probe when
        due."""
        if not self.guard.enabled:
            return
        if self.guard.quarantined and not self._host_live:
            self._quarantine_live()
        if self._host_live and self.guard.quarantined \
                and self.guard.probe_due():
            ok = self._device_probe()
            self.guard.note_probe(ok)
            if ok:
                self._readmit_device()
                log.warning("device path re-admitted after probe; host "
                            "state re-uploaded")

    _pallas_ok: Optional[bool] = None
    # process-lifetime count of Pallas->XLA demotions, surfaced in the
    # flush self-telemetry (veneur.flush.pallas_fallback_total) so a
    # TPU-side kernel bug can't silently demote every flush to the slow
    # path with no signal
    pallas_fallbacks: int = 0

    def _extract(self, fields: tuple, qs):
        """Flush extraction: the fused Pallas kernel on TPU, the XLA
        program elsewhere (ops/pallas_kernels.py). `fields` is the
        14-tuple of (possibly row-sliced, possibly staged-folded) digest
        arrays in HistoDeviceState order."""
        (means, weights, dmin, dmax, drecip, drecip_c,
         lmin, lmax, lsum, lsum_c, lweight, lweight_c,
         lrecip, lrecip_c) = fields
        if DeviceWorker._pallas_ok is None:
            from veneur_tpu.ops import pallas_kernels as pk

            DeviceWorker._pallas_ok = pk.supported()
        if DeviceWorker._pallas_ok:
            from veneur_tpu.ops import pallas_kernels as pk

            try:
                quant, dsum, dcount = self.guard.call(
                    "extract", pk.flush_extract,
                    means, weights, dmin, dmax, qs, retryable=True)
                return (quant, dmin, dmax, dsum, dcount,
                        drecip + drecip_c,
                        lmin, lmax,
                        lsum + lsum_c,
                        lweight + lweight_c,
                        lrecip + lrecip_c)
            except dg.DeviceFaultError:
                # a classified device fault is NOT a Pallas lowering bug:
                # let the flush's failover handle it (host completion)
                # without demoting the kernel for the process lifetime
                raise
            except Exception:  # pragma: no cover - TPU-only path
                DeviceWorker._pallas_ok = False
                DeviceWorker.pallas_fallbacks += 1
                log.error(
                    "pallas flush_extract failed; demoting to the XLA "
                    "extraction path for the process lifetime",
                    exc_info=True)
        return self.guard.call(
            "extract", _histo_flush_extract,
            means, weights, dmin, dmax, drecip, drecip_c, lmin, lmax,
            lsum, lsum_c, lweight, lweight_c, lrecip, lrecip_c, qs,
            retryable=True)

    # -- flush --------------------------------------------------------------

    def _shed_spill_budget(self, spill_histo):
        """Bound the fold work this flush inherits: backlog past what
        the measured fold rate can absorb in the budget sheds here
        (newest samples kept — freshest values win), counted like every
        other overload drop. Without this a starved host hands a 40s+
        backlog to every flush and the cadence collapses (round-5
        overload measurement). Tenant-aware when a ledger is installed
        (health/policy.py): over-budget tenants shed first."""
        if spill_histo is None:
            return None
        budget = max(_FOLD_CHUNK,
                     int(self._fold_rate_ewma * self.fold_budget_s))
        total = len(spill_histo[0])
        if total <= budget:
            return spill_histo
        shed = total - budget
        self.overload_dropped += shed
        self.overload_dropped_total += shed
        led = self.tenancy
        if led is None:
            return tuple(a[-budget:] for a in spill_histo)
        # tenant-aware shed (health/policy.py): samples of over-budget
        # tenants go first; with no such tenant the keep set reduces
        # bitwise to the a[-budget:] slice above. Per-tenant drop
        # attribution lands in the epoch tallies and the governor (the
        # isolation soak's zero-innocent-shed assertion reads both).
        from veneur_tpu.health.policy import shed_spill_keep

        sp_rows = spill_histo[0]
        hrows = self.directory.histo.rows
        row_tenants = np.array(
            [m.tenant or DEFAULT_TENANT for m in hrows],
            dtype=object)
        abusive = led.over_budget()
        if abusive:
            is_abusive = np.isin(
                row_tenants[sp_rows],
                np.array(sorted(abusive), dtype=object))
            keep = shed_spill_keep(is_abusive, budget)
        else:
            keep = np.arange(total - budget, total, dtype=np.int64)
        drop_mask = np.ones(total, bool)
        drop_mask[keep] = False
        t_list, t_counts = np.unique(
            row_tenants[sp_rows[drop_mask]],
            return_counts=True)
        tt = self.tenant_tallies
        gov = self.governor
        for t, c in zip(t_list.tolist(), t_counts.tolist()):
            tt.dropped[t] = tt.dropped.get(t, 0) + int(c)
            if gov is not None:
                try:
                    gov.note_tenant_shed(t, int(c))
                except AttributeError:
                    pass
        return tuple(a[keep] for a in spill_histo)

    def swap(self, quantiles: np.ndarray) -> "SwappedEpoch":
        """Close the current epoch and return the old-interval state.

        The map-swap analog of worker.go:498-517, split from extraction so
        the caller's ingest lock is held only across this method: native
        drain/reset, pending device *dispatches* (async on TPU), import
        merges, and the epoch reset — no device readback. Next-interval
        ingest proceeds while extract_snapshot() reads the old buffers.

        The mesh path (global tier) is the one exception: MeshHistoPool
        state is not double-buffered, so its extract+reset happens here,
        under the lock. The overlap-critical 1M-series local path never
        takes it.
        """
        # lifetime sample tally, taken BEFORE the native reset below
        # destroys the per-epoch counter (the server's flush telemetry
        # reads `processed` pre-swap; Server.ingress_stats reads this
        # accumulator — same split as overload_dropped vs
        # overload_dropped_total). The caller holds this worker's ingest
        # lock across swap(), which is what keeps the pair (total,
        # per-epoch) consistent for locked readers. Reader-shard mode
        # accumulates the native deltas inside the flush-edge fence
        # instead: owned readers commit WITHOUT the worker lock, so an
        # unlocked pre-fence read here would miss lines landing before
        # each context's locked fence read and break the exact
        # attribution books (sum(reader_committed) == processed_total).
        if self._native is not None and self._reader_ctxs:
            self.processed_total += self._processed_py
        else:
            self.processed_total += self.processed
        native_stage = None
        spill_histo = None
        micro_s = 0.0
        micro_coo: list = []
        native_mirrored = False
        reader_planes = None
        if self._native is not None and self._reader_ctxs:
            # shared-nothing flush-edge fence: walk [home] + reader
            # contexts; each context's lock is held only for its OWN
            # drain + detach + reset, so a committing reader contends
            # only when the fence reaches its shard — exactly once per
            # flush. Micro-folds are inactive in shard mode (see
            # _micro_active), so no mirror fence is needed.
            raws = []
            for i, ctx in enumerate(self._all_ctxs()):
                seen = (self._native_proc_seen if i == 0
                        else self._reader_proc_seen[i - 1])
                ctx.lock()
                try:
                    raw = self._drain_native_raw_ctx(
                        ctx, i, detach_stage=True)
                    # per-context committed attribution, read inside
                    # the lock so the reset below can't race a commit;
                    # the same locked delta feeds processed_total (see
                    # the swap-top comment)
                    delta = int(ctx.processed) - seen
                    self.reader_committed[i] += delta
                    self.processed_total += delta
                    ctx.reset()
                    if i == 0:
                        self._native_errs_seen = 0
                        self._native_proc_seen = 0
                        self._native_drop_seen = 0
                    else:
                        self._reader_errs_seen[i - 1] = 0
                        self._reader_proc_seen[i - 1] = 0
                        self._reader_drop_seen[i - 1] = 0
                finally:
                    ctx.unlock()
                raws.append(raw)
            self._native_epoch_closed = True
            # off-lock: translate each context's SoA rows to canonical,
            # apply them in context order (counters add in order,
            # gauges stay last-write-wins in context order — the
            # serialized-reader-order ground truth the parity tests
            # pin), and collect the detached planes with map COPIES
            # for the stacked fold at extraction
            others: list = []
            ssf_fb: list = []
            spills: list = []
            planes: list = []
            for i, raw in enumerate(raws):
                mapped = self._map_raw_rows(i, raw)
                d = self._apply_native_raw(mapped, defer_histo_spill=True)
                if d is not None and len(d[0]):
                    spills.append(d)
                others.extend(raw[5])
                ssf_fb.extend(raw[6])
                if raw[4] is not None:
                    planes.append((raw[4], np.frombuffer(
                        self._ctx_maps[i][0], dtype=np.int32).copy()))
            self.pending_other_lines = others
            self.pending_ssf_fallback = ssf_fb
            if spills:
                spill_histo = (spills[0] if len(spills) == 1 else tuple(
                    np.concatenate([sp[k] for sp in spills])
                    for k in range(3)))
            spill_histo = self._shed_spill_budget(spill_histo)
            reader_planes = planes or None
            if reader_planes:
                # the stacked fold lands in the canonical pool: it must
                # exist even when every sample this epoch was staged
                self._ensure_histo(self.directory.num_histo_rows)
        elif self._native is not None:
            # drain, detach the staging plane, and close the native epoch
            # under one lock hold: a routed commit can otherwise land
            # between the last drain and the reset and be destroyed with
            # the old epoch
            self._native.lock()
            try:
                if self._micro_active():
                    # residual micro-drain in the SAME critical section
                    # as the detach: every staged sample is either
                    # already mirrored or copied out here, and nothing
                    # can land in between — the swap fence that makes
                    # in-flight micro-folds lose or double-fold nothing.
                    # Host memcpy only; the device feeds run after
                    # unlock so reader commits aren't stalled.
                    _t = time.perf_counter()
                    try:
                        cap = 1 << 18
                        while True:
                            coo = self._native.drain_stage_delta(cap)
                            if not len(coo[0]):
                                break
                            micro_coo.append(coo)
                            if len(coo[0]) < cap:
                                break
                        native_mirrored = self._native.stage_pending == 0
                    except AttributeError:  # stale .so: plane path below
                        native_mirrored = False
                    micro_s += time.perf_counter() - _t
                raw = self._drain_native_raw(detach_stage=True)
                native_stage = raw[4]
                # event/service-check lines + fallback SSF payloads caught
                # at epoch close; the server parses them into the NEW
                # epoch after swap
                self.pending_other_lines = raw[5]
                self.pending_ssf_fallback = raw[6]
                self._native.reset()
                self._native_errs_seen = 0
                self._native_proc_seen = 0
                self._native_drop_seen = 0
                self._native_epoch_closed = True
            finally:
                self._native.unlock()
            spill_histo = self._shed_spill_budget(
                self._apply_native_raw(raw, defer_histo_spill=True))
            if native_stage is not None and self._mesh_pool is not None:
                # samples staged before attach_mesh_pool() disabled
                # staging belong to the mesh shards, not the local fold
                # (extract would overwrite the local output with mesh_out,
                # silently dropping them)
                sv, sw, counts, _unit, free = native_stage
                mask = (np.arange(sv.shape[1])[None, :]
                        < counts[:, None])
                rows = np.repeat(
                    np.arange(len(counts), dtype=np.int32),
                    np.minimum(counts, sv.shape[1]))
                vals, wts = sv[mask], sw[mask]  # copies; plane can go
                free()
                native_stage = None
                self._mesh_pool.add_samples_bulk(rows, vals, wts)
            if native_stage is not None:
                # all samples may be staged: the device pool must still
                # exist for the fold to land in
                self._ensure_histo(self.directory.num_histo_rows)
        self._flush_pending_histos()
        if self._ph_rows:
            # a device fault during the pending-batch fold re-staged the
            # batch instead of folding it (_fold_batch_direct's failover
            # contract). The epoch reset below would destroy it — divert
            # the batch into the spill backlog, which extract_snapshot
            # folds off-lock with its own fault handling. No sample is
            # lost to the fault; it just rides the slower path.
            ph = (np.asarray(self._ph_rows, np.int32),
                  np.asarray(self._ph_vals, np.float32),
                  np.asarray(self._ph_wts, np.float32))
            self._ph_rows, self._ph_vals, self._ph_wts = [], [], []
            spill_histo = (ph if spill_histo is None else tuple(
                np.concatenate([spill_histo[k], ph[k]]) for k in range(3)))
        self._flush_pending_sets()
        self._merge_imports()

        mesh_out = None
        if self._mesh_pool is not None and self.directory.num_histo_rows:
            mesh_out = self._mesh_pool.extract(
                quantiles, self.directory.num_histo_rows)
            self._mesh_pool.reset()

        # close the epoch's micro-fold mirror: python-path residual
        # drain (the caller's ingest lock serializes this against
        # _device_histo_step) is host-only COO collection; the device
        # feeds + carry dispatch are DEFERRED to extract_snapshot via
        # micro_residual — a starved scheduler leaves a large residual,
        # and feeding it here would put the upload burst back on the
        # very tick path micro-folds exist to clear. The new epoch gets
        # a fresh mirror lazily (_ensure_micro).
        device_stage = None
        micro_residual = None
        if self._micro_active():
            _t = time.perf_counter()
            if self._native is None:
                coo = self._python_stage_delta()
                if coo is not None:
                    micro_coo.append(coo)
            mirror, self._micro = self._micro, None
            residual_n = sum(len(c[0]) for c in micro_coo)
            if (mirror is not None and mirror.samples > 0) or residual_n:
                if mirror is None:
                    mirror = mf.MicroFoldMirror(
                        self.stage_depth, ledger=self.ledger,
                        initial_rows=self._initial_histo_rows,
                        shard=self._shard, guard=self.guard)
                mirror.book_in_flush = True
                micro_residual = (mirror, micro_coo)
                micro_samples = mirror.samples + residual_n
            micro_s += time.perf_counter() - _t
        self.micro_drain_swapped_s = micro_s
        self.micro_folds_swapped = self.micro_folds_epoch
        # micro-fold upload bytes belong to the flush that extracts this
        # epoch: queue the closed epoch's tally for its begin_flush
        self.ledger.roll_epoch()

        staged = 0
        staged_histo = []
        # device-fault replay batch (ops/device_guard failover): when a
        # staging plane is handed over as a MIRROR (micro_residual)
        # instead of a host plane, the mirror is the only carrier of
        # those samples — and the mirror is device state. micro_replay
        # retains the host ground truth (the staging plane's content,
        # which the mirror duplicates bit-for-bit) until the mirror's
        # flush fold succeeds; if the mirror faults first, the replay
        # batch folds through the host engine instead. Freed by
        # extract_snapshot after a clean mirror fold.
        micro_replay = None
        # a mirrored plane is handed over as micro_residual (mirror +
        # deferred COO) INSTEAD of a host plane — exactly one of the two
        # carries a given sample
        python_mirrored = micro_residual is not None and self._native is None
        if self._stage_count is not None and self._stage_count.any():
            if python_mirrored:
                # the dense host pair IS the mirror's ground truth (the
                # drains copied deltas out; the plane keeps everything)
                micro_replay = StagedPlane(
                    self._stage_vals, self._stage_wts, None, None)
            else:
                staged += int(self._stage_count.sum())
                # hand the host staging planes to the closed epoch; the
                # fold into the digest runs in extract_snapshot, OFF the
                # ingest lock
                self._ensure_stage()  # pool may have grown since staging
                staged_histo.append(StagedPlane(
                    self._stage_vals, self._stage_wts, None, None))
        if native_stage is not None:
            sv, sw, counts, unit, free = native_stage
            if native_mirrored and micro_residual is not None:
                # plane content fully captured by the mirror + residual
                # COO (all copies): compact a host replay copy out of the
                # C++ memory, then release it — nothing to upload at
                # flush unless the mirror faults
                B = sv.shape[1]
                counts_np = np.minimum(counts, B).astype(np.int32)
                r_mask = (np.arange(B, dtype=np.int32)[None, :]
                          < counts_np[:, None])
                flat_v = sv[r_mask]
                flat_w = None if unit else sw[r_mask]
                free()
                micro_replay = StagedPlane(flat_v, flat_w, counts_np, None)
            else:
                staged += int(counts.sum())
                # unit weights (no sampled metrics this epoch): skip the
                # weights plane upload; the fold rebuilds it from counts
                staged_histo.append(
                    StagedPlane(sv, None if unit else sw, counts, free))
        if micro_residual is not None:
            staged += micro_samples
        if reader_planes:
            staged += sum(int(st[2].sum()) for st, _m in reader_planes)
        staged_histo = staged_histo or None
        # flush self-telemetry (veneur.worker.samples_staged_total)
        self.staged_samples_swapped = staged
        swapped = SwappedEpoch(
            directory=self.directory, scalars=self.scalars,
            histo=self._histo, sets=self._sets,
            staged_sets=self._staged_sets, umts=self._umts,
            mesh_out=mesh_out, staged_histo=staged_histo,
            spill_histo=spill_histo, device_stage=device_stage,
            micro_residual=micro_residual, reader_planes=reader_planes,
            micro_replay=micro_replay,
        )
        # per-tenant lifetime fold, still under the caller's ingest lock
        # and BEFORE the epoch reset zeroes the per-epoch dicts — the
        # processed_total pattern above, per tenant per kind, so a
        # tenant's drops in this epoch survive a late pipelined extract
        self.tenant_tallies.accumulate_into(self.tenant_tallies_total)
        self.processed = 0
        self.imported = 0
        self._reset_epoch()
        return swapped

    def tenant_lifetime(self) -> dict:
        """Lifetime + current-epoch per-tenant tallies as plain dicts
        (the ingress_stats pattern: totals + live epoch). Caller holds
        this worker's ingest lock."""
        return self.tenant_tallies_total.merged_with(self.tenant_tallies)

    def _fold_one_plane(self, fields: tuple, pending: list, s_eff: int
                        ) -> tuple:
        """Upload pending[0], release its native memory, fold it into the
        digest fields, and pop it. The caller owns cleanup of whatever is
        left in `pending` on failure."""
        plane: StagedPlane = pending[0]
        if plane.free is not None:
            # native C++ plane: COMPACT before upload. The dense
            # [rows, B] plane is O(S×B) bytes regardless of fill; the
            # filled slots are O(samples). Host-side fancy indexing
            # copies them out of the C++ memory (so `free` is safe
            # immediately after the tiny uploads land), the device
            # rebuilds the dense plane from flat + counts
            # (_expand_flat_planes), and the host→device transfer drops
            # from 268 MB to ~17 MB at 1M series × depth 64 × 4
            # samples/series — the difference between blowing and
            # fitting the 10s budget on a transfer-bound link.
            B = plane.vals.shape[1]
            rows_avail = min(plane.vals.shape[0], s_eff)
            counts_np = np.minimum(plane.counts[:rows_avail],
                                   B).astype(np.int32)
            mask = (np.arange(B, dtype=np.int32)[None, :]
                    < counts_np[:, None])
            flat_v = plane.vals[:rows_avail][mask]  # copies out of C++
            if rows_avail < s_eff:
                # the native plane grows by its own pow2 schedule and
                # can trail the pool's; rows past its end are empty
                counts_np = np.pad(counts_np, (0, s_eff - rows_avail))
            unit = plane.wts is None
            flat_w = None if unit else plane.wts[:rows_avail][mask]
            sh = self._shard
            if sh is not None:
                fvj, fwj, cj = self._shard_flat_upload(
                    flat_v, flat_w, counts_np, s_eff)
                if unit:
                    fwj = fvj  # ignored under unit=True (XLA DCEs it)
                plane.free()
                # re-stage the HOST copies in place of the freed native
                # plane: a device fault in the fold below leaves pending[0]
                # replayable through the host engine (free=None also means
                # the caller's cleanup won't double-free)
                pending[0] = StagedPlane(flat_v, flat_w, counts_np, None)
                svj, swj = sh.expand_flat(fvj, fwj, cj, B, unit)
            else:
                n_pad = _next_pow2(max(len(flat_v), 1), 1024)
                fv = np.zeros(n_pad, np.float32)
                fv[:len(flat_v)] = flat_v
                # fv/fw/counts_np are Python-owned copies (fancy indexing /
                # np.minimum / np.pad) — nothing below aliases the C++
                # plane, so free() needs no upload synchronization. The
                # ledger pins these uploads at O(samples) + O(rows) bytes:
                # the whole point of the compaction, and what the
                # test_health_ledger regression test asserts
                fvj = self.ledger.h2d(fv, "staged_flat")
                cj = self.ledger.h2d(counts_np, "staged_counts")
                if unit:
                    fwj = fvj  # ignored under unit=True (XLA DCEs it)
                else:
                    fw = np.zeros(n_pad, np.float32)
                    fw[:len(flat_w)] = flat_w
                    fwj = self.ledger.h2d(fw, "staged_flat")
                plane.free()
                # freed: re-stage the host copies (fault-replayable, and
                # the caller's cleanup must not free the plane again)
                pending[0] = StagedPlane(flat_v, flat_w, counts_np, None)
                svj, swj = _expand_flat_planes(fvj, fwj, cj, B, unit)
        elif plane.counts is not None:
            # pre-compacted flat plane (ops/reader_stack.merge_reader_
            # planes): vals/wts are ALREADY the 1-D row-major compaction
            # the native branch above builds, in canonical row order —
            # skip the compaction and go straight to the flat upload +
            # on-device expand, the exact legacy program
            flat_v = plane.vals
            counts_np = plane.counts
            if len(counts_np) < s_eff:
                counts_np = np.pad(counts_np, (0, s_eff - len(counts_np)))
            elif len(counts_np) > s_eff:
                counts_np = counts_np[:s_eff]
            unit = plane.wts is None
            B = self.stage_depth
            sh = self._shard
            if sh is not None:
                fvj, fwj, cj = self._shard_flat_upload(
                    flat_v, plane.wts, counts_np, s_eff)
                if unit:
                    fwj = fvj  # ignored under unit=True (XLA DCEs it)
                svj, swj = sh.expand_flat(fvj, fwj, cj, B, unit)
            else:
                n_pad = _next_pow2(max(len(flat_v), 1), 1024)
                fv = np.zeros(n_pad, np.float32)
                fv[:len(flat_v)] = flat_v
                fvj = self.ledger.h2d(fv, "staged_flat")
                cj = self.ledger.h2d(counts_np.astype(np.int32),
                                     "staged_counts")
                if unit:
                    fwj = fvj  # ignored under unit=True (XLA DCEs it)
                else:
                    fw = np.zeros(n_pad, np.float32)
                    fw[:len(plane.wts)] = plane.wts
                    fwj = self.ledger.h2d(fw, "staged_flat")
                svj, swj = _expand_flat_planes(fvj, fwj, cj, B, unit)
        else:
            # Python-owned plane: the dense upload IS O(rows x depth) —
            # acceptable only because this path serves small non-native
            # deployments; the ledger keeps it visible ("staged_dense"
            # stays zero whenever native staging is attached)
            sh = self._shard
            if sh is not None:
                sv = np.asarray(plane.vals[:s_eff], np.float32)
                sw = np.asarray(plane.wts[:s_eff], np.float32)
                if sv.shape[0] < s_eff:
                    pad = s_eff - sv.shape[0]
                    sv = np.pad(sv, ((0, pad), (0, 0)))
                    sw = np.pad(sw, ((0, pad), (0, 0)))
                # host-permute to the physical interleave, then one
                # partitioned placement per plane
                p2l = sh.perm_p2l(s_eff)
                d = sh.shards
                self.ledger.count_h2d_shards(
                    [sv.nbytes // d] * d, "staged_dense")
                self.ledger.count_h2d_shards(
                    [sw.nbytes // d] * d, "staged_dense")
                svj = sh.place(sv[p2l])
                swj = sh.place(sw[p2l])
            else:
                svj = self.ledger.h2d(plane.vals[:s_eff], "staged_dense")
                swj = self.ledger.h2d(plane.wts[:s_eff], "staged_dense")
                if svj.shape[0] < s_eff:
                    pad = s_eff - svj.shape[0]
                    svj = jnp.concatenate(
                        [svj, jnp.zeros((pad, svj.shape[1]), jnp.float32)])
                    swj = jnp.concatenate(
                        [swj, jnp.zeros((pad, swj.shape[1]), jnp.float32)])
        if self._shard is not None:
            fields = self.guard.call(
                "staged", self._shard.fold_staged, *fields, svj, swj)
        else:
            fields = self.guard.call(
                "staged", _histo_fold_staged, *fields, svj, swj,
                compression=self.compression)
        pending.pop(0)
        return fields

    def _shard_flat_upload(self, flat_v, flat_w, counts_np, s_eff: int):
        """Split one compacted staged plane (flat samples in LOGICAL row
        order + per-row counts) into per-shard segments for the sharded
        expand (ops/series_shard.expand_flat).

        Each shard's segment concatenates its local rows' samples in
        local order (= the physical counts order), padded to a common
        pow2 length: a [D, Lmax] upload that stays O(samples/shard) per
        device, against the [s_eff] counts in physical order. Returns
        the placed (flat_v, flat_w_or_None, counts) device arrays with
        per-shard ledger bookings."""
        sh = self._shard
        d = sh.shards
        p2l = sh.perm_p2l(s_eff)
        counts64 = counts_np.astype(np.int64)
        # logical sample offsets per row, then gathered per-shard-major
        off = np.zeros(s_eff, np.int64)
        np.cumsum(counts64[:-1], out=off[1:])
        reps = counts64[p2l]
        total = int(reps.sum())
        run_starts = np.cumsum(reps) - reps
        gidx = (np.repeat(off[p2l], reps)
                + np.arange(total, dtype=np.int64)
                - np.repeat(run_starts, reps))
        seg_len = reps.reshape(d, -1).sum(axis=1)
        lmax = _next_pow2(int(seg_len.max()) if total else 1, 1024)
        seg_off = np.cumsum(seg_len) - seg_len
        col = (np.arange(total, dtype=np.int64)
               - np.repeat(seg_off, seg_len))
        srd = np.repeat(np.arange(d), seg_len)
        led = self.ledger
        fv2 = np.zeros((d, lmax), np.float32)
        fv2[srd, col] = flat_v[gidx]
        led.count_h2d_shards([lmax * 4] * d, "staged_flat")
        fvj = sh.place(fv2)
        counts_phys = counts_np[p2l].astype(np.int32)
        led.count_h2d_shards(
            [counts_phys.nbytes // d] * d, "staged_counts")
        cj = sh.place(counts_phys)
        fwj = None
        if flat_w is not None:
            fw2 = np.zeros((d, lmax), np.float32)
            fw2[srd, col] = flat_w[gidx]
            led.count_h2d_shards([lmax * 4] * d, "staged_flat")
            fwj = sh.place(fw2)
        return fvj, fwj, cj

    def _device_extract_histo(self, snap, swapped, full, s_eff, n,
                              spill, pending, quantiles, gov, st):
        """The device half of the histo extraction: spill fold, staged
        plane folds, micro-mirror fold, quantile extract, column unpack,
        tenant-sketch fold, digest readback. On a DeviceFaultError the
        caller completes the flush on the host engine; ``st`` tracks the
        replayable progress (the newest fold state + the spill sample
        offset) so the failover resumes exactly where the device stopped.
        The injected-fault seam (ops/device_guard.dispatch) raises BEFORE
        a dispatch executes, so the tracked state is exact under seeded
        chaos; a real mid-execution device loss instead replays the
        retained host inputs with whatever fold state is still readable
        (honest degraded replay, logged). Returns (view_fields, s_eff)
        for the query-view publish."""
        directory = swapped.directory
        if spill is not None:
            # hot-row spill backlog deferred by swap(): chunked fold
            # off the ingest lock (plain numpy from drain_histo — no
            # native memory to free). Folded at the FULL pool shape —
            # the exact jit specialization _fold_batch_direct keeps
            # warm all interval — because a fresh s_eff-shaped
            # compile on a starved host stalls the flush for longer
            # than the fold itself (observed: 40s+ XLA compile under
            # 33x overload). Timed: the measured rate sizes the NEXT
            # swap's fold budget (closed-loop shedding).
            sp_rows, sp_vals, sp_wts = spill
            pool_rows = full[0].shape[0]
            t_fold = time.perf_counter()
            inflight = 0
            for i in range(0, len(sp_rows), _FOLD_CHUNK):
                full = self._fold_spill_chunk(
                    full, sp_rows[i:i + _FOLD_CHUNK],
                    sp_vals[i:i + _FOLD_CHUNK],
                    sp_wts[i:i + _FOLD_CHUNK], pool_rows)
                st["fields"] = full
                st["spill_off"] = min(i + _FOLD_CHUNK, len(sp_rows))
                inflight += 1
                if inflight >= 8:  # bound the dispatch queue's memory
                    self.guard.call("spill", full[0].block_until_ready)
                    inflight = 0
                    if gov is not None:
                        gov.beat()
            self.guard.call("spill", full[0].block_until_ready)
            t_fold = time.perf_counter() - t_fold
            if t_fold > 0.01:
                rate = len(sp_rows) / t_fold
                self._fold_rate_ewma = (
                    0.5 * self._fold_rate_ewma + 0.5 * rate)
        sh = self._shard
        if sh is None:
            fields = tuple(
                a if a.shape[0] == s_eff else a[:s_eff] for a in full)
        else:
            # sharded shrink: each shard keeps its local prefix (the
            # interleave closure property) — no resharding
            fields = tuple(
                a if a.shape[0] == s_eff else sh.slice_field(a, s_eff)
                for a in full)
        st["fields"] = fields
        st["spill_off"] = len(spill[0]) if spill is not None else 0
        try:
            while pending:
                fields = self._fold_one_plane(fields, pending, s_eff)
                st["fields"] = fields
                if gov is not None:
                    gov.beat()
        except dg.DeviceFaultError:
            # hand the not-yet-folded tail to the failover as host
            # copies (pending[0] already is one — _fold_one_plane
            # re-stages before it dispatches): nothing replayable may
            # be freed, and nothing native may survive this frame
            for k in range(len(pending)):
                pending[k] = _staged_plane_to_host(pending[k])
            raise
        except Exception:
            # an upload/fold failure must not leak the C++ planes: a
            # repeated failing flush at 1M rows would otherwise leak
            # hundreds of MB per interval. Data loss here is fine
            # (per-flush data is expendable, README.md:135-137);
            # leaked native memory is not.
            _free_staged_planes(pending)
            pending.clear()
            raise
        if swapped.micro_residual is not None:
            # deferred residual feeds: whatever the scheduler had not
            # streamed by swap time lands on the device HERE, in the
            # extract stage, exactly like the batch path's upload —
            # the tick paid only the host-side COO memcpy
            mirror, coos = swapped.micro_residual
            swapped.micro_residual = None
            for coo in coos:
                mirror.feed(*coo)
            swapped.device_stage = mirror.finish()
            if gov is not None:
                gov.beat()
        dstage = swapped.device_stage
        swapped.device_stage = None
        if dstage is not None:
            # micro-fold mirror: the epoch's staging plane is already
            # resident on device, so this is the SAME single fold the
            # batch path runs minus the upload — mirror_dense yields
            # bitwise the array _expand_flat_planes / the dense
            # Python upload would have built (values and weights at
            # the same absolute slots, zeros elsewhere), which is
            # what pins micro-folded == batch-folded
            dense = (mf.mirror_dense if sh is None
                     else sh.mirror_dense)
            folder = (sh.fold_staged if sh is not None
                      else functools.partial(
                          _histo_fold_staged,
                          compression=self.compression))

            def _mirror_fold(fl):
                return folder(*fl, dense(dstage.vals, s_eff),
                              dense(dstage.wts, s_eff))

            fields = self.guard.call("staged", _mirror_fold, fields)
            st["fields"] = fields
            if gov is not None:
                gov.beat()
        # the mirror's content is folded (or there was none): the host
        # replay copy swap() retained is no longer needed
        swapped.micro_replay = None
        qnp = np.asarray(quantiles, dtype=np.float32)
        if sh is None:
            qs = self.ledger.h2d(qnp, "quantiles")
        else:
            qs = self.ledger.h2d(qnp, "quantiles",
                                 replicas=sh.shards, put=sh.replicate)
        run = (gov.begin_extract(s_eff, sh.shards if sh else 1)
               if gov is not None and gov.enabled else None)
        if run is None:
            if sh is not None:
                # sharded extract bypasses the Pallas single-device
                # kernel: the GSPMD XLA program runs shard-local and
                # the one packed readback assembles all shards
                out = self.guard.call("extract", sh.flush_extract,
                                      *fields, qs, retryable=True)
                packed = np.asarray(_pack_extract_columns(*out))
                self.ledger.count_d2h_shards(
                    [packed.nbytes // sh.shards] * sh.shards,
                    "extract_packed")
                packed = packed[sh.perm_l2p(s_eff)]
            else:
                out = self._extract(fields, qs)
                # ONE device→host transfer for the whole extraction:
                # eleven per-array np.asarray calls are eleven
                # synchronous D2H round-trips, and on a link with
                # per-transfer latency (the tunnelled relay; any
                # remote-device setup) the round-trips dominate the
                # bytes at 1M rows
                packed = self.ledger.d2h(
                    _pack_extract_columns(*out), "extract_packed")
            p = out[0].shape[1]
        else:
            # governed degraded mode: extract in row chunks sized to
            # flush_chunk_target_ms (health/governor.py) so an
            # extraction-bound host produces a longer-but-BOUNDED
            # flush with a progress beat per chunk (the watchdog
            # deferral signal). dynamic_slice keeps one executable
            # per (pool, chunk) shape pair — a static a[i:j] slice
            # would compile per start offset.
            parts = []
            p = 0
            while (c := run.next_rows()):
                t0 = time.perf_counter()
                if sh is not None:
                    # lockstep per-shard slice: a c-row chunk at a
                    # D-aligned start is rows [start/D, start/D+c/D)
                    # on every shard; the per-chunk inverse perm
                    # restores logical order, so the concat below is
                    # already logical end to end
                    sub = tuple(sh.slice_chunk(a, run.start, c)
                                for a in fields)
                    out = self.guard.call("extract", sh.flush_extract,
                                          *sub, qs, retryable=True)
                    pk = np.asarray(_pack_extract_columns(*out))
                    self.ledger.count_d2h_shards(
                        [pk.nbytes // sh.shards] * sh.shards,
                        "extract_packed")
                    parts.append(pk[sh.chunk_perm(c)])
                else:
                    sub = tuple(
                        jax.lax.dynamic_slice_in_dim(a, run.start, c, 0)
                        for a in fields)
                    out = self._extract(sub, qs)
                    parts.append(self.ledger.d2h(
                        _pack_extract_columns(*out), "extract_packed"))
                p = out[0].shape[1]
                run.note(c, time.perf_counter() - t0)
            packed = (parts[0] if len(parts) == 1
                      else np.concatenate(parts, axis=0))
        qv, (dmin, dmax, dsum, dcount, drecip, lmin, lmax, lsum,
             lweight, lrecip) = columnar.unpack_extract_columns(
                 packed, p)
        snap.quantile_values = qv[:n]
        snap.quantile_qs = np.asarray(quantiles, dtype=np.float64)
        snap.dmin, snap.dmax = dmin[:n], dmax[:n]
        snap.dsum, snap.dcount, snap.drecip = dsum[:n], dcount[:n], drecip[:n]
        snap.lmin, snap.lmax = lmin[:n], lmax[:n]
        snap.lsum, snap.lweight, snap.lrecip = lsum[:n], lweight[:n], lrecip[:n]
        sk = self.tenant_sketch
        if sk is not None and n:
            # heavy-hitter fold (core/tenancy.TenantSketch): one
            # (tenant row, series key, folded sample count) triple
            # per live histo series per interval, scatter-added into
            # the per-tenant count-min pool on device. Runs here —
            # off the ingest lock, extractions never overlap — so
            # detection costs the ingest path nothing.
            hrows = directory.histo.rows
            tenants = [m.tenant or DEFAULT_TENANT for m in hrows]
            skeys = [m.key.key_string() for m in hrows]
            kcounts = np.maximum(
                np.nan_to_num(snap.dcount[:n]), 0).astype(np.int64)
            sk.fold(tenants, skeys, kcounts,
                    _next_pow2(min(len(skeys), 1 << 15), 256))
        # the [S,C] centroid pools are read back ONLY where forwarding
        # can consume them (a local tier serializes digests upstream;
        # reference flusher.go:338-433). A terminal server — global or
        # standalone, forward_address unset — never touches them, and
        # at 1M series the two arrays are ~1GB of device→host traffic
        # that round-4's on-chip E2E run measured at >90s of the 105s
        # extract phase. Consumers (codec.py, flusher.forward
        # iterator) already handle digest_means is None.
        if self.is_local:
            if sh is not None:
                l2p = sh.perm_l2p(s_eff)[:n]
                dm = np.asarray(fields[0])
                dw = np.asarray(fields[1])
                self.ledger.count_d2h_shards(
                    [(dm.nbytes + dw.nbytes) // sh.shards] * sh.shards,
                    "forward_digests")
                snap.digest_means = dm[l2p]
                snap.digest_weights = dw[l2p]
            else:
                snap.digest_means = self.ledger.d2h(
                    fields[0], "forward_digests")[:n]
                snap.digest_weights = self.ledger.d2h(
                    fields[1], "forward_digests")[:n]
        return fields, s_eff

    def _fields_to_host(self, fields) -> tuple:
        """d2h the 14 fold-state arrays in LOGICAL row order for the
        host engine. On a d2h failure (hard device loss took the fold
        state with it) the failover restarts from an empty host pool —
        logged, honest, degraded data loss rather than a dead flush."""
        sh = self._shard
        try:
            rows = int(fields[0].shape[0])
            perm = sh.perm_l2p(rows) if sh is not None else None
            out = []
            for a in fields:
                h = np.asarray(a)
                out.append(np.array(h[perm] if perm is not None else h,
                                    copy=True))
            return tuple(out)
        except Exception:
            log.exception(
                "device fold state unreadable during failover — "
                "restarting from an empty host pool (data loss)")
            return he.HostHistoState.create(
                int(fields[0].shape[0]), self.capacity).fields()

    def _host_fold_plane(self, fields: tuple, plane: StagedPlane,
                         s_eff: int) -> tuple:
        """Host twin of _fold_one_plane's upload + fold for one
        host-owned plane (flat + counts, or dense)."""
        if plane.counts is not None:
            counts = np.asarray(plane.counts, np.int32)
            if len(counts) < s_eff:
                counts = np.pad(counts, (0, s_eff - len(counts)))
            elif len(counts) > s_eff:
                counts = counts[:s_eff]
            unit = plane.wts is None
            sv, sw = he.np_expand_flat_planes(
                np.asarray(plane.vals, np.float32),
                None if unit else np.asarray(plane.wts, np.float32),
                counts, self.stage_depth, unit)
        else:
            sv = np.asarray(plane.vals[:s_eff], np.float32)
            sw = np.asarray(plane.wts[:s_eff], np.float32)
            if sv.shape[0] < s_eff:
                pad = s_eff - sv.shape[0]
                sv = np.pad(sv, ((0, pad), (0, 0)))
                sw = np.pad(sw, ((0, pad), (0, 0)))
        return he.np_fold_staged(*fields, sv, sw,
                                 compression=self.compression)

    def _host_complete_extract(self, snap, swapped, fields, s_eff, n,
                               spill, spill_off, pending, quantiles, gov):
        """Finish a histo extraction on the host engine: the remaining
        spill chunks, the staged planes, the micro replay batch, then
        the quantile extract — the bitwise twin programs in
        ops/host_engine, applied in the device path's exact order with
        the device path's exact chunk boundaries, which is what makes a
        host-completed flush == an all-device flush bit for bit. Called
        either for an epoch quarantined before swap (fields are the
        HostHistoState's arrays) or mid-extraction after a device fault
        (fields are the d2h'd fold state at the fault point). Returns
        (view_fields, s_eff) for the query-view publish."""
        directory = swapped.directory
        fields = tuple(np.asarray(a) for a in fields)
        if spill is not None and spill_off < len(spill[0]):
            sp_rows, sp_vals, sp_wts = spill
            pool_rows = fields[0].shape[0]
            for i in range(spill_off, len(sp_rows), _FOLD_CHUNK):
                active, lids, v, w = self._pad_spill_batch(
                    sp_rows[i:i + _FOLD_CHUNK],
                    sp_vals[i:i + _FOLD_CHUNK],
                    sp_wts[i:i + _FOLD_CHUNK], pool_rows - 1)
                fields = he.np_ingest_step(
                    *fields, active, lids, v, w,
                    compression=self.compression)
                if gov is not None:
                    gov.beat()
        fields = tuple(a if a.shape[0] == s_eff else a[:s_eff]
                       for a in fields)
        while pending:
            plane = _staged_plane_to_host(pending[0])
            fields = self._host_fold_plane(fields, plane, s_eff)
            pending.pop(0)
            if gov is not None:
                gov.beat()
        # the micro mirror (device state) is unreachable or already
        # dropped; its samples fold from the host replay batch swap()
        # retained — the no-epoch-lost contract for streamed samples
        replay = swapped.micro_replay
        swapped.micro_replay = None
        swapped.device_stage = None
        swapped.micro_residual = None
        if replay is not None:
            fields = self._host_fold_plane(fields, replay, s_eff)
            if gov is not None:
                gov.beat()
        qnp = np.asarray(quantiles, dtype=np.float32)
        out = he.np_flush_extract(*fields, qnp)
        packed = he.np_pack_extract_columns(*out)
        p = out[0].shape[1]
        qv, (dmin, dmax, dsum, dcount, drecip, lmin, lmax, lsum,
             lweight, lrecip) = columnar.unpack_extract_columns(packed, p)
        snap.quantile_values = qv[:n]
        snap.quantile_qs = np.asarray(quantiles, dtype=np.float64)
        snap.dmin, snap.dmax = dmin[:n], dmax[:n]
        snap.dsum, snap.dcount, snap.drecip = (dsum[:n], dcount[:n],
                                               drecip[:n])
        snap.lmin, snap.lmax = lmin[:n], lmax[:n]
        snap.lsum, snap.lweight, snap.lrecip = (lsum[:n], lweight[:n],
                                                lrecip[:n])
        sk = self.tenant_sketch
        if sk is not None and n:
            # the sketch pool is device state: best-effort under a
            # fault — one interval of heavy-hitter attribution is
            # expendable, the flush is not
            try:
                hrows = directory.histo.rows
                tenants = [m.tenant or DEFAULT_TENANT for m in hrows]
                skeys = [m.key.key_string() for m in hrows]
                kcounts = np.maximum(
                    np.nan_to_num(snap.dcount[:n]), 0).astype(np.int64)
                sk.fold(tenants, skeys, kcounts,
                        _next_pow2(min(len(skeys), 1 << 15), 256))
            except Exception:
                log.exception("tenant sketch fold skipped during host"
                              " failover")
        if self.is_local:
            snap.digest_means = np.array(fields[0][:n])
            snap.digest_weights = np.array(fields[1][:n])
        return fields, s_eff

    def extract_snapshot(self, swapped: "SwappedEpoch",
                         quantiles: np.ndarray,
                         interval_s: float = 10.0) -> FlushSnapshot:
        """Device readback for a swapped epoch. Safe to run outside the
        ingest lock — it touches only the swapped objects (plus immutable
        worker config), never the live epoch."""
        # one extraction == one transfer window. The reset lives HERE,
        # not in swap(): every ledger-counted transfer (staged-plane
        # uploads, quantile upload, packed readback) happens inside this
        # method, and under the stage pipeline the NEXT tick's swap runs
        # on the ticker thread while this extraction is still counting —
        # a swap-time reset would clobber the window mid-read. Extractions
        # never overlap each other (single extract stage), so resetting
        # on this thread keeps the windows tiling exactly.
        self.ledger.begin_flush()
        directory = swapped.directory
        scalars = swapped.scalars
        histo = swapped.histo
        sets = swapped.sets
        staged_sets = swapped.staged_sets

        snap = FlushSnapshot(
            directory=directory, scalars=scalars, interval_s=interval_s,
            unique_timeseries_registers=swapped.umts,
        )

        def _mark_degraded():
            # first host-fallback event of this flush: flag the snapshot
            # (query responses surface it as degraded: true) and book the
            # fallback once in the health ledger
            if not snap.degraded:
                snap.degraded = True
                self.ledger.note_fallback()
                self.host_fallback_flushes += 1
        # pop the deferred spill backlog UNCONDITIONALLY: when the histo
        # block below is skipped (pool absent / zero rows) the batch is
        # unfoldable and must be counted as shed, not silently discarded
        # still attached to the swapped epoch
        spill = swapped.spill_histo
        swapped.spill_histo = None
        # the fold loops below are the flush's other long-running stages:
        # each bounded step publishes a progress beat so the watchdog's
        # deferral rule (health/policy.py) sees a fold-bound flush as
        # live, not stalled — chunked extraction alone would leave a
        # multi-second fold silent for longer than the stall window
        gov = self.governor
        # epoch read view for the live query path: the fully-folded field
        # arrays (and their effective row count) captured after the last
        # fold below — the same arrays the extraction reads, retained
        # because no extract program donates them
        view_fields = None
        view_s_eff = 0
        if histo is not None and directory.num_histo_rows:
            n = directory.num_histo_rows
            # fold + extract over the USED rows only: the pool is up to 2x
            # oversized from power-of-two growth, and both programs' cost
            # is linear in rows. Pow2 bucketing bounds compile variants.
            s_eff = min(histo.num_rows, _next_pow2(n, 1024))
            full = (histo.means, histo.weights, histo.dmin,
                    histo.dmax, histo.drecip, histo.drecip_c,
                    histo.lmin, histo.lmax, histo.lsum, histo.lsum_c,
                    histo.lweight, histo.lweight_c, histo.lrecip,
                    histo.lrecip_c)
            merged_plane = None
            rplanes = swapped.reader_planes
            swapped.reader_planes = None
            if rplanes:
                # stacked reader-shard fold: host-merge the per-context
                # planes into ONE canonical flat batch (stable context
                # order per row — the serialized-reader-order ground
                # truth), release the C++ memory, and feed the batch to
                # the same flat-upload fold the legacy plane takes.
                # Rows whose stacked total exceeds the staging depth
                # route the excess through the spill fold below —
                # conservation stays exact.
                flat_v, flat_w, rcounts, rspill, per_ctx = (
                    rstack.merge_reader_planes(rplanes, s_eff))
                for st, _m in rplanes:
                    if st[4] is not None:
                        try:
                            st[4]()
                        except Exception:  # pragma: no cover
                            log.exception("reader plane free failed")
                if any(per_ctx):
                    # per-reader upload attribution (health/ledger.py):
                    # the actual h2d bytes are booked by the fold below;
                    # this records who contributed them
                    self.ledger.count_h2d_readers(
                        [int(k) * 4 for k in per_ctx], "staged_flat")
                if flat_v is not None:
                    merged_plane = StagedPlane(flat_v, flat_w, rcounts,
                                               None)
                if rspill is not None:
                    spill = (rspill if spill is None else tuple(
                        np.concatenate([spill[k], rspill[k]])
                        for k in range(3)))
            pending = list(swapped.staged_histo or ())
            if merged_plane is not None:
                pending.append(merged_plane)
            swapped.staged_histo = None
            if isinstance(histo, he.HostHistoState):
                # the epoch quarantined before swap: the fold state is
                # already host-resident, so the whole flush runs on the
                # host engine (the bitwise twin programs)
                _mark_degraded()
                view_fields, view_s_eff = self._host_complete_extract(
                    snap, swapped, full, s_eff, n, spill, 0, pending,
                    quantiles, gov)
            else:
                # replayable progress for the device→host failover:
                # "fields" is the newest device fold state (full-pool
                # until the shrink, s_eff after), "spill_off" counts the
                # spill samples already folded into it
                st = {"fields": None, "spill_off": 0}
                try:
                    view_fields, view_s_eff = self._device_extract_histo(
                        snap, swapped, full, s_eff, n, spill, pending,
                        quantiles, gov, st)
                except dg.DeviceFaultError as exc:
                    log.error(
                        "device fault during extraction (%s) — completing"
                        " the flush on the host engine", exc)
                    _mark_degraded()
                    host_fields = self._fields_to_host(
                        st["fields"] if st["fields"] is not None else full)
                    view_fields, view_s_eff = self._host_complete_extract(
                        snap, swapped, host_fields, s_eff, n, spill,
                        st["spill_off"], pending, quantiles, gov)
        elif spill is not None and len(spill[0]):
            # deferred spill with nowhere to fold (ADVICE item 2): the
            # samples are lost either way, but lost-and-counted — the
            # overload_dropped tallies are how operators see shedding
            n_lost = int(len(spill[0]))
            self.overload_dropped += n_lost
            self.overload_dropped_total += n_lost
            log.warning(
                "extract: dropped %d deferred spill samples — swapped "
                "epoch has no histogram pool to fold them into", n_lost)
        if swapped.staged_histo:
            # histo block skipped (no rows): planes can hold nothing
            # meaningful, but C++ memory must still be released
            _free_staged_planes(swapped.staged_histo)
            swapped.staged_histo = None
        if swapped.reader_planes:
            # same skip case for reader-shard planes: no canonical histo
            # rows means no staged histo samples synced, but the C++
            # plane memory must still be released
            for st, _m in swapped.reader_planes:
                if st[4] is not None:
                    try:
                        st[4]()
                    except Exception:  # pragma: no cover
                        log.exception("reader plane free failed")
            swapped.reader_planes = None
        # (a mirror with nowhere to fold is just device garbage — drop it,
        # along with any never-fed residual and its host replay copy: no
        # rows means nothing to lose)
        swapped.device_stage = None
        swapped.micro_residual = None
        swapped.micro_replay = None
        if swapped.mesh_out is not None:
            mout = swapped.mesh_out
            n = directory.num_histo_rows
            snap.quantile_values = mout["quant"]
            snap.quantile_qs = np.asarray(quantiles, dtype=np.float64)
            snap.dmin, snap.dmax = mout["dmin"], mout["dmax"]
            snap.dsum = mout["dsum"]
            snap.dcount = mout["dcount"]
            snap.drecip = mout["drecip"]
            # mesh rows carry no host-local scalar aggregates (global
            # tier emits digest-derived values; see attach_mesh_pool)
            snap.lmin = np.full(n, np.inf, np.float32)
            snap.lmax = np.full(n, -np.inf, np.float32)
            snap.lsum = np.zeros(n, np.float64)
            snap.lweight = np.zeros(n, np.float64)
            snap.lrecip = np.zeros(n, np.float64)
        if staged_sets is not None and directory.num_set_rows:
            n = directory.num_set_rows
            snap.set_estimates = staged_sets.estimates(n)
            # register materialization is [n, 2^p] host bytes — only pay
            # it where forwarding can read it (locals forward mixed sets;
            # a global is a terminal aggregator for them)
            if self.is_local:
                snap.set_registers = staged_sets.registers(n)
            if staged_sets.host_mode:
                # the store fell to (or started on) its host registers —
                # the estimates above came from the np twin
                _mark_degraded()
        elif sets is not None and directory.num_set_rows:
            n = directory.num_set_rows
            if isinstance(sets, np.ndarray):
                # quarantined epoch: host registers, np estimate twin
                # (already in logical row order — _sets_to_host gathers)
                _mark_degraded()
                snap.set_estimates = he.np_hll_estimate_exact(
                    sets, self.hll_precision)[:n]
                snap.set_registers = sets[:n]
            else:
                try:
                    if self._shard is not None:
                        est = self.guard.call(
                            "extract", self._shard.hll_estimate, sets,
                            self.hll_precision, retryable=True)
                        l2p = self._shard.perm_l2p(sets.shape[0])[:n]
                        snap.set_estimates = np.asarray(est)[l2p]
                        snap.set_registers = np.asarray(sets)[l2p]
                    else:
                        est = self.guard.call(
                            "extract", hll_ops.estimate, sets,
                            self.hll_precision, retryable=True)
                        snap.set_estimates = np.asarray(est)[:n]
                        snap.set_registers = np.asarray(sets)[:n]
                except dg.DeviceFaultError:
                    _mark_degraded()
                    regs = self._sets_to_host(sets)
                    snap.set_estimates = he.np_hll_estimate_exact(
                        regs, self.hll_precision)[:n]
                    snap.set_registers = regs[:n]
        pub = self.query_publisher
        if pub is not None:
            # publish this epoch's read view. A publish failure must not
            # fail the flush — the query surface going stale for one
            # interval is strictly better than losing the interval.
            self.query_epoch_seq += 1
            sk = self.tenant_sketch
            try:
                pub(self.query_epoch_seq, snap,
                    self._make_query_eval(view_fields, view_s_eff),
                    sk.snapshot() if sk is not None else None)
            except Exception:
                log.exception("query view publish failed")
        return snap

    def _make_query_eval(self, fields, s_eff: int):
        """Build the epoch's device query evaluator: a closure over the
        retained post-fold field arrays that re-runs the SAME compiled
        extraction programs the flush used (`_extract` unsharded,
        `SeriesSharding.flush_extract` sharded) at an arbitrary quantile
        vector. Identical executable + identical input arrays is what
        makes a query at the flush qs bitwise equal to the flush readback
        (the parity CI lane in tools/ci.sh). Retaining `fields` is safe:
        no extract program donates them (the donating fold programs ran
        earlier, producing these arrays). Transfers here deliberately
        bypass the flush TransferLedger — a query must not perturb the
        O(samples) transfer-window accounting the flush telemetry pins.

        Returns None when the epoch had no histogram rows.

        Host-fallback epochs (quarantined at swap, or failed over
        mid-extraction) retain HOST field arrays; their evaluator runs
        the np twin programs — same bits, no device dependency, so the
        query surface stays live through a quarantine."""
        if fields is None:
            return None
        sh = self._shard

        if isinstance(fields[0], np.ndarray):
            def evaluate_host(qs_np: np.ndarray) -> tuple[np.ndarray, int]:
                qnp = np.asarray(qs_np, dtype=np.float32)
                out = he.np_flush_extract(*fields, qnp)
                return he.np_pack_extract_columns(*out), out[0].shape[1]

            return evaluate_host

        def evaluate(qs_np: np.ndarray) -> tuple[np.ndarray, int]:
            """f32[P] quantiles → (packed [s_eff, P+10] host array in
            LOGICAL row order, P). Column layout: see
            columnar.unpack_extract_columns. A device fault falls back
            to the np twins over a one-shot d2h of the fields — a query
            must survive the breaker tripping between publish and
            read."""
            qnp = np.asarray(qs_np, dtype=np.float32)
            try:
                if sh is not None:
                    qs = sh.replicate(qnp)
                    out = self.guard.call("query", sh.flush_extract,
                                          *fields, qs, retryable=True)
                    packed = np.asarray(_pack_extract_columns(*out))
                    packed = packed[sh.perm_l2p(s_eff)]
                else:
                    qs = jnp.asarray(qnp)
                    # _extract is already guard-wrapped (op "extract")
                    out = self._extract(fields, qs)
                    packed = np.asarray(_pack_extract_columns(*out))
                return packed, out[0].shape[1]
            except dg.DeviceFaultError:
                host = self._fields_to_host(fields)
                out = he.np_flush_extract(*host, qnp)
                return he.np_pack_extract_columns(*out), out[0].shape[1]

        return evaluate

    def flush(self, quantiles: np.ndarray, interval_s: float = 10.0
              ) -> FlushSnapshot:
        """Swap state and extract the finished interval in one call.

        Callers that want ingest to continue during extraction (the server
        flush loop) use swap() under the ingest lock and extract_snapshot()
        outside it; this composition is for tests/tools and the import
        paths where overlap doesn't matter.
        """
        return self.extract_snapshot(self.swap(quantiles), quantiles,
                                     interval_s)
