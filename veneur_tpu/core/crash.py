"""Panic capture: report, then die.

Parity spec: reference sentry.go:22-60 — ``ConsumePanic`` reports the panic
(with a full-goroutine traceback) to Sentry, waits briefly for delivery, and
re-panics so process supervision restarts the server. Every long-lived
goroutine is wrapped (e.g. server.go:395-400, 909-912).

Here the same contract wraps every long-lived server thread: on an unhandled
exception we build a crash report containing the exception traceback plus a
stack dump of every live thread (the "full goroutine traceback" analog),
deliver it best-effort to ``sentry_dsn``, and abort the process.

DSN forms:
- ``file:///path/to/crash.log`` — append one JSON report per line. The
  native choice for air-gapped TPU pods; a supervisor ships the file.
- ``http(s)://key@host/project`` — minimal Sentry store-API POST with a
  short timeout. Delivery errors are swallowed: reporting is best-effort,
  dying is mandatory.
"""

from __future__ import annotations

import json
import logging
import sys
import threading
import time
import traceback
from typing import Callable, Optional
from urllib import request as urlrequest
from urllib.parse import urlsplit

log = logging.getLogger("veneur_tpu.crash")

REPORT_TIMEOUT_S = 3.0


def format_all_threads() -> str:
    """Stack dump of every live interpreter thread (the
    full-goroutine-traceback analog from the reference's panic handler).

    Iterates sys._current_frames() rather than threading.enumerate() so
    threads created outside the threading module (C-extension pools, e.g.
    grpc executors) are included; names come from the threading map when
    known."""
    by_ident = {t.ident: t for t in threading.enumerate()}
    chunks = []
    for ident, frame in sys._current_frames().items():
        t = by_ident.get(ident)
        label = (f"{t.name} (daemon={t.daemon})" if t is not None
                 else f"tid {ident} (unregistered)")
        chunks.append(f"--- thread {label}\n"
                      + "".join(traceback.format_stack(frame)))
    return "\n".join(chunks)


def build_report(exc: BaseException, component: str) -> dict:
    return {
        "timestamp": time.time(),
        "component": component,
        "error": repr(exc),
        "traceback": "".join(
            traceback.format_exception(type(exc), exc, exc.__traceback__)),
        "threads": format_all_threads(),
    }


def deliver(report: dict, dsn: str) -> None:
    """Best-effort delivery; never raises."""
    try:
        if dsn.startswith("file://"):
            with open(dsn[len("file://"):], "a", encoding="utf-8") as f:
                f.write(json.dumps(report) + "\n")
            return
        parts = urlsplit(dsn)
        if parts.scheme in ("http", "https") and parts.username:
            # Sentry store API: scheme://key@host/project
            project = parts.path.strip("/")
            url = (f"{parts.scheme}://{parts.hostname}"
                   + (f":{parts.port}" if parts.port else "")
                   + f"/api/{project}/store/")
            body = json.dumps({
                "message": report["error"],
                "timestamp": report["timestamp"],
                "logger": "veneur_tpu",
                "platform": "python",
                "extra": {"component": report["component"],
                          "threads": report["threads"]},
                "exception": {"values": [{"type": report["error"],
                                          "value": report["traceback"]}]},
            }).encode("utf-8")
            req = urlrequest.Request(url, data=body, headers={
                "Content-Type": "application/json",
                "X-Sentry-Auth": ("Sentry sentry_version=7, "
                                  f"sentry_key={parts.username}, "
                                  "sentry_client=veneur-tpu/1"),
            })
            urlrequest.urlopen(req, timeout=REPORT_TIMEOUT_S).read()
            return
        log.error("unrecognized sentry_dsn %r; crash report dropped", dsn)
    except Exception as e:  # reporting must never mask the crash
        log.error("crash report delivery failed: %s", e)


def consume_panic(exc: BaseException, dsn: str, component: str,
                  exit_fn: Optional[Callable[[int], None]] = None) -> None:
    """Report the exception, then abort (reference ConsumePanic,
    sentry.go:22-60: report → wait → re-panic). ``exit_fn`` defaults to
    ``os._exit(1)``; tests inject a recorder instead."""
    report = build_report(exc, component)
    log.critical("panic in %s: %s\n%s", component, report["error"],
                 report["traceback"])
    if dsn:
        deliver(report, dsn)
    if exit_fn is None:
        import os

        exit_fn = os._exit
    exit_fn(1)


def guard(fn: Callable[[], None], dsn: str, component: str,
          exit_fn: Optional[Callable[[int], None]] = None,
          suppress: Optional[Callable[[], bool]] = None) -> Callable[[], None]:
    """Wrap a long-lived thread target with panic capture. ``suppress``
    (e.g. "server is shutting down") turns a crash into a debug log —
    sockets closing underneath reader threads during shutdown is routine."""

    def wrapped() -> None:
        try:
            fn()
        except BaseException as exc:  # noqa: BLE001 — panic boundary
            if suppress is not None and suppress():
                log.debug("%s exited during shutdown: %r", component, exc)
                return
            consume_panic(exc, dsn, component, exit_fn=exit_fn)

    return wrapped
