"""Span ingestion: SpanWorker and the span→metric bridge.

Parity: reference SpanWorker (worker.go:611-695 — consumes the span
channel, applies common tags, fans each span out to every span sink with a
per-sink timeout) and the ssfmetrics extraction sink
(sinks/ssfmetrics/metrics.go:66-141 — pulls the samples attached to a span,
derives indicator/objective timers from indicator spans, counts span-name
uniqueness, and feeds it all back into the metric workers by digest).
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Callable, Optional

from veneur_tpu import ssf
from veneur_tpu.core.metrics import UDPMetric
from veneur_tpu.protocol.dogstatsd import parse_metric_ssf, ParseError

log = logging.getLogger("veneur_tpu.spans")


def convert_metrics(span: ssf.SSFSpan) -> tuple[list[UDPMetric], int]:
    """Extract the SSF samples attached to a span as UDPMetrics; returns
    (metrics, invalid_count) (reference ConvertMetrics,
    samplers/parser.go:103-120)."""
    out = []
    invalid = 0
    for sample in span.metrics:
        try:
            m = parse_metric_ssf(sample)
        except ParseError:
            invalid += 1
            continue
        if not m.key.name or m.value is None:
            invalid += 1
            continue
        out.append(m)
    return out, invalid


def convert_indicator_metrics(
    span: ssf.SSFSpan, indicator_timer_name: str, objective_timer_name: str
) -> list[UDPMetric]:
    """Derive duration timers from an indicator span (reference
    ConvertIndicatorMetrics, samplers/parser.go:129-181): the "indicator"
    timer is tagged with service+error; the "objective" timer adds the
    span name (overridable via the ssf_objective tag) and is global-only.
    """
    if not span.indicator or not ssf.valid_trace_span(span):
        return []
    duration_ns = span.end_timestamp - span.start_timestamp
    out = []
    if indicator_timer_name:
        tags = {
            "service": span.service,
            "error": "true" if span.error else "false",
        }
        out.append(parse_metric_ssf(
            ssf.timing_ns(indicator_timer_name, duration_ns, tags)))
    if objective_timer_name:
        tags = {
            "service": span.service,
            "objective": span.tags.get("ssf_objective") or span.name,
            "error": "true" if span.error else "false",
            "veneurglobalonly": "true",
        }
        out.append(parse_metric_ssf(
            ssf.timing_ns(objective_timer_name, duration_ns, tags)))
    return out


def convert_span_uniqueness_metrics(span: ssf.SSFSpan, rate: float
                                    ) -> list[UDPMetric]:
    """Span-name uniqueness Set per service/indicator flag, sampled at
    ``rate`` (reference ConvertSpanUniquenessMetrics,
    samplers/parser.go:187-208)."""
    if not span.service:
        return []
    samples = ssf.randomly_sample(
        rate,
        ssf.set_sample(
            "ssf.names_unique", span.name,
            {
                "indicator": str(span.indicator).lower(),
                "service": span.service,
                "root_span": str(span.id == span.trace_id).lower(),
            },
        ),
    )
    return [parse_metric_ssf(s) for s in samples]


class MetricExtractionSink:
    """Span sink bridging spans back into the metric pipeline
    (reference sinks/ssfmetrics — registered like any other span sink)."""

    def __init__(
        self,
        route_metric: Callable[[UDPMetric], None],
        indicator_timer_name: str = "",
        objective_timer_name: str = "",
        uniqueness_rate: float = 0.0,
    ) -> None:
        self.route_metric = route_metric
        self.indicator_timer_name = indicator_timer_name
        self.objective_timer_name = objective_timer_name
        self.uniqueness_rate = uniqueness_rate
        self.invalid_samples = 0
        # ingest runs concurrently under num_span_workers > 1
        self._stats_lock = threading.Lock()

    def name(self) -> str:
        return "metric_extraction"

    def start(self, trace_client=None) -> None:
        pass

    def ingest(self, span: ssf.SSFSpan) -> None:
        metrics, invalid = convert_metrics(span)
        if invalid:
            with self._stats_lock:
                self.invalid_samples += invalid
        try:
            metrics.extend(convert_indicator_metrics(
                span, self.indicator_timer_name, self.objective_timer_name))
        except ParseError:
            with self._stats_lock:
                self.invalid_samples += 1
        if self.uniqueness_rate > 0:
            metrics.extend(
                convert_span_uniqueness_metrics(span, self.uniqueness_rate))
        for m in metrics:
            self.route_metric(m)

    def flush(self) -> None:
        pass


class SpanWorker:
    """Fans ingested spans out to every span sink
    (reference SpanWorker.Work, worker.go:611-695)."""

    def __init__(self, span_sinks: list, common_tags: Optional[dict] = None,
                 capacity: int = 100, sink_timeout_s: float = 9.0,
                 workers: int = 1) -> None:
        self.span_sinks = span_sinks
        self.common_tags = common_tags or {}
        self.chan: "queue.Queue[Optional[ssf.SSFSpan]]" = queue.Queue(capacity)
        self.sink_timeout_s = sink_timeout_s
        self.spans_ingested = 0
        self.spans_dropped = 0
        self.sink_errors: dict[str, int] = {}
        # N consumers off one channel (reference num_span_workers,
        # server.go:842-850)
        self.workers = max(1, workers)
        self._threads: list[threading.Thread] = []
        self._stats_lock = threading.Lock()

    def ingest(self, span: ssf.SSFSpan) -> None:
        """Non-blocking enqueue; drops when full (backpressure policy of
        the span pipeline: loss over stalling)."""
        try:
            self.chan.put_nowait(span)
        except queue.Full:
            self.spans_dropped += 1

    def start(self) -> None:
        for i in range(self.workers):
            t = threading.Thread(
                target=self.work, daemon=True, name=f"span-worker-{i}")
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        for _ in self._threads or [None]:
            self.chan.put(None)
        for t in self._threads:
            t.join(timeout=5)

    def work(self) -> None:
        while True:
            span = self.chan.get()
            if span is None:
                return
            with self._stats_lock:
                self.spans_ingested += 1
            # common tags fill in missing span tags (worker.go:627-634)
            for k, v in self.common_tags.items():
                span.tags.setdefault(k, v)
            for sink in self.span_sinks:
                try:
                    sink.ingest(span)
                except Exception as e:
                    with self._stats_lock:
                        self.sink_errors[sink.name()] = (
                            self.sink_errors.get(sink.name(), 0) + 1)
                    log.debug("span sink %s ingest failed: %s",
                              sink.name(), e)

    def flush(self) -> None:
        for sink in self.span_sinks:
            try:
                sink.flush()
            except Exception:
                log.exception("span sink %s flush failed", sink.name())
