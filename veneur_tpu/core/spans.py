"""Span ingestion: SpanWorker and the span→metric bridge.

Parity: reference SpanWorker (worker.go:611-695 — consumes the span
channel, applies common tags, fans each span out to every span sink with a
per-sink timeout) and the ssfmetrics extraction sink
(sinks/ssfmetrics/metrics.go:66-141 — pulls the samples attached to a span,
derives indicator/objective timers from indicator spans, counts span-name
uniqueness, and feeds it all back into the metric workers by digest).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, Optional

from veneur_tpu import ssf
from veneur_tpu.core.metrics import UDPMetric
from veneur_tpu.protocol.dogstatsd import parse_metric_ssf, ParseError

log = logging.getLogger("veneur_tpu.spans")


def convert_metrics(span: ssf.SSFSpan) -> tuple[list[UDPMetric], int]:
    """Extract the SSF samples attached to a span as UDPMetrics; returns
    (metrics, invalid_count) (reference ConvertMetrics,
    samplers/parser.go:103-120)."""
    out = []
    invalid = 0
    for sample in span.metrics:
        try:
            m = parse_metric_ssf(sample)
        except ParseError:
            invalid += 1
            continue
        if not m.key.name or m.value is None:
            invalid += 1
            continue
        out.append(m)
    return out, invalid


def convert_indicator_metrics(
    span: ssf.SSFSpan, indicator_timer_name: str, objective_timer_name: str
) -> list[UDPMetric]:
    """Derive duration timers from an indicator span (reference
    ConvertIndicatorMetrics, samplers/parser.go:129-181): the "indicator"
    timer is tagged with service+error; the "objective" timer adds the
    span name (overridable via the ssf_objective tag) and is global-only.
    """
    if not span.indicator or not ssf.valid_trace_span(span):
        return []
    duration_ns = span.end_timestamp - span.start_timestamp
    out = []
    if indicator_timer_name:
        tags = {
            "service": span.service,
            "error": "true" if span.error else "false",
        }
        out.append(parse_metric_ssf(
            ssf.timing_ns(indicator_timer_name, duration_ns, tags)))
    if objective_timer_name:
        tags = {
            "service": span.service,
            "objective": span.tags.get("ssf_objective") or span.name,
            "error": "true" if span.error else "false",
            "veneurglobalonly": "true",
        }
        out.append(parse_metric_ssf(
            ssf.timing_ns(objective_timer_name, duration_ns, tags)))
    return out


def convert_span_uniqueness_metrics(span: ssf.SSFSpan, rate: float
                                    ) -> list[UDPMetric]:
    """Span-name uniqueness Set per service/indicator flag, sampled at
    ``rate`` (reference ConvertSpanUniquenessMetrics,
    samplers/parser.go:187-208)."""
    if not span.service:
        return []
    samples = ssf.randomly_sample(
        rate,
        ssf.set_sample(
            "ssf.names_unique", span.name,
            {
                "indicator": str(span.indicator).lower(),
                "service": span.service,
                "root_span": str(span.id == span.trace_id).lower(),
            },
        ),
    )
    return [parse_metric_ssf(s) for s in samples]


class MetricExtractionSink:
    """Span sink bridging spans back into the metric pipeline
    (reference sinks/ssfmetrics — registered like any other span sink)."""

    def __init__(
        self,
        route_metric: Callable[[UDPMetric], None],
        indicator_timer_name: str = "",
        objective_timer_name: str = "",
        uniqueness_rate: float = 0.0,
    ) -> None:
        self.route_metric = route_metric
        self.indicator_timer_name = indicator_timer_name
        self.objective_timer_name = objective_timer_name
        self.uniqueness_rate = uniqueness_rate
        self.invalid_samples = 0
        # lifetime tallies for span conservation (ingress_stats folds
        # these into received == derived + dropped + pending)
        self.spans_seen = 0
        self.derived_rows = 0
        # ingest runs concurrently under num_span_workers > 1
        self._stats_lock = threading.Lock()

    def name(self) -> str:
        return "metric_extraction"

    def start(self, trace_client=None) -> None:
        pass

    def ingest(self, span: ssf.SSFSpan) -> None:
        metrics, invalid = convert_metrics(span)
        if invalid:
            with self._stats_lock:
                self.invalid_samples += invalid
        try:
            metrics.extend(convert_indicator_metrics(
                span, self.indicator_timer_name, self.objective_timer_name))
        except ParseError:
            with self._stats_lock:
                self.invalid_samples += 1
        if self.uniqueness_rate > 0:
            metrics.extend(
                convert_span_uniqueness_metrics(span, self.uniqueness_rate))
        with self._stats_lock:
            self.spans_seen += 1
            self.derived_rows += len(metrics)
        for m in metrics:
            self.route_metric(m)

    def flush(self) -> None:
        pass


class _SinkLane:
    """One consumer thread + bounded queue per span sink.

    The isolation guarantee behind the reference's per-span 9s sink
    timeout (worker.go:612,650-688: ingest in a goroutine, stop waiting
    after the timeout): a wedged sink fills its own lane and loses spans
    (loss-over-stall) while every other sink keeps flowing — without a
    thread per (span, sink)."""

    def __init__(self, sink, capacity: int, consumers: int = 1) -> None:
        self.sink = sink
        self.q: "queue.Queue" = queue.Queue(capacity)
        self.consumers = max(1, consumers)
        # per-consumer monotonic start of its in-flight ingest (0 = idle):
        # the oldest nonzero slot tells whether ANY consumer is wedged,
        # even while the others keep finishing work
        self._busy = [0.0] * self.consumers
        self.errors = 0
        self._err_lock = threading.Lock()
        self._threads: list[threading.Thread] = []

    def oldest_busy(self) -> float:
        """Monotonic start time of the longest-running in-flight ingest,
        or 0.0 when all consumers are idle."""
        stuck = [b for b in self._busy if b]
        return min(stuck) if stuck else 0.0

    def start(self) -> None:
        for i in range(self.consumers):
            t = threading.Thread(
                target=self._run, args=(i,), daemon=True,
                name=f"span-sink-{self.sink.name()}-{i}")
            t.start()
            self._threads.append(t)

    def put(self, span) -> bool:
        try:
            self.q.put_nowait(span)
            return True
        except queue.Full:
            return False

    def drain(self, deadline: float) -> bool:
        """Wait briefly (until monotonic `deadline`) for the lane to go
        idle so spans accepted this interval make the flush they arrived
        in rather than the next one (reference ingests synchronously in
        Work, worker.go:611-695, so never observes this skew). Idleness is
        tracked with the queue's unfinished-task counter, which only drops
        after ingest completes — immune to the get()-returned-but-not-yet-
        busy window. Bounded: a wedged sink costs at most the deadline,
        never a stall."""
        while time.monotonic() < deadline:
            if self.q.unfinished_tasks == 0:
                return True
            time.sleep(0.001)
        return False

    def take_errors(self) -> int:
        with self._err_lock:
            n = self.errors
            self.errors = 0
        return n

    def _run(self, slot: int) -> None:
        while True:
            span = self.q.get()
            if span is None:
                self.q.task_done()
                return
            self._busy[slot] = time.monotonic()
            try:
                self.sink.ingest(span)
            except Exception as e:
                with self._err_lock:
                    self.errors += 1
                log.debug("span sink %s ingest failed: %s",
                          self.sink.name(), e)
            finally:
                self._busy[slot] = 0.0
                self.q.task_done()

    def stop(self) -> None:
        # sentinel delivery must not block on a full lane (the lane being
        # full of a wedged sink's spans is exactly the shutdown scenario
        # this design survives): make room by discarding queued spans —
        # per-flush span data is expendable at shutdown
        for _ in self._threads:
            while True:
                try:
                    self.q.put_nowait(None)
                    break
                except queue.Full:
                    try:
                        self.q.get_nowait()
                    except queue.Empty:
                        pass
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []


class SpanWorker:
    """Fans ingested spans out to every span sink through per-sink lanes
    (reference SpanWorker.Work, worker.go:611-695)."""

    def __init__(self, span_sinks: list, common_tags: Optional[dict] = None,
                 capacity: int = 100, sink_timeout_s: float = 9.0,
                 workers: int = 1, flush_drain_s: float = 0.5) -> None:
        self.span_sinks = span_sinks
        self.common_tags = common_tags or {}
        self.chan: "queue.Queue[Optional[ssf.SSFSpan]]" = queue.Queue(capacity)
        self.capacity = capacity
        self.sink_timeout_s = sink_timeout_s
        # shared lane-drain budget per flush pass (config
        # span_flush_drain_s; was a hardcoded 0.5s)
        self.flush_drain_s = max(0.0, flush_drain_s)
        self.spans_ingested = 0
        self.spans_dropped = 0
        self.sink_errors: dict[str, int] = {}
        # per-sink lane-full drops, split by whether the sink's consumer
        # had been stuck past sink_timeout_s (the reference's
        # worker.span.ingest_timeout_total vs a plain burst overflow)
        self.lane_drops: dict[str, int] = {}
        self.ingest_timeouts: dict[str, int] = {}
        # N consumers off one channel (reference num_span_workers,
        # server.go:842-850)
        self.workers = max(1, workers)
        self._threads: list[threading.Thread] = []
        self._stats_lock = threading.Lock()
        self._lanes: dict[int, _SinkLane] = {}

    def ingest(self, span: ssf.SSFSpan) -> None:
        """Non-blocking enqueue; drops when full (backpressure policy of
        the span pipeline: loss over stalling)."""
        try:
            self.chan.put_nowait(span)
        except queue.Full:
            # ingest is called from every listener thread; the tally must
            # take the same lock work() takes for spans_ingested or drops
            # under-count exactly when the channel is contended
            with self._stats_lock:
                self.spans_dropped += 1

    def _lane_for(self, sink) -> _SinkLane:
        lane = self._lanes.get(id(sink))
        if lane is None:
            with self._stats_lock:
                lane = self._lanes.get(id(sink))
                if lane is None:
                    # as many consumers as span workers, so a sink that
                    # scaled with num_span_workers before lanes existed
                    # still does (sinks must stay ingest-thread-safe)
                    lane = _SinkLane(sink, self.capacity,
                                     consumers=self.workers)
                    lane.start()
                    self._lanes[id(sink)] = lane
        return lane

    def start(self) -> None:
        for i in range(self.workers):
            t = threading.Thread(
                target=self.work, daemon=True, name=f"span-worker-{i}")
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        # Non-blocking sentinel insert, same discipline as _SinkLane.stop:
        # a server used programmatically (flush() driven, start() never
        # called) has no consumer on this channel, yet internal flush
        # spans still ingest into it — a blocking put(None) against the
        # full 100-slot queue deadlocks shutdown forever once ~100
        # intervals have run. Drop a queued span to make room instead.
        for _ in self._threads or [None]:
            while True:
                try:
                    self.chan.put_nowait(None)
                    break
                except queue.Full:
                    try:
                        self.chan.get_nowait()
                    except queue.Empty:
                        pass
        for t in self._threads:
            t.join(timeout=5)
        for lane in list(self._lanes.values()):
            lane.stop()

    def work(self) -> None:
        while True:
            span = self.chan.get()
            if span is None:
                return
            with self._stats_lock:
                self.spans_ingested += 1
            # common tags fill in missing span tags (worker.go:627-634)
            for k, v in self.common_tags.items():
                span.tags.setdefault(k, v)
            for sink in list(self.span_sinks):
                lane = self._lane_for(sink)
                if lane.put(span):
                    continue
                busy = lane.oldest_busy()
                name = sink.name()
                with self._stats_lock:
                    if (busy and time.monotonic() - busy
                            > self.sink_timeout_s):
                        self.ingest_timeouts[name] = (
                            self.ingest_timeouts.get(name, 0) + 1)
                    else:
                        self.lane_drops[name] = (
                            self.lane_drops.get(name, 0) + 1)

    def pending(self) -> int:
        """Spans accepted but not yet through every sink: channel backlog
        plus the deepest lane's unfinished work (a span fans out to all
        lanes, so the max — not the sum — is the count still in flight)."""
        lanes = list(self._lanes.values())
        deepest = max((lane.q.unfinished_tasks for lane in lanes),
                      default=0)
        return self.chan.qsize() + deepest

    def flush(self) -> None:
        # fold lane-level ingest errors into the per-sink error tally
        with self._stats_lock:
            for lane in list(self._lanes.values()):
                n = lane.take_errors()
                if n:
                    name = lane.sink.name()
                    self.sink_errors[name] = (
                        self.sink_errors.get(name, 0) + n)
        # give the lanes a moment to finish spans already accepted this
        # interval, so they ship in this flush instead of the next; one
        # shared deadline bounds the whole pass at flush_drain_s no
        # matter how many sinks are backed up
        drain_deadline = time.monotonic() + self.flush_drain_s
        for sink in self.span_sinks:
            lane = self._lanes.get(id(sink))
            if lane is not None:
                lane.drain(drain_deadline)
            try:
                sink.flush()
            except Exception:
                log.exception("span sink %s flush failed", sink.name())
