"""Server assembly from config: sinks, plugins, forwarding, import servers.

Parity: reference NewFromConfig (server.go:262-822) — per-config sink
construction (:474-732), plugin registration (:737-785), importsrv when
grpc_address is set (:807-817), and sink-name routing/excluded tags.
"""

from __future__ import annotations

import logging
from typing import Optional

from veneur_tpu.core.config import Config, parse_duration
from veneur_tpu.core.server import Server

log = logging.getLogger("veneur_tpu.factory")


def build_server(cfg: Config, extra_metric_sinks=None, extra_span_sinks=None,
                 opener=None, inherited_fds=None) -> Server:
    """Construct a fully wired Server from configuration.

    opener (optional) is injected into every HTTP-based sink for tests.
    inherited_fds carries listener fds across a zero-downtime re-exec
    (see Server.prepare_handoff).
    """
    from veneur_tpu.sinks.delivery import DeliveryPolicy

    metric_sinks = list(extra_metric_sinks or [])
    span_sinks = list(extra_span_sinks or [])
    interval = cfg.interval_seconds()
    # one shared delivery policy: every network sink gets its own
    # DeliveryManager built from it (sinks/delivery.py)
    policy = DeliveryPolicy.from_config(cfg, interval)
    kw = {"opener": opener} if opener else {}
    # sinks that have grown the delivery layer take the policy; the
    # rest (kafka, xray, newrelic, lightstep) keep their own handling
    dkw = {**kw, "delivery": policy}

    hostname = cfg.hostname
    if not hostname and not cfg.omit_empty_hostname:
        import socket as _socket

        hostname = _socket.gethostname()

    if cfg.datadog_api_key and cfg.datadog_api_hostname:
        from veneur_tpu.sinks.datadog import DatadogMetricSink

        metric_sinks.append(DatadogMetricSink(
            interval=interval,
            flush_max_per_body=cfg.datadog_flush_max_per_body,
            hostname=hostname,
            tags=list(cfg.tags),
            dd_hostname=cfg.datadog_api_hostname,
            api_key=cfg.datadog_api_key,
            metric_name_prefix_drops=cfg.datadog_metric_name_prefix_drops,
            exclude_tags_prefix_by_prefix_metric={
                e.metric_prefix: e.tags
                for e in cfg.datadog_exclude_tags_prefix_by_prefix_metric
            },
            **dkw,
        ))
    if cfg.datadog_trace_api_address:
        from veneur_tpu.sinks.datadog import DatadogSpanSink

        span_sinks.append(DatadogSpanSink(
            cfg.datadog_trace_api_address,
            buffer_size=cfg.datadog_span_buffer_size,
            **dkw,
        ))

    if cfg.signalfx_api_key:
        from veneur_tpu.sinks.signalfx import SignalFxMetricSink

        metric_sinks.append(SignalFxMetricSink(
            api_key=cfg.signalfx_api_key,
            hostname=hostname,
            hostname_tag=cfg.signalfx_hostname_tag,
            endpoint_base=(cfg.signalfx_endpoint_base
                           or "https://ingest.signalfx.com"),
            per_tag_api_keys={
                k.name: k.api_key for k in cfg.signalfx_per_tag_api_keys
            },
            vary_key_by=cfg.signalfx_vary_key_by,
            metric_name_prefix_drops=cfg.signalfx_metric_name_prefix_drops,
            metric_tag_prefix_drops=cfg.signalfx_metric_tag_prefix_drops,
            flush_max_per_body=cfg.signalfx_flush_max_per_body,
            dynamic_per_tag_keys=(
                cfg.signalfx_dynamic_per_tag_api_keys_enable),
            dynamic_key_refresh_period_s=(
                parse_duration(
                    cfg.signalfx_dynamic_per_tag_api_keys_refresh_period)
                if cfg.signalfx_dynamic_per_tag_api_keys_refresh_period
                else 300.0),
            api_endpoint=(cfg.signalfx_endpoint_api
                          or "https://api.signalfx.com"),
            **dkw,
        ))

    if cfg.prometheus_repeater_address:
        from veneur_tpu.sinks.prometheus import PrometheusMetricSink

        metric_sinks.append(PrometheusMetricSink(
            cfg.prometheus_repeater_address, cfg.prometheus_network_type,
            flush_timeout_s=cfg.flush_timeout_s, delivery=policy))

    if cfg.prometheus_pushgateway_address:
        from veneur_tpu.sinks.prometheus import PrometheusExpositionSink

        metric_sinks.append(PrometheusExpositionSink(
            cfg.prometheus_pushgateway_address, **dkw))

    if cfg.forward_statsd_address:
        from veneur_tpu.sinks.forward_statsd import ForwardStatsdSink

        metric_sinks.append(ForwardStatsdSink(
            cfg.forward_statsd_address, cfg.forward_statsd_network,
            flush_timeout_s=cfg.flush_timeout_s, delivery=policy))

    if cfg.newrelic_insert_key and cfg.newrelic_account_id:
        from veneur_tpu.sinks.newrelic import NewRelicMetricSink

        metric_sinks.append(NewRelicMetricSink(
            account_id=cfg.newrelic_account_id,
            insert_key=cfg.newrelic_insert_key,
            event_type=cfg.newrelic_event_type,
            service_check_event_type=cfg.newrelic_service_check_event_type,
            common_tags=cfg.newrelic_common_tags,
            region=cfg.newrelic_region,
            **kw,
        ))
    if cfg.newrelic_insert_key and cfg.newrelic_trace_observer_url:
        from veneur_tpu.sinks.newrelic import NewRelicSpanSink

        span_sinks.append(NewRelicSpanSink(
            insert_key=cfg.newrelic_insert_key,
            trace_observer_url=cfg.newrelic_trace_observer_url,
            common_tags=cfg.newrelic_common_tags,
            **kw,
        ))

    if cfg.kafka_broker:
        from veneur_tpu.sinks.kafka import (
            KafkaMetricSink, KafkaSpanSink, default_producer)

        def _buf_ms(spec: str) -> float:
            return parse_duration(spec) * 1000.0 if spec else 0.0

        # one producer per sink, each with its own ack/buffer tuning
        # (reference builds a sarama config per sink,
        # sinks/kafka/kafka.go:83,264)
        try:
            if cfg.kafka_metric_topic or cfg.kafka_check_topic:
                producer = default_producer(
                    cfg.kafka_broker, cfg.kafka_retry_max,
                    cfg.kafka_metric_require_acks,
                    buffer_bytes=cfg.kafka_metric_buffer_bytes,
                    buffer_ms=_buf_ms(cfg.kafka_metric_buffer_frequency),
                    buffer_messages=cfg.kafka_metric_buffer_messages,
                    partitioner=cfg.kafka_partitioner or "hash")
                metric_sinks.append(KafkaMetricSink(
                    producer, cfg.kafka_check_topic, cfg.kafka_event_topic,
                    cfg.kafka_metric_topic))
            if cfg.kafka_span_topic:
                span_producer = default_producer(
                    cfg.kafka_broker, cfg.kafka_retry_max,
                    (cfg.kafka_span_require_acks
                     or cfg.kafka_metric_require_acks),
                    buffer_bytes=cfg.kafka_span_buffer_bytes,
                    buffer_ms=_buf_ms(cfg.kafka_span_buffer_frequency),
                    buffer_messages=cfg.kafka_span_buffer_mesages,
                    partitioner=cfg.kafka_partitioner or "hash")
                if cfg.kafka_span_serialization_format == "columnar":
                    # columnar batch lane: one VSB1 frame per sealed
                    # batch through the delivery manager (retry/breaker/
                    # spill) instead of the drop-only per-span sink
                    from veneur_tpu.spans import (
                        KafkaBatchWriter, SpanBatchSink)

                    span_sinks.append(SpanBatchSink(
                        KafkaBatchWriter(span_producer,
                                         cfg.kafka_span_topic),
                        name="kafka",
                        delivery=policy,
                        batch_rows=cfg.span_batch_rows,
                        pending_cap=cfg.span_pending_cap))
                else:
                    span_sinks.append(KafkaSpanSink(
                        span_producer, cfg.kafka_span_topic,
                        cfg.kafka_span_serialization_format,
                        cfg.kafka_span_sample_rate_percent,
                        cfg.kafka_span_sample_tag))
        except RuntimeError as e:
            log.warning("kafka sink disabled: %s", e)

    if cfg.splunk_hec_address and cfg.splunk_hec_token:
        from veneur_tpu.sinks.splunk import SplunkSpanSink

        span_sinks.append(SplunkSpanSink(
            hec_address=cfg.splunk_hec_address,
            token=cfg.splunk_hec_token,
            hostname=hostname,
            batch_size=cfg.splunk_hec_batch_size,
            submission_workers=cfg.splunk_hec_submission_workers,
            span_sample_rate=cfg.splunk_span_sample_rate,
            send_timeout_s=(parse_duration(cfg.splunk_hec_send_timeout)
                            if cfg.splunk_hec_send_timeout else 10.0),
            ingest_timeout_s=(
                parse_duration(cfg.splunk_hec_ingest_timeout)
                if cfg.splunk_hec_ingest_timeout else 0.0),
            connection_lifetime_s=(
                parse_duration(cfg.splunk_hec_max_connection_lifetime)
                if cfg.splunk_hec_max_connection_lifetime else 60.0),
            connection_lifetime_jitter_s=(
                parse_duration(cfg.splunk_hec_connection_lifetime_jitter)
                if cfg.splunk_hec_connection_lifetime_jitter else 30.0),
            tls_validate_hostname=cfg.splunk_hec_tls_validate_hostname,
            **dkw,
        ))

    if cfg.xray_address:
        from veneur_tpu.sinks.xray import XRaySpanSink

        span_sinks.append(XRaySpanSink(
            cfg.xray_address, cfg.xray_sample_percentage,
            cfg.xray_annotation_tags))

    if cfg.lightstep_access_token or cfg.trace_lightstep_access_token:
        from veneur_tpu.sinks.lightstep import LightStepSpanSink

        span_sinks.append(LightStepSpanSink(
            access_token=(cfg.lightstep_access_token
                          or cfg.trace_lightstep_access_token),
            collector_host=(cfg.lightstep_collector_host
                            or cfg.trace_lightstep_collector_host
                            or "https://collector.lightstep.com"),
            num_clients=(cfg.lightstep_num_clients
                         or cfg.trace_lightstep_num_clients or 1),
            maximum_spans=(cfg.lightstep_maximum_spans
                           or cfg.trace_lightstep_maximum_spans or 100000),
            reconnect_period_s=(
                parse_duration(cfg.lightstep_reconnect_period
                               or cfg.trace_lightstep_reconnect_period)
                if (cfg.lightstep_reconnect_period
                    or cfg.trace_lightstep_reconnect_period) else 0.0),
            **kw,
        ))

    if cfg.falconer_address:
        from veneur_tpu.sinks.grpsink import FalconerSpanSink

        span_sinks.append(FalconerSpanSink(cfg.falconer_address))

    if cfg.span_log_dir:
        from veneur_tpu.spans import SegmentedLogWriter, SpanBatchSink

        span_sinks.append(SpanBatchSink(
            SegmentedLogWriter(cfg.span_log_dir),
            name="span_log",
            delivery=policy,
            batch_rows=cfg.span_batch_rows,
            pending_cap=cfg.span_pending_cap))

    if cfg.archive_dir:
        from veneur_tpu.archive import (
            MetricArchiveSink, SegmentedArchiveWriter)

        metric_sinks.append(MetricArchiveSink(
            SegmentedArchiveWriter(cfg.archive_dir,
                                   max_segment_bytes=cfg.archive_max_bytes,
                                   max_segments=cfg.archive_max_segments),
            hostname=hostname,
            delivery=policy))

    if cfg.debug_flushed_metrics:
        from veneur_tpu.sinks.debug import DebugMetricSink

        metric_sinks.append(DebugMetricSink())
    if cfg.debug_ingested_spans:
        from veneur_tpu.sinks.debug import DebugSpanSink

        span_sinks.append(DebugSpanSink())

    server = Server(cfg, metric_sinks=metric_sinks, span_sinks=span_sinks,
                    inherited_fds=inherited_fds)

    # plugins (reference server.go:737-785)
    if cfg.flush_file:
        from veneur_tpu.plugins.localfile import LocalFilePlugin

        server.plugins.append(LocalFilePlugin(cfg.flush_file, interval))
    if cfg.aws_s3_bucket and cfg.aws_access_key_id:
        from veneur_tpu.plugins.s3 import S3Plugin

        server.plugins.append(S3Plugin(
            cfg.aws_s3_bucket, cfg.aws_region or "us-east-1",
            cfg.aws_access_key_id, cfg.aws_secret_access_key, interval,
            **kw,
        ))
    if cfg.archive_blob_bucket and cfg.archive_blob_access_key:
        from veneur_tpu.archive import ArchiveBlobPlugin

        server.plugins.append(ArchiveBlobPlugin(
            cfg.archive_blob_bucket, cfg.archive_blob_region,
            cfg.archive_blob_access_key, cfg.archive_blob_secret_key,
            **dkw,
        ))

    # forwarding (local instances): one static upstream, or the sharded
    # proxy tier (comma-separated forward_address / discovered fleet)
    if cfg.forward_address or cfg.forward_discovery_file:
        from veneur_tpu.distributed.forward import install_forwarder

        install_forwarder(server)

    # import servers (global instances; reference server.go:807-817 for
    # gRPC, http.go:22-60 for the HTTP /import + healthcheck API)
    if cfg.grpc_address:
        from veneur_tpu.distributed.import_server import ImportServer

        server.import_server = ImportServer(server)
        server.import_server.start_grpc(cfg.grpc_address)
    if cfg.http_address:
        from veneur_tpu.distributed.import_server import (
            ImportHTTPServer, ImportServer)

        if server.import_server is None:
            server.import_server = ImportServer(server)
        from veneur_tpu.utils.http import parse_host_port

        host, port = parse_host_port(cfg.http_address, what="http_address")
        server.import_http = ImportHTTPServer(server.import_server)
        server.import_http.start(host, port)

    # per-sink excluded tags from tags_exclude "tag:sink1:sink2" syntax
    # (reference setSinkExcludedTags, server.go:1522-1548: a plain entry
    # excludes the tag everywhere; "tag|sink" limits it to one sink)
    for entry in cfg.tags_exclude:
        if "|" in entry:
            tag, _, sink_name = entry.partition("|")
            server.sink_excluded_tags.setdefault(sink_name, set()).add(tag)
        else:
            for sink in metric_sinks:
                server.sink_excluded_tags.setdefault(
                    sink.name(), set()).add(entry)

    return server
