"""Configuration: YAML file + VENEUR_* environment overlay.

Parity spec: reference config.go:3-131 (field inventory), config_parse.go
(strict-then-loose YAML parse with unknown-key warnings, envconfig overlay,
defaults struct :14-30). The reference generates its struct from
example.yaml; here the dataclass is the source of truth and yaml keys are
derived from field names.
"""

from __future__ import annotations

import logging
import os
import re
from dataclasses import dataclass, field, fields
from typing import Any, Optional

import yaml

log = logging.getLogger("veneur_tpu.config")

_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)")
_DURATION_UNITS = {
    "ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 1e-3,
    "s": 1.0, "m": 60.0, "h": 3600.0,
}


def parse_duration(s: str) -> float:
    """Go-style duration string → seconds ("10s", "500ms", "2m30s")."""
    if not s:
        raise ValueError("empty duration")
    if s in ("0",):
        return 0.0
    pos = 0
    total = 0.0
    for m in _DURATION_RE.finditer(s):
        if m.start() != pos:
            raise ValueError(f"invalid duration {s!r}")
        total += float(m.group(1)) * _DURATION_UNITS[m.group(2)]
        pos = m.end()
    if pos != len(s):
        raise ValueError(f"invalid duration {s!r}")
    return total


@dataclass
class PerTagApiKey:
    name: str = ""
    api_key: str = ""


@dataclass
class ExcludeTagsPrefixByPrefixMetric:
    metric_prefix: str = ""
    tags: list[str] = field(default_factory=list)


@dataclass
class MetricsScopes:
    counter: str = ""
    gauge: str = ""
    histogram: str = ""
    set: str = ""
    status: str = ""


@dataclass
class Config:
    """Server configuration; field names are the yaml keys
    (reference config.go:3-131)."""

    # core pipeline
    aggregates: list[str] = field(
        default_factory=lambda: ["min", "max", "count"])
    percentiles: list[float] = field(default_factory=list)
    interval: str = "10s"
    synchronize_with_interval: bool = False
    metric_max_length: int = 4096
    trace_max_length_bytes: int = 16 * 1024 * 1024
    num_workers: int = 1
    num_readers: int = 1
    num_span_workers: int = 1
    count_unique_timeseries: bool = False
    flush_watchdog_missed_flushes: int = 0
    # flush-deadline governor (veneur_tpu/health/): >0 slices the flush
    # extraction into power-of-two row chunks sized so each chunk takes
    # about this long, giving an extraction-bound host (CPU fallback at
    # high cardinality) longer-but-BOUNDED flushes with per-chunk
    # progress — which the flush watchdog's deferral rule consumes
    # instead of killing a flush that is demonstrably draining. 0 (the
    # default, right for TPU) keeps the single-program extraction and
    # the reference's unconditional watchdog behavior.
    flush_chunk_target_ms: int = 0
    # stage-parallel flush executor (core/pipeline.py): the flush tick
    # stays a cheap snapshot swap, but device extract for interval N,
    # InterMetric generation for N-1, and sink emission for N-2 run
    # concurrently on dedicated single-worker stages, so flush cadence
    # decouples from flush latency (JAX async dispatch covers the
    # device work while the host stages drain earlier intervals).
    # Output is bit-identical to the serial flush per interval
    # (tests/test_pipeline.py). Off by default: serial flush remains
    # the reference-shaped path.
    flush_pipeline: bool = False
    # intervals a stage queue may hold beyond the in-progress one
    # before the tick sheds instead of enqueueing (health/policy.py
    # MAX_STAGE_BACKLOG documents why the default is one).
    flush_pipeline_backlog: int = 1
    # native emit tier (native/emit.cpp): sinks that can hand their wire
    # serialization (JSON bodies, exposition text, statsd lines, deflate)
    # to the C++ serializers do so with the GIL released; per-sink
    # negotiation falls back to the Python formatters automatically when
    # the library is absent or a batch uses an uncovered feature. Off
    # forces the Python columnar formatters everywhere.
    flush_emit_native: bool = True
    # sink delivery reliability (sinks/delivery.py): every network sink
    # posts through a shared retry/breaker/spill layer.
    # flush_timeout_s is the per-attempt network timeout (connects and
    # POSTs — the one knob that replaced the hardcoded 10s openers) and
    # the unit of the retry deadline math: the whole retry budget for a
    # flush is clipped to the remaining flush interval, so a sick sink
    # can never stall the emit stage past its tick.
    flush_timeout_s: float = 10.0
    # retries after the first attempt on RETRYABLE failures only
    # (connect refused/reset, timeouts, HTTP 408/429/5xx; other 4xx are
    # payload errors and never retry), exponential backoff + full jitter
    sink_retry_max: int = 2
    # consecutive delivery failures before a sink's circuit breaker
    # opens (then: one half-open probe per flush interval until the
    # endpoint recovers). 0 disables the breaker.
    sink_breaker_threshold: int = 3
    # bounded per-sink spill of failed serialized payloads, retried
    # ahead of fresh data next interval; when EITHER cap is exceeded the
    # oldest payloads drop with honest delivery.dropped_payloads/_bytes
    # counters — graceful degradation, never unbounded memory
    sink_spill_max_bytes: int = 4194304
    sink_spill_max_payloads: int = 256
    # write-ahead spill journal (utils/journal.py): when a directory is
    # set, every journalable sink's spill gets a durable shadow — a
    # SIGKILL no longer destroys deferred payloads; the next incarnation
    # replays them AHEAD of fresh data and the conservation contract
    # extends across process lifetimes. Empty (the default) = off,
    # byte-identical to the in-RAM-only behaviour.
    spill_journal_dir: str = ""
    # fsync policy: "always" (per append — strongest, slowest),
    # "interval" (at each flush edge — the default), "never" (OS cache)
    spill_journal_fsync: str = "interval"
    # journal bounds: total bytes across segment files and segment-file
    # count; oldest segment evicted first when either cap bites (live
    # records evicted are counted, never silent)
    spill_journal_max_bytes: int = 64 << 20
    spill_journal_max_segments: int = 8
    # graceful drain (SIGTERM): final-epoch flush then bounded
    # spill-settling passes before exit; whatever the deadline clips is
    # counted under shutdown.* (and stays journaled when the journal is
    # on). 0 disables the drain (the pre-PR-9 hard stop).
    shutdown_drain_deadline_s: float = 10.0
    # config hot-reload: poll the config file's mtime every N seconds
    # and re-apply WHITELISTED keys (tenant budgets, journal knobs,
    # drain deadline) without a restart; other changed keys log-and-
    # ignore with a counter. 0 (default) = off.
    config_reload_s: float = 0.0
    flush_max_per_body: int = 0
    flush_file: str = ""
    omit_empty_hostname: bool = False
    hostname: str = ""
    tags: list[str] = field(default_factory=list)
    tags_exclude: list[str] = field(default_factory=list)
    span_channel_capacity: int = 100
    # accepted for config compatibility only: upstream this is a
    # deprecated alias for datadog_span_buffer_size (config_parse.go:
    # 172-176), a span-count knob — NOT a recv-buffer size. SSF recv
    # buffers are sized from trace_max_length_bytes (server.go:859-863).
    ssf_buffer_size: int = 16 * 1024
    read_buffer_size_bytes: int = 2 * 1048576

    # listeners
    statsd_listen_addresses: list[str] = field(default_factory=list)
    ssf_listen_addresses: list[str] = field(default_factory=list)
    http_address: str = ""
    grpc_address: str = ""
    http_quit: bool = False
    stats_address: str = ""
    # live query subsystem (veneur_tpu/query/): addresses to serve
    # epoch-fenced reads on, each "http://host:port" (exposition /metrics
    # + JSON /query) or "grpc://host:port" (veneurtpu.Query/Query).
    # Port 0 binds ephemerally (tests). Empty list keeps the whole query
    # path dormant — no retained device views, no listeners.
    query_listen_addrs: list[str] = field(default_factory=list)

    # TLS
    tls_key: str = ""
    tls_certificate: str = ""
    tls_authority_certificate: str = ""

    # forwarding
    forward_address: str = ""
    forward_use_grpc: bool = False
    # wire format for gRPC forwarding: "veneurtpu" (this framework's own
    # proto) or "forwardrpc" (the reference Go fleet's
    # forwardrpc.Forward/SendMetrics + metricpb wire, for forwarding into
    # a stock veneur global — see distributed/interop.py)
    forward_format: str = "veneurtpu"
    # exactly-once forwards: the import path keeps a bounded per-sender
    # window of recently seen dedup ids and drops replays
    # (distributed/import_server.py DedupWindow). Sized by ids AND
    # bytes; eviction degrades to at-least-once (counted), never blocks
    # ingest. forward_dedup: false applies payloads without the window
    # check (envelopes still decode for interop).
    forward_dedup: bool = True
    forward_dedup_window_ids: int = 65536
    forward_dedup_window_bytes: int = 8 << 20
    # streaming forwards: ride one long-lived StreamMetrics channel to
    # the upstream instead of a unary call per flush payload, with at
    # most forward_stream_window unacked frames in flight (client
    # buffer ≈ window × flush payload bytes). An old upstream answers
    # UNIMPLEMENTED once and the client downgrades to unary for the
    # connection's lifetime, so mixed fleets interop either way.
    forward_streaming: bool = True
    forward_stream_window: int = 32
    # adaptive ack window (distributed/rpc.py _WindowController): the
    # in-flight window self-tunes AIMD-style per destination — +1/W per
    # clean ack, halved on busy-acks/ack-timeouts — clamped to
    # [forward_stream_window_min, forward_stream_window_max];
    # forward_stream_window is the starting point. Off (or the
    # VENEUR_STREAM_ADAPTIVE=0 escape hatch) pins the PR-15 fixed
    # window for old-peer interop, byte-identical on the wire.
    forward_stream_adaptive: bool = True
    forward_stream_window_min: int = 1
    forward_stream_window_max: int = 128
    # byte target per stream frame: senders coalesce flush payloads up
    # to ~this many bytes per frame (a frame's cost becomes predictable,
    # making the window controller's unit meaningful); per-destination
    # frame memory is bounded by window_max × frame_bytes. The import
    # side's StreamCoalescer group-commits on a multiple of the same
    # budget.
    forward_stream_frame_bytes: int = 262144
    # sharded proxy tier (distributed/spread.py): instead of pinning ONE
    # upstream in forward_address, the local tier can discover the proxy
    # FLEET and spread each flush's forward payloads across live proxies
    # (per-proxy streaming client + delivery manager; spread policy
    # below). forward_discovery_file names a FileWatchDiscoverer
    # members/standby file — the same watchable membership format the
    # elastic global tier uses, so one fleet file feeds both the senders
    # (read) and a proxy-tier autoscale controller (write).
    # forward_address doubles as a STATIC fleet when it holds a
    # comma-separated address list (no discovery daemon needed).
    forward_discovery_file: str = ""
    forward_discovery_interval: str = "10s"
    # probe-gate discovered proxies (elastic.HealthGate over tcp_probe):
    # unreachable candidates never enter the spread; a proxy whose
    # breaker stays open across refreshes is quarantined out and
    # re-admitted only on probe success
    forward_discovery_probe: bool = True
    # "p2c" = power-of-two-choices on in-flight window depth with a
    # sticky round-robin fallback when depths tie; "round_robin" = plain
    # rotation
    forward_spread_policy: str = "p2c"
    # per-proxy delivery knobs for the spread lanes (sinks/delivery.py
    # DeliveryPolicy — the same machinery the proxies run per global)
    forward_retry_max: int = 2
    forward_breaker_threshold: int = 3
    forward_spill_max_bytes: int = 8 << 20
    forward_spill_max_payloads: int = 256
    # set-element hash: "fnv" (this framework's own, utils/hashing.hll_hash)
    # or "metro" (metro64 seed=1337, what the Go fleet inserts with —
    # REQUIRED on any instance that shares set series with Go veneur
    # instances, since HLL unions are only valid under one element hash)
    set_hash: str = "fnv"

    # device / TPU execution
    # mesh sharding (global aggregation tier): >1 shards histogram state
    # over a (tpu_mesh_hosts × series-shards) device mesh; imported
    # digests merge via ICI collectives at flush (distributed/mesh.py).
    # Requires num_workers: 1 (the mesh IS the sharding).
    tpu_mesh_devices: int = 0
    tpu_mesh_hosts: int = 0  # 0 = auto (2 when the device count is even)
    tpu_native_ingest: bool = True
    # C++ reader threads own the UDP recv loop (datagram -> parse ->
    # staged sample, no Python/GIL on the path); requires
    # tpu_native_ingest. Python readers remain for TCP/TLS/unixgram/SSF.
    tpu_native_readers: bool = True
    tpu_batch_size: int = 16384
    # raw-sample staging slots per histogram row: ingest stores samples
    # into a host [rows, depth] plane and the digest compress runs once
    # per interval (worker._histo_fold_staged); rows that fill their
    # staging mid-interval spill through the direct device fold
    tpu_stage_depth: int = 64
    # always-hot flush (ops/microfold.py): stream the staging plane to a
    # device mirror in sub-interval micro-folds, every time the staged
    # backlog crosses micro_fold_rows samples or ages past
    # micro_fold_max_age_s, so the flush tick's fold collapses to a
    # residual drain. Bit-identical to the batch fold per metric class
    # (tests/test_microfold.py); VENEUR_MICRO_FOLD=0 is the env escape
    # hatch. Inert when staging is off (tpu_stage_depth 0) or a device
    # mesh is attached.
    micro_fold: bool = True
    micro_fold_rows: int = 8192
    micro_fold_max_age_s: float = 0.25
    # device-sharded series axis (ops/series_shard.py): >1 partitions
    # each worker's sketch pools (t-digest rows, HLL registers, the
    # micro-fold mirror) over that many devices with a shard_map row
    # interleave — upload, micro-fold, and fold all run shard-local, one
    # packed readback at extract. Must be a power of two <= the visible
    # device count; bit-identical to the single-device path per metric
    # class (tests/test_series_shard.py). VENEUR_SERIES_SHARDS=0 is the
    # env escape hatch. Mutually exclusive with tpu_mesh_devices (the
    # global tier's mesh owns its own layout).
    series_shards: int = 0
    # shared-nothing multi-reader ingest: each C++ UDP reader thread
    # commits into its OWN native context (private directory + staging
    # plane + SoA spill epoch — no shared mutex on the line path), and
    # the flush reconciles the per-reader row spaces at the series sync
    # and folds all planes on-device as one stacked batch
    # (ops/reader_stack.py). -1 (default) = auto: one shard per reader
    # when native ingest + native readers are on, num_workers is 1 and
    # num_readers > 1; 0 disables (legacy digest-routed commits through
    # the shared per-worker context). Explicit N requests N shards.
    # Bit-identical flush output either way per metric class
    # (tests/test_reader_shards.py); VENEUR_READER_SHARDS=0 is the env
    # escape hatch. Requires num_workers: 1 (the canonical row space is
    # the single worker's directory); incompatible requests degrade to
    # the legacy path with a warning rather than failing ingest.
    reader_shards: int = -1
    # device fault domain (ops/device_guard.py): every device entry
    # point on the worker hot path runs under a guarded executor that
    # classifies device errors (device.fault.{oom,compile,lost,other}),
    # retries once where operands are not donated, and — after
    # device_fault_streak CONSECUTIVE faults — trips a per-worker
    # breaker that quarantines the device path and fails over to the
    # host engine (ops/host_engine.py), bit-identical per metric class.
    # While quarantined, a compile+fold+extract probe runs every
    # device_probe_interval_s; success re-admits the device path and
    # re-uploads the host state. VENEUR_DEVICE_GUARD=0 is the env
    # escape hatch (disables the guard entirely for bisection).
    device_guard: bool = True
    device_fault_streak: int = 3
    device_probe_interval_s: float = 30.0
    # entries per pending-batch (SoA) class before ingest sheds samples
    # (drop-don't-block under overload; counted in
    # veneur.ingest.overload_dropped_total). Bounds native ingest memory
    # the way the reference's fixed worker channels do (worker.go:31-48)
    tpu_spill_cap: int = 1 << 22
    tpu_compression: float = 100.0
    tpu_hll_precision: int = 14
    # loadgen workload spec (veneur_tpu/loadgen): declarative shape of
    # synthesized DogStatsD traffic — the standing load harness every
    # ingest change is measured against (tools/bench_sustained.py).
    # Type mix is {c, g, ms, h, s} weights in that fixed order.
    loadgen_seed: int = 7
    loadgen_num_keys: int = 10000
    loadgen_zipf_s: float = 1.1  # 0 = uniform key popularity
    loadgen_type_mix: list[float] = field(
        default_factory=lambda: [0.35, 0.15, 0.25, 0.15, 0.10])
    loadgen_num_tags: int = 3
    loadgen_tag_cardinality: int = 50
    loadgen_prefix: str = "lg"
    loadgen_datagram_bytes: int = 1400  # pack target per datagram
    loadgen_ring_lines: int = 200000  # distinct lines in the send ring
    # multi-tenant workloads (per-tenant QoS soak): >1 stamps every line
    # with a tenant:tN tag. The LAST tenant (t{count-1}) is the abusive
    # one: abusive_frac of all lines go to it, and its key space churns
    # over tenant_churn_keys extra names (the cardinality attack the
    # series budget defends against). Innocent tenants draw Zipf
    # (tenant_zipf_s; 0 = uniform) over the remaining ids. 1 (default)
    # emits byte-identical legacy output — no tenant tag at all.
    loadgen_tenant_count: int = 1
    loadgen_tenant_abusive_frac: float = 0.0
    loadgen_tenant_zipf_s: float = 0.0
    loadgen_tenant_churn_keys: int = 0
    # per-tenant QoS (core/tenancy.py): tag key whose value names the
    # owning tenant (samples without it belong to the "default" tenant),
    # a per-tenant distinct-series budget enforced at series-adopt time
    # (over budget: NEW series are rejected with honest
    # tenant.samples_rejected_total counters; existing series keep
    # aggregating — reject-new, never evict-live), and the on-device
    # heavy-hitter sketch dimensions (ops/heavyhitter.py) behind the
    # per-tenant top-k telemetry. tenant_default_budget 0 with no
    # per-tenant override disables the whole layer (zero overhead).
    tenant_tag_key: str = "tenant"
    tenant_default_budget: int = 0  # distinct series per tenant; 0 = off
    tenant_budgets: dict = field(default_factory=dict)  # tenant → budget
    tenant_sketch_depth: int = 4
    tenant_sketch_width: int = 2048  # power of two
    tenant_topk: int = 8
    # set-sketch storage: "staged" keeps small sets host-side sparse and
    # promotes rows past 2^p/8 distinct registers to dense device rows
    # (the scalable default — 1M small-set series costs ~MBs instead of
    # 16GB of HBM; see ops/staged_sets.py for the crossover math);
    # "dense" keeps the all-dense device pool
    tpu_set_store: str = "staged"
    tpu_initial_histo_rows: int = 4096
    tpu_initial_set_rows: int = 512
    # persistent XLA compilation cache: first compile of each flush/fold
    # program shape costs ~20-40s on TPU; with a cache dir set, restarts
    # (watchdog, fd-handoff upgrades) reuse compiled programs instead of
    # re-paying it. Empty = disabled.
    tpu_compilation_cache_dir: str = ""
    # precompile the flush programs at startup (background thread, first
    # row bucket) so the first real flush doesn't pay the per-shape XLA
    # compile inside the interval
    tpu_warmup_compile: bool = True

    # self-telemetry & debugging
    debug: bool = False
    debug_flushed_metrics: bool = False
    debug_ingested_spans: bool = False
    enable_profiling: bool = False
    # where the XLA/JAX profiler trace is written when enable_profiling
    # (TPU-native analog of the reference's pprof profile.Start())
    profile_dir: str = ""
    block_profile_rate: int = 0
    mutex_profile_fraction: int = 0
    sentry_dsn: str = ""
    veneur_metrics_additional_tags: list[str] = field(default_factory=list)
    veneur_metrics_scopes: MetricsScopes = field(default_factory=MetricsScopes)

    # spans → derived metrics
    indicator_span_timer_name: str = ""
    objective_span_timer_name: str = ""
    # span-name uniqueness Set sampling rate; the reference hardcodes 0.01
    # (sinks/ssfmetrics/metrics.go ConvertSpanUniquenessMetrics)
    ssf_span_uniqueness_rate: float = 0.01
    # columnar span pipeline (veneur_tpu/spans/): ingest batches spans
    # into interned columns, derivation runs at the flush edge straight
    # into the device workers, and batch-capable sinks get sealed batches
    # instead of per-span objects. Env escape hatch: VENEUR_SPAN_COLUMNAR=0
    # falls back to the per-span SpanWorker path.
    span_columnar: bool = True
    # rows per sealed columnar batch (one VSB1 frame per batch on egress)
    span_batch_rows: int = 512
    # span rows buffered between flushes before ingest sheds
    # (loss-over-stall, counted; the columnar analog of
    # span_channel_capacity)
    span_pending_cap: int = 1 << 20
    # shared lane-drain budget per SpanWorker.flush pass (seconds);
    # was a hardcoded 0.5s
    span_flush_drain_s: float = 0.5
    # when set, a SegmentedLogWriter SpanBatchSink appends VSB1 frames
    # to this directory (brokerless columnar span egress)
    span_log_dir: str = ""

    # sink: datadog
    datadog_api_hostname: str = ""
    datadog_api_key: str = ""
    datadog_flush_max_per_body: int = 25000
    datadog_metric_name_prefix_drops: list[str] = field(default_factory=list)
    datadog_exclude_tags_prefix_by_prefix_metric: list[
        ExcludeTagsPrefixByPrefixMetric] = field(default_factory=list)
    datadog_span_buffer_size: int = 1 << 14
    datadog_trace_api_address: str = ""

    # sink: signalfx
    signalfx_api_key: str = ""
    signalfx_dynamic_per_tag_api_keys_enable: bool = False
    signalfx_dynamic_per_tag_api_keys_refresh_period: str = ""
    signalfx_endpoint_base: str = ""
    signalfx_endpoint_api: str = ""
    signalfx_flush_max_per_body: int = 0
    signalfx_hostname_tag: str = ""
    signalfx_metric_name_prefix_drops: list[str] = field(default_factory=list)
    signalfx_metric_tag_prefix_drops: list[str] = field(default_factory=list)
    signalfx_per_tag_api_keys: list[PerTagApiKey] = field(default_factory=list)
    signalfx_vary_key_by: str = ""

    # sink: kafka
    kafka_broker: str = ""
    kafka_check_topic: str = ""
    kafka_event_topic: str = ""
    kafka_metric_topic: str = ""
    kafka_span_topic: str = ""
    kafka_metric_buffer_bytes: int = 0
    kafka_metric_buffer_frequency: str = ""
    kafka_metric_buffer_messages: int = 0
    kafka_metric_require_acks: str = ""
    kafka_partitioner: str = ""
    kafka_retry_max: int = 0
    kafka_span_buffer_bytes: int = 0
    kafka_span_buffer_frequency: str = ""
    kafka_span_buffer_mesages: int = 0
    kafka_span_require_acks: str = ""
    kafka_span_sample_rate_percent: float = 100.0
    kafka_span_sample_tag: str = ""
    kafka_span_serialization_format: str = "protobuf"

    # sink: splunk
    splunk_hec_address: str = ""
    splunk_hec_token: str = ""
    splunk_hec_batch_size: int = 100
    splunk_hec_connection_lifetime_jitter: str = ""
    splunk_hec_ingest_timeout: str = ""
    splunk_hec_max_connection_lifetime: str = "10s"
    splunk_hec_send_timeout: str = ""
    splunk_hec_submission_workers: int = 1
    splunk_hec_tls_validate_hostname: str = ""
    splunk_span_sample_rate: int = 100

    # sink: newrelic
    newrelic_account_id: int = 0
    newrelic_common_tags: list[str] = field(default_factory=list)
    newrelic_event_type: str = ""
    newrelic_insert_key: str = ""
    newrelic_region: str = ""
    newrelic_service_check_event_type: str = ""
    newrelic_trace_observer_url: str = ""

    # sink: lightstep
    lightstep_access_token: str = ""
    lightstep_collector_host: str = ""
    lightstep_maximum_spans: int = 0
    lightstep_num_clients: int = 0
    lightstep_reconnect_period: str = ""
    trace_lightstep_access_token: str = ""
    trace_lightstep_collector_host: str = ""
    trace_lightstep_maximum_spans: int = 0
    trace_lightstep_num_clients: int = 0
    trace_lightstep_reconnect_period: str = ""

    # sink: xray
    xray_address: str = ""
    xray_annotation_tags: list[str] = field(default_factory=list)
    xray_sample_percentage: float = 100.0

    # sink: falconer (grpsink)
    falconer_address: str = ""

    # sink: prometheus repeater
    prometheus_repeater_address: str = ""
    prometheus_network_type: str = "tcp"
    # sink: prometheus pushgateway (exposition-text POST per flush)
    prometheus_pushgateway_address: str = ""

    # sink: forward-statsd (flushed series re-emitted as verbatim
    # DogStatsD lines to a downstream aggregator)
    forward_statsd_address: str = ""
    forward_statsd_network: str = "udp"

    # plugins: s3
    aws_access_key_id: str = ""
    aws_secret_access_key: str = ""
    aws_region: str = ""
    aws_s3_bucket: str = ""

    # flush archival (veneur_tpu/archive/): a rotated, size-and-count-
    # bounded local VMB1 archive of every flush, replayable through the
    # import path (tools/replay_archive.py). Empty archive_dir = off.
    archive_dir: str = ""
    archive_max_bytes: int = 64 << 20    # per-segment rotation size
    archive_max_segments: int = 8        # oldest segment unlinked past this
    # blob egress: the same VMB1 frames PUT to S3-compatible storage
    # under archive/<hostname>/<timestamp>-<seq>.vmb, through the
    # delivery layer (retry/breaker/spill). Empty bucket = off.
    archive_blob_bucket: str = ""
    archive_blob_region: str = "us-east-1"
    archive_blob_access_key: str = ""
    archive_blob_secret_key: str = ""

    def interval_seconds(self) -> float:
        return parse_duration(self.interval)

    def is_local(self) -> bool:
        """A server is 'local' iff it forwards upstream — through a
        static address (or comma-separated fleet) OR a discovered proxy
        fleet (reference server.go:1489-1491)."""
        return bool(self.forward_address or self.forward_discovery_file)

    def forward_destinations(self) -> list[str]:
        """forward_address split as a static destination list (scheme
        prefixes stripped for the gRPC path by the forwarder)."""
        return [a.strip() for a in self.forward_address.split(",")
                if a.strip()]


@dataclass
class ProxyConfig:
    """veneur-proxy configuration (reference config_proxy.go:3-27)."""

    consul_forward_grpc_service_name: str = ""
    consul_forward_service_name: str = ""
    consul_refresh_interval: str = "30s"
    consul_trace_service_name: str = ""
    consul_url: str = "http://127.0.0.1:8500"
    idle_connection_timeout: str = ""  # downstream conn idle timeout
    runtime_metrics_interval: str = "10s"
    kubernetes_forward_service_name: str = ""
    kubernetes_namespace: str = "default"
    debug: bool = False
    enable_profiling: bool = False
    forward_address: str = ""  # static destination (no discovery)
    forward_timeout: str = "10s"
    # exactly-once forwards: mint a journal-backed dedup id per forward
    # fragment and carry it in a versioned wire envelope so the import
    # path can reject replays (retries, handoff re-sends, network
    # duplicates). Escape hatch: VENEUR_FORWARD_DEDUP=0. The window
    # keys size this proxy's OWN import window when it receives
    # forwards (same keys as the server config).
    forward_dedup: bool = True
    forward_dedup_window_ids: int = 65536
    forward_dedup_window_bytes: int = 8 << 20
    # streaming forwards (the PR-15 hop): one long-lived StreamMetrics
    # channel per destination with a bounded in-flight ack window
    # replacing a unary call per fragment. A frame is delivered only on
    # its ack, so retry/breaker/spill and the dedup keys behave exactly
    # as on the unary path; old destinations downgrade the client to
    # unary via UNIMPLEMENTED. Escape hatch: VENEUR_FORWARD_STREAMING=0.
    forward_streaming: bool = True
    forward_stream_window: int = 32
    # adaptive AIMD ack window + byte-sized frames (same keys and
    # semantics as the server config; see Config above). Escape hatch:
    # VENEUR_STREAM_ADAPTIVE=0 pins the fixed PR-15 window.
    forward_stream_adaptive: bool = True
    forward_stream_window_min: int = 1
    forward_stream_window_max: int = 128
    forward_stream_frame_bytes: int = 262144
    # forward-path delivery guarantees (the PR-5 sink delivery layer
    # applied per destination; sinks/delivery.py DeliveryPolicy):
    # bounded retry on transient failures, per-destination circuit
    # breaker, bounded spill re-routed on the current ring each drain
    forward_retry_max: int = 2
    forward_breaker_threshold: int = 3
    forward_spill_max_bytes: int = 8 << 20
    forward_spill_max_payloads: int = 512
    # bounded reshard-handoff window: the drain cadence and the budget
    # for re-routing spilled fragments after a membership change
    handoff_window_s: float = 5.0
    # write-ahead spill journal for the forward-path spill (shared
    # across per-destination managers; utils/journal.py). Empty = off.
    spill_journal_dir: str = ""
    spill_journal_fsync: str = "interval"
    spill_journal_max_bytes: int = 64 << 20
    spill_journal_max_segments: int = 8
    # SIGTERM drain budget: bounded spill-settling passes before exit
    shutdown_drain_deadline_s: float = 10.0
    # bounded routing executor replacing per-batch thread spawn
    routing_pool_workers: int = 4
    routing_queue_max: int = 128
    grpc_address: str = ""
    grpc_forward_address: str = ""
    http_address: str = ""
    # total cap on kept-alive downstream connections across all
    # destinations (reference config_proxy.go:16 -> http.Transport
    # MaxIdleConns); 0 = unlimited, matching the Go zero value
    max_idle_conns: int = 0
    max_idle_conns_per_host: int = 100
    sentry_dsn: str = ""
    # elastic tier (distributed/elastic.py): watchable file-based
    # membership + health-gated admission/quarantine + optional
    # load-driven autoscaling. Setting elastic_membership_file selects
    # the FileWatchDiscoverer (takes precedence over consul/k8s) and
    # arms the HealthGate on the refresh path.
    elastic_membership_file: str = ""
    elastic_probe_timeout_s: float = 1.0
    # refresh intervals a member's breaker must stay open before it is
    # quarantined out of the ring
    elastic_quarantine_intervals: int = 3
    # autoscale controller: K consecutive pressured (calm) observation
    # intervals before scale-out (scale-in), plus a cooldown between
    # actions so one reshard settles before the next reading
    elastic_autoscale: bool = False
    elastic_hysteresis_intervals: int = 3
    elastic_cooldown_s: float = 60.0
    elastic_min_members: int = 1
    elastic_max_members: int = 0       # 0 = uncapped
    elastic_observe_interval_s: float = 10.0
    # proxy-TIER elastics (the other half of "elastic both tiers"): this
    # proxy can run the FLEET's autoscale controller over a shared
    # members/standby file — the same watchable file the local tier's
    # senders read through forward_discovery_file. Pressure comes from
    # the proxy's OWN fan-in signals (routing-queue admission timeouts,
    # stream window stalls, routing sheds; elastic.ProxyTierPressureSource)
    # and the controller applies the same hysteresis/cooldown/
    # graceful-leave semantics (elastic_* keys above) to the proxy
    # fleet. Exactly one proxy per fleet should arm fleet_autoscale.
    fleet_membership_file: str = ""
    fleet_autoscale: bool = False
    # accepted for YAML compatibility with reference proxy configs;
    # nothing consumes it there either (config_proxy.go:23 has no
    # reader outside the config struct)
    trace_api_address: str = ""
    ssf_destination_address: str = ""
    stats_address: str = ""
    trace_address: str = ""  # static trace destination (no discovery)
    tracing_client_capacity: int = 1024
    tracing_client_flush_interval: str = "500ms"
    tracing_client_metrics_interval: str = "1s"


def load_proxy_config(path: Optional[str] = None,
                      data: Optional[dict] = None,
                      env: Optional[dict] = None) -> ProxyConfig:
    """reference ReadProxyConfig (config_parse.go:33)."""
    raw: dict[str, Any] = {}
    if path is not None:
        with open(path) as f:
            raw = yaml.safe_load(f) or {}
    if data is not None:
        raw.update(data)
    cfg = ProxyConfig()
    known = {f.name for f in fields(cfg)}
    unknown = [k for k in raw if k not in known]
    if unknown:
        log.warning("unknown proxy config keys: %s", sorted(unknown))
    for key, value in raw.items():
        if key in known and value is not None:
            setattr(cfg, key, _coerce(value, getattr(cfg, key), key))
    env = os.environ if env is None else env
    for name in known:
        for candidate in ("VENEUR_" + name.upper(),
                          "VENEUR_" + name.upper().replace("_", "")):
            if candidate in env:
                setattr(cfg, name,
                        _coerce(env[candidate], getattr(cfg, name), name))
                break
    validate_proxy_config(cfg)
    return cfg


def _validate_journal_keys(cfg) -> None:
    """Shared journal/drain key validation (Config and ProxyConfig carry
    the same spill_journal_* / shutdown_drain_deadline_s knobs)."""
    from veneur_tpu.utils.journal import FSYNC_POLICIES

    if cfg.spill_journal_fsync not in FSYNC_POLICIES:
        raise ValueError(
            f"spill_journal_fsync must be one of {FSYNC_POLICIES}")
    if cfg.spill_journal_max_bytes < 1:
        raise ValueError("spill_journal_max_bytes must be >= 1 (unset"
                         " spill_journal_dir to disable journaling)")
    if cfg.spill_journal_max_segments < 1:
        raise ValueError("spill_journal_max_segments must be >= 1")
    if cfg.shutdown_drain_deadline_s < 0:
        raise ValueError("shutdown_drain_deadline_s must be >= 0"
                         " (0 disables the graceful drain)")


def _validate_dedup_keys(cfg) -> None:
    """Shared dedup-window validation (Config and ProxyConfig carry the
    same forward_dedup_* knobs)."""
    if cfg.forward_dedup_window_ids < 1:
        raise ValueError("forward_dedup_window_ids must be >= 1 (set"
                         " forward_dedup: false to disable dedup)")
    if cfg.forward_dedup_window_bytes < 1:
        raise ValueError("forward_dedup_window_bytes must be >= 1 (set"
                         " forward_dedup: false to disable dedup)")


def _validate_stream_keys(cfg) -> None:
    """Shared streaming-forward validation (Config and ProxyConfig carry
    the same forward_streaming/forward_stream_* knobs)."""
    if cfg.forward_stream_window < 1:
        raise ValueError("forward_stream_window must be >= 1 (set"
                         " forward_streaming: false to disable streaming)")
    if cfg.forward_stream_window_min < 1:
        raise ValueError("forward_stream_window_min must be >= 1 (a"
                         " zero window can never admit a frame)")
    if cfg.forward_stream_window_max < cfg.forward_stream_window_min:
        raise ValueError("forward_stream_window_max must be >="
                         " forward_stream_window_min")
    if not (cfg.forward_stream_window_min <= cfg.forward_stream_window
            <= cfg.forward_stream_window_max):
        raise ValueError("forward_stream_window (the adaptive starting"
                         " point) must lie in [forward_stream_window_min,"
                         " forward_stream_window_max]")
    if cfg.forward_stream_frame_bytes < 1:
        raise ValueError("forward_stream_frame_bytes must be >= 1")


def _validate_elastic_keys(cfg) -> None:
    if cfg.elastic_probe_timeout_s <= 0:
        raise ValueError("elastic_probe_timeout_s must be positive")
    if cfg.elastic_quarantine_intervals < 1:
        raise ValueError("elastic_quarantine_intervals must be >= 1")
    if cfg.elastic_hysteresis_intervals < 1:
        raise ValueError("elastic_hysteresis_intervals must be >= 1")
    if cfg.elastic_cooldown_s < 0:
        raise ValueError("elastic_cooldown_s must be >= 0")
    if cfg.elastic_min_members < 1:
        raise ValueError("elastic_min_members must be >= 1 (an empty"
                         " ring loses routing entirely)")
    if cfg.elastic_max_members and \
            cfg.elastic_max_members < cfg.elastic_min_members:
        raise ValueError("elastic_max_members must be 0 (uncapped) or"
                         " >= elastic_min_members")
    if cfg.elastic_observe_interval_s <= 0:
        raise ValueError("elastic_observe_interval_s must be positive")
    if cfg.elastic_autoscale and not cfg.elastic_membership_file:
        raise ValueError("elastic_autoscale requires"
                         " elastic_membership_file (the controller"
                         " writes the desired member set back through"
                         " the watchable file)")
    if getattr(cfg, "fleet_autoscale", False) \
            and not getattr(cfg, "fleet_membership_file", ""):
        raise ValueError("fleet_autoscale requires fleet_membership_file"
                         " (the proxy-tier controller writes the fleet's"
                         " desired member set back through the watchable"
                         " file the senders discover from)")


def validate_proxy_config(cfg: ProxyConfig) -> None:
    parse_duration(cfg.forward_timeout)  # raises on nonsense
    parse_duration(cfg.consul_refresh_interval)
    parse_duration(cfg.runtime_metrics_interval)
    if (cfg.forward_address and cfg.grpc_forward_address
            and cfg.forward_address != cfg.grpc_forward_address):
        # this proxy routes ALL forwards over one gRPC ring, so two
        # different static addresses is an ambiguous config that used to
        # be silently resolved by dropping forward_address — reject it
        # at validation instead (set exactly one, or the same value)
        raise ValueError(
            "forward_address and grpc_forward_address are both set (to"
            f" {cfg.forward_address!r} and {cfg.grpc_forward_address!r})"
            " but this proxy routes all forwards over one gRPC ring —"
            " set exactly one of them")
    if cfg.idle_connection_timeout:
        parse_duration(cfg.idle_connection_timeout)
    if cfg.forward_retry_max < 0:
        raise ValueError("forward_retry_max must be >= 0 (0 means one"
                         " attempt, no retries)")
    if cfg.forward_breaker_threshold < 0:
        raise ValueError("forward_breaker_threshold must be >= 0"
                         " (0 disables the circuit breaker)")
    if cfg.forward_spill_max_bytes < 0 or cfg.forward_spill_max_payloads < 0:
        raise ValueError("forward spill caps must be >= 0 (0 drops failed"
                         " fragments instead of spilling them)")
    if cfg.handoff_window_s <= 0:
        raise ValueError("handoff_window_s must be positive (it bounds"
                         " the reshard drain AND paces the drain thread)")
    _validate_journal_keys(cfg)
    _validate_dedup_keys(cfg)
    _validate_stream_keys(cfg)
    _validate_elastic_keys(cfg)
    if cfg.routing_pool_workers < 1:
        raise ValueError("routing_pool_workers must be >= 1")
    if cfg.routing_queue_max < 1:
        raise ValueError("routing_queue_max must be >= 1 (the bound is"
                         " the whole point of the routing executor)")
    if cfg.max_idle_conns < 0:
        raise ValueError("max_idle_conns must be >= 0 (0 = unlimited)")


SECRET_FIELDS = {
    "datadog_api_key", "signalfx_api_key", "sentry_dsn",
    "aws_access_key_id", "aws_secret_access_key", "newrelic_insert_key",
    "splunk_hec_token", "lightstep_access_token",
    "trace_lightstep_access_token", "tls_key",
    "archive_blob_secret_key",
}


def redacted_dict(cfg: Config) -> dict[str, Any]:
    """Config as a dict with secrets masked, for debug logging
    (reference server.go:794-802)."""
    out = {}
    for f in fields(cfg):
        v = getattr(cfg, f.name)
        if f.name in SECRET_FIELDS and v:
            v = "REDACTED"
        out[f.name] = v
    return out


class UnknownConfigKeys(Warning):
    pass


def _coerce(value: Any, target: Any, key: str) -> Any:
    if isinstance(target, bool):
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        return bool(value)
    if isinstance(target, int) and not isinstance(target, bool):
        return int(value)
    if isinstance(target, float):
        return float(value)
    if isinstance(target, list):
        if isinstance(value, str):
            return [v for v in value.split(",") if v]
        return list(value)
    if isinstance(target, dict):
        # env overlay form: "name:value,name:value" (tenant_budgets)
        if isinstance(value, str):
            out: dict[str, int] = {}
            for part in value.split(","):
                if not part:
                    continue
                name, _, v = part.partition(":")
                out[name] = int(v)
            return out
        return dict(value)
    return value


def load_config(path: Optional[str] = None, data: Optional[dict] = None,
                env: Optional[dict] = None, strict: bool = False) -> Config:
    """Read config: yaml → env overlay → defaults.

    Unknown yaml keys warn (the reference falls back from strict to loose
    parse, config_parse.go:115). Environment variables named VENEUR_<KEY>
    (yaml key uppercased, with or without underscores) override file values
    (reference envconfig overlay).
    """
    raw: dict[str, Any] = {}
    if path is not None:
        with open(path) as f:
            raw = yaml.safe_load(f) or {}
    if data is not None:
        raw.update(data)

    cfg = Config()
    known = {f.name: f for f in fields(cfg)}
    unknown = []
    for key, value in raw.items():
        if key not in known:
            unknown.append(key)
            continue
        if value is None:
            continue
        current = getattr(cfg, key)
        if key == "veneur_metrics_scopes" and isinstance(value, dict):
            setattr(cfg, key, MetricsScopes(**value))
        elif key == "signalfx_per_tag_api_keys":
            setattr(cfg, key, [PerTagApiKey(**v) for v in value])
        elif key == "datadog_exclude_tags_prefix_by_prefix_metric":
            setattr(cfg, key,
                    [ExcludeTagsPrefixByPrefixMetric(**v) for v in value])
        else:
            setattr(cfg, key, _coerce(value, current, key))
    if unknown:
        msg = f"unknown config keys: {sorted(unknown)}"
        if strict:
            raise ValueError(msg)
        log.warning(msg)

    env = os.environ if env is None else env
    for name in known:
        for candidate in (
            "VENEUR_" + name.upper(),
            "VENEUR_" + name.upper().replace("_", ""),
        ):
            if candidate in env:
                setattr(
                    cfg, name, _coerce(env[candidate], getattr(cfg, name), name)
                )
                break

    # deprecated-alias fixups (reference config_parse.go:172-183)
    if cfg.ssf_buffer_size != Config.ssf_buffer_size:
        log.warning("ssf_buffer_size has been replaced by"
                    " datadog_span_buffer_size")
        if cfg.datadog_span_buffer_size == Config.datadog_span_buffer_size:
            cfg.datadog_span_buffer_size = cfg.ssf_buffer_size
    if cfg.flush_max_per_body != Config.flush_max_per_body:
        log.warning("flush_max_per_body has been replaced by"
                    " datadog_flush_max_per_body")
        if (cfg.datadog_flush_max_per_body
                == Config.datadog_flush_max_per_body):
            cfg.datadog_flush_max_per_body = cfg.flush_max_per_body

    validate_config(cfg)
    return cfg


def resolve_reader_shards(cfg: Config) -> int:
    """Effective reader-shard count for this process.

    VENEUR_READER_SHARDS overrides the config key (same escape-hatch
    idiom as VENEUR_SERIES_SHARDS, ops/series_shard.py): =0 pins the
    legacy digest-routed path. -1 (auto) resolves to num_readers when
    the shared-nothing layout applies — native ingest + native readers
    on, a single worker (the canonical row space is that worker's
    directory), and more than one reader to shard. Incompatible
    explicit requests degrade to 0 with a warning rather than failing
    ingest."""
    value = cfg.reader_shards
    env = os.environ.get("VENEUR_READER_SHARDS")
    if env is not None:
        try:
            value = int(env)
        except ValueError:
            log.warning("VENEUR_READER_SHARDS=%r is not an integer;"
                        " using reader_shards=%d", env, value)
    if value == 0:
        return 0
    if not (cfg.tpu_native_ingest and cfg.tpu_native_readers):
        if value > 0:
            log.warning("reader_shards=%d needs tpu_native_ingest and"
                        " tpu_native_readers; using the legacy path",
                        value)
        return 0
    if cfg.num_workers != 1:
        if value > 0:
            log.warning("reader_shards=%d requires num_workers: 1 (the"
                        " canonical row space is the single worker's"
                        " directory); using the legacy digest-routed"
                        " path", value)
        return 0
    if cfg.tpu_mesh_devices > 1:
        if value > 0:
            log.warning("reader_shards=%d is incompatible with the"
                        " global tier's mesh; using the legacy path",
                        value)
        return 0
    if value == -1:
        return cfg.num_readers if cfg.num_readers > 1 else 0
    return value


def validate_config(cfg: Config) -> None:
    parse_duration(cfg.interval)  # raises on nonsense
    if cfg.interval_seconds() <= 0:
        raise ValueError("interval must be positive")
    for p in cfg.percentiles:
        if not (0 <= p <= 1):
            raise ValueError(f"percentile {p} out of [0,1]")
    if cfg.num_workers < 1 or cfg.num_readers < 1:
        raise ValueError("num_workers and num_readers must be >= 1")
    if cfg.forward_format not in ("veneurtpu", "forwardrpc", "jsonmetric"):
        raise ValueError("forward_format must be 'veneurtpu', 'forwardrpc'"
                         " or 'jsonmetric'")
    if cfg.forward_format == "forwardrpc" and not cfg.forward_use_grpc:
        raise ValueError("forward_format: forwardrpc requires"
                         " forward_use_grpc: true")
    if cfg.forward_format == "jsonmetric" and cfg.forward_use_grpc:
        raise ValueError("forward_format: jsonmetric is the legacy HTTP"
                         " body; set forward_use_grpc: false")
    # sharded proxy tier: the multi-destination spread rides the
    # native-wire gRPC path only (spread.py sends serialized MetricBatch
    # bytes per lane; the HTTP and forwardrpc interop forwarders stay
    # single-destination)
    multi_dest = (bool(cfg.forward_discovery_file)
                  or len(cfg.forward_destinations()) > 1)
    if multi_dest and not cfg.forward_use_grpc:
        raise ValueError("a proxy fleet (forward_discovery_file or a"
                         " comma-separated forward_address) requires"
                         " forward_use_grpc: true")
    if multi_dest and cfg.forward_format != "veneurtpu":
        raise ValueError("a proxy fleet requires forward_format:"
                         " veneurtpu (interop forwarders are"
                         " single-destination)")
    if cfg.forward_spread_policy not in ("p2c", "round_robin"):
        raise ValueError("forward_spread_policy must be 'p2c' or"
                         " 'round_robin'")
    if cfg.forward_retry_max < 0:
        raise ValueError("forward_retry_max must be >= 0 (0 means one"
                         " attempt, no retries)")
    if cfg.forward_breaker_threshold < 0:
        raise ValueError("forward_breaker_threshold must be >= 0"
                         " (0 disables the circuit breaker)")
    if cfg.forward_spill_max_bytes < 0 or cfg.forward_spill_max_payloads < 0:
        raise ValueError("forward spill caps must be >= 0 (0 drops"
                         " failed payloads instead of spilling them)")
    parse_duration(cfg.forward_discovery_interval)  # raises on nonsense
    if cfg.tpu_mesh_devices > 1 and cfg.num_workers != 1:
        raise ValueError(
            "tpu_mesh_devices requires num_workers: 1 (the mesh shards"
            " series; in-process worker sharding would double it)")
    if cfg.tpu_mesh_devices > 1 and cfg.tpu_mesh_hosts:
        if cfg.tpu_mesh_devices % cfg.tpu_mesh_hosts:
            raise ValueError("tpu_mesh_devices must be divisible by"
                             " tpu_mesh_hosts")
    if cfg.series_shards < 0:
        raise ValueError("series_shards must be >= 0 (0/1 disable"
                         " series sharding)")
    if cfg.series_shards > 1:
        s = cfg.series_shards
        if s & (s - 1):
            raise ValueError("series_shards must be a power of two (the"
                             " row interleave needs shards | pool rows,"
                             " and pool sizes are powers of two)")
        if s > 1024:
            raise ValueError("series_shards must be <= 1024 (chunked"
                             " extraction aligns chunk starts to the"
                             " shard count, floored at 1024 rows)")
        if cfg.tpu_mesh_devices > 1:
            raise ValueError(
                "series_shards and tpu_mesh_devices are mutually"
                " exclusive: the global tier's mesh owns the device"
                " layout; a worker cannot also shard its pools over it")
    if cfg.reader_shards < -1:
        raise ValueError("reader_shards must be >= -1 (-1 auto, 0"
                         " disables reader sharding)")
    if cfg.reader_shards > 256:
        raise ValueError("reader_shards must be <= 256 (each shard is a"
                         " full native context; hundreds of readers"
                         " should be split across processes)")
    if cfg.set_hash not in ("fnv", "metro"):
        raise ValueError("set_hash must be 'fnv' or 'metro'")
    if cfg.tpu_set_store not in ("staged", "dense"):
        raise ValueError("tpu_set_store must be 'staged' or 'dense'")
    if not (4 <= cfg.tpu_hll_precision <= 18):
        raise ValueError("tpu_hll_precision must be in [4,18]")
    if cfg.flush_chunk_target_ms < 0:
        raise ValueError("flush_chunk_target_ms must be >= 0"
                         " (0 disables chunked extraction)")
    if (cfg.flush_chunk_target_ms
            and cfg.flush_chunk_target_ms >= cfg.interval_seconds() * 1000):
        raise ValueError("flush_chunk_target_ms must be below the flush"
                         " interval (a chunk IS a sub-interval unit)")
    if cfg.flush_pipeline_backlog < 1:
        raise ValueError("flush_pipeline_backlog must be >= 1 (a stage"
                         " needs at least the in-progress interval)")
    if cfg.flush_timeout_s <= 0:
        raise ValueError("flush_timeout_s must be positive (it is the"
                         " per-attempt network timeout)")
    if cfg.sink_retry_max < 0:
        raise ValueError("sink_retry_max must be >= 0 (0 means one"
                         " attempt, no retries)")
    if cfg.sink_breaker_threshold < 0:
        raise ValueError("sink_breaker_threshold must be >= 0"
                         " (0 disables the circuit breaker)")
    if cfg.sink_spill_max_bytes < 0 or cfg.sink_spill_max_payloads < 0:
        raise ValueError("sink spill caps must be >= 0 (0 drops failed"
                         " payloads instead of spilling them)")
    _validate_journal_keys(cfg)
    _validate_dedup_keys(cfg)
    _validate_stream_keys(cfg)
    if cfg.config_reload_s < 0:
        raise ValueError("config_reload_s must be >= 0 (0 disables the"
                         " config hot-reload watcher)")
    if cfg.forward_statsd_network not in ("udp", "tcp"):
        raise ValueError("forward_statsd_network must be 'udp' or 'tcp'")
    if cfg.tpu_stage_depth < 1:
        raise ValueError("tpu_stage_depth must be >= 1")
    if cfg.tpu_spill_cap < 1:
        raise ValueError("tpu_spill_cap must be >= 1")
    if cfg.micro_fold_rows < 1:
        raise ValueError("micro_fold_rows must be >= 1")
    if cfg.micro_fold_max_age_s <= 0:
        raise ValueError("micro_fold_max_age_s must be positive (it is"
                         " the staged-backlog age that forces a drain)")
    if cfg.device_fault_streak < 1:
        raise ValueError("device_fault_streak must be >= 1 (the"
                         " consecutive-fault count that trips the"
                         " device breaker)")
    if cfg.device_probe_interval_s <= 0:
        raise ValueError("device_probe_interval_s must be positive (it"
                         " paces re-admission probes while the device"
                         " path is quarantined)")
    if not (1 <= cfg.loadgen_num_keys <= (1 << 24)):
        raise ValueError("loadgen_num_keys must be in [1, 2^24]")
    if cfg.loadgen_zipf_s < 0:
        raise ValueError("loadgen_zipf_s must be >= 0")
    if (len(cfg.loadgen_type_mix) != 5
            or any(w < 0 for w in cfg.loadgen_type_mix)
            or sum(cfg.loadgen_type_mix) <= 0):
        raise ValueError("loadgen_type_mix must be 5 non-negative weights"
                         " ({c,g,ms,h,s} order) with a positive sum")
    if not (0 <= cfg.loadgen_num_tags <= 16):
        raise ValueError("loadgen_num_tags must be in [0,16]")
    if cfg.loadgen_tag_cardinality < 1:
        raise ValueError("loadgen_tag_cardinality must be >= 1")
    if not (64 <= cfg.loadgen_datagram_bytes <= 65507):
        raise ValueError("loadgen_datagram_bytes must be in [64,65507]"
                         " (a UDP datagram)")
    if cfg.loadgen_ring_lines < 1:
        raise ValueError("loadgen_ring_lines must be >= 1")
    if not cfg.loadgen_prefix or cfg.loadgen_prefix[0] in "0123456789":
        raise ValueError("loadgen_prefix must be a valid metric name stem")
    if not (1 <= cfg.loadgen_tenant_count <= 4096):
        raise ValueError("loadgen_tenant_count must be in [1, 4096]")
    if not (0.0 <= cfg.loadgen_tenant_abusive_frac <= 1.0):
        raise ValueError("loadgen_tenant_abusive_frac must be in [0,1]")
    if cfg.loadgen_tenant_zipf_s < 0:
        raise ValueError("loadgen_tenant_zipf_s must be >= 0")
    if cfg.loadgen_tenant_churn_keys < 0:
        raise ValueError("loadgen_tenant_churn_keys must be >= 0")
    if not cfg.tenant_tag_key:
        raise ValueError("tenant_tag_key must be non-empty")
    if cfg.tenant_default_budget < 0:
        raise ValueError("tenant_default_budget must be >= 0 (0 disables"
                         " the tenant QoS layer)")
    if not isinstance(cfg.tenant_budgets, dict) or any(
            not isinstance(k, str) or int(v) < 0
            for k, v in cfg.tenant_budgets.items()):
        raise ValueError("tenant_budgets must map tenant name → series"
                         " budget >= 0 (0 = unlimited for that tenant)")
    if not (1 <= cfg.tenant_sketch_depth <= 8):
        raise ValueError("tenant_sketch_depth must be in [1,8]")
    w = cfg.tenant_sketch_width
    if not (64 <= w <= (1 << 20)) or (w & (w - 1)):
        raise ValueError("tenant_sketch_width must be a power of two"
                         " in [64, 2^20] (the sketch hash masks, never"
                         " mods)")
    if not (1 <= cfg.tenant_topk <= 1024):
        raise ValueError("tenant_topk must be in [1,1024]")
    if cfg.span_flush_drain_s < 0:
        raise ValueError("span_flush_drain_s must be >= 0 (0 skips the"
                         " lane drain entirely; spans accepted late ship"
                         " next flush)")
    if cfg.span_batch_rows < 1:
        raise ValueError("span_batch_rows must be >= 1")
    if cfg.span_pending_cap < 1:
        raise ValueError("span_pending_cap must be >= 1")
    if cfg.kafka_span_serialization_format not in (
            "protobuf", "json", "columnar"):
        raise ValueError("kafka_span_serialization_format must be"
                         " 'protobuf', 'json' or 'columnar' (columnar"
                         " ships one VSB1 frame per sealed span batch"
                         " through the delivery manager)")
    _validate_archive_keys(cfg)
    _validate_query_keys(cfg)


def _validate_archive_keys(cfg) -> None:
    if cfg.archive_max_bytes < 1:
        raise ValueError("archive_max_bytes must be >= 1 (a segment must"
                         " be able to hold at least one byte; rotation"
                         " is checked per-frame, not mid-frame)")
    if cfg.archive_max_segments < 1:
        raise ValueError("archive_max_segments must be >= 1 (the archive"
                         " keeps at least the active segment)")
    if cfg.archive_blob_bucket and not cfg.archive_blob_access_key:
        raise ValueError("archive_blob_bucket requires"
                         " archive_blob_access_key (+ secret); the blob"
                         " egress signs every PUT with SigV4")
    if cfg.archive_blob_access_key and not cfg.archive_blob_secret_key:
        raise ValueError("archive_blob_access_key requires"
                         " archive_blob_secret_key")


def _validate_query_keys(cfg) -> None:
    for addr in cfg.query_listen_addrs:
        scheme, sep, hostport = addr.partition("://")
        if not sep or scheme not in ("http", "grpc"):
            raise ValueError(
                f"query_listen_addrs entry {addr!r} must be"
                " 'http://host:port' or 'grpc://host:port'")
        host, sep, port = hostport.rpartition(":")
        if not sep or not host or not port.isdigit():
            raise ValueError(
                f"query_listen_addrs entry {addr!r} needs host:port"
                " (port 0 binds ephemerally)")
