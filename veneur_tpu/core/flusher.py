"""InterMetric generation from a flushed interval.

Behavioral spec: reference generateInterMetrics (flusher.go:225-298) plus the
per-sampler Flush methods (samplers/samplers.go:147-158 Counter, :230-242
Gauge, :319-324 StatusCheck, :392-403 Set, :511-675 Histo) — including the
mixed-scope double-count avoidance: a local (forwarding) instance emits only
host-local aggregates for mixed histograms, never percentiles; the global
instance emits percentiles but no local aggregates (flusher.go:61-74).

The flusher consumes a FlushSnapshot (dense arrays + row metadata) and emits
InterMetric objects row by row; all numeric work already happened on device.
"""

from __future__ import annotations

import math
import time
from typing import Optional

import numpy as np

from veneur_tpu.core.directory import ScopeClass
from veneur_tpu.core.metrics import (
    Aggregate,
    HistogramAggregates,
    InterMetric,
    MetricType,
)
from veneur_tpu.core.worker import FlushSnapshot


def device_quantiles(
    percentiles: list[float], aggregates: HistogramAggregates
) -> np.ndarray:
    """The quantile vector the device must evaluate: configured percentiles
    plus the median when the median aggregate is enabled (reference
    samplers.go:622-636 pulls the median from the digest)."""
    qs = list(percentiles)
    if aggregates.value & Aggregate.MEDIAN and 0.5 not in qs:
        qs.append(0.5)
    # float64 so host-side lookups by the exact configured value round-trip;
    # the worker casts to f32 only at the device boundary
    return np.asarray(qs, dtype=np.float64)


def _percentile_name(name: str, p: float) -> str:
    # reference formats with int(p*100) (samplers.go:657-672)
    return f"{name}.{int(p * 100)}percentile"


def generate_inter_metrics(
    snap: FlushSnapshot,
    is_local: bool,
    percentiles: list[float],
    aggregates: HistogramAggregates,
    now: Optional[int] = None,
) -> list[InterMetric]:
    """Emit every InterMetric this interval owes its sinks."""
    ts = int(time.time()) if now is None else now
    out: list[InterMetric] = []

    # mixed histograms/timers forward their digests, so a local instance
    # flushes only aggregates for them (flusher.go:61-74)
    mixed_percentiles: list[float] = [] if is_local else list(percentiles)

    # -- histogram/timer rows ---------------------------------------------
    hrows = snap.directory.histo.rows
    if hrows:
        q_index = {
            float(q): i for i, q in enumerate(np.asarray(snap.quantile_qs))
        }
        for row, meta in enumerate(hrows):
            cls = meta.scope_class
            if cls == ScopeClass.MIXED:
                ps, use_global = mixed_percentiles, False
            elif cls == ScopeClass.LOCAL:
                ps, use_global = list(percentiles), False
            else:  # GLOBAL: flushed only by the global instance, from digest
                if is_local:
                    continue
                ps, use_global = list(percentiles), True
            out.extend(
                _flush_histo_row(snap, row, meta, ts, ps, aggregates,
                                 use_global, q_index)
            )

    # -- set rows ----------------------------------------------------------
    srows = snap.directory.sets.rows
    if srows:
        for row, meta in enumerate(srows):
            # mixed sets have no local part: only the global instance emits
            # them (flusher.go:269-274); local-only sets always flush
            if meta.scope_class == ScopeClass.MIXED and is_local:
                continue
            out.append(
                InterMetric(
                    name=meta.key.name,
                    timestamp=ts,
                    value=float(snap.set_estimates[row]),
                    tags=list(meta.tags),
                    type=MetricType.GAUGE,
                    sinks=meta.sinks,
                )
            )

    # -- counters ----------------------------------------------------------
    for (key, tags, cls, sinks), value in zip(
        snap.scalars.counter_meta, snap.scalars.counter_values
    ):
        if cls == ScopeClass.GLOBAL and is_local:
            continue  # forwarded, not emitted (flusher.go:276-283)
        out.append(
            InterMetric(
                name=key.name, timestamp=ts, value=float(value),
                tags=list(tags), type=MetricType.COUNTER, sinks=sinks,
            )
        )

    # -- gauges ------------------------------------------------------------
    for (key, tags, cls, sinks), value in zip(
        snap.scalars.gauge_meta, snap.scalars.gauge_values
    ):
        if cls == ScopeClass.GLOBAL and is_local:
            continue
        out.append(
            InterMetric(
                name=key.name, timestamp=ts, value=float(value),
                tags=list(tags), type=MetricType.GAUGE, sinks=sinks,
            )
        )

    # -- status checks -----------------------------------------------------
    for (key, tags, _cls, sinks), sv in zip(
        snap.scalars.status_meta, snap.scalars.status_values
    ):
        value, message, hostname = sv
        out.append(
            InterMetric(
                name=key.name, timestamp=ts, value=float(value),
                tags=list(tags), type=MetricType.STATUS, message=message,
                hostname=hostname, sinks=sinks,
            )
        )

    return out


def _flush_histo_row(
    snap: FlushSnapshot,
    row: int,
    meta,
    ts: int,
    percentiles: list[float],
    aggregates: HistogramAggregates,
    use_global: bool,
    q_index: dict[float, int],
) -> list[InterMetric]:
    """One histogram/timer row → aggregate + percentile series
    (reference Histo.Flush, samplers.go:511-675)."""
    name = meta.key.name
    tags = list(meta.tags)
    sinks = meta.sinks
    agg = aggregates.value
    out: list[InterMetric] = []

    lmin = float(snap.lmin[row])
    lmax = float(snap.lmax[row])
    lsum = float(snap.lsum[row])
    lweight = float(snap.lweight[row])
    lrecip = float(snap.lrecip[row])

    def gauge(metric_name: str, value: float) -> InterMetric:
        return InterMetric(name=metric_name, timestamp=ts, value=value,
                           tags=list(tags), type=MetricType.GAUGE, sinks=sinks)

    if agg & Aggregate.MAX and (not math.isinf(lmax) or use_global):
        val = float(snap.dmax[row]) if use_global else lmax
        out.append(gauge(f"{name}.max", val))
    if agg & Aggregate.MIN and (not math.isinf(lmin) or use_global):
        val = float(snap.dmin[row]) if use_global else lmin
        out.append(gauge(f"{name}.min", val))
    if agg & Aggregate.SUM and (lsum != 0 or use_global):
        val = float(snap.dsum[row]) if use_global else lsum
        out.append(gauge(f"{name}.sum", val))
    if agg & Aggregate.AVERAGE and (use_global or (lsum != 0 and lweight != 0)):
        if use_global:
            val = float(snap.dsum[row]) / float(snap.dcount[row])
        else:
            val = lsum / lweight
        out.append(gauge(f"{name}.avg", val))
    if agg & Aggregate.COUNT and (lweight != 0 or use_global):
        val = float(snap.dcount[row]) if use_global else lweight
        out.append(
            InterMetric(name=f"{name}.count", timestamp=ts, value=val,
                        tags=list(tags), type=MetricType.COUNTER, sinks=sinks)
        )
    if agg & Aggregate.MEDIAN:
        # always emitted when configured; the value comes from the digest
        out.append(
            gauge(f"{name}.median",
                  float(snap.quantile_values[row, q_index[0.5]]))
        )
    if agg & Aggregate.HARMONIC_MEAN and (
        use_global or (lrecip != 0 and lweight != 0)
    ):
        if use_global:
            val = float(snap.dcount[row]) / float(snap.drecip[row])
        else:
            val = lweight / lrecip
        out.append(gauge(f"{name}.hmean", val))

    for p in percentiles:
        out.append(
            gauge(_percentile_name(name, p),
                  float(snap.quantile_values[row, q_index[float(p)]]))
        )

    return out


# ---------------------------------------------------------------------------
# Forwarding selection


def forwardable_rows(snap: FlushSnapshot):
    """Yield the forwardable content of a snapshot, typed, mirroring
    reference ForwardableMetrics (worker.go:181-209): global counters and
    gauges, mixed+global histograms/timers, mixed sets. Local-only series
    never leave the instance.

    Yields tuples:
      ("counter", key, tags, value)
      ("gauge", key, tags, value)
      ("histogram"|"timer", key, tags, scope_class, means, weights,
       dmin, dmax, drecip)
      ("set", key, tags, registers)
    """
    for (key, tags, cls, _sinks), value in zip(
        snap.scalars.counter_meta, snap.scalars.counter_values
    ):
        if cls == ScopeClass.GLOBAL:
            yield ("counter", key, tags, value)
    for (key, tags, cls, _sinks), value in zip(
        snap.scalars.gauge_meta, snap.scalars.gauge_values
    ):
        if cls == ScopeClass.GLOBAL:
            yield ("gauge", key, tags, value)
    for row, meta in enumerate(snap.directory.histo.rows):
        if meta.scope_class == ScopeClass.LOCAL:
            continue
        if snap.digest_means is None:
            # mesh-mode snapshots don't materialize per-row centroid
            # arrays host-side; a mesh global is a terminal aggregator
            # (chained-global forwarding needs the single-device path)
            break
        yield (
            meta.key.type, meta.key, meta.tags, meta.scope_class,
            snap.digest_means[row], snap.digest_weights[row],
            float(snap.dmin[row]), float(snap.dmax[row]),
            float(snap.drecip[row]),
        )
    if snap.set_registers is not None:
        # terminal (global) snapshots skip register materialization
        for row, meta in enumerate(snap.directory.sets.rows):
            if meta.scope_class == ScopeClass.MIXED:
                yield ("set", meta.key, meta.tags, snap.set_registers[row])
