"""InterMetric generation from a flushed interval.

Behavioral spec: reference generateInterMetrics (flusher.go:225-298) plus the
per-sampler Flush methods (samplers/samplers.go:147-158 Counter, :230-242
Gauge, :319-324 StatusCheck, :392-403 Set, :511-675 Histo) — including the
mixed-scope double-count avoidance: a local (forwarding) instance emits only
host-local aggregates for mixed histograms, never percentiles; the global
instance emits percentiles but no local aggregates (flusher.go:61-74).

The flusher consumes a FlushSnapshot (dense arrays + row metadata) and emits
InterMetric objects row by row; all numeric work already happened on device.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from veneur_tpu.core.directory import ScopeClass
from veneur_tpu.core.metrics import (
    Aggregate,
    HistogramAggregates,
    InterMetric,
    MetricType,
)
from veneur_tpu.core.worker import FlushSnapshot


def device_quantiles(
    percentiles: list[float], aggregates: HistogramAggregates
) -> np.ndarray:
    """The quantile vector the device must evaluate: configured percentiles
    plus the median when the median aggregate is enabled (reference
    samplers.go:622-636 pulls the median from the digest)."""
    qs = list(percentiles)
    if aggregates.value & Aggregate.MEDIAN and 0.5 not in qs:
        qs.append(0.5)
    # float64 so host-side lookups by the exact configured value round-trip;
    # the worker casts to f32 only at the device boundary
    return np.asarray(qs, dtype=np.float64)


def _percentile_name(name: str, p: float) -> str:
    # reference formats with int(p*100) (samplers.go:657-672)
    return f"{name}.{int(p * 100)}percentile"


def generate_inter_metrics(
    snap: FlushSnapshot,
    is_local: bool,
    percentiles: list[float],
    aggregates: HistogramAggregates,
    now: Optional[int] = None,
    governor=None,
) -> list[InterMetric]:
    """Emit every InterMetric this interval owes its sinks."""
    if governor is not None:
        # liveness beat for the flush watchdog's deferral rule: at high
        # cardinality the generate phase is seconds of host work, and a
        # deferred-panic decision should see it as progress, not silence
        governor.beat()
    ts = int(time.time()) if now is None else now
    out: list[InterMetric] = []

    # mixed histograms/timers forward their digests, so a local instance
    # flushes only aggregates for them (flusher.go:61-74)
    mixed_percentiles: list[float] = [] if is_local else list(percentiles)

    # -- histogram/timer rows ---------------------------------------------
    # This loop runs once per series per flush (1M+ rows in the
    # prometheus_1m scenario); per-element numpy indexing costs ~µs each,
    # so every column is materialized to a plain Python list up front
    # (tolist is one C pass) and rows touch only list indexing.
    hrows = snap.directory.histo.rows
    if hrows:
        q_index = {
            float(q): i for i, q in enumerate(np.asarray(snap.quantile_qs))
        }
        quant = {float(q): snap.quantile_values[:, i].tolist()
                 for q, i in q_index.items()}
        # digest-side columns are read only on the global instance
        # (use_global rows are skipped on locals): don't box 5M floats
        # a local flush never touches
        empty: list = []
        cols = _HistoCols(
            lmin=snap.lmin.tolist(), lmax=snap.lmax.tolist(),
            lsum=snap.lsum.tolist(), lweight=snap.lweight.tolist(),
            lrecip=snap.lrecip.tolist(),
            dmin=empty if is_local else snap.dmin.tolist(),
            dmax=empty if is_local else snap.dmax.tolist(),
            dsum=empty if is_local else snap.dsum.tolist(),
            dcount=empty if is_local else snap.dcount.tolist(),
            drecip=empty if is_local else snap.drecip.tolist(),
            quant=quant,
            pcols=[(_percentile_name("", p), quant[float(p)])
                   for p in percentiles],
            want_max=bool(aggregates.value & Aggregate.MAX),
            want_min=bool(aggregates.value & Aggregate.MIN),
            want_sum=bool(aggregates.value & Aggregate.SUM),
            want_avg=bool(aggregates.value & Aggregate.AVERAGE),
            want_count=bool(aggregates.value & Aggregate.COUNT),
            want_median=bool(aggregates.value & Aggregate.MEDIAN),
            want_hmean=bool(aggregates.value & Aggregate.HARMONIC_MEAN),
        )
        hrej = snap.directory.histo.rejected_rows > 0
        for row, meta in enumerate(hrows):
            if governor is not None and row and row % 200_000 == 0:
                # the entry beat above covers small flushes; at 1M rows
                # this loop is seconds of host work, and under the stage
                # pipeline it overlaps the NEXT interval's extract — the
                # watchdog must keep seeing progress, not entry-silence
                governor.beat()
            if hrej and not meta.admitted:
                # tenant-budget-rejected series (native path marks the
                # row instead of refusing it; see directory.RowMeta) —
                # never emitted, by either path
                continue
            cls = meta.scope_class
            if cls == ScopeClass.MIXED:
                # locals forward mixed digests and emit no percentiles
                ps, use_global = bool(mixed_percentiles), False
            elif cls == ScopeClass.LOCAL:
                ps, use_global = bool(percentiles), False
            else:  # GLOBAL: flushed only by the global instance, from digest
                if is_local:
                    continue
                ps, use_global = bool(percentiles), True
            _flush_histo_row(cols, row, meta, ts, ps, use_global, out)

    # -- set rows ----------------------------------------------------------
    srows = snap.directory.sets.rows
    if srows:
        srej = snap.directory.sets.rejected_rows > 0
        for row, meta in enumerate(srows):
            if srej and not meta.admitted:
                continue
            # mixed sets have no local part: only the global instance emits
            # them (flusher.go:269-274); local-only sets always flush
            if meta.scope_class == ScopeClass.MIXED and is_local:
                continue
            out.append(
                InterMetric(
                    name=meta.key.name,
                    timestamp=ts,
                    value=float(snap.set_estimates[row]),
                    tags=list(meta.tags),
                    type=MetricType.GAUGE,
                    sinks=meta.sinks,
                )
            )

    # -- counters ----------------------------------------------------------
    cpool = snap.scalars.counters
    crej = cpool.rejected_rows > 0
    for row, ((key, tags, cls, sinks), value) in enumerate(zip(
        snap.scalars.counter_meta, snap.scalars.counter_values
    )):
        if crej and not cpool.admit_codes[row]:
            continue
        if cls == ScopeClass.GLOBAL and is_local:
            continue  # forwarded, not emitted (flusher.go:276-283)
        out.append(
            InterMetric(
                name=key.name, timestamp=ts, value=float(value),
                tags=list(tags), type=MetricType.COUNTER, sinks=sinks,
            )
        )

    # -- gauges ------------------------------------------------------------
    gpool = snap.scalars.gauges
    grej = gpool.rejected_rows > 0
    for row, ((key, tags, cls, sinks), value) in enumerate(zip(
        snap.scalars.gauge_meta, snap.scalars.gauge_values
    )):
        if grej and not gpool.admit_codes[row]:
            continue
        if cls == ScopeClass.GLOBAL and is_local:
            continue
        out.append(
            InterMetric(
                name=key.name, timestamp=ts, value=float(value),
                tags=list(tags), type=MetricType.GAUGE, sinks=sinks,
            )
        )

    # -- status checks -----------------------------------------------------
    for (key, tags, _cls, sinks), sv in zip(
        snap.scalars.status_meta, snap.scalars.status_values
    ):
        value, message, hostname = sv
        out.append(
            InterMetric(
                name=key.name, timestamp=ts, value=float(value),
                tags=list(tags), type=MetricType.STATUS, message=message,
                hostname=hostname, sinks=sinks,
            )
        )

    return out


@dataclass
class _HistoCols:
    """Snapshot columns pre-materialized as Python lists for the per-row
    emission loop."""

    lmin: list
    lmax: list
    lsum: list
    lweight: list
    lrecip: list
    dmin: list
    dmax: list
    dsum: list
    dcount: list
    drecip: list
    quant: dict  # percentile -> per-row list
    # (suffix, per-row values) per configured percentile, precomputed so
    # the row loop does one concat instead of number formatting
    pcols: list = None
    # aggregate-flag membership tested once (Flag-enum `&` costs ~1µs a
    # call; at 7 tests × 1M rows that alone was most of the loop)
    want_max: bool = False
    want_min: bool = False
    want_sum: bool = False
    want_avg: bool = False
    want_count: bool = False
    want_median: bool = False
    want_hmean: bool = False


def _flush_histo_row(
    cols: _HistoCols,
    row: int,
    meta,
    ts: int,
    emit_percentiles: bool,
    use_global: bool,
    out: list,
) -> None:
    """One histogram/timer row → aggregate + percentile series
    (reference Histo.Flush, samplers.go:511-675). Appends to `out`.

    The tags list is shared across this row's metrics — InterMetric
    consumers never mutate tags (exclusion builds new lists)."""
    name = meta.key.name
    tags = meta.tags
    sinks = meta.sinks
    append = out.append
    GAUGE = MetricType.GAUGE

    lmin = cols.lmin[row]
    lmax = cols.lmax[row]
    lsum = cols.lsum[row]
    lweight = cols.lweight[row]
    lrecip = cols.lrecip[row]

    if cols.want_max and (not math.isinf(lmax) or use_global):
        append(InterMetric(name + ".max", ts,
                           cols.dmax[row] if use_global else lmax,
                           tags, GAUGE, sinks=sinks))
    if cols.want_min and (not math.isinf(lmin) or use_global):
        append(InterMetric(name + ".min", ts,
                           cols.dmin[row] if use_global else lmin,
                           tags, GAUGE, sinks=sinks))
    if cols.want_sum and (lsum != 0 or use_global):
        append(InterMetric(name + ".sum", ts,
                           cols.dsum[row] if use_global else lsum,
                           tags, GAUGE, sinks=sinks))
    if cols.want_avg and (use_global or (lsum != 0 and lweight != 0)):
        if use_global:
            val = cols.dsum[row] / cols.dcount[row]
        else:
            val = lsum / lweight
        append(InterMetric(name + ".avg", ts, val, tags, GAUGE, sinks=sinks))
    if cols.want_count and (lweight != 0 or use_global):
        append(InterMetric(name + ".count", ts,
                           cols.dcount[row] if use_global else lweight,
                           tags, MetricType.COUNTER, sinks=sinks))
    if cols.want_median:
        # always emitted when configured; the value comes from the digest
        append(InterMetric(name + ".median", ts, cols.quant[0.5][row],
                           tags, GAUGE, sinks=sinks))
    if cols.want_hmean and (
        use_global or (lrecip != 0 and lweight != 0)
    ):
        if use_global:
            val = cols.dcount[row] / cols.drecip[row]
        else:
            val = lweight / lrecip
        append(InterMetric(name + ".hmean", ts, val, tags, GAUGE,
                           sinks=sinks))

    if emit_percentiles:
        for suffix, col in cols.pcols:
            append(InterMetric(name + suffix, ts, col[row], tags, GAUGE,
                               sinks=sinks))


# ---------------------------------------------------------------------------
# Columnar generation (the SoA fast path; see core/columnar.py)


def generate_columnar(
    snap: FlushSnapshot,
    is_local: bool,
    percentiles: list[float],
    aggregates: HistogramAggregates,
    now: Optional[int] = None,
    governor=None,
):
    """Columnar twin of generate_inter_metrics: numpy masks instead of a
    per-row Python loop. Emits the identical metric multiset (pinned by
    tests/test_columnar.py); costs O(R) numpy, not O(R·families) Python.
    """
    from veneur_tpu.core.columnar import (
        ColumnarMetrics, ColumnGroup, MetricFamily,
    )

    if governor is not None:
        # liveness beat for the flush watchdog's deferral rule (see
        # generate_inter_metrics)
        governor.beat()
    ts = int(time.time()) if now is None else now
    batch = ColumnarMetrics(timestamp=ts)
    GAUGE = MetricType.GAUGE

    # -- histogram/timer rows ---------------------------------------------
    hrows = snap.directory.histo.rows
    if hrows:
        sc = np.frombuffer(snap.directory.histo.scope_codes,
                           dtype=np.int8)[: len(hrows)]
        is_global_row = sc == int(ScopeClass.GLOBAL)
        # a local instance forwards global rows instead of emitting them
        base = ~is_global_row if is_local else None
        # tenant-budget-rejected rows (native path) are cut from EVERY
        # family; hadm folds into base AND into pmask below — percentile
        # families bypass base, and a rejected row must not leak through
        # them. Zero-tenant runs never build the mask (rejected_rows 0).
        hadm = None
        if snap.directory.histo.rejected_rows > 0:
            hadm = np.frombuffer(snap.directory.histo.admit_codes,
                                 dtype=np.int8)[: len(hrows)] != 0
            base = hadm if base is None else (base & hadm)
        use_global = (np.zeros(len(hrows), bool) if is_local
                      else is_global_row)
        # widen to f64 up front: the object path boxes every f32 column
        # through .tolist() before arithmetic, so divisions (avg, hmean)
        # happen in f64 — match that exactly
        def as64(a):
            return None if a is None else np.asarray(a, np.float64)

        lmin, lmax = as64(snap.lmin), as64(snap.lmax)
        lsum, lweight, lrecip = (as64(snap.lsum), as64(snap.lweight),
                                 as64(snap.lrecip))
        dmin, dmax = as64(snap.dmin), as64(snap.dmax)
        dsum, dcount, drecip = (as64(snap.dsum), as64(snap.dcount),
                                as64(snap.drecip))

        def _and(a, b):
            return b if a is None else (a & b)

        def pick(global_col, local_col):
            if not use_global.any():
                return local_col
            return np.where(use_global, global_col, local_col)

        fams: list[MetricFamily] = []
        with np.errstate(divide="ignore", invalid="ignore"):
            if aggregates.value & Aggregate.MAX:
                fams.append(MetricFamily(
                    ".max", GAUGE, pick(dmax, lmax),
                    _and(base, ~np.isinf(lmax) | use_global)))
            if aggregates.value & Aggregate.MIN:
                fams.append(MetricFamily(
                    ".min", GAUGE, pick(dmin, lmin),
                    _and(base, ~np.isinf(lmin) | use_global)))
            if aggregates.value & Aggregate.SUM:
                fams.append(MetricFamily(
                    ".sum", GAUGE, pick(dsum, lsum),
                    _and(base, (lsum != 0) | use_global)))
            if aggregates.value & Aggregate.AVERAGE:
                fams.append(MetricFamily(
                    ".avg", GAUGE,
                    pick(dsum / dcount if not is_local else 0.0,
                         lsum / np.maximum(lweight, 1e-300)),
                    _and(base,
                         ((lsum != 0) & (lweight != 0)) | use_global)))
            if aggregates.value & Aggregate.COUNT:
                fams.append(MetricFamily(
                    ".count", MetricType.COUNTER,
                    pick(dcount, lweight),
                    _and(base, (lweight != 0) | use_global)))
            if aggregates.value & Aggregate.MEDIAN:
                q_index = {float(q): i for i, q in
                           enumerate(np.asarray(snap.quantile_qs))}
                fams.append(MetricFamily(
                    ".median", GAUGE,
                    np.asarray(snap.quantile_values[:, q_index[0.5]],
                               np.float64),
                    base))
            if aggregates.value & Aggregate.HARMONIC_MEAN:
                fams.append(MetricFamily(
                    ".hmean", GAUGE,
                    pick(dcount / drecip if not is_local else 0.0,
                         lweight / np.where(lrecip != 0, lrecip, 1.0)),
                    _and(base,
                         ((lrecip != 0) & (lweight != 0)) | use_global)))
            if percentiles:
                # mixed rows emit percentiles only on the global instance
                # (flusher.go:61-74); local-only rows always do
                pmask = (sc == int(ScopeClass.LOCAL)) if is_local else None
                if hadm is not None:
                    pmask = hadm if pmask is None else (pmask & hadm)
                q_index = {float(q): i for i, q in
                           enumerate(np.asarray(snap.quantile_qs))}
                for p in percentiles:
                    fams.append(MetricFamily(
                        _percentile_name("", p), GAUGE,
                        np.asarray(
                            snap.quantile_values[:, q_index[float(p)]],
                            np.float64),
                        pmask))
        pool = snap.directory.histo

        def histo_meta(i, _rows=hrows):
            m = _rows[i]
            return m.key.name, m.tags, m.sinks

        batch.groups.append(ColumnGroup(
            nrows=len(hrows), meta_at=histo_meta, families=fams,
            has_routing=pool.routed_rows > 0,
            frag_at=lambda i, _rows=hrows: _rows[i].wire_frag(),
            meta_blob=pool.frag_blob()))

    # -- set rows ----------------------------------------------------------
    srows = snap.directory.sets.rows
    if srows:
        ssc = np.frombuffer(snap.directory.sets.scope_codes,
                            dtype=np.int8)[: len(srows)]
        smask = (~(ssc == int(ScopeClass.MIXED))) if is_local else None
        if snap.directory.sets.rejected_rows > 0:
            sadm = np.frombuffer(snap.directory.sets.admit_codes,
                                 dtype=np.int8)[: len(srows)] != 0
            smask = sadm if smask is None else (smask & sadm)

        def set_meta(i, _rows=srows):
            m = _rows[i]
            return m.key.name, m.tags, m.sinks

        batch.groups.append(ColumnGroup(
            nrows=len(srows), meta_at=set_meta,
            families=[MetricFamily(
                "", GAUGE, np.asarray(snap.set_estimates, np.float64),
                smask)],
            has_routing=snap.directory.sets.routed_rows > 0,
            frag_at=lambda i, _rows=srows: _rows[i].wire_frag(),
            meta_blob=snap.directory.sets.frag_blob()))

    # -- counters / gauges -------------------------------------------------
    for pool, mtype in ((snap.scalars.counters, MetricType.COUNTER),
                        (snap.scalars.gauges, GAUGE)):
        n = pool.used
        if not n:
            continue
        csc = np.frombuffer(pool.scope_codes, dtype=np.int8)[:n]
        cmask = (~(csc == int(ScopeClass.GLOBAL))) if is_local else None
        if pool.rejected_rows > 0:
            cadm = np.frombuffer(pool.admit_codes, dtype=np.int8)[:n] != 0
            cmask = cadm if cmask is None else (cmask & cadm)

        def scalar_meta(i, _meta=pool.meta):
            key, tags, _cls, sinks = _meta[i]
            return key.name, tags, sinks

        def scalar_frag(i, _meta=pool.meta):
            key, tags, _cls, _sinks = _meta[i]
            rec = (key.name + "\x1f" + "\x1f".join(tags)
                   if tags else key.name)
            if "\x1e" in rec or "\x1f" in key.name or any(
                    "\x1f" in t or "\x1e" in t for t in tags):
                return None
            return rec.encode("utf-8")

        batch.groups.append(ColumnGroup(
            nrows=n, meta_at=scalar_meta,
            families=[MetricFamily(
                "", mtype, np.asarray(pool.values[:n], np.float64),
                cmask)],
            has_routing=pool.routed_rows > 0,
            frag_at=scalar_frag,
            meta_blob=pool.frag_blob()))

    # -- status checks (rare; objects) -------------------------------------
    for (key, tags, _cls, sinks), sv in zip(
        snap.scalars.status_meta, snap.scalars.status_values
    ):
        value, message, hostname = sv
        batch.extras.append(
            InterMetric(
                name=key.name, timestamp=ts, value=float(value),
                tags=list(tags), type=MetricType.STATUS, message=message,
                hostname=hostname, sinks=sinks,
            )
        )
    return batch


# ---------------------------------------------------------------------------
# Forwarding selection


def forwardable_rows(snap: FlushSnapshot):
    """Yield the forwardable content of a snapshot, typed, mirroring
    reference ForwardableMetrics (worker.go:181-209): global counters and
    gauges, mixed+global histograms/timers, mixed sets. Local-only series
    never leave the instance.

    Yields tuples:
      ("counter", key, tags, value)
      ("gauge", key, tags, value)
      ("histogram"|"timer", key, tags, scope_class, means, weights,
       dmin, dmax, drecip)
      ("set", key, tags, registers)
    """
    # tenant-budget-rejected rows never forward either: letting them ride
    # upstream would re-spend the tenant's budget on the global tier
    cpool = snap.scalars.counters
    crej = cpool.rejected_rows > 0
    for row, ((key, tags, cls, _sinks), value) in enumerate(zip(
        snap.scalars.counter_meta, snap.scalars.counter_values
    )):
        if crej and not cpool.admit_codes[row]:
            continue
        if cls == ScopeClass.GLOBAL:
            yield ("counter", key, tags, value)
    gpool = snap.scalars.gauges
    grej = gpool.rejected_rows > 0
    for row, ((key, tags, cls, _sinks), value) in enumerate(zip(
        snap.scalars.gauge_meta, snap.scalars.gauge_values
    )):
        if grej and not gpool.admit_codes[row]:
            continue
        if cls == ScopeClass.GLOBAL:
            yield ("gauge", key, tags, value)
    hrej = snap.directory.histo.rejected_rows > 0
    for row, meta in enumerate(snap.directory.histo.rows):
        if hrej and not meta.admitted:
            continue
        if meta.scope_class == ScopeClass.LOCAL:
            continue
        if snap.digest_means is None:
            # mesh-mode snapshots don't materialize per-row centroid
            # arrays host-side; a mesh global is a terminal aggregator
            # (chained-global forwarding needs the single-device path)
            break
        yield (
            meta.key.type, meta.key, meta.tags, meta.scope_class,
            snap.digest_means[row], snap.digest_weights[row],
            float(snap.dmin[row]), float(snap.dmax[row]),
            float(snap.drecip[row]),
        )
    if snap.set_registers is not None:
        # terminal (global) snapshots skip register materialization
        srej = snap.directory.sets.rejected_rows > 0
        for row, meta in enumerate(snap.directory.sets.rows):
            if srej and not meta.admitted:
                continue
            if meta.scope_class == ScopeClass.MIXED:
                yield ("set", meta.key, meta.tags, snap.set_registers[row])
