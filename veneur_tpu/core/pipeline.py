"""Stage-parallel flush executor: overlap extract, generate, emit.

SUSTAINED_PIPELINE.json's rig is cadence-bound, not packet-bound: the
C++ ingest path holds 500k lines/s at <0.03% loss, but the serial flush
runs device extraction, InterMetric generation, and sink emission
back-to-back inside the tick, all timeslicing against ingest. This
module keeps the cheap snapshot swap on the flush tick and hands the
swapped epoch to three dedicated single-worker stages, so device
fold/extract for interval N, generation for N-1, and sink emission for
N-2 proceed concurrently — the same "overlap host work with accelerator
dispatch" discipline the JAX scaling literature prescribes for step
loops, applied to the flush loop. The reference hides sink latency the
same way with per-sink goroutines (flusher.go:92-115); this extends the
overlap across whole flush phases.

Invariants:

- Bit-identical output. Each stage runs the SAME server methods the
  serial flush runs (_flush_extract/_flush_generate/_flush_emit), over
  a FlushJob that froze its timestamp at tick time, so the pipelined
  InterMetric stream for an interval is byte-for-byte the serial one
  (tests/test_pipeline.py pins this across all metric classes).
- Single-worker stages. One thread per stage, bounded queues between
  them: intervals cannot reorder, and a stage's work for interval N
  always finishes before its work for N+1 starts.
- Bounded backpressure (health/policy.py MAX_STAGE_BACKLOG). A stage
  more than `max_backlog` intervals behind sheds instead of queueing:
  an over-full extract queue defers the TICK (nothing is swapped — the
  epoch keeps aggregating and the next tick flushes two intervals'
  worth, so counters are late, not lost), an over-full downstream
  queue drops that interval's flush output (per-flush data is
  expendable by design, README.md:135-137). Both paths count loudly;
  a shed interval or a RUN of deferred ticks (two consecutive — one is
  a transient the overlap absorbs) also kicks the standing shedding
  loop (_adapt_spill_caps) so the overload is attacked at the parse
  boundary.

The governor sees one in-flight flush per admitted interval
(begin_stage_flush / end_flush refcount), so the watchdog's deferral
rule keeps working under overlap, and the extract stage owns the
per-flush chunk report (begin_report).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any

from veneur_tpu.health.policy import MAX_STAGE_BACKLOG, pipeline_should_shed

log = logging.getLogger(__name__)

STAGES = ("extract", "generate", "emit")


@dataclass
class FlushJob:
    """One interval's flush state, passed stage to stage.

    `ts` is frozen at tick time so generation stamps InterMetrics with
    the interval's own wall clock regardless of how long earlier stages
    queued — the serial path stamps the identical value (bit-identity).
    """

    seq: int = 0
    ts: int = 0
    flush_start: float = 0.0
    qs: Any = None
    swapped: list = field(default_factory=list)
    span_counts: dict = field(default_factory=dict)
    phases: dict = field(default_factory=dict)
    snaps: list = field(default_factory=list)
    batch: Any = None
    final: list = field(default_factory=list)
    n_flushed: int = 0
    span: Any = None
    stage_s: dict = field(default_factory=dict)
    failed: bool = False


class FlushPipeline:
    """Owns the stage threads and queues; the server owns the phases."""

    def __init__(self, server, max_backlog: int = MAX_STAGE_BACKLOG) -> None:
        self._server = server
        self.max_backlog = max(1, int(max_backlog))
        self._queues = [queue.Queue(maxsize=self.max_backlog)
                        for _ in STAGES]
        self._threads: list[threading.Thread] = []
        self._stop_event = threading.Event()
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._inflight = 0
        self._seq = 0
        self.completed = 0
        self.completed_seq = 0
        self.deferred_ticks = 0
        # ticker-thread only: consecutive deferrals since the last
        # admitted tick. One deferral is a transient (an XLA recompile
        # billed to one extract) and costs nothing — the epoch keeps
        # aggregating; only a RUN of them means the extract stage is
        # persistently behind and the parse boundary should shed.
        self._consec_deferred = 0
        self.shed = {name: 0 for name in STAGES}
        # slowest stage of the most recently completed interval: the
        # pipeline's throughput bound, fed to _adapt_spill_caps in
        # place of the serial flush duration
        self.last_cycle_s = 0.0
        # times the delivery layer reported a sink persistently behind
        # (server delivery reporting via note_downstream_behind)
        self.downstream_behind = 0

    def start(self) -> None:
        if self._threads:
            return
        for idx, name in enumerate(STAGES):
            # server._spawn: crash capture + (for the device-touching
            # extract stage) the bounded compute-thread join at shutdown
            t = self._server._spawn(
                lambda i=idx: self._stage_loop(i),
                f"flush-{name}", compute=(name == "extract"))
            self._threads.append(t)

    # -- tick (called by the flush ticker only: single producer) ----------

    def tick(self, now: float | None = None) -> str:
        """Admit one interval: swap under the ingest locks, enqueue the
        swapped epoch for the stage threads. Returns "ok", or
        "deferred" when the extract stage is a full interval behind
        (backpressure: nothing is swapped, the epoch keeps aggregating
        and the next successful tick flushes it — late, not lost)."""
        srv = self._server
        if self._stop_event.is_set():
            return "stopped"
        if pipeline_should_shed(self._queues[0].qsize(), self.max_backlog):
            self.deferred_ticks += 1
            self._consec_deferred += 1
            srv.stats.count("flush.pipeline_deferred_total", 1)
            if self._consec_deferred >= 2:
                # persistently behind — attack the overload at the
                # parse boundary too (a single deferral sheds nothing:
                # measured on the 1-core rig, halving the spill caps on
                # every deferral threw away ~3% of an interval's lines
                # for stalls the pipeline absorbed by itself)
                srv._pipeline_overrun()
            log.warning("flush pipeline: extract stage %d interval(s) "
                        "behind; deferring tick (epoch keeps aggregating)",
                        self._queues[0].qsize())
            return "deferred"
        self._consec_deferred = 0
        gov = srv.flush_governor
        # refcounted in-flight mark, NOT begin_flush: the tick must not
        # clobber the chunk report an overlapped extract is still filling
        gov.begin_stage_flush()
        span = srv.tracer.start_span("flush")
        try:
            job = srv._flush_begin(now=now)
        except Exception:
            try:
                span.finish()
            finally:
                gov.end_flush()
            raise
        job.span = span
        with self._lock:
            self._seq += 1
            job.seq = self._seq
            self._inflight += 1
        # cannot be Full: this is the sole producer and the queue was
        # below the backlog bound above (consumers only drain it)
        self._queues[0].put_nowait(job)
        return "ok"

    # -- stage threads -----------------------------------------------------

    def _stage_loop(self, idx: int) -> None:
        q = self._queues[idx]
        while True:
            try:
                job = q.get(timeout=0.2)
            except queue.Empty:
                if self._stop_event.is_set():
                    return
                continue
            self._run(idx, job)

    def _run(self, idx: int, job: FlushJob) -> None:
        srv = self._server
        name = STAGES[idx]
        t0 = time.perf_counter()
        try:
            if idx == 0:
                # the extract stage owns the per-flush chunk report
                # (serial flushes reset it in begin_flush instead)
                srv.flush_governor.begin_report()
                srv._flush_extract(job)
            elif idx == 1:
                srv._flush_generate(job)
            else:
                srv._flush_emit(job)
        except Exception:
            # per-flush data is expendable; the stage thread is not.
            # crash.guard would abort the process on an escape, which is
            # right for a wedged loop but wrong for one bad interval.
            job.failed = True
            log.exception("flush pipeline: %s stage failed (interval %d)",
                          name, job.seq)
        job.stage_s[name] = time.perf_counter() - t0
        if job.failed or idx == len(STAGES) - 1:
            self._finish(job)
            return
        try:
            self._queues[idx + 1].put_nowait(job)
        except queue.Full:
            nxt = STAGES[idx + 1]
            self.shed[nxt] += 1
            srv.stats.count("flush.pipeline_shed_total", 1,
                            tags=[f"stage:{nxt}"])
            srv._pipeline_overrun()
            log.warning("flush pipeline: %s stage backlog full; shedding "
                        "interval %d's flush output", nxt, job.seq)
            self._finish(job)

    def _finish(self, job: FlushJob) -> None:
        try:
            if job.span is not None:
                job.span.finish()
        except Exception:
            log.debug("flush span finish failed", exc_info=True)
        finally:
            self._server.flush_governor.end_flush()
        with self._lock:
            self._inflight -= 1
            self.completed += 1
            if job.seq > self.completed_seq:
                self.completed_seq = job.seq
            if job.stage_s:
                self.last_cycle_s = max(job.stage_s.values())
            self._idle.notify_all()

    def note_downstream_behind(self) -> None:
        """Delivery layer signal (server._flush_emit): a sink has been
        behind — open breaker or spill deferrals — for
        DELIVERY_BEHIND_INTERVALS consecutive flushes. Treated like a
        persistent stage backlog: kick the standing shedding loop so
        the overload is attacked at the parse boundary instead of
        accumulating in sink spills."""
        with self._lock:
            self.downstream_behind += 1
        self._server._pipeline_overrun()

    # -- lifecycle ---------------------------------------------------------

    def drain(self, timeout: float | None = None) -> bool:
        """Block until every admitted interval has finished (emitted,
        shed, or failed). True on drained, False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while self._inflight > 0:
                if deadline is None:
                    self._idle.wait(timeout=0.5)
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._idle.wait(timeout=remaining)
            return True

    def stop(self, drain: bool = True, timeout: float = 30.0) -> bool:
        """Drain in-flight intervals (the shutdown contract: the final
        tick's data reaches the sinks), then stop the stage threads."""
        drained = self.drain(timeout) if drain else True
        self._stop_event.set()
        for t in self._threads:
            t.join(timeout=2.0)
        return drained

    def stats(self) -> dict:
        with self._lock:
            return {
                "inflight": self._inflight,
                "completed": self.completed,
                "deferred_ticks": self.deferred_ticks,
                "shed": dict(self.shed),
                "last_cycle_s": self.last_cycle_s,
                "max_backlog": self.max_backlog,
                "downstream_behind": self.downstream_behind,
            }
