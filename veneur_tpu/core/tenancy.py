"""Per-tenant QoS: series budgets, honest tallies, and heavy-hitter folds.

The reference has no tenant concept — its only defense against one client
exploding key cardinality is coarse worker shedding (PAPER.md L2/L7). At
production scale that is the failure mode that kills an aggregator
(ROADMAP open item 4), so this module adds the missing layer:

* ``TenantLedger`` — per-tenant *series budgets* enforced at directory
  adopt time. The semantics are deliberately reject-new-series, never
  evict-live: once a tenant crosses its budget, samples for series the
  tenant has not yet registered are refused, while every already-admitted
  series keeps aggregating — innocent dashboards never flap, and an
  abusive tenant's damage is capped at exactly its budget. Budget 0 means
  unlimited (the single-tenant default: the QoS layer costs nothing until
  configured).

* ``TenantTallies`` — the per-epoch sample accounting (accepted / kept /
  rejected / dropped per tenant) that the worker accumulates into
  lifetime totals pre-swap, exactly like ``Worker.processed_total``, so a
  tenant's drops in a swapped-out epoch survive a late pipelined extract.
  Conservation is exact per tenant: accepted == kept + rejected + dropped
  (the isolation soak's core assertion).

* ``TenantSketch`` — the detection half: a per-tenant count-min pool
  (ops/heavyhitter.py) folded on-device over the flushed columnar batch,
  plus a host-side space-saving top-k per tenant, so telemetry can name
  *which* keys a hot tenant is exploding without holding exact per-key
  state.

One ledger is shared by every worker on a host (admission must be a
global decision — a tenant's series spread across workers by digest), so
``admit`` takes a lock; it only runs on new-series adopts, never on the
per-sample hot path.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Iterable, Optional

import numpy as np

from veneur_tpu.core.metrics import DEFAULT_TENANT

# Bounded dedup memory for the distinct-rejected-series counter: past this
# many tracked keys (across all tenants) the dedup sets are cleared, same
# discipline as the worker's adopt cache — after a clear a re-rejected
# series recounts, so `series_rejected` may overcount under extreme churn
# (documented; the alternative is unbounded memory, i.e. the attack).
REJECTED_SEEN_CAP = 1 << 16


class TenantLedger:
    """Per-tenant admitted-series sets + budget decisions (host-global)."""

    def __init__(self, default_budget: int = 0,
                 budgets: Optional[dict[str, int]] = None,
                 tag_key: str = "tenant") -> None:
        self.tag_key = tag_key
        self.default_budget = int(default_budget)
        self.budgets: dict[str, int] = {
            str(k): int(v) for k, v in (budgets or {}).items()}
        self._lock = threading.Lock()
        self._admitted: dict[str, set[str]] = {}
        self._rejected_seen: dict[str, set[str]] = {}
        self._rejected_seen_entries = 0
        self.series_rejected: dict[str, int] = {}  # lifetime, per tenant

    def budget_for(self, tenant: str) -> int:
        return self.budgets.get(tenant, self.default_budget)

    def set_budgets(self, default_budget: int,
                    budgets: Optional[dict[str, int]] = None) -> None:
        """Hot-swap the budget table (config reload). Admitted series are
        untouched — a lowered budget rejects *new* series only, keeping
        the reject-new-never-evict contract; a raised budget takes effect
        on the next adopt."""
        with self._lock:
            self.default_budget = int(default_budget)
            self.budgets = {
                str(k): int(v) for k, v in (budgets or {}).items()}

    def admit(self, tenant: str, series_key: str) -> bool:
        """True iff ``series_key`` may (continue to) aggregate for
        ``tenant``. Idempotent: an admitted series stays admitted for the
        ledger's lifetime (the directory swaps wholesale every interval and
        the adopt cache can be cleared — re-admission must be free and
        must not re-consume budget)."""
        with self._lock:
            adm = self._admitted.get(tenant)
            if adm is None:
                adm = self._admitted[tenant] = set()
            if series_key in adm:
                return True
            budget = self.budgets.get(tenant, self.default_budget)
            if budget <= 0 or len(adm) < budget:
                adm.add(series_key)
                return True
            seen = self._rejected_seen.setdefault(tenant, set())
            if series_key not in seen:
                if self._rejected_seen_entries >= REJECTED_SEEN_CAP:
                    for s in self._rejected_seen.values():
                        s.clear()
                    self._rejected_seen_entries = 0
                seen.add(series_key)
                self._rejected_seen_entries += 1
                self.series_rejected[tenant] = (
                    self.series_rejected.get(tenant, 0) + 1)
            return False

    def live(self, tenant: str) -> int:
        with self._lock:
            adm = self._admitted.get(tenant)
            return len(adm) if adm else 0

    def live_counts(self) -> dict[str, int]:
        with self._lock:
            return {t: len(s) for t, s in self._admitted.items()}

    def series_rejected_counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self.series_rejected)

    def over_budget(self) -> frozenset[str]:
        """Tenants at/over a finite budget — the shed-first set the
        tenant-aware spill partition (health/policy.py) consumes."""
        with self._lock:
            out = []
            for t, adm in self._admitted.items():
                budget = self.budgets.get(t, self.default_budget)
                if budget > 0 and len(adm) >= budget:
                    out.append(t)
            return frozenset(out)


class TenantTallies:
    """Per-epoch per-tenant sample accounting (one instance per worker).

    Not locked: every mutation happens under the owning worker's ingest
    lock (process_metric / swap), the same discipline as ``processed``.
    """

    KINDS = ("accepted", "kept", "rejected", "dropped")

    __slots__ = ("accepted", "kept", "rejected", "dropped")

    def __init__(self) -> None:
        self.accepted: dict[str, int] = {}
        self.kept: dict[str, int] = {}
        self.rejected: dict[str, int] = {}
        self.dropped: dict[str, int] = {}

    def reset(self) -> None:
        self.accepted.clear()
        self.kept.clear()
        self.rejected.clear()
        self.dropped.clear()

    def accumulate_into(self, totals: "TenantTallies") -> None:
        """The pre-swap lifetime fold (the ``processed_total +=
        processed`` pattern, per tenant per kind)."""
        for kind in self.KINDS:
            src = getattr(self, kind)
            dst = getattr(totals, kind)
            for t, n in src.items():
                dst[t] = dst.get(t, 0) + n

    def merged_with(self, other: "TenantTallies") -> dict[str, dict[str, int]]:
        """totals + current epoch, as plain dicts — the locked-read view
        (mirrors Server.ingress_stats' processed_total + processed)."""
        out: dict[str, dict[str, int]] = {}
        for kind in self.KINDS:
            acc: dict[str, int] = dict(getattr(self, kind))
            for t, n in getattr(other, kind).items():
                acc[t] = acc.get(t, 0) + n
            out[kind] = acc
        return out

    def conservation_gaps(self) -> dict[str, int]:
        """accepted - (kept + rejected + dropped) per tenant — all zeros
        when accounting is exact (the soak's invariant)."""
        tenants = set(self.accepted) | set(self.kept) | set(
            self.rejected) | set(self.dropped)
        return {
            t: self.accepted.get(t, 0) - self.kept.get(t, 0)
            - self.rejected.get(t, 0) - self.dropped.get(t, 0)
            for t in tenants
        }


class TenantSketch:
    """Per-tenant heavy-hitter state: a count-min pool row per tenant plus
    a host-side space-saving top-k, fed once per flush from the already-
    folded per-row counts (one offer per live series per interval, never
    per sample — the device pays one scatter-add batch per flush)."""

    def __init__(self, depth: int, width: int, topk: int,
                 max_tenants: int = 64) -> None:
        # import here so the zero-tenant path never touches jax for this
        from veneur_tpu.ops import heavyhitter

        self._hh = heavyhitter
        self.depth = depth
        self.width = width
        self.max_tenants = max_tenants
        self.pool = heavyhitter.init_pool(max_tenants, depth, width)
        # row 0 is reserved for the default tenant; tenants past the cap
        # alias onto it rather than growing the pool
        self._row_of: dict[str, int] = {DEFAULT_TENANT: 0}
        self.topk: dict[str, "object"] = {}
        self._topk_cap = topk

    def row_for(self, tenant: str) -> int:
        row = self._row_of.get(tenant)
        if row is None:
            if len(self._row_of) >= self.max_tenants:
                return 0
            row = len(self._row_of)
            self._row_of[tenant] = row
        return row

    def fold(self, tenants: Iterable[str], keys: list[str],
             counts: np.ndarray, chunk: int) -> None:
        """Fold one flush interval's (tenant, series key, sample count)
        triples into the device pool and the host top-k summaries."""
        if not keys:
            return
        rows = np.fromiter((self.row_for(t) for t in tenants),
                           dtype=np.int32, count=len(keys))
        hashes = self._hh.hash_keys(keys)
        cols = self._hh.split_hashes(hashes, self.depth, self.width)
        cnts = np.asarray(counts, dtype=np.int32)
        self.pool = self._hh.insert_chunked(self.pool, rows, cols, cnts,
                                            chunk)
        for tenant, key, n in zip(tenants, keys, cnts.tolist()):
            if n <= 0:
                continue
            summ = self.topk.get(tenant)
            if summ is None:
                summ = self.topk[tenant] = self._hh.SpaceSavingTopK(
                    self._topk_cap)
            summ.offer(key, int(n))

    def totals(self) -> dict[str, int]:
        """Exact per-tenant inserted sample totals (one depth row of the
        CMS sums to the insert total)."""
        tt = np.asarray(self._hh.tenant_totals(self.pool))
        return {t: int(tt[row]) for t, row in self._row_of.items()}

    def top_keys(self, tenant: str) -> list[tuple[str, int, int]]:
        summ = self.topk.get(tenant)
        return summ.items() if summ is not None else []

    def snapshot(self) -> "SketchView":
        """Fenced read view for the live query path (veneur_tpu/query/).

        Captured at the epoch fence — inside extract_snapshot, right
        after fold(), where extractions never overlap — so the view is a
        consistent point-in-time read. The pool reference is safe to
        share without copying: every pool mutation goes through
        insert_chunked, which REPLACES self.pool with a new array, never
        writes in place, so a captured reference stays bit-identical
        forever. The top-k summaries DO mutate in place (host dicts), so
        their items are copied out here."""
        return SketchView(
            pool=self.pool,
            row_of=dict(self._row_of),
            topk={t: s.items() for t, s in self.topk.items()},
        )


@dataclass
class SketchView:
    """Immutable heavy-hitter read view from TenantSketch.snapshot():
    what a live query serves between epoch fences. All reads go through
    the fenced (non-mutating) entry points in ops/heavyhitter."""

    pool: object  # i32[T, D, W] device array (reference, never mutated)
    row_of: dict[str, int]
    topk: dict[str, list[tuple[str, int, int]]]

    def totals(self) -> dict[str, int]:
        from veneur_tpu.ops import heavyhitter

        tt = heavyhitter.read_totals(self.pool)
        return {t: int(tt[row]) for t, row in self.row_of.items()}

    def top_keys(self, tenant: str) -> list[tuple[str, int, int]]:
        return list(self.topk.get(tenant, ()))

    def estimate(self, tenant: str, keys: list[str]) -> np.ndarray:
        from veneur_tpu.ops import heavyhitter

        return heavyhitter.read_query(
            self.pool, self.row_of.get(tenant, 0), keys)
