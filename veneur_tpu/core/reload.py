"""Config hot-reload: mtime-watched, whitelist-only live updates.

The reference restarts to pick up config (cmd/veneur/main.go reads it
once); SIGHUP here is already taken by the zero-downtime graceful
restart (cli/veneur_main.py). For the knobs where a restart is
disproportionate — tenant series budgets during an incident, journal
fsync/retention, the shutdown drain deadline — this module polls the
config file's mtime and applies *only* a whitelisted set of keys live.

Everything else is deliberately log-and-ignore (counted in
``ignored_keys_total``): most keys wire object graphs at build time
(listeners, sinks, worker pools) and "reloading" them would silently
do nothing or, worse, half-apply. An operator who edits a
non-reloadable key gets a WARNING naming it, not a mystery.

A config edit that no longer parses/validates is rejected wholesale
(``reload_rejected`` counter, nothing applied) — a typo'd file must
never degrade a running server.
"""

from __future__ import annotations

import logging
import os
import threading

log = logging.getLogger("veneur_tpu.reload")

# Keys that apply safely to a live server. Each one is consumed at use
# time (no object-graph rebuild): tenant budgets are read per-adopt under
# the ledger lock, journal policy per-append, the drain deadline at
# SIGTERM time.
RELOADABLE = frozenset({
    "tenant_budgets",
    "tenant_default_budget",
    "spill_journal_fsync",
    "spill_journal_max_bytes",
    "spill_journal_max_segments",
    "shutdown_drain_deadline_s",
})


class ConfigReloader:
    """Polls ``path`` for mtime changes and applies RELOADABLE diffs to
    ``server`` in place. Runs as a daemon thread (``start``/``stop``);
    ``check_once`` is the testable unit."""

    def __init__(self, path: str, server, poll_s: float = 5.0) -> None:
        self.path = path
        self.server = server
        self.poll_s = max(0.5, float(poll_s))
        self._stop = threading.Event()
        self._thread = None
        self._mtime = self._stat_mtime()
        # honest telemetry: reloads that applied, were rejected (invalid
        # file), and edits to keys we refuse to hot-apply
        self.reloads_applied = 0
        self.reload_rejected = 0
        self.ignored_keys_total = 0

    def _stat_mtime(self):
        try:
            return os.stat(self.path).st_mtime_ns
        except OSError:
            return None

    def check_once(self) -> bool:
        """Re-read the config if the file changed; returns True iff a
        reload was applied (even one applying zero whitelisted keys)."""
        mtime = self._stat_mtime()
        if mtime is None or mtime == self._mtime:
            return False
        self._mtime = mtime
        from veneur_tpu.core.config import load_config

        try:
            new = load_config(self.path)
        except Exception as e:
            self.reload_rejected += 1
            log.warning("config reload rejected (nothing applied): %s", e)
            return False
        old = self.server.config
        changed = [f for f in old.__dataclass_fields__
                   if getattr(old, f) != getattr(new, f)]
        ignored = [f for f in changed if f not in RELOADABLE]
        if ignored:
            self.ignored_keys_total += len(ignored)
            log.warning("config reload: ignoring non-reloadable key(s) "
                        "%s (restart to apply)", sorted(ignored))
        applied = [f for f in changed if f in RELOADABLE]
        for f in applied:
            setattr(old, f, getattr(new, f))
        if ("tenant_budgets" in applied
                or "tenant_default_budget" in applied):
            led = self.server.tenant_ledger
            if led is not None:
                led.set_budgets(old.tenant_default_budget,
                                old.tenant_budgets)
            else:
                # tenancy was off at build time: the ledger (and the
                # per-worker sketches) only exist when a budget was
                # configured at start — that wiring is a build-time graph
                log.warning("config reload: tenant budgets set but "
                            "tenancy was disabled at startup; restart "
                            "to enable enforcement")
        if any(f.startswith("spill_journal_") for f in applied):
            for j in getattr(self.server, "_journals", {}).values():
                j.set_policy(fsync=old.spill_journal_fsync,
                             max_bytes=old.spill_journal_max_bytes,
                             max_segments=old.spill_journal_max_segments)
        # shutdown_drain_deadline_s needs no push: graceful_drain reads
        # server.config at SIGTERM time, which we just mutated
        self.reloads_applied += 1
        if applied:
            log.info("config reload applied: %s", sorted(applied))
        return True

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.check_once()
            except Exception:
                log.exception("config reload check failed")

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="config-reload", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
