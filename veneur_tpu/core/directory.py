"""Series directory: MetricKey → dense device-pool row assignment.

The reference keys per-flush sampler state with 13 Go maps split by type and
scope (worker.go:60-103). On TPU, sketch state must live in dense, fixed-
shape device arrays, so the maps become this directory: each (key, class)
gets a row index into one of two device pools (t-digest rows for
histogram/timer series, HLL rows for set series), and the scope split
becomes a per-row class label consulted only at flush/forward time — the
device programs are scope-oblivious and operate on whole pools.

Like the reference, all aggregation state lives exactly one flush interval:
the directory (and its pools) is swapped wholesale at flush (the map-swap of
worker.go:498-517 becomes a directory+buffer swap).
"""

from __future__ import annotations

import enum
from array import array
from dataclasses import dataclass, field
from typing import Optional

from veneur_tpu.core.metrics import MetricKey, MetricScope, route_info


class ScopeClass(enum.IntEnum):
    """Which of the reference's map groups a series belongs to
    (worker.go:60-103: plain / global* / local* maps)."""

    MIXED = 0
    LOCAL = 1
    GLOBAL = 2


def classify(mtype: str, scope: MetricScope) -> ScopeClass:
    """Reference WorkerMetrics.Upsert routing (worker.go:108-177)."""
    if mtype in ("counter", "gauge"):
        return (
            ScopeClass.GLOBAL
            if scope == MetricScope.GLOBAL_ONLY
            else ScopeClass.MIXED
        )
    if mtype in ("histogram", "timer"):
        if scope == MetricScope.LOCAL_ONLY:
            return ScopeClass.LOCAL
        if scope == MetricScope.GLOBAL_ONLY:
            return ScopeClass.GLOBAL
        return ScopeClass.MIXED
    if mtype == "set":
        return (
            ScopeClass.LOCAL
            if scope == MetricScope.LOCAL_ONLY
            else ScopeClass.MIXED
        )
    if mtype == "status":
        return ScopeClass.LOCAL
    return ScopeClass.MIXED


def build_frag(name: str, tags: list[str]):
    """One blob record for the native batch encoders:
    "name \\x1f tag \\x1f tag ..." utf-8, or None when the data itself
    contains the record/field separators (those rows need the Python
    formatter)."""
    rec = name + "\x1f" + "\x1f".join(tags) if tags else name
    if "\x1e" in rec or "\x1f" in name or any(
            "\x1f" in t or "\x1e" in t for t in tags):
        return None
    return rec.encode("utf-8")


@dataclass
class RowMeta:
    """Host-side metadata for one pool row (what the dense arrays can't
    hold: names, tags, routing)."""

    key: MetricKey
    tags: list[str]
    scope_class: ScopeClass
    sinks: Optional[frozenset[str]]  # from veneursinkonly: tags
    # per-tenant QoS (core/tenancy.py): which tenant owns the series, and
    # whether the tenant ledger admitted it. The Python upsert path never
    # creates a row for a rejected series; the native path assigns rows in
    # C++ before Python sees them, so a rejected series lands here with
    # admitted=False and the flush skips it (both emit paths).
    tenant: str = ""
    admitted: bool = True
    # lazily-built wire fragment for the native encoders; False = not
    # yet built, None = contains the separators, use the Python path
    _frag: object = False

    def wire_frag(self):
        """Cached blob record for the native batch encoders. RowMeta
        objects outlive epochs (the worker's adopt cache), so this
        builds once per series lifetime."""
        frag = self._frag
        if frag is False:
            frag = build_frag(self.key.name, self.tags)
            self._frag = frag
        return frag


@dataclass
class _Pool:
    index: dict[tuple[MetricKey, ScopeClass], int] = field(default_factory=dict)
    rows: list[RowMeta] = field(default_factory=list)
    # per-row scope codes as a packed byte array (zero-copy numpy view for
    # the columnar flush — no O(rows) attribute walk at flush time), plus
    # a count of rows carrying veneursinkonly routing so the common
    # no-routing case skips per-row checks entirely
    scope_codes: array = field(default_factory=lambda: array("b"))
    routed_rows: int = 0
    # per-row admission codes (1 admitted / 0 rejected), same packed-byte
    # idiom as scope_codes so the columnar flush gets a zero-copy numpy
    # mask; rejected_rows counts them so the common all-admitted case
    # skips per-row checks entirely
    admit_codes: array = field(default_factory=lambda: array("b"))
    rejected_rows: int = 0
    # \x1e-joined wire_frag arena over rows [0, len(rows)), maintained
    # incrementally at adopt so the flush hands the native emit tier one
    # contiguous buffer with zero per-row work; poisoned (frag_clean
    # False, arena abandoned) the moment any row's frag is None
    frag_arena: bytearray = field(default_factory=bytearray)
    frag_clean: bool = True

    def frag_blob(self) -> Optional[bytearray]:
        """The native emitters' metadata buffer for this pool, or None
        when some row needs the Python path."""
        return self.frag_arena if self.frag_clean else None

    def upsert(self, key: MetricKey, scope_class: ScopeClass, tags: list[str],
               tenant: str = "") -> tuple[int, bool]:
        k = (key, scope_class)
        row = self.index.get(k)
        if row is not None:
            return row, False
        row = len(self.rows)
        self.adopt(row, key, scope_class, tags, tenant=tenant)
        return row, True

    def adopt(self, row: int, key: MetricKey, scope_class: ScopeClass,
              tags: list[str], tenant: str = "") -> None:
        """Register metadata for a row assigned externally (the native
        directory assigns rows in the same append order)."""
        self.adopt_meta(row, RowMeta(
            key=key, tags=tags, scope_class=scope_class,
            sinks=route_info(tags), tenant=tenant))

    def upsert_meta(self, meta: RowMeta) -> tuple[int, bool]:
        """Upsert with prebuilt metadata: the reader-shard reconcile path
        (core/worker._sync_native_series) folds N per-reader row spaces
        into this canonical directory, so the same series arriving via
        several readers must dedup here instead of adopting per-context
        rows verbatim."""
        k = (meta.key, meta.scope_class)
        row = self.index.get(k)
        if row is not None:
            return row, False
        row = len(self.rows)
        self.adopt_meta(row, meta)
        return row, True

    def adopt_meta(self, row: int, meta: RowMeta) -> None:
        """Adopt with prebuilt metadata (the worker's cross-epoch adopt
        cache reuses one RowMeta per series: the same series re-registers
        every interval, and rebuilding key/tags/routing per epoch was
        the global tier's import bottleneck)."""
        assert row == len(self.rows), "rows must be adopted in order"
        self.index[(meta.key, meta.scope_class)] = row
        if meta.sinks is not None:
            self.routed_rows += 1
        self.scope_codes.append(int(meta.scope_class))
        self.admit_codes.append(1 if meta.admitted else 0)
        if not meta.admitted:
            self.rejected_rows += 1
        self.rows.append(meta)
        if self.frag_clean:
            frag = meta.wire_frag()
            if frag is None:
                self.frag_clean = False
            else:
                if row:
                    self.frag_arena += b"\x1e"
                self.frag_arena += frag


class SeriesDirectory:
    """One flush interval's series → row mapping for both device pools.

    Distinct (key, scope_class) pairs get distinct rows, mirroring the
    reference where the same MetricKey can live in e.g. both `timers` and
    `globalTimers` maps simultaneously.
    """

    def __init__(self) -> None:
        self.histo = _Pool()  # histogram + timer series → t-digest rows
        self.sets = _Pool()  # set series → HLL rows

    def upsert_histo(self, key: MetricKey, scope_class: ScopeClass,
                     tags: list[str], tenant: str = "") -> tuple[int, bool]:
        return self.histo.upsert(key, scope_class, tags, tenant=tenant)

    def upsert_set(self, key: MetricKey, scope_class: ScopeClass,
                   tags: list[str], tenant: str = "") -> tuple[int, bool]:
        return self.sets.upsert(key, scope_class, tags, tenant=tenant)

    @property
    def num_histo_rows(self) -> int:
        return len(self.histo.rows)

    @property
    def num_set_rows(self) -> int:
        return len(self.sets.rows)

    def shard_counts(self, shards: int) -> tuple[list[int], list[int]]:
        """Live rows per device shard under the series-sharded row
        interleave (ops/series_shard.py: logical row r lives on shard
        r % shards): (histo_rows_per_shard, set_rows_per_shard).

        The interleave balances by construction — max−min ≤ 1 per pool —
        so this is a telemetry/bench readout (shard occupancy for
        capacity math), never a balancing input."""
        nh, ns = len(self.histo.rows), len(self.sets.rows)
        return ([(nh + shards - 1 - d) // shards for d in range(shards)],
                [(ns + shards - 1 - d) // shards for d in range(shards)])
