"""The Server: listeners → parser → device workers → flush loop → sinks.

Parity spec: reference server.go — NewFromConfig (:262), Start (:826),
HandleMetricPacket (:994), processMetricPacket (:1136), ReadMetricSocket
(:1123), TCP/TLS statsd (:1254-1335, networking.go:97), flush ticker with
clock alignment (:908-946, CalculateTickDelay :1517), FlushWatchdog
(:948-990), Shutdown (:1473). Ingest listeners are OS threads (socket reads
release the GIL); aggregation is batched onto the device by DeviceWorker.

The reference shards series across N workers by Digest%N (server.go:1028,
1039) so each series lives in exactly one sampler; we keep the same routing
(it also keeps every series in exactly one device-pool row).
"""

from __future__ import annotations

import gc
import logging
import os
import socket
import ssl
import threading
import time
from typing import Callable, Optional

from veneur_tpu import __version__
from veneur_tpu.core import crash
from veneur_tpu.core.config import Config, parse_duration
from veneur_tpu.core.flusher import device_quantiles, generate_inter_metrics
from veneur_tpu.core.metrics import HistogramAggregates, InterMetric
from veneur_tpu.core.spans import MetricExtractionSink, SpanWorker
from veneur_tpu.spans import ColumnarSpanPipeline, columnar_enabled
from veneur_tpu.core.worker import DeviceWorker, FlushSnapshot
from veneur_tpu.protocol import dogstatsd, ssf_wire
from veneur_tpu.sinks import (
    DELIVERY_STAT_COUNTERS,
    MetricSink,
    SpanSink,
    filter_routed,
    strip_excluded_tags,
)
from veneur_tpu.ssf import SSFSample
from veneur_tpu.utils.proc import current_rss_bytes as _current_rss_bytes

log = logging.getLogger("veneur_tpu.server")

# ssf.error_total tag sets, verbatim from the reference
# (server.go:1052-1072, 1238-1246); one definition so the five emit
# sites cannot drift from dashboard parity
_SSF_ERR_ZEROLENGTH = ["ssf_format:packet", "packet_type:unknown",
                       "reason:zerolength"]
_SSF_ERR_UNMARSHAL = ["ssf_format:packet", "packet_type:ssf_metric",
                      "reason:unmarshal"]
_SSF_ERR_EMPTY_ID = ["ssf_format:packet", "packet_type:ssf_metric",
                     "reason:empty_id"]
_SSF_ERR_PROCESSING = ["ssf_format:framed", "packet_type:unknown",
                       "reason:processing"]
_SSF_ERR_FRAMING = ["ssf_format:framed", "packet_type:unknown",
                    "reason:framing"]


class EventWorker:
    """Accumulates DogStatsD events (as SSF samples) until flush
    (reference EventWorker, worker.go:527-572)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._samples: list[SSFSample] = []

    def ingest(self, sample: SSFSample) -> None:
        with self._lock:
            self._samples.append(sample)

    def flush(self) -> list[SSFSample]:
        with self._lock:
            out = self._samples
            self._samples = []
        return out


def calculate_tick_delay(interval_s: float, now: float) -> float:
    """Seconds until the next interval-aligned tick
    (reference CalculateTickDelay, server.go:1517)."""
    return interval_s - (now % interval_s)


class _SpanPipelineClient:
    """Trace-client adapter: finished internal spans re-enter the owning
    server's span pipeline (sinks + ssfmetrics extraction)."""

    def __init__(self, server: "Server") -> None:
        self._server = server

    def record(self, span) -> None:
        self._server.ingest_internal_span(span)


class Server:
    """One veneur_tpu instance (local or global)."""

    def __init__(self, cfg: Config,
                 metric_sinks: Optional[list[MetricSink]] = None,
                 span_sinks: Optional[list[SpanSink]] = None,
                 inherited_fds: Optional[dict[str, list[int]]] = None
                 ) -> None:
        self.config = cfg
        self.interval = cfg.interval_seconds()
        if cfg.tpu_compilation_cache_dir:
            # restarts (watchdog, fd-handoff upgrade) reuse compiled
            # flush/fold programs instead of re-paying the 20-40s
            # first-compile per shape on TPU
            import jax as _jax

            _jax.config.update("jax_compilation_cache_dir",
                               cfg.tpu_compilation_cache_dir)
        self.hostname = cfg.hostname or (
            "" if cfg.omit_empty_hostname else socket.gethostname())
        self.tags = list(cfg.tags)
        self.percentiles = list(cfg.percentiles)
        self.aggregates = HistogramAggregates.from_names(cfg.aggregates)

        self.workers = [
            DeviceWorker(
                batch_size=cfg.tpu_batch_size,
                stage_depth=cfg.tpu_stage_depth,
                compression=cfg.tpu_compression,
                hll_precision=cfg.tpu_hll_precision,
                initial_histo_rows=cfg.tpu_initial_histo_rows,
                initial_set_rows=cfg.tpu_initial_set_rows,
                count_unique_timeseries=cfg.count_unique_timeseries,
                is_local=self.is_local,
                set_hash=cfg.set_hash,
                set_store=cfg.tpu_set_store,
                spill_cap=cfg.tpu_spill_cap,
                micro_fold=cfg.micro_fold,
                micro_fold_rows=cfg.micro_fold_rows,
                micro_fold_max_age_s=cfg.micro_fold_max_age_s,
                series_shards=cfg.series_shards,
                device_guard=cfg.device_guard,
                device_fault_streak=cfg.device_fault_streak,
                device_probe_interval_s=cfg.device_probe_interval_s,
            )
            for _ in range(cfg.num_workers)
        ]
        self._worker_locks = [threading.Lock() for _ in self.workers]
        # device fault domain bookkeeping: last guard fault seen per
        # worker (so each new classified fault reaches the governor's
        # watchdog verdict exactly once) and the lifetime guard-counter
        # totals already emitted (telemetry reports deltas)
        self._guard_last_fault: dict = {}
        self._guard_counters_reported: dict = {}
        self._host_fallbacks_reported = 0
        # adaptive overload shedding starts at the configured ceiling and
        # tightens when flushes overrun the interval (_adapt_spill_caps);
        # each flush may inherit at most half an interval of spill-fold
        # work (worker.swap sheds the excess, counted)
        self._spill_cap_now = cfg.tpu_spill_cap
        self.compute_threads_joined = True  # set by shutdown()
        # flush-deadline governor (health/): chunked degraded-mode
        # extraction + the progress signal the watchdog's deferral rule
        # reads. Shared across workers — extraction is sequential within
        # one flush, so one rate EWMA and one progress clock describe it.
        from veneur_tpu.health import FlushDeadlineGovernor

        self.flush_governor = FlushDeadlineGovernor(
            chunk_target_ms=cfg.flush_chunk_target_ms,
            interval_s=self.interval)
        for w in self.workers:
            w.fold_budget_s = 0.5 * self.interval
            w.governor = self.flush_governor
        # per-tenant QoS (core/tenancy.py): one shared series-budget
        # ledger across workers (a tenant's budget is global, not
        # per-shard) plus a per-worker heavy-hitter sketch folded over
        # the columnar batch at extract time. Disabled entirely (zero
        # overhead, bitwise-identical flushes) unless a budget is set.
        self.tenant_ledger = None
        self._tenant_reported: dict = {}
        if cfg.tenant_default_budget > 0 or cfg.tenant_budgets:
            from veneur_tpu.core.tenancy import TenantLedger, TenantSketch

            self.tenant_ledger = TenantLedger(
                default_budget=cfg.tenant_default_budget,
                budgets=cfg.tenant_budgets,
                tag_key=cfg.tenant_tag_key)
            for w in self.workers:
                w.tenancy = self.tenant_ledger
                w.tenant_sketch = TenantSketch(
                    depth=cfg.tenant_sketch_depth,
                    width=cfg.tenant_sketch_width,
                    topk=cfg.tenant_topk)
        # live query subsystem (veneur_tpu/query/): dormant unless
        # addresses are configured. Each worker's extract fence publishes
        # its epoch view into the engine (stage); _flush_extract commits
        # all workers' views as one epoch after the loop — the two-phase
        # publish that makes cross-worker reads tear-free.
        self.query_engine = None
        self._query_servers: list = []
        self._query_reported = (0, 0)  # (served, failed) at last report
        if cfg.query_listen_addrs:
            import functools

            from veneur_tpu.query import QueryEngine

            self.query_engine = QueryEngine(
                percentiles=self.percentiles,
                aggregates=self.aggregates,
                is_local=self.is_local,
                topk=cfg.tenant_topk)
            for i, w in enumerate(self.workers):
                w.query_publisher = functools.partial(
                    self.query_engine.stage, i)
        if cfg.tpu_mesh_devices > 1:
            # config-driven mesh sharding for the aggregation state (the
            # global tier's import merge rides ICI collectives; see
            # distributed/mesh.py)
            from veneur_tpu.distributed.mesh import MeshHistoPool, make_mesh

            mesh = make_mesh(cfg.tpu_mesh_devices,
                             cfg.tpu_mesh_hosts or None)
            self.mesh = mesh
            self.workers[0].attach_mesh_pool(MeshHistoPool(
                mesh, compression=cfg.tpu_compression,
                batch_size=cfg.tpu_batch_size))
            log.info("mesh aggregation enabled: %s", dict(mesh.shape))
        else:
            self.mesh = None
        self.event_worker = EventWorker()

        self.metric_sinks: list[MetricSink] = list(metric_sinks or [])
        self.span_sinks: list[SpanSink] = list(span_sinks or [])
        self.sink_excluded_tags: dict[str, set[str]] = {}

        # the span→metric bridge is always wired in, like the reference's
        # ssfmetrics sink (server.go:407-415)
        self._extraction_sink = MetricExtractionSink(
            route_metric=self._route,
            indicator_timer_name=cfg.indicator_span_timer_name,
            objective_timer_name=cfg.objective_span_timer_name,
            uniqueness_rate=cfg.ssf_span_uniqueness_rate,
        )
        common_tags = dict(
            t.split(":", 1) for t in self.tags if ":" in t)
        self.span_worker = SpanWorker(
            [self._extraction_sink] + self.span_sinks,
            common_tags=common_tags,
            capacity=cfg.span_channel_capacity,
            workers=cfg.num_span_workers,
            flush_drain_s=cfg.span_flush_drain_s,
        )
        # columnar span pipeline (veneur_tpu/spans/): on when configured
        # and every span sink takes sealed batches; one per-span-only
        # sink keeps the whole path on the SpanWorker lanes — a span must
        # flow through exactly one of the two or it derives twice
        self.span_pipeline: Optional[ColumnarSpanPipeline] = None
        if columnar_enabled(cfg.span_columnar) and all(
                hasattr(s, "ingest_batch") for s in self.span_sinks):
            self.span_pipeline = ColumnarSpanPipeline(
                route_many=self._route_many,
                batch_sinks=self.span_sinks,
                common_tags=common_tags,
                indicator_timer_name=cfg.indicator_span_timer_name,
                objective_timer_name=cfg.objective_span_timer_name,
                uniqueness_rate=cfg.ssf_span_uniqueness_rate,
                batch_rows=cfg.span_batch_rows,
                pending_cap=cfg.span_pending_cap,
            )
        # handle_ssf's columnar fast path stands down the moment the
        # span worker is customized at runtime (a sink appended to
        # span_worker.span_sinks, or ingest itself tapped/replaced —
        # established patterns for observing the span stream); the
        # baseline length is what "uncustomized" means
        self._span_worker_sink_count = len(self.span_worker.span_sinks)
        # per-service span ingest counters (reference server.go:1088-1101)
        self.ssf_spans_received: dict[str, int] = {}
        # lifetime tallies for span conservation (the per-service dict
        # swaps every flush; ingress_stats needs monotonic counts)
        self.ssf_spans_received_total = 0
        self._spans_native_total = 0
        self._ssf_stats_lock = threading.Lock()

        # installed by distributed/forward.py on local instances
        self.forwarder: Optional[Callable[[list[FlushSnapshot]], None]] = None
        # flush-time archival plugins (reference plugins/plugins.go)
        self.plugins: list = []
        # attached by core/factory.py when grpc/http addresses are set
        self.import_server = None
        self.import_http = None
        # installed by protocol/ssf_server.py for span ingest
        self.span_handler = None

        self._threads: list[threading.Thread] = []
        self._compute_threads: list[threading.Thread] = []
        self._sockets: list[socket.socket] = []
        self._socket_locks: list[int] = []
        # zero-downtime restart (einhorn-style fd handoff): listener fds
        # inherited from the previous process image, keyed by listener
        # spec; datagrams queue in the kernel socket buffers across the
        # re-exec instead of being dropped (reference server.go:1401-1429)
        self._inherited: dict[str, list[int]] = dict(inherited_fds or {})
        self._listener_fds: dict[str, list[int]] = {}
        self._adopt: list[int] = []
        self._handoff = False
        self._quiesce = threading.Event()
        self._shutdown = threading.Event()
        self._shutdown_once_lock = threading.Lock()
        self._shutdown_done = False
        # set once the WINNING shutdown() caller finishes its bounded
        # join + teardown; losing callers wait on it so they report the
        # real join outcome instead of the stale initial True
        self._shutdown_complete = threading.Event()
        self.last_flush_unix = time.time()
        # when the most recent flush finished sink emission (== the tick
        # time on the serial path; trails it under the stage pipeline)
        self.last_emit_unix = 0.0
        self.last_flush_phases: dict[str, float] = {}
        # per-flush transfer-ledger totals and chunk report (health/),
        # read by tools/bench_e2e_flush.py alongside the phase times
        self.last_flush_transfers: dict[str, int] = {}
        self.last_flush_chunks: dict = {}
        self.flush_count = 0
        # wall time the last flush tick held the ticker thread: the
        # serial flush duration, or (pipelined) just the swap+enqueue —
        # the cadence decomposition the loadgen controller reports
        self.last_tick_s = 0.0
        # stage-parallel flush executor (core/pipeline.py): extract,
        # generate and emit for successive intervals overlap on
        # dedicated stage threads while the tick stays a cheap swap.
        # None = serial flush (the reference-shaped default).
        if cfg.flush_pipeline:
            from veneur_tpu.core.pipeline import FlushPipeline

            self.flush_pipeline = FlushPipeline(
                self, max_backlog=cfg.flush_pipeline_backlog)
        else:
            self.flush_pipeline = None
        # native emit tier (native/emit.cpp): sinks serialize their wire
        # payloads GIL-free straight from the flush arrays; off = always
        # use the Python columnar formatters
        self.flush_emit_native = bool(
            getattr(cfg, "flush_emit_native", True))

        # ingest counters (self-telemetry). Incremented from every reader
        # thread: a bare `self.x += 1` loses increments at GIL switches
        # (LOAD/ADD/STORE interleave), so each thread gets its own cell
        # and the public counters are sums over the cells — single-writer
        # per cell, so no increment can be lost. Cells of dead threads
        # (per-connection stream readers exit constantly) are folded into
        # _ctr_base on read so the cell list stays bounded by the number
        # of LIVE threads.
        self._ctr_lock = threading.Lock()
        self._ctr_base = [0, 0]
        self._ctr_cells: list[tuple[threading.Thread, list[int]]] = []
        self._ctr_local = threading.local()
        self._errors_reported = 0
        self._span_sink_reported: dict[tuple[str, str], int] = {}
        # delivery.* interval-delta bookkeeping + the consecutive
        # behind-interval count gating the downstream-behind signal
        # (health/policy.py delivery_should_signal_behind)
        self._delivery_reported: dict[tuple[str, str], int] = {}
        self._delivery_behind_consec = 0
        # plugins.* interval-delta bookkeeping (plugin flush failures
        # ride the self-telemetry stream, not just the logs)
        self._plugin_reported: dict[tuple[str, str], int] = {}
        # forward.* interval-delta bookkeeping: per-proxy sender-side
        # forwarder counters, keyed (proxy_addr, stat)
        self._forward_reported: dict[tuple[str, str], int] = {}
        # write-ahead spill journals (utils/journal.py), one per
        # journalable delivery manager, attached in start() when
        # spill_journal_dir is set; shutdown_stats is filled by
        # graceful_drain (the SIGTERM path)
        self._journals: dict = {}
        self.shutdown_stats: dict = {}

        # scoped self-telemetry statsd client (reference server.go:298-308
        # builds a datadog-go client with namespace "veneur." wrapped by
        # scopedstatsd per veneur_metrics_scopes)
        from veneur_tpu import scopedstatsd
        if cfg.stats_address:
            sender: scopedstatsd.Sender = scopedstatsd.UDPSender(
                cfg.stats_address)
        else:
            sender = scopedstatsd.NullSender()
        self.stats = scopedstatsd.ScopedClient(
            sender,
            # self-telemetry carries the common tags plus the dedicated
            # veneur_metrics_additional_tags (reference server.go:300-307)
            add_tags=self.tags + list(cfg.veneur_metrics_additional_tags),
            scopes=cfg.veneur_metrics_scopes,
            namespace="veneur.",
        )
        if cfg.block_profile_rate or cfg.mutex_profile_fraction:
            # accepted for config compatibility (server.go:334-347); these
            # tune the Go runtime's profilers, which have no analog here —
            # enable_profiling drives the XLA profiler instead
            log.info("block_profile_rate/mutex_profile_fraction have no "
                     "effect in veneur-tpu (Go runtime knobs); see "
                     "enable_profiling for the XLA profiler")

        # native C++ ingest path: each worker gets its own parser context;
        # readers parse lock-free and commit to shard digest % N under
        # per-shard C++ mutexes (contention-free like the reference's
        # Digest%N channel routing, server.go:1028-1039)
        self.native_mode = False
        self._native_router = None
        self._native_ingest_tick = 0
        # C++ reader-thread handles (vn_reader_start) + their retained
        # packet counts after stop (the handle dies with the thread)
        self._native_readers: list = []
        self._native_ssf_readers: list = []
        self._native_stream_readers: list = []
        self._native_reader_packets_stopped = 0
        self._native_reader_lock = threading.Lock()
        if cfg.tpu_native_ingest:
            self.native_mode = all(w.attach_native() for w in self.workers)
            if self.native_mode:
                from veneur_tpu.native import NativeRouter

                self._native_router = NativeRouter(
                    [w._native for w in self.workers])
                log.info("native C++ ingest pipeline enabled"
                         " (%d shards)", len(self.workers))
        # shared-nothing reader shards: each C++ reader thread commits
        # into a PRIVATE context (no shared mutex on the line path); the
        # flush folds the per-reader planes on-device as one stacked
        # batch (core/worker.attach_reader_shards, ops/reader_stack.py).
        # resolve_reader_shards gates on single-worker native-reader
        # mode and honors the VENEUR_READER_SHARDS=0 legacy hatch.
        self._reader_shards = 0
        self._lock_stats_enabled = False
        self.last_reader_stats = None
        if self.native_mode:
            from veneur_tpu.core.config import resolve_reader_shards

            n_rs = resolve_reader_shards(cfg)
            if n_rs and self.workers[0].attach_reader_shards(n_rs):
                self._reader_shards = n_rs
                log.info("reader-sharded ingest enabled"
                         " (%d shared-nothing reader shards)", n_rs)

        # native SSF span fast path: only when the extraction sink is the
        # sole span consumer (other span sinks need the Python span
        # object), and single-shard only — the C++ extractor commits into
        # one context, so with several workers the Python path (which
        # routes each derived metric by digest) keeps series on their
        # home shard
        self._native_ssf = (self.native_mode and not self.span_sinks
                            and len(self.workers) == 1)

        # OpenTracing tracer for cross-hop propagation: spans it finishes
        # rejoin this server's own span pipeline (the reference's internal
        # spans flow through SpanChan the same way, server.go:310-317)
        from veneur_tpu.trace.opentracing import Tracer as _OTTracer

        self.tracer = _OTTracer(client=_SpanPipelineClient(self),
                                service="veneur-tpu")
        self._native_ssf_indicator = (
            cfg.indicator_span_timer_name.encode())
        self._native_ssf_objective = (
            cfg.objective_span_timer_name.encode())
        if self._native_ssf:
            log.info("native SSF span extraction enabled")

    @property
    def is_local(self) -> bool:
        return self.config.is_local()

    # -- packet handling ----------------------------------------------------

    def handle_metric_packet(self, packet: bytes) -> None:
        """Dispatch one line: event / service check / metric
        (reference HandleMetricPacket, server.go:994-1046)."""
        if not packet:
            return
        try:
            if packet.startswith(b"_e{"):
                sample = dogstatsd.parse_event(packet)
                self.event_worker.ingest(sample)
            elif packet.startswith(b"_sc"):
                metric = dogstatsd.parse_service_check(packet)
                self._route(metric)
            else:
                metric = dogstatsd.parse_metric(packet)
                self._route(metric)
        except dogstatsd.ParseError as e:
            self._bump_errors()
            log.debug("bad metric packet %r: %s", packet[:128], e)

    def _ctr_cell(self) -> list:
        """This thread's [packets, errors] counter cell."""
        c = getattr(self._ctr_local, "cell", None)
        if c is None:
            c = self._ctr_local.cell = [0, 0]
            with self._ctr_lock:
                self._ctr_cells.append((threading.current_thread(), c))
        return c

    def _ctr_sum(self, i: int) -> int:
        """Sum counter column i, reclaiming dead threads' cells. A dead
        thread can never increment again, so folding its cell into the
        base is exact; a live thread racing an increment is at worst off
        by the in-flight bump, same as any snapshot read."""
        with self._ctr_lock:
            if any(not t.is_alive() for t, _ in self._ctr_cells):
                live = []
                for t, c in self._ctr_cells:
                    if t.is_alive():
                        live.append((t, c))
                    else:
                        self._ctr_base[0] += c[0]
                        self._ctr_base[1] += c[1]
                self._ctr_cells = live
            return self._ctr_base[i] + sum(c[i] for _, c in self._ctr_cells)

    @property
    def packets_received(self) -> int:
        n = self._ctr_sum(0) + self._native_reader_packets_stopped
        router = self._native_router
        if router is not None:
            with self._native_reader_lock:
                for h in self._native_readers:
                    n += router.reader_packets(h)
        return n

    def set_lock_stats(self, enabled: bool) -> None:
        """Toggle commit-mutex contention recording on every native
        context (global C++ flag; ~10-20% per-line overhead while on)
        and reset the tallies, so a measurement window starts clean.
        Stats surface in ingress_stats()["reader_shards"]["lock"] and
        the per-flush reader telemetry."""
        self._lock_stats_enabled = bool(enabled)
        for w in self.workers:
            native = getattr(w, "_native", None)
            if native is None:
                continue
            fn = getattr(native._lib, "vn_set_lock_stats", None)
            if fn is not None:
                fn(1 if enabled else 0)
            for ctx in [native] + list(getattr(w, "_reader_ctxs", ())):
                ctx.reset_lock_stats()

    def ingress_stats(self) -> dict:
        """Cumulative ingress counters for the loadgen controller
        (veneur_tpu/loadgen): lifetime tallies that survive epoch swaps,
        so sent-vs-accepted loss over a load run is a subtraction of two
        snapshots. Every field is monotonic for the life of the process.

        samples_processed sums each worker's swap-accumulated
        processed_total plus its live in-epoch count; overload_dropped
        likewise folds in the not-yet-drained native delta."""
        processed = 0
        dropped = 0
        for i, w in enumerate(self.workers):
            # per-worker lock: a swap moves `processed` into
            # processed_total; reading the pair unlocked could miss a
            # whole epoch mid-swap
            with self._worker_locks[i]:
                processed += getattr(w, "processed_total", 0) + w.processed
                dropped += getattr(w, "overload_dropped_total", 0)
                native = getattr(w, "_native", None)
                if native is not None:
                    dropped += (int(native.overload_dropped)
                                - getattr(w, "_native_drop_seen", 0))
                    for j, ctx in enumerate(
                            getattr(w, "_reader_ctxs", ())):
                        dropped += (int(ctx.overload_dropped)
                                    - w._reader_drop_seen[j])
        out = {
            "packets_received": self.packets_received,
            "parse_errors": self.parse_errors,
            "samples_processed": processed,
            "overload_dropped": dropped,
            "flush_count": self.flush_count,
            "last_flush_unix": self.last_flush_unix,
            "last_emit_unix": self.last_emit_unix,
            "last_flush_phases": dict(self.last_flush_phases),
            # how long the last flush tick held the ticker thread: the
            # ingest-stall component of the cadence decomposition (the
            # loadgen controller reports it per interval)
            "last_tick_s": self.last_tick_s,
            # always-hot flush: lifetime micro-fold drains plus the last
            # closed interval's count (the controller's per-interval
            # micro_folds is a delta of the lifetime tally)
            "micro_folds_total": sum(
                getattr(w, "micro_folds_total", 0) for w in self.workers),
            "last_micro_folds": getattr(self, "last_micro_folds", 0),
        }
        w0 = self.workers[0]
        if getattr(w0, "_reader_ctxs", None):
            # shared-nothing ingest: per-context lifetime attribution
            # (index 0 = home context, 1.. = reader shards) plus the
            # commit-mutex contention record when recording is on —
            # contended_fraction ~ 0 is the shared-nothing proof
            out["reader_shards"] = w0.reader_stats(
                lock_stats=self._lock_stats_enabled)
        out["spans"] = self._span_stats()
        if self.flush_pipeline is not None:
            out["pipeline"] = self.flush_pipeline.stats()
        delivery = {rname: man.stats()
                    for rname, man in self._delivery_managers()}
        if delivery:
            out["delivery"] = delivery
        if self._journals:
            out["journal"] = {rname: j.stats()
                              for rname, j in self._journals.items()}
        if self.shutdown_stats:
            out["shutdown"] = dict(self.shutdown_stats)
        return out

    def _span_stats(self) -> dict:
        """Span conservation for the loadgen controller. On the columnar
        path the books balance exactly:
        received == derived + dropped + pending (received counts every
        handle_ssf plus native-extracted spans; derived counts spans
        whose metrics reached the workers — on device for the native
        rows). The legacy SpanWorker path reports the same fields from
        its channel/lane tallies; its pending is a point-in-time queue
        depth, so the balance there is an eventual one, not an exact
        invariant."""
        with self._ssf_stats_lock:
            received = self.ssf_spans_received_total
        native = self._spans_native_total
        received += native
        if self.span_pipeline is not None:
            ps = self.span_pipeline.stats()
            # legacy-worker tallies are zero in pure columnar operation,
            # but a runtime customization (see handle_ssf) reroutes the
            # stream through the lanes — fold those books in so the
            # conservation invariant survives the mixed case too
            ext = self._extraction_sink
            sw = self.span_worker
            with ext._stats_lock:
                lderived = ext.spans_seen
                lrows = ext.derived_rows
                linvalid = ext.invalid_samples
            with sw._stats_lock:
                ldropped = (sw.spans_dropped
                            + sw.lane_drops.get(ext.name(), 0)
                            + sw.ingest_timeouts.get(ext.name(), 0))
            return {
                "received": received,
                "derived": ps["spans_derived"] + native + lderived,
                "derived_rows": ps["derived_rows"] + lrows,
                "dropped": ps["spans_dropped"] + ldropped,
                "pending": ps["pending"] + sw.pending(),
                "invalid_samples": ps["invalid_samples"] + linvalid,
                "columnar": True,
            }
        ext = self._extraction_sink
        sw = self.span_worker
        with ext._stats_lock:
            derived = ext.spans_seen
            rows = ext.derived_rows
            invalid = ext.invalid_samples
        with sw._stats_lock:
            dropped = (sw.spans_dropped
                       + sw.lane_drops.get(ext.name(), 0)
                       + sw.ingest_timeouts.get(ext.name(), 0))
        return {
            "received": received,
            "derived": derived + native,
            "derived_rows": rows,
            "dropped": dropped,
            "pending": sw.pending(),
            "invalid_samples": invalid,
            "columnar": False,
        }

    def _delivery_managers(self):
        """(report name, DeliveryManager) for every sink that carries
        one; span sinks report under <name>_spans so a metric/span sink
        pair sharing a vendor name stays distinguishable."""
        out = []
        for sink in self.metric_sinks:
            man = getattr(sink, "delivery", None)
            if man is not None:
                out.append((sink.name(), man))
        for sink in self.span_sinks:
            man = getattr(sink, "delivery", None)
            if man is not None:
                out.append((sink.name() + "_spans", man))
        for plugin in self.plugins:
            man = getattr(plugin, "delivery", None)
            if man is not None:
                out.append((plugin.name(), man))
        return out

    @property
    def parse_errors(self) -> int:
        """Total parse/overlong errors: Python-side cells, each worker's
        drained-and-attributed count, and the not-yet-drained native
        delta. Monotonic — a drain only MOVES the native delta into the
        worker's cumulative count (reset per process, not per epoch)."""
        n = self._ctr_sum(1)
        for w in self.workers:
            n += getattr(w, "parse_errors", 0)
            native = getattr(w, "_native", None)
            if native is not None:
                n += int(native.errors) - w._native_errs_seen
                for j, ctx in enumerate(getattr(w, "_reader_ctxs", ())):
                    n += int(ctx.errors) - w._reader_errs_seen[j]
        return n

    def _bump_errors(self, n: int = 1) -> None:
        self._ctr_cell()[1] += n

    def _route(self, metric) -> None:
        i = metric.digest % len(self.workers)
        with self._worker_locks[i]:
            self.workers[i].process_metric(metric)

    def _route_many(self, metrics: list) -> None:
        """Route a burst of metrics taking each worker lock once per
        group instead of once per metric (the columnar span pipeline
        derives thousands of rows at the flush edge). Per-worker order is
        exactly what per-metric _route would produce — grouping is a
        stable partition of one FIFO stream — so sketch state stays
        bit-identical to the per-span path."""
        nw = len(self.workers)
        if nw == 1:
            with self._worker_locks[0]:
                process = self.workers[0].process_metric
                for m in metrics:
                    process(m)
            return
        groups: dict[int, list] = {}
        for m in metrics:
            groups.setdefault(m.digest % nw, []).append(m)
        for i, group in groups.items():
            with self._worker_locks[i]:
                process = self.workers[i].process_metric
                for m in group:
                    process(m)

    def process_metric_packet(self, datagram: bytes) -> None:
        """Split a datagram on newlines and handle each line
        (reference processMetricPacket, server.go:1136)."""
        self._ctr_cell()[0] += 1
        if len(datagram) > self.config.metric_max_length:
            self._bump_errors()
            log.debug("overlong metric datagram (%d bytes)", len(datagram))
            return
        if self.native_mode:
            # no Python lock here: the C++ router parses lock-free and
            # commits under per-shard mutexes, so concurrent readers scale
            self._native_router.ingest(datagram)
            # pending-drain check is strided: each check is a ctypes call
            # per shard, which at line rate would rival the parse cost.
            # The counter is racy across readers — that only skews WHICH
            # packet triggers the check; buffers are bounded by
            # batch_size + stride·lines_per_packet and always drain at
            # flush.
            self._native_ingest_tick += 1
            if self._native_ingest_tick % 64 == 0:
                self._drain_native_thresholds()
            # events and service checks come back for the Python parser
            if b"_e{" in datagram or b"_sc" in datagram:
                self._drain_native_events()
            return
        for line in datagram.split(b"\n"):
            if line:
                self.handle_metric_packet(line)

    def _drain_native_thresholds(self) -> None:
        """Drain any worker whose native SoA spill/set/scalar batches
        crossed batch_size (shared by the strided ingest check and the
        native-reader pump)."""
        for i, w in enumerate(self.workers):
            ctxs = [w._native] + list(getattr(w, "_reader_ctxs", ()))
            if any(c.pending_histo >= w.batch_size
                   or c.pending_set >= w.batch_size for c in ctxs):
                with self._worker_locks[i]:
                    w.drain_native()

    def _drain_native_events(self) -> None:
        """Pull buffered event/service-check lines out of the C++ context
        and parse them on the Python path. MUST NOT be called while
        holding a worker lock — the parsed lines re-enter _route, which
        takes them. Deliberately lock-free on the Python side: the drain
        serializes on the C++ ctx mutex (per-thread scratch in native.py),
        so reader threads no longer funnel through worker 0's ingest
        lock; each parsed line then routes to its digest owner."""
        for w in self.workers:
            for ctx in [w._native] + list(getattr(w, "_reader_ctxs", ())):
                for line in ctx.drain_other():
                    self.handle_metric_packet(line)

    def _drain_native_ssf_fallbacks(self) -> None:
        """Raw SSF payloads the C++ SSF reader handed back (STATUS spans
        need the Python pipeline). Same no-lock-held, no-funnel rule as
        events."""
        if not self._native_ssf_readers:
            return
        for pkt in self.workers[0]._native.drain_ssf_fallback():
            self.handle_trace_packet(pkt)

    # -- SSF ingest ---------------------------------------------------------

    def handle_trace_packet(self, packet: bytes) -> None:
        """One unframed SSF datagram → span pipeline
        (reference HandleTracePacket, server.go:1046)."""
        if not packet:
            self._bump_errors()
            # reference tag set verbatim (server.go:1052)
            self.stats.count("ssf.error_total", 1,
                             tags=_SSF_ERR_ZEROLENGTH)
            return
        if self._native_ssf:
            # native decode + span→metric extraction in one C++ pass;
            # rc -1 = span carries STATUS samples → Python path below
            with self._worker_locks[0]:
                rc = self.workers[0].ingest_ssf_packet(
                    packet, self._native_ssf_indicator,
                    self._native_ssf_objective,
                    self.config.ssf_span_uniqueness_rate)
            if rc == 1:
                return
            if rc == 0:
                self._bump_errors()
                self.stats.count("ssf.error_total", 1,
                                 tags=_SSF_ERR_UNMARSHAL)
                return
        try:
            span = ssf_wire.parse_ssf(packet)
        except ssf_wire.FramingError as e:
            self._bump_errors()
            self.stats.count("ssf.error_total", 1,
                             tags=_SSF_ERR_UNMARSHAL)
            log.debug("bad SSF packet: %s", e)
            return
        if span.id == 0:
            # client problem, counted but the span is still handled
            # (reference server.go:1067-1072)
            self.stats.count("ssf.error_total", 1,
                             tags=_SSF_ERR_EMPTY_ID)
            log.debug("trace packet has zero span id")
        self.handle_ssf(span)

    def ingest_internal_span(self, span) -> None:
        """Self-tracing entry: a finished internal span enters the same
        pipeline external SSF spans do."""
        self.handle_ssf(span)

    def handle_trace_packets_native(self, packets: list[bytes]) -> None:
        """Batched twin of handle_trace_packet for the native SSF fast
        path: one C call decodes+extracts the whole burst; STATUS-bearing
        spans come back for the Python pipeline."""
        worker = self.workers[0]
        with self._worker_locks[0]:
            ok, errs, fallbacks = worker._native.ingest_ssf_many(
                packets, self._native_ssf_indicator,
                self._native_ssf_objective,
                self.config.ssf_span_uniqueness_rate)
            worker.processed += ok
            if (worker._native.pending_histo >= worker.batch_size
                    or worker._native.pending_set >= worker.batch_size):
                worker.drain_native()
        self._bump_errors(errs)
        if errs:
            self.stats.count("ssf.error_total", errs,
                             tags=_SSF_ERR_UNMARSHAL)
        for pkt in fallbacks:
            try:
                span = ssf_wire.parse_ssf(pkt)
            except ssf_wire.FramingError as e:
                self._bump_errors()
                self.stats.count("ssf.error_total", 1,
                                 tags=_SSF_ERR_UNMARSHAL)
                log.debug("bad SSF packet: %s", e)
                continue
            if span.id == 0:
                # same client-problem counter as the single-packet path
                self.stats.count("ssf.error_total", 1,
                                 tags=_SSF_ERR_EMPTY_ID)
            self.handle_ssf(span)

    def handle_ssf(self, span) -> None:
        """reference handleSSF (server.go:1077): per-service counters,
        then into the span worker."""
        service = span.service or "unknown"
        with self._ssf_stats_lock:
            self.ssf_spans_received[service] = (
                self.ssf_spans_received.get(service, 0) + 1)
            self.ssf_spans_received_total += 1
        sw = self.span_worker
        # columnar only while the worker is pristine: a runtime-appended
        # per-span sink or a tapped/replaced ingest (both long-standing
        # observation patterns) must keep seeing every span, so either
        # customization routes the whole stream back through the lanes
        if (self.span_pipeline is not None
                and getattr(sw.ingest, "__func__", None) is SpanWorker.ingest
                and len(sw.span_sinks) == self._span_worker_sink_count):
            self.span_pipeline.ingest(span)
        else:
            sw.ingest(span)

    def start_ssf_udp(self, addr: str, port: int) -> int:
        sock = self._adopt_fd()
        if sock is None:
            sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind((addr, port))
        bound_port = sock.getsockname()[1]
        self._sockets.append(sock)

        if (self._native_ssf and self.config.tpu_native_readers
                and self._native_router is not None):
            # C++ SSF reader: datagram -> proto decode -> span->metric
            # extraction with no Python on the path; STATUS spans buffer
            # for the pump's fallback drain
            try:
                sock.setblocking(True)
                h = self._native_router.start_ssf_reader(
                    self.workers[0]._native, sock.fileno(),
                    min(self.config.trace_max_length_bytes, 65536),
                    self._native_ssf_indicator, self._native_ssf_objective,
                    self.config.ssf_span_uniqueness_rate)
                self._native_ssf_readers.append(h)
                self._start_native_pump()
                return bound_port
            except (AttributeError, RuntimeError) as e:
                log.warning("native SSF reader unavailable (%s); using the"
                            " Python reader", e)

        def loop():
            sock.settimeout(0.5)  # quiesce-able without closing (handoff)
            # per-datagram read buffer sized from trace_max_length_bytes,
            # matching the reference's tracePool (server.go:859-863) — NOT
            # ssf_buffer_size, which upstream is a deprecated span-count
            # alias (config_parse.go:172-176). Inet UDP datagrams cap at
            # 65507B, so clamp there; a datagram larger than the buffer is
            # truncated by recv and fails proto parse -> parse error, as
            # in the reference.
            max_len = min(self.config.trace_max_length_bytes, 65536)
            buf = bytearray(max_len)
            while not (self._shutdown.is_set() or self._quiesce.is_set()):
                try:
                    n = sock.recv_into(buf, max_len)
                    data = bytes(buf[:n])
                except socket.timeout:
                    continue
                except OSError:
                    return
                if not self._native_ssf:
                    self.handle_trace_packet(data)
                    continue
                # native fast path: greedily drain whatever else is
                # already queued and decode the whole burst in one C call
                # (the per-call overhead is ~1/3 of per-span cost)
                batch = [data]
                sock.setblocking(False)
                try:
                    while len(batch) < 512:
                        batch.append(sock.recv(max_len))
                except (BlockingIOError, OSError):
                    pass
                finally:
                    sock.settimeout(0.5)
                self.handle_trace_packets_native(batch)

        self._spawn(loop, "ssf-udp")
        return bound_port

    def start_ssf_unix(self, path: str) -> None:
        """Framed SSF over a unix stream socket
        (reference startSSFUnix, networking.go:222-285)."""
        sock = self._bind_unix_socket(path, socket.SOCK_STREAM)
        sock.listen(64)

        def accept_loop():
            while not self._shutdown.is_set():
                try:
                    conn, _ = sock.accept()
                except OSError:
                    return
                self._spawn(lambda c=conn: self._read_ssf_stream(c),
                            "ssf-unix-conn")

        self._spawn(accept_loop, "ssf-unix-accept")

    def _read_ssf_stream(self, conn: socket.socket) -> None:
        """Framed read loop; a framing error poisons the stream
        (reference ReadSSFStreamSocket, server.go:1215)."""
        f = conn.makefile("rb")
        try:
            while not self._shutdown.is_set():
                try:
                    span = ssf_wire.read_ssf(
                        f, max_length=self.config.trace_max_length_bytes)
                except ssf_wire.SSFUnmarshalError as e:
                    # the frame was consumed whole; the stream can keep
                    # reading (reference ReadSSFStreamSocket continues on
                    # non-framing errors, server.go:1243-1248)
                    self._bump_errors()
                    self.stats.count("ssf.error_total", 1,
                                     tags=_SSF_ERR_PROCESSING)
                    log.debug("bad SSF frame payload: %s", e)
                    continue
                if span is None:
                    # clean client hangup at a frame boundary
                    # (reference server.go:1229-1232)
                    self.stats.count("frames.disconnects", 1)
                    return
                self.handle_ssf(span)
        except ssf_wire.FramingError as e:
            # a framing violation poisons the stream: close it
            # (reference protocol/wire.go IsFramingError path,
            # server.go:1234-1241)
            self._bump_errors()
            self.stats.count("ssf.error_total", 1,
                             tags=_SSF_ERR_FRAMING)
            log.debug("SSF stream framing error, closing: %s", e)
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def start_ssf_unixgram(self, path: str) -> None:
        """Unframed SSF datagrams over a unix datagram socket (reference
        ReadSSFPacketSocket over unixgram, networking.go:222-285)."""
        sock = self._bind_unix_socket(path, socket.SOCK_DGRAM)

        def loop():
            # unix datagrams are not bound by the inet 64KiB limit, so the
            # buffer is the full trace_max_length_bytes (reference
            # tracePool, server.go:859-863), allocated once per listener
            max_len = self.config.trace_max_length_bytes
            buf = bytearray(max_len)
            while not self._shutdown.is_set():
                try:
                    n = sock.recv_into(buf, max_len)
                except OSError:
                    return
                self.handle_trace_packet(bytes(buf[:n]))

        self._spawn(loop, "ssf-unixgram")

    def start_ssf_listeners(self) -> dict[str, int]:
        ports = {}
        for spec in self.config.ssf_listen_addresses:
            proto, _, rest = spec.partition("://")
            # fd-manifest key is namespaced: a statsd listener with the
            # IDENTICAL spec string (e.g. both "udp://127.0.0.1:0") must
            # not cross-wire its handed-off fds with this one's
            key = "ssf:" + spec
            if proto == "udp":
                self._adopt = list(
                    self._inherited.pop(key, None)
                    or self._inherited.pop(spec, []))  # pre-ns manifests
                before = len(self._sockets)
                host, _, port = rest.rpartition(":")
                ports[spec] = self.start_ssf_udp(host or "127.0.0.1",
                                                 int(port))
                self._listener_fds[key] = [
                    s.fileno() for s in self._sockets[before:]]
                self._close_unused_adopted()
            elif proto in ("unix", "unixstream"):
                self.start_ssf_unix(rest)
            elif proto == "unixgram":
                self.start_ssf_unixgram(rest)
            else:
                raise ValueError(f"unsupported SSF listener {spec!r}")
        return ports

    # -- listeners ----------------------------------------------------------

    def _spawn(self, target, name: str,
               compute: bool = False) -> threading.Thread:
        """Every long-lived server thread is wrapped in panic capture
        (reference ConsumePanic around goroutines, sentry.go:22-60,
        server.go:395-400): report to sentry_dsn, then abort so process
        supervision restarts us. Exceptions during shutdown are routine
        (sockets closed underneath readers) and are suppressed.

        compute=True marks a thread that runs device programs; shutdown
        joins those (bounded) so the interpreter never finalizes while
        one is inside XLA/C++ (see shutdown())."""
        t = threading.Thread(
            target=crash.guard(target, self.config.sentry_dsn, name,
                               suppress=self._shutdown.is_set),
            name=name, daemon=True)
        t.start()
        self._threads.append(t)
        if compute:
            self._compute_threads.append(t)
        return t

    def _adopt_fd(self) -> Optional[socket.socket]:
        """Take one inherited listener fd (if the previous process image
        handed one off for the listener being started)."""
        while self._adopt:
            fd = self._adopt.pop(0)
            try:
                return socket.socket(fileno=fd)
            except OSError:
                log.warning("inherited fd %d unusable; binding fresh", fd)
        return None

    def start_statsd_udp(self, addr: str, port: int) -> int:
        """N reader threads sharing the port via SO_REUSEPORT
        (reference networking.go:41-91, socket_linux.go)."""
        if self._adopt and len(self._adopt) != self.config.num_readers:
            # num_readers changed across the restart: a mixed
            # adopted/fresh set can't share the port (the old sockets'
            # SO_REUSEPORT state is fixed at their bind), so fall back to
            # an all-fresh bind — a brief re-bind window, logged, instead
            # of an EADDRINUSE crash
            log.warning(
                "num_readers changed across restart (%d inherited fds,"
                " %d readers); re-binding fresh", len(self._adopt),
                self.config.num_readers)
            self._close_unused_adopted()
        bound_port = port
        for i in range(self.config.num_readers):
            sock = self._adopt_fd()
            if sock is None:
                sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                if self.config.num_readers > 1:
                    sock.setsockopt(socket.SOL_SOCKET,
                                    socket.SO_REUSEPORT, 1)
                if self.config.read_buffer_size_bytes:
                    sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                                    self.config.read_buffer_size_bytes)
                sock.bind((addr, bound_port))
            bound_port = sock.getsockname()[1]  # resolve port 0 once
            self._sockets.append(sock)
            if self._start_native_metric_reader(sock):
                continue
            self._spawn(
                lambda s=sock: self._read_metric_socket(s),
                f"statsd-udp-{i}",
            )
        return bound_port

    def _start_native_metric_reader(self, sock: socket.socket) -> bool:
        """Hand a bound datagram fd to a C++ reader thread: datagram →
        parse → staged sample with no Python (or GIL) on the path. The
        Python socket object stays in self._sockets so the fd outlives
        the thread (handoff keeps it open for the successor). Returns
        False when native readers are off/unavailable — the caller spawns
        the Python reader instead."""
        if not (self.native_mode and self.config.tpu_native_readers):
            return False
        try:
            sock.setblocking(True)
            with self._native_reader_lock:
                idx = len(self._native_readers)
            if self._reader_shards:
                # shared-nothing: reader idx commits exclusively into
                # reader context idx % R — no shared mutex on the line
                # path (events/errors stay on that context too)
                ctxs = self.workers[0]._reader_ctxs
                h = ctxs[idx % len(ctxs)].start_owned_reader(
                    sock.fileno(), self.config.metric_max_length)
            else:
                # digest-routed commits; `home` spreads each reader's
                # event/service-check/error buffers across the worker
                # contexts instead of funnelling them onto shard 0
                h = self._native_router.start_reader(
                    sock.fileno(), self.config.metric_max_length,
                    home=idx % len(self.workers))
            with self._native_reader_lock:
                self._native_readers.append(h)
            self._start_native_pump()
            return True
        except (AttributeError, RuntimeError) as e:
            log.warning("native reader unavailable (%s); using the"
                        " Python reader", e)
            return False

    def _start_native_pump(self) -> None:
        """With C++ readers, no Python code sees datagrams — this thread
        takes over the strided duties of process_metric_packet: threshold
        drains of the spill/set/scalar SoA batches and the event/service-
        check handback (both also run at every flush)."""
        if getattr(self, "_native_pump_started", False):
            return

        def pump() -> None:
            while not (self._shutdown.is_set() or self._quiesce.is_set()):
                time.sleep(0.1)
                try:
                    self._drain_native_thresholds()
                    self._drain_native_events()
                    self._drain_native_ssf_fallbacks()
                    self._reap_stream_readers()
                except Exception:
                    if self._shutdown.is_set():
                        return
                    raise

        self._spawn(pump, "native-pump", compute=True)
        # only after a successful spawn: a thread-creation failure must
        # leave the flag unset so the next caller retries
        self._native_pump_started = True

    def _reap_stream_readers(self) -> None:
        """Join C++ stream readers whose connection ended — an unjoined
        dead thread pins its stack for the process lifetime, and TCP
        connection churn would accumulate them."""
        with self._native_reader_lock:
            live = []
            for h in self._native_stream_readers:
                try:
                    if self._native_router.stream_reader_done(h):
                        self._native_router.stop_stream_reader(h)
                        self.stats.count("tcp.disconnects", 1)
                    else:
                        live.append(h)
                except Exception:
                    log.exception("stream reader reap failed")
            self._native_stream_readers = live

    def _stop_native_readers(self) -> None:
        """Join the C++ reader threads WITHOUT closing their fds (handoff
        leaves queued datagrams for the successor). Idempotent."""
        with self._native_reader_lock:
            readers, self._native_readers = self._native_readers, []
            for h in readers:
                try:
                    # stop_reader returns the FINAL count (post-join);
                    # reading before the join would lose the packets of
                    # the thread's last recv-timeout window
                    self._native_reader_packets_stopped += (
                        self._native_router.stop_reader(h))
                except Exception:
                    log.exception("native reader stop failed")
            ssf_readers = self._native_ssf_readers
            self._native_ssf_readers = []
            for h in ssf_readers:
                try:
                    # SSF packets are spans, not statsd packets: counted
                    # via the ssf.spans.received_total pipeline, not here
                    self._native_router.stop_ssf_reader(h)
                except Exception:
                    log.exception("native SSF reader stop failed")
            stream_readers = self._native_stream_readers
            self._native_stream_readers = []
            if stream_readers:
                self.stats.count("tcp.disconnects", len(stream_readers))
            for h in stream_readers:
                try:
                    # stream readers own their (dup'd) conn fds and close
                    # them; TCP connections don't ride the handoff
                    self._native_router.stop_stream_reader(h)
                except Exception:
                    log.exception("native stream reader stop failed")

    def _read_metric_socket(self, sock: socket.socket,
                            handoff_capable: bool = True) -> None:
        """reference ReadMetricSocket (server.go:1123): tight recv loop.
        Reads max_length+1 so overlong datagrams are detectable. The
        periodic timeout lets a handoff quiesce readers WITHOUT closing
        the socket — once quiesced, datagrams queue in the kernel buffer
        for the next process image instead of being consumed here.
        handoff_capable=False (path-based unixgram sockets, which re-bind
        instead of riding the exec) keeps consuming until shutdown —
        quiescing a socket that is about to be closed would destroy
        whatever queued behind it."""
        bufsize = self.config.metric_max_length + 1
        sock.settimeout(0.5)
        while not (self._shutdown.is_set()
                   or (handoff_capable and self._quiesce.is_set())):
            try:
                data = sock.recv(bufsize)
            except socket.timeout:
                continue
            except OSError:
                return  # socket closed during shutdown
            self.process_metric_packet(data)

    def start_statsd_tcp(self, addr: str, port: int) -> int:
        """Line-delimited TCP statsd, optional (mutual) TLS
        (reference server.go:1254-1335, TLS setup :438-472)."""
        sock = self._adopt_fd()  # inherited fds are already listening
        if sock is None:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind((addr, port))
            sock.listen(128)
        bound_port = sock.getsockname()[1]
        self._sockets.append(sock)

        ssl_ctx = None
        if self.config.tls_key and self.config.tls_certificate:
            ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ssl_ctx.load_cert_chain(self.config.tls_certificate,
                                    self.config.tls_key)
            if self.config.tls_authority_certificate:
                ssl_ctx.load_verify_locations(
                    self.config.tls_authority_certificate)
                ssl_ctx.verify_mode = ssl.CERT_REQUIRED

        def accept_loop():
            sock.settimeout(0.5)  # quiesce-able for handoff (see below)
            while not (self._shutdown.is_set() or self._quiesce.is_set()):
                try:
                    conn, peer = sock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                conn.settimeout(None)
                if (ssl_ctx is None and self.native_mode
                        and self.config.tpu_native_readers):
                    # plain TCP: a C++ line-stream reader owns the
                    # connection (TLS must stay Python — ssl wraps the
                    # socket object). Reader gets its own dup so the
                    # Python socket can be closed here; the pump reaps
                    # finished readers.
                    # the try covers ONLY dup+reader-start: once the C++
                    # reader owns the fd, a later failure (e.g. pump
                    # thread creation) must neither close the fd again
                    # nor fall back to the Python handler on it
                    fd = None
                    h = None
                    try:
                        fd = os.dup(conn.fileno())
                        h = self._native_router.start_stream_reader(
                            fd, self.config.metric_max_length)
                    except (AttributeError, RuntimeError) as e:
                        if fd is not None:
                            os.close(fd)
                        log.warning("native stream reader unavailable "
                                    "(%s); using the Python handler", e)
                    if h is not None:
                        self.stats.count("tcp.connects", 1)
                        with self._native_reader_lock:
                            self._native_stream_readers.append(h)
                        conn.close()
                        try:
                            self._start_native_pump()
                        except RuntimeError:
                            # thread creation failed; the reader is live
                            # and the next start attempt (UDP reader
                            # setup, next conn) retries the pump
                            log.exception("native pump start failed")
                        continue
                self._spawn(
                    lambda c=conn, p=peer: self._handle_tcp_conn(c, p, ssl_ctx),
                    "statsd-tcp-conn",
                )

        self._spawn(accept_loop, "statsd-tcp-accept")
        return bound_port

    def _handle_tcp_conn(self, conn: socket.socket, peer, ssl_ctx) -> None:
        """reference handleTCPGoroutine (server.go:1254-1335)."""
        self.stats.count("tcp.connects", 1)
        try:
            if ssl_ctx is not None:
                try:
                    conn = ssl_ctx.wrap_socket(conn, server_side=True)
                except (ssl.SSLError, OSError):
                    # a peer resetting mid-handshake raises plain
                    # ConnectionResetError, not ssl.SSLError
                    self.stats.count("tcp.tls_handshake_failures", 1)
                    raise
            conn.settimeout(10.0 * self.interval)
            buf = b""
            while not self._shutdown.is_set():
                data = conn.recv(65536)
                if not data:
                    break
                buf += data
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if len(line) > self.config.metric_max_length:
                        self._bump_errors()
                        continue
                    if line:
                        self.handle_metric_packet(line)
            # trailing partial line without newline still counts
            if buf and len(buf) <= self.config.metric_max_length:
                self.handle_metric_packet(buf)
        except (OSError, ssl.SSLError) as e:
            log.debug("tcp statsd conn from %s error: %s", peer, e)
        finally:
            self.stats.count("tcp.disconnects", 1)
            try:
                conn.close()
            except OSError:
                pass

    def _bind_unix_socket(self, path: str, sock_type: int) -> socket.socket:
        """Bind a unix socket with flock-based exclusivity (reference
        acquireLockForSocket, networking.go:289-306): a `<path>.lock` file
        is flocked exclusively before the stale socket file is unlinked, so
        two server instances can never steal each other's socket. Abstract
        sockets (`@name`) have no filesystem presence and need no lock."""
        if path.startswith("@"):
            addr: bytes | str = "\0" + path[1:]
        else:
            import fcntl

            lock_path = path + ".lock"
            fd = os.open(lock_path, os.O_CREAT | os.O_RDWR, 0o644)
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                os.close(fd)
                raise RuntimeError(
                    f"socket {path!r} is locked by another veneur instance "
                    f"(flock on {lock_path!r} held)")
            self._socket_locks.append(fd)
            if os.path.exists(path):
                os.unlink(path)
            addr = path
        sock = socket.socket(socket.AF_UNIX, sock_type)
        sock.bind(addr)
        self._sockets.append(sock)
        return sock

    def start_statsd_unixgram(self, path: str) -> None:
        """Datagram unix socket statsd (reference networking.go:144-196),
        with flock exclusivity and abstract-socket (@name) support."""
        sock = self._bind_unix_socket(path, socket.SOCK_DGRAM)
        # same datagram semantics as UDP: the C++ reader works on any
        # bound datagram fd (_bind_unix_socket already registered the
        # socket in self._sockets, keeping the fd alive for the thread)
        if self._start_native_metric_reader(sock):
            return
        self._spawn(
            lambda: self._read_metric_socket(sock, handoff_capable=False),
            "statsd-unixgram")

    def start_listeners(self) -> dict[str, int]:
        """Start every configured statsd listener; returns resolved ports
        keyed by address string (reference StartStatsd, networking.go:19)."""
        ports = {}
        for spec in self.config.statsd_listen_addresses:
            proto, _, rest = spec.partition("://")
            self._adopt = list(self._inherited.pop(spec, []))
            before = len(self._sockets)
            if proto == "udp":
                host, _, port = rest.rpartition(":")
                ports[spec] = self.start_statsd_udp(host or "127.0.0.1",
                                                    int(port))
            elif proto == "tcp":
                host, _, port = rest.rpartition(":")
                ports[spec] = self.start_statsd_tcp(host or "127.0.0.1",
                                                    int(port))
            elif proto == "unixgram":
                # path-based sockets re-bind (flock exclusivity); no fd
                # handoff
                self.start_statsd_unixgram(rest)
            else:
                raise ValueError(f"unsupported statsd listener {spec!r}")
            if proto in ("udp", "tcp"):
                self._listener_fds[spec] = [
                    s.fileno() for s in self._sockets[before:]]
            self._close_unused_adopted()
        return ports

    def _close_unused_adopted(self) -> None:
        # config (e.g. num_readers) shrank across a restart: surplus
        # inherited fds must not leak
        for fd in self._adopt:
            try:
                os.close(fd)
            except OSError:
                pass
        self._adopt = []

    def prepare_handoff(self) -> dict[str, list[int]]:
        """Mark every network listener fd inheritable and return the
        spec→fds manifest for the next process image (einhorn-style
        zero-downtime restart, reference server.go:1401-1429). After this,
        shutdown() leaves those fds open so queued datagrams survive the
        re-exec."""
        self._handoff = True
        # stop the reader/accept loops first (without closing the
        # sockets) so datagrams queue in kernel buffers and TCP
        # connections wait in the listen backlog for the successor
        self._quiesce.set()
        self._stop_native_readers()  # joins; fds stay open for handoff
        deadline = time.time() + 2.0
        for t in self._threads:
            if t.name.startswith(("statsd-udp", "ssf-udp",
                                  "statsd-tcp-accept")):
                t.join(timeout=max(0.0, deadline - time.time()))
        for fds in self._listener_fds.values():
            for fd in fds:
                try:
                    os.set_inheritable(fd, True)
                except OSError:
                    log.warning("fd %d not inheritable; it will re-bind",
                                fd)
        return dict(self._listener_fds)

    # -- flush loop ---------------------------------------------------------

    def start(self) -> dict[str, int]:
        """Start listeners, sinks and the flush ticker
        (reference Server.Start, server.go:826)."""
        if self.config.enable_profiling:
            # XLA-native analog of the reference's profile.Start()
            # (server.go:1392-1399): a JAX profiler trace capturing both
            # host Python and device (TPU) activity, viewable in
            # TensorBoard / Perfetto.
            try:
                import jax.profiler

                self._profile_dir = (self.config.profile_dir
                                     or "veneur-tpu-profile")
                jax.profiler.start_trace(self._profile_dir)
                log.info("XLA profiling enabled -> %s", self._profile_dir)
            except Exception:
                log.exception("could not start the JAX profiler")
                self._profile_dir = None
        # durable spill: attach + replay journals BEFORE sinks start, so
        # a prior incarnation's journaled payloads sit in the spill and
        # go out ahead of fresh data at the first flush (retry_spill)
        self._attach_journals()
        for sink in self.metric_sinks + self.span_sinks:
            sink.start()
        self.span_worker.start()
        ports = self.start_listeners()
        for spec, port in self.start_ssf_listeners().items():
            # identical spec on both listener lists (e.g. two ephemeral
            # "udp://127.0.0.1:0" binds): don't let the SSF port shadow
            # the statsd one in the report
            ports["ssf:" + spec if spec in ports else spec] = port
        # inherited fds whose listener spec left the config: close them,
        # or the old port stays bound with no reader and blackholes
        # traffic silently (clients get no ICMP error)
        for spec, fds in self._inherited.items():
            log.warning("closing %d inherited fds for removed listener %s",
                        len(fds), spec)
            for fd in fds:
                try:
                    os.close(fd)
                except OSError:
                    pass
        self._inherited.clear()
        for spec, port in self._start_query_listeners().items():
            ports[spec] = port
        if self.config.tpu_warmup_compile:
            self._spawn(self._warmup_compile, "warmup-compile",
                        compute=True)
        if self.flush_pipeline is not None:
            # stage threads must exist before the first tick enqueues
            self.flush_pipeline.start()
        self._spawn(self._flush_loop, "flush-ticker", compute=True)
        if self.config.micro_fold:
            # always-hot flush scheduler (worker.micro_fold_once): the
            # staged ingest planes stream to the device mirrors DURING
            # the interval, so the tick's fold shrinks to a drain
            self._spawn(self._micro_fold_loop, "micro-fold", compute=True)
        if self.native_mode:
            self._spawn(self._series_sync_loop, "series-sync",
                        compute=True)
        return ports

    def _start_query_listeners(self) -> dict[str, int]:
        """Bind the live query fronts (config query_listen_addrs):
        http:// addresses serve /metrics (exposition) + /query (JSON),
        grpc:// addresses serve veneurtpu.Query/Query. Returns
        {spec: bound_port} merged into the start() port report."""
        ports: dict[str, int] = {}
        if self.query_engine is None:
            return ports
        for spec in self.config.query_listen_addrs:
            scheme, _, hostport = spec.partition("://")
            try:
                if scheme == "grpc":
                    from veneur_tpu.query.service import make_query_server

                    server, port = make_query_server(
                        self.query_engine, hostport)
                else:
                    from veneur_tpu.query.http import make_http_server

                    server, port = make_http_server(
                        self.query_engine, hostport)
            except Exception:
                # a query front failing to bind must not take down
                # ingest — the pipeline is the product, reads are a view
                log.exception("query listener %s failed to start", spec)
                continue
            self._query_servers.append((scheme, server))
            ports[spec] = port
            log.info("query listener on %s (port %d)", spec, port)
        return ports

    def _attach_journals(self) -> None:
        """Back every journalable sink's delivery spill with a
        write-ahead journal under <spill_journal_dir>/sink-<name>/ and
        replay whatever a prior incarnation left unacked. Managers that
        refuse (journal_exempt — splunk's send-once semantics) stay
        RAM-only. No spill_journal_dir = no-op, byte-identical to the
        in-RAM behaviour."""
        jdir = self.config.spill_journal_dir
        if not jdir:
            return
        from veneur_tpu.sinks.journal_codec import make_entry_codec
        from veneur_tpu.utils.journal import SpillJournal

        encode, decode = make_entry_codec()
        for rname, man in self._delivery_managers():
            if getattr(man, "journal_exempt", False):
                log.info("sink %s: spill journal skipped (send-once "
                         "semantics)", rname)
                continue
            journal = SpillJournal(
                os.path.join(jdir, f"sink-{rname}"),
                fsync=self.config.spill_journal_fsync,
                max_bytes=self.config.spill_journal_max_bytes,
                max_segments=self.config.spill_journal_max_segments,
                log=log.warning)
            if not man.attach_journal(journal, encode):
                journal.close()
                continue
            self._journals[rname] = journal
            n = man.recover(decode)
            if n:
                log.info("sink %s: %d journaled payload(s) recovered, "
                         "will retry ahead of fresh data", rname, n)

    def graceful_drain(self, deadline_s: Optional[float] = None) -> dict:
        """SIGTERM contract: final-epoch flush, then bounded delivery/
        spill-settling passes, with honest shutdown.* counters for
        whatever the deadline clips. Returns (and stores on
        self.shutdown_stats) the drain ledger; call before shutdown().

        With the journal on, clipped payloads stay durable and the next
        incarnation recovers them — the deadline bounds shutdown
        LATENCY, never silently converts spill into loss."""
        if deadline_s is None:
            deadline_s = self.config.shutdown_drain_deadline_s
        t0 = time.monotonic()
        deadline = t0 + max(0.0, float(deadline_s))
        stats: dict = {"deadline_s": float(deadline_s),
                       "final_flush": False, "drained_payloads": 0,
                       "drain_passes": 0}
        # 1) final-epoch swap + flush of whatever the last interval
        #    accumulated (the pipelined path drains in shutdown();
        #    serial flushes run inline here)
        if deadline_s > 0:
            try:
                self.flush()
                stats["final_flush"] = True
            except Exception:  # noqa: BLE001 — drain anyway
                log.exception("graceful drain: final flush failed")
        # 2) bounded spill-settling passes across every manager until
        #    the spill is empty or the deadline clips
        managers = self._delivery_managers()
        while time.monotonic() < deadline:
            remaining = deadline - time.monotonic()
            spilled = 0
            for _, man in managers:
                if len(man.spill):
                    man.begin_flush(remaining)
                    stats["drained_payloads"] += man.retry_spill()
                spilled += len(man.spill)
            stats["drain_passes"] += 1
            if not spilled:
                break
            time.sleep(min(0.05, max(0.0, deadline - time.monotonic())))
        # 3) the honest remainder: what the deadline clipped
        left_payloads = left_bytes = 0
        for _, man in managers:
            s = man.stats()
            left_payloads += s["spilled_payloads"]
            left_bytes += s["spilled_bytes"]
        for journal in self._journals.values():
            journal.sync()
        stats.update({
            "clipped_payloads": left_payloads,
            "clipped_bytes": left_bytes,
            "deadline_clipped": left_payloads > 0,
            "journal_pending_records": sum(
                j.pending_records() for j in self._journals.values()),
            "duration_s": round(time.monotonic() - t0, 3),
        })
        self.shutdown_stats = stats
        self.stats.count("shutdown.drained_payloads",
                         stats["drained_payloads"])
        self.stats.count("shutdown.clipped_payloads", left_payloads)
        self.stats.count("shutdown.clipped_bytes", left_bytes)
        if left_payloads:
            log.warning(
                "graceful drain clipped by deadline: %d payload(s) / %d "
                "bytes still spilled%s", left_payloads, left_bytes,
                " (journaled for the next incarnation)"
                if self._journals else "")
        else:
            log.info("graceful drain complete in %.3fs (%d payload(s) "
                     "re-delivered)", stats["duration_s"],
                     stats["drained_payloads"])
        return stats

    def _warmup_compile(self) -> None:
        """Precompile the flush programs (staged fold + extraction) on a
        throwaway worker at the first pow2 row bucket, concurrent with
        startup. Without this the FIRST real flush pays the 20-40s
        per-shape XLA compile on TPU inside the interval — enough to trip
        a tight flush watchdog on a perfectly healthy server. Later
        growth buckets still compile lazily (and land in the persistent
        cache when tpu_compilation_cache_dir is set)."""
        try:
            from veneur_tpu.core.flusher import device_quantiles

            w = DeviceWorker(
                batch_size=self.config.tpu_batch_size,
                stage_depth=self.config.tpu_stage_depth,
                compression=self.config.tpu_compression,
                hll_precision=self.config.tpu_hll_precision,
                # must mirror the real workers' initial pool size or the
                # warmed shapes differ from the first real flush's
                initial_histo_rows=self.config.tpu_initial_histo_rows,
                is_local=self.is_local,
                series_shards=self.config.series_shards,
            )
            w.process_metric(
                dogstatsd.parse_metric(b"veneur.warmup:1|ms"))
            qs = device_quantiles(self.percentiles, self.aggregates)
            w.flush(qs, interval_s=self.interval)
            log.debug("flush programs warm (first row bucket)")
        except Exception:
            # warmup is best-effort: a failure only restores the lazy
            # first-flush compile
            log.debug("flush warmup failed", exc_info=True)

    def sync_native_series_once(self) -> None:
        """One locked new-series adoption sweep across all workers.

        The pending probe is a lock-free C call, so an idle sweep costs
        no worker-lock churn."""
        for i, worker in enumerate(self.workers):
            if worker._native is None or not worker.native_series_pending():
                continue
            with self._worker_locks[i]:
                worker.sync_native_series()

    def _series_sync_loop(self) -> None:
        """Adopt new-series registrations from the C++ contexts as they
        arrive instead of all at once inside flush's swap phase — at 1M
        fresh series per interval the adoption is ~7s of Python work
        that would otherwise sit under the ingest lock (profiled:
        _sync_native_series was 0.88s of a 0.99s swap at 131k series).
        Cadence is a fraction of the interval so the swap-time tail is
        small; the sweep early-returns when nothing is pending."""
        cadence = max(0.1, min(1.0, self.interval / 8.0))
        while not self._shutdown.wait(cadence):
            try:
                self.sync_native_series_once()
            except Exception:
                log.exception("series sync sweep failed")

    def _micro_fold_loop(self) -> None:
        """Sub-interval micro-fold scheduler (always-hot flush): poll
        each worker's staged backlog and drain it to the device mirror
        whenever the row-count or age threshold trips
        (worker.micro_fold_due / micro_fold_once). The due probe is
        lock-free (native: one C call; Python: a numpy sum); only an
        actual drain takes the worker's ingest lock, and briefly — the
        COO copy is a memcpy and the device feeds are async dispatches.
        Poll cadence tracks the age threshold so a trickle workload
        still drains within ~max_age."""
        cadence = max(0.01, min(1.0,
                                self.config.micro_fold_max_age_s / 2.0,
                                self.interval / 20.0))
        while not (self._shutdown.is_set() or self._quiesce.is_set()):
            if self._shutdown.wait(cadence):
                return
            for i, worker in enumerate(self.workers):
                try:
                    if worker.micro_fold_due():
                        with self._worker_locks[i]:
                            worker.micro_fold_once()
                except Exception:
                    if self._shutdown.is_set():
                        return
                    # counted, not fatal: the staging plane retains every
                    # sample the mirror held, so the flush still folds the
                    # epoch — but a recurring drain error must be visible
                    self.stats.count("micro_fold.errors_total", 1,
                                     tags=[f"worker:{i}"])
                    log.exception("micro-fold drain failed (worker %d)", i)

    def _flush_loop(self) -> None:
        """Interval ticker, optionally aligned to the wall clock
        (reference server.go:908-946)."""
        if self.config.synchronize_with_interval:
            time.sleep(calculate_tick_delay(self.interval, time.time()))
        next_tick = time.time()
        while not self._shutdown.is_set():
            next_tick += self.interval
            delay = next_tick - time.time()
            if delay > 0 and self._shutdown.wait(delay):
                return
            try:
                _t0 = time.perf_counter()
                if self.flush_pipeline is not None:
                    outcome = self.flush_pipeline.tick()
                    self.last_tick_s = time.perf_counter() - _t0
                    if outcome == "ok":
                        # growth only: under overlap a stage may run
                        # most of the interval and still keep pace, so
                        # stage DURATION is not an overload signal —
                        # BACKLOG is, and persistent backlog sheds via
                        # _pipeline_overrun on the deferred/shed paths.
                        # Duration-driven halving here was measured
                        # shedding 94k lines of a 2.7M-line confirm run
                        # that the pipeline was absorbing fine.
                        self._adapt_spill_caps(
                            max(self.last_tick_s,
                                self.flush_pipeline.last_cycle_s),
                            allow_shrink=False)
                else:
                    self.flush()
                    self.last_tick_s = time.perf_counter() - _t0
                    self._adapt_spill_caps(self.last_tick_s)
            except Exception:
                log.exception("flush failed")

    def _pipeline_overrun(self) -> None:
        """A flush-pipeline stage fell a full interval behind (deferred
        tick or shed interval): treat it exactly like a flush that
        consumed the whole interval, so the standing shedding loop
        halves the spill caps instead of letting queues grow
        (health/policy.py MAX_STAGE_BACKLOG documents the contract)."""
        self._adapt_spill_caps(self.interval)

    def _adapt_spill_caps(self, flush_dur: float,
                          allow_shrink: bool = True) -> None:
        """Closed-loop overload shedding: bound the backlog one flush can
        inherit so the flush fits the interval. The C++ spill caps bound
        the direct-fold work a swap hands to extraction; when a flush
        overruns most of the interval, halve them (shed earlier at the
        parse boundary — cheap, counted — and keep the cadence); when
        flushes run comfortably fast, grow back toward the configured
        ceiling. The reference's equivalents are fixed-size worker
        channels (worker.go:31-48) plus a watchdog that kills a stalled
        flush (server.go:948-990); adapting the cap keeps the flush from
        being the thing that stalls."""
        ceiling = self.config.tpu_spill_cap
        floor = min(1 << 16, ceiling)
        cur = self._spill_cap_now
        if allow_shrink and flush_dur > 0.9 * self.interval:
            new = max(floor, cur >> 1)
        elif flush_dur < 0.3 * self.interval:
            new = min(ceiling, cur << 1)
        else:
            return
        if new == cur:
            return
        self._spill_cap_now = new
        self.stats.gauge("ingest.spill_cap", new)
        for i, w in enumerate(self.workers):
            # under the worker's ingest lock (ADVICE item 3): _native is
            # published by attach paths and read by every ingest call;
            # the lock also orders the cap write against a concurrent
            # swap's drain/reset critical section
            with self._worker_locks[i]:
                w.spill_cap = new
                if w._native is not None:
                    try:
                        w._native.set_spill_cap(new)
                        for ctx in getattr(w, "_reader_ctxs", ()):
                            ctx.set_spill_cap(new)
                    except AttributeError:  # stale .so without the cap API
                        pass

    def flush(self, now: float | None = None):
        """One flush pass (reference Server.Flush, flusher.go:28-134).

        Returns list[InterMetric] on the object path, or a
        ColumnarMetrics batch (len() works; call .materialize() for
        objects) when every sink consumed columns.

        `now` pins the interval's timestamp (tests compare serial and
        pipelined output bit-for-bit by flushing both at one clock).

        Self-traced: every flush is a span (reference
        tracer.StartSpan("flush"), flusher.go:29) that rejoins this
        server's own span pipeline and surfaces as derived metrics on
        the NEXT interval."""
        # bracket the whole flush for the governor: in_flight + progress
        # beats are what the watchdog's deferral rule reads, so end_flush
        # must run even when a phase raises
        self.flush_governor.begin_flush()
        try:
            with self.tracer.start_span("flush"):
                return self._flush_inner(now=now)
        finally:
            self.flush_governor.end_flush()

    def _flush_inner(self, now: float | None = None):
        # serial composition of the four flush phases; the stage-parallel
        # executor (core/pipeline.py) runs the SAME methods on dedicated
        # stage threads with up to an interval of overlap between them,
        # which is what keeps pipelined output bit-identical to this path
        job = self._flush_begin(now=now)
        self._flush_extract(job)
        self._flush_generate(job)
        self._flush_emit(job)
        if job.batch is not None:
            # columnar flush: the batch supports len(); callers needing
            # objects use .materialize()
            return job.batch
        return job.final

    def _flush_begin(self, now: float | None = None):
        """Tick-side flush phase: epoch close + device dispatches under
        the per-worker ingest locks (the map-swap analog of
        worker.go:498-517) — no device readback, so a pipelined tick
        stays a fraction of the interval. Freezes the interval's
        timestamp in job.ts: generation stamps InterMetrics from it on
        both serial and pipelined paths, so output stays bit-identical
        even when generation runs a full interval later."""
        from veneur_tpu.core.pipeline import FlushJob

        flush_start = time.time() if now is None else float(now)
        self.last_flush_unix = flush_start
        self.flush_count += 1
        self.stats.gauge("flush.flush_timestamp_ns", flush_start * 1e9)
        # per-phase wall times of this flush (reference tallyMetrics/
        # generateInterMetrics timing samples, flusher.go:169-298);
        # read by tools/bench_e2e_flush.py for the 1M-series artifact.
        # last_flush_phases rebinds only when _flush_emit COMPLETES:
        # observers polling mid-flush (the loadgen cadence decomposition)
        # must see the last finished flush, not a half-filled dict
        phases: dict[str, float] = {}
        _t = time.perf_counter()

        if self.native_mode:
            # events/service checks buffered in C++ (native readers have
            # no Python on the datagram path; the pump drains every 100ms
            # but this flush must see everything received before it).
            # Lines landing AFTER this drain are caught at epoch close —
            # worker.swap drains other_lines in the same critical section
            # as the context reset — and parsed into the next epoch below.
            self._drain_native_events()
            self._drain_native_ssf_fallbacks()

        other_samples = self.event_worker.flush()
        if other_samples:
            self.stats.count("worker.other_samples_flushed_total",
                             len(other_samples))
        for sink in self.metric_sinks:
            try:
                sink.flush_other_samples(other_samples)
            except Exception:
                log.exception("sink %s FlushOtherSamples failed", sink.name())

        _t_span = time.perf_counter()
        if self.span_pipeline is not None:
            # derive the interval's span batches into the workers BEFORE
            # the epoch swap below, so a span's metrics land in the same
            # epoch as the statsd samples that arrived beside it
            self.span_pipeline.flush()
        self.span_worker.flush()
        self.stats.time_in_nanoseconds(
            "worker.span.flush_duration_ns",
            (time.perf_counter() - _t_span) * 1e9)

        # per-service span counters (reference handleSSF sync.Map counters
        # reported at flush, server.go:1088-1101)
        with self._ssf_stats_lock:
            span_counts = self.ssf_spans_received
            self.ssf_spans_received = {}

        qs = device_quantiles(self.percentiles, self.aggregates)
        # Two-phase flush: the per-worker ingest lock is held only across
        # swap() (epoch close + device dispatches — the map-swap analog of
        # worker.go:498-517); the device readback in extract_snapshot()
        # runs unlocked, so next-interval ingest proceeds concurrently
        # with a large extraction (SURVEY §7 "Latency budget").
        swapped = []
        for i, (worker, lock) in enumerate(
                zip(self.workers, self._worker_locks)):
            with lock:
                if i == 0 and self._native_ssf:
                    # drained in the SAME lock hold as the worker swap —
                    # the swap resets the C++ context, and a span landing
                    # between a separate drain and the reset would lose
                    # its service count
                    for svc, n in (
                            worker._native.drain_ssf_services().items()):
                        span_counts[svc] = span_counts.get(svc, 0) + n
                        # native-extracted spans derive on device and
                        # never pass handle_ssf: fold them into the
                        # conservation tallies here (same lock hold as
                        # the context reset, so none are lost mid-swap)
                        self._spans_native_total += n
                # canonical per-worker tallies (README.md:292-294),
                # captured before flush resets the epoch counters
                self.stats.count("worker.metrics_processed_total",
                                 worker.processed, tags=[f"worker:{i}"])
                self.stats.count("worker.metrics_imported_total",
                                 worker.imported, tags=[f"worker:{i}"])
                dropped = worker.overload_dropped
                if dropped:
                    # samples shed at the native spill caps (overload;
                    # drop-don't-block) — loud in self-telemetry, since
                    # sustained nonzero means the host can't keep up
                    self.stats.count("ingest.overload_dropped_total",
                                     dropped, tags=[f"worker:{i}"])
                    worker.overload_dropped = 0
                swapped.append(worker.swap(qs))
                n_staged = getattr(worker, "staged_samples_swapped", 0)
                if n_staged:
                    self.stats.count("worker.samples_staged_total",
                                     n_staged, tags=[f"worker:{i}"])
                if getattr(worker, "_reader_ctxs", None):
                    # per-reader commit attribution (swap's fence just
                    # settled reader_committed) + contention record:
                    # emitted as lifetime-deltas per context, stashed
                    # whole for ingress_stats/bench readers
                    rs = worker.reader_stats(
                        lock_stats=self._lock_stats_enabled)
                    prev = getattr(self, "_reader_reported", None) or {}
                    for kind, stat in (
                            ("committed", "ingest.reader_committed_total"),
                            ("dropped", "ingest.reader_dropped_total")):
                        for j, total in enumerate(rs[kind]):
                            delta = total - prev.get((kind, j), 0)
                            if delta:
                                self.stats.count(
                                    stat, delta, tags=[f"reader:{j}"])
                            prev[(kind, j)] = total
                    self._reader_reported = prev
                    self.last_reader_stats = rs
                if self.tenant_ledger is not None:
                    # per-tenant honest-drop counters, emitted as deltas
                    # of the worker's LIFETIME tallies (read post-swap:
                    # swap() folds the closing epoch — including any
                    # swap-time shed attribution — into the totals before
                    # resetting, exactly like processed_total). Lifetime
                    # deltas survive the epoch swap; a pre-swap per-epoch
                    # read would miss samples shed inside swap() itself.
                    life = worker.tenant_lifetime()
                    for kind, stat in (
                            ("rejected", "tenant.samples_rejected_total"),
                            ("dropped", "tenant.overload_dropped_total")):
                        for t, total in life[kind].items():
                            k = (i, kind, t)
                            delta = total - self._tenant_reported.get(k, 0)
                            if delta:
                                self._tenant_reported[k] = total
                                self.stats.count(
                                    stat, delta, tags=[f"tenant:{t}"])
        # event lines the swap caught at epoch close (would otherwise be
        # destroyed by the context reset): parse them into the NEW epoch,
        # OUTSIDE the worker locks — parsing re-enters _route
        for worker in self.workers:
            lines = getattr(worker, "pending_other_lines", None)
            if lines:
                worker.pending_other_lines = []
                for line in lines:
                    self.handle_metric_packet(line)
            pkts = getattr(worker, "pending_ssf_fallback", None)
            if pkts:
                worker.pending_ssf_fallback = []
                for pkt in pkts:
                    self.handle_trace_packet(pkt)
        phases["swap_s"] = time.perf_counter() - _t
        # always-hot flush decomposition: how many micro-folds streamed
        # the closed epoch to the device mirrors, and how much of the
        # swap above was the final residual drain + mirror handoff (the
        # loadgen controller reports both per interval as micro_folds /
        # drain_ms)
        micro_folds = sum(getattr(w, "micro_folds_swapped", 0)
                          for w in self.workers)
        phases["drain_s"] = sum(
            getattr(w, "micro_drain_swapped_s", 0.0) for w in self.workers)
        self.last_micro_folds = micro_folds
        if micro_folds:
            self.stats.count("worker.micro_folds_total", micro_folds)
        self.flush_governor.beat()  # swap complete: flush is live
        return FlushJob(ts=int(flush_start), flush_start=flush_start,
                        qs=qs, swapped=swapped, span_counts=span_counts,
                        phases=phases)

    def _flush_extract(self, job) -> None:
        """Device-readback flush phase: runs UNLOCKED, so next-interval
        ingest proceeds concurrently with a large extraction
        (SURVEY §7 "Latency budget")."""
        _t = time.perf_counter()
        snaps = job.snaps
        for i, (worker, sw) in enumerate(zip(self.workers, job.swapped)):
            try:
                snaps.append(
                    worker.extract_snapshot(sw, job.qs, self.interval))
            except Exception:
                # per-flush data is expendable by design (README.md:135-137)
                # but a readback failure on one worker must not destroy the
                # already-swapped intervals of the others
                log.exception("flush extraction failed for worker %d", i)
            self.flush_governor.beat()  # one worker's extraction done
            # guard maintenance runs with the ingest lock held — it
            # mutates LIVE state (quarantine to host / probe re-admit),
            # unlike the extraction above which only reads swapped state
            with self._worker_locks[i]:
                worker.device_guard_tick()
            g = worker.guard
            if (g.last_fault is not None
                    and g.last_fault != self._guard_last_fault.get(i)):
                # surface each new classified fault to the governor, so
                # a watchdog panic right after names the device error
                self._guard_last_fault[i] = g.last_fault
                desc = g.last_fault + (
                    f" — {g.trip_reason}" if g.trip_reason else "")
                self.flush_governor.note_fault(desc)
        if self.query_engine is not None:
            # commit AFTER every worker extracted: the query surface
            # flips to the new epoch atomically across workers
            self.query_engine.commit(job.ts)
        for snap in snaps:
            # per-type flushed-series counts (README.md:293)
            d = snap.directory
            for mtype, n in (
                ("counter", len(snap.scalars.counter_meta)),
                ("gauge", len(snap.scalars.gauge_meta)),
                ("histogram", d.num_histo_rows),
                ("set", d.num_set_rows),
            ):
                if n:
                    self.stats.count("worker.metrics_flushed_total", n,
                                     tags=[f"metric_type:{mtype}"])

        job.phases["extract_s"] = time.perf_counter() - _t
        # per-flush transfer accounting (health/ledger.py): the byte
        # counts that pin the O(samples) upload/readback diet, surfaced
        # the same way the reference surfaces flush phase timings
        h2d = sum(w.ledger.flush_h2d_bytes() for w in self.workers)
        d2h = sum(w.ledger.flush_d2h_bytes() for w in self.workers)
        self.last_flush_transfers = {"h2d_bytes": h2d, "d2h_bytes": d2h}
        if h2d or d2h:
            self.stats.count("flush.transfer_h2d_bytes", h2d)
            self.stats.count("flush.transfer_d2h_bytes", d2h)
        chunk_report = self.flush_governor.last_report
        self.last_flush_chunks = chunk_report
        # a micro-folds-only report (sub-floor pool: no chunking ran)
        # carries no chunk keys — guard on the key, not truthiness
        if "chunks" in chunk_report:
            self.stats.gauge("flush.extract_chunks",
                             chunk_report["chunks"])
            self.stats.time_in_nanoseconds(
                "flush.extract_chunk_max_ns",
                chunk_report["chunk_max_s"] * 1e9)

    def _flush_generate(self, job) -> None:
        """InterMetric-generation flush phase (host work over the
        already-extracted snapshots). Stamps every metric with job.ts —
        the tick-time clock — on both the columnar and object paths."""
        _t = time.perf_counter()
        snaps = job.snaps
        # Columnar fast path: the flush never materializes per-metric
        # Python objects up front — at 1M series the object loop alone is
        # seconds of host time (core/columnar.py). Columnar-capable sinks
        # consume the SoA batch directly; the rest share ONE memoized
        # materialization via the base flush_columnar, so a single legacy
        # sink no longer demotes every sink to the object path. Plugins
        # ride it too: they receive the batch itself — archival plugins
        # (veneur_tpu/archive/blob.py) serialize its arrays zero-copy,
        # and legacy TSV plugins iterate it, which shares the same
        # memoized materialization the object-path sinks use.
        use_columnar = bool(self.metric_sinks or self.plugins)
        final = job.final
        batch = None
        n_flushed = 0
        if use_columnar:
            from veneur_tpu.core.flusher import generate_columnar

            for snap in snaps:
                b = generate_columnar(
                    snap, self.is_local, self.percentiles,
                    self.aggregates, now=job.ts,
                    governor=self.flush_governor)
                if batch is None:
                    batch = b
                else:
                    batch.groups.extend(b.groups)
                    batch.extras.extend(b.extras)
            n_flushed = batch.count() if batch is not None else 0
        else:
            for snap in snaps:
                final.extend(
                    generate_inter_metrics(
                        snap, self.is_local, self.percentiles,
                        self.aggregates, now=job.ts,
                        governor=self.flush_governor
                    )
                )
            n_flushed = len(final)
        job.batch = batch
        job.n_flushed = n_flushed
        job.phases["generate_s"] = time.perf_counter() - _t

        if self.is_local and self.forwarder is not None:
            fwd_thread = threading.Thread(
                target=self.forwarder, args=(snaps,), daemon=True,
                name="forward",
            )
            fwd_thread.start()

    def _flush_emit(self, job) -> None:
        """Sink-emission flush phase plus the flush's self-telemetry
        tail. Rebinds last_flush_phases at the end so observers always
        read the phases of the most recently COMPLETED flush."""
        _t = time.perf_counter()
        phases = job.phases
        batch = job.batch
        final = job.final
        n_flushed = job.n_flushed
        snaps = job.snaps
        span_counts = job.span_counts
        if batch is not None and n_flushed:
            threads = []
            for sink in self.metric_sinks:
                t = threading.Thread(
                    target=self._flush_sink_columnar,
                    args=(sink, batch,
                          self.sink_excluded_tags.get(sink.name())),
                    daemon=True, name=f"flush-{sink.name()}",
                )
                t.start()
                threads.append(t)
            for t in threads:
                t.join(timeout=self.interval)
            phases["sink_flush_s"] = time.perf_counter() - _t
            if self.plugins:
                self._run_plugins_clipped(batch, phases)
        elif final:
            threads = []
            for sink in self.metric_sinks:
                routed = filter_routed(final, sink.name())
                routed = strip_excluded_tags(
                    routed, self.sink_excluded_tags.get(sink.name()))
                t = threading.Thread(
                    target=self._flush_sink, args=(sink, routed),
                    daemon=True, name=f"flush-{sink.name()}",
                )
                t.start()
                threads.append(t)
            for t in threads:
                t.join(timeout=self.interval)
            phases["sink_flush_s"] = time.perf_counter() - _t
            if self.plugins:
                self._run_plugins_clipped(final, phases)
        else:
            # quiet tick (nothing aggregated this interval): the sinks'
            # flush funnels never ran, but spilled payloads must keep
            # draining — an idle server would otherwise freeze its spill
            # (and an open breaker would never get its half-open probe),
            # stranding recovered-journal backlogs and post-outage
            # retries until fresh traffic happens to arrive
            threads = []
            for rname, man in self._delivery_managers():
                if not len(man.spill):
                    continue

                def _drain(m=man):
                    m.begin_flush()
                    m.retry_spill()

                t = threading.Thread(target=_drain, daemon=True,
                                     name=f"spill-drain-{rname}")
                t.start()
                threads.append(t)
            for t in threads:
                t.join(timeout=self.interval)
            if threads:
                phases["sink_flush_s"] = time.perf_counter() - _t

        # flush self-telemetry (reference flusher.go:38-47, worker.go:513)
        if self.config.count_unique_timeseries:
            self.stats.count(
                "flush.unique_timeseries_total", self._tally_timeseries(snaps),
                tags=[f"global_veneur:{str(not self.is_local).lower()}"])
        self.stats.count("flush.post_metrics_total", n_flushed)
        if self.query_engine is not None:
            served = self.query_engine.queries_served
            failed = self.query_engine.queries_failed
            if served - self._query_reported[0]:
                self.stats.count("query.served_total",
                                 served - self._query_reported[0])
            if failed - self._query_reported[1]:
                self.stats.count("query.errors_total",
                                 failed - self._query_reported[1])
            self._query_reported = (served, failed)
        # per-phase wall times as self-metrics (the reference samples its
        # flush phases via ssf.Timing in tallyMetrics/generateInterMetrics,
        # flusher.go:169-298; ours are exact phase boundaries)
        for phase_name, secs in phases.items():
            self.stats.time_in_nanoseconds(
                "flush.phase_duration_ns", secs * 1e9,
                tags=[f"phase:{phase_name.removesuffix('_s')}"])
        from veneur_tpu.core.worker import DeviceWorker as _DW

        if _DW.pallas_fallbacks:
            # nonzero means the fused TPU kernel raised and extraction
            # was demoted to the XLA path for the process lifetime
            self.stats.count("flush.pallas_fallback_total",
                             _DW.pallas_fallbacks)
            _DW.pallas_fallbacks = 0
        # device fault domain telemetry (ops/device_guard.py): the guard
        # counters are lifetime totals — emit deltas, same discipline as
        # the reader/tenant counters above. host_fallbacks counts flushes
        # that completed on the host engine (degraded but conserved).
        fallbacks = sum(w.host_fallback_flushes for w in self.workers)
        if fallbacks - self._host_fallbacks_reported:
            self.stats.count("flush.host_fallbacks",
                             fallbacks - self._host_fallbacks_reported)
        self._host_fallbacks_reported = fallbacks
        quarantined = 0
        for i, w in enumerate(self.workers):
            if w.guard.quarantined:
                quarantined += 1
            for key, total in w.guard.counters().items():
                k = (i, key)
                delta = total - self._guard_counters_reported.get(k, 0)
                if delta:
                    self._guard_counters_reported[k] = total
                    self.stats.count(key, delta, tags=[f"worker:{i}"])
        self.stats.gauge("device.guard.quarantined_workers", quarantined)
        for svc, n in span_counts.items():
            self.stats.count("ssf.spans.received_total", n,
                             tags=[f"service:{svc}"])
        # statsd counters are per-interval increments: report the delta
        # (the property already totals the Python cells, the workers'
        # attributed counts, and the undrained native delta). The
        # property's reads aren't atomic vs a concurrent pump drain, so a
        # snapshot can transiently run BEHIND the last report — clamp so
        # a negative increment is never emitted; the next interval's
        # delta absorbs it.
        errors_now = max(self.parse_errors, self._errors_reported)
        self.stats.count("packet.error_total",
                         errors_now - self._errors_reported)
        self._errors_reported = errors_now
        # span-pipeline counters (reference worker.go:688,716-717:
        # ingest_timeout_total per sink, hit_chan_cap for channel drops)
        for name, total in list(self.span_worker.ingest_timeouts.items()):
            key = ("__span_worker__", f"timeout:{name}")
            delta = total - self._span_sink_reported.get(key, 0)
            self._span_sink_reported[key] = total
            if delta:
                self.stats.count("worker.span.ingest_timeout_total", delta,
                                 tags=[f"sink:{name}"])
        for name, total in list(self.span_worker.lane_drops.items()):
            key = ("__span_worker__", f"lane:{name}")
            delta = total - self._span_sink_reported.get(key, 0)
            self._span_sink_reported[key] = total
            if delta:
                # burst overflow of a sink's lane (no reference analog:
                # upstream blocks per span instead; this is the
                # loss-over-stall counterpart)
                self.stats.count("worker.span.lane_drop_total", delta,
                                 tags=[f"sink:{name}"])
        key = ("__span_worker__", "chan_cap")
        delta = self.span_worker.spans_dropped - self._span_sink_reported.get(
            key, 0)
        self._span_sink_reported[key] = self.span_worker.spans_dropped
        if delta:
            self.stats.count("worker.span.hit_chan_cap", delta)
        # span→metric derivation counters (satellite of the columnar
        # pipeline: soaks assert span conservation from these plus the
        # ingress_stats "spans" block)
        if self.span_pipeline is not None:
            pstats = self.span_pipeline.stats()
            pairs = (
                ("spans_ingested", "worker.span.columnar_ingested_total"),
                ("spans_derived", "worker.span.derived_total"),
                ("derived_rows", "worker.span.derived_metric_rows_total"),
                ("spans_dropped", "worker.span.pipeline_drop_total"),
                ("invalid_samples", "worker.span.invalid_samples_total"),
            )
            for attr, metric in pairs:
                key = ("__span_pipeline__", attr)
                delta = pstats[attr] - self._span_sink_reported.get(key, 0)
                self._span_sink_reported[key] = pstats[attr]
                if delta:
                    self.stats.count(metric, delta)
        else:
            ext = self._extraction_sink
            with ext._stats_lock:
                ext_pairs = (
                    ("spans_seen", "worker.span.derived_total",
                     ext.spans_seen),
                    ("derived_rows", "worker.span.derived_metric_rows_total",
                     ext.derived_rows),
                    ("invalid_samples", "worker.span.invalid_samples_total",
                     ext.invalid_samples),
                )
            for attr, metric, total in ext_pairs:
                key = ("__extraction__", attr)
                delta = total - self._span_sink_reported.get(key, 0)
                self._span_sink_reported[key] = total
                if delta:
                    self.stats.count(metric, delta)
        # span-sink delta counters (reference sinks/sinks.go:60-78;
        # sinks track cumulative attributes, telemetry reports deltas)
        for sink in self.span_sinks:
            tags = [f"sink:{sink.name()}"]
            for attr, metric in (("spans_flushed", "sink.spans_flushed_total"),
                                 ("spans_dropped", "sink.spans_dropped_total")):
                total = getattr(sink, attr, None)
                if total is None:
                    continue
                key = (sink.name(), attr)
                delta = total - self._span_sink_reported.get(key, 0)
                self._span_sink_reported[key] = total
                if delta:
                    self.stats.count(metric, delta, tags=tags)
        # plugin delta counters: the plugins' own cumulative failure /
        # progress attributes (localfile/s3/archive_blob) reported as
        # interval deltas like the sinks above, so a silently failing
        # archiver shows up on the same dashboard as a failing sink
        for plugin in self.plugins:
            pname = plugin.name()
            ptags = [f"plugin:{pname}"]
            for attr, metric in (
                    ("flush_errors", "plugins.flush_errors_total"),
                    ("uploads", "plugins.uploads_total"),
                    ("rotations", "plugins.rotations_total")):
                total = getattr(plugin, attr, None)
                if total is None:
                    continue
                key = (pname, attr)
                delta = total - self._plugin_reported.get(key, 0)
                self._plugin_reported[key] = total
                if delta:
                    self.stats.count(metric, delta, tags=ptags)
        # delivery-reliability telemetry (sinks/delivery.py): every
        # manager's cumulative counters as interval deltas, breaker and
        # spill occupancy as gauges. A sink behind — breaker not closed
        # or fresh spill deferrals — for DELIVERY_BEHIND_INTERVALS
        # consecutive flushes feeds the pipeline's downstream-behind
        # shed signal; serial servers skip the signal (their emit stage
        # already backpressures the tick, and shedding ingest for a
        # dead backend would drop data the other sinks still take).
        behind = False
        for rname, man in self._delivery_managers():
            if (self.tenant_ledger is not None
                    and man.abusive_tenants is None):
                # tenant-aware spill eviction (sinks/delivery.py): wired
                # lazily so sinks attached after server construction
                # still get the hook by their first flush
                man.abusive_tenants = self.tenant_ledger.over_budget
            dstats = man.stats()
            tags = [f"sink:{rname}"]
            for key in DELIVERY_STAT_COUNTERS:
                total = dstats[key]
                rkey = (rname, key)
                delta = total - self._delivery_reported.get(rkey, 0)
                self._delivery_reported[rkey] = total
                if delta:
                    self.stats.count(f"delivery.{key}", delta, tags=tags)
                    if key == "deferred_payloads":
                        behind = True
            self.stats.gauge("delivery.circuit_state",
                             float(dstats["circuit_state_code"]), tags=tags)
            self.stats.gauge("delivery.spilled_payloads",
                             float(dstats["spilled_payloads"]), tags=tags)
            self.stats.gauge("delivery.spilled_bytes",
                             float(dstats["spilled_bytes"]), tags=tags)
            if dstats["circuit_state"] != "closed":
                behind = True
        self._delivery_behind_consec = (
            self._delivery_behind_consec + 1 if behind else 0)
        from veneur_tpu.health.policy import delivery_should_signal_behind

        if (self.flush_pipeline is not None
                and delivery_should_signal_behind(
                    self._delivery_behind_consec)):
            self.stats.count("flush.delivery_behind_total", 1)
            self.flush_pipeline.note_downstream_behind()
        # forward-path self-telemetry: the local forwarder's cumulative
        # counters as interval deltas, per proxy destination (tagged
        # proxy:<addr>) plus the spread-level respread/pick counters.
        # GRPCForwarder and SpreadForwarder report the same shape via
        # forward_stats() (the plain `stats` attribute is their
        # telemetry sink), so a single-proxy deployment shows the same
        # dashboard with one proxy tag value.
        fwd = self.forwarder
        if fwd is not None and hasattr(fwd, "forward_stats"):
            try:
                fstats = fwd.forward_stats()
            except Exception:  # noqa: BLE001 — telemetry must not wedge
                log.exception("forwarder stats failed")
                fstats = None
            if fstats:
                for name in ("respread_total", "respread_ambiguous_total",
                             "dropped_metrics", "picks_p2c", "picks_rr"):
                    total = fstats.get(name)
                    if total is None:
                        continue
                    key = ("", name)
                    delta = total - self._forward_reported.get(key, 0)
                    self._forward_reported[key] = total
                    if delta:
                        self.stats.count(f"forward.{name}", delta)
                self.stats.gauge("forward.proxies",
                                 float(fstats.get("proxies", 0)))
                for addr, dest in fstats.get("destinations", {}).items():
                    ptags = [f"proxy:{addr}"]
                    for name in ("sent_metrics", "sent_batches",
                                 "respread_in", "respread_out"):
                        total = dest.get(name)
                        if total is None:
                            continue
                        key = (addr, name)
                        delta = total - self._forward_reported.get(key, 0)
                        self._forward_reported[key] = total
                        if delta:
                            self.stats.count(f"forward.{name}", delta,
                                             tags=ptags)
                    for cause, total in (dest.get("errors") or {}).items():
                        key = (addr, f"errors.{cause}")
                        delta = total - self._forward_reported.get(key, 0)
                        self._forward_reported[key] = total
                        if delta:
                            self.stats.count(
                                "forward.errors_total", delta,
                                tags=ptags + [f"cause:{cause}"])
                    live = bool(dest.get("live", True))
                    self.stats.gauge("forward.lane_live",
                                     1.0 if live else 0.0, tags=ptags)
                    if live and "depth" in dest:
                        self.stats.gauge("forward.lane_depth",
                                         float(dest["depth"]), tags=ptags)
        # per-tenant QoS gauges (core/tenancy.py): live/rejected series
        # per tenant from the shared ledger, plus overload-shed samples
        # attributed by the governor — the operator-facing view of which
        # tenant is spending the cardinality budget
        led = self.tenant_ledger
        if led is not None:
            for t, n in led.live_counts().items():
                self.stats.gauge("tenant.series_live", float(n),
                                 tags=[f"tenant:{t}"])
            for t, n in led.series_rejected_counts().items():
                self.stats.gauge("tenant.series_rejected", float(n),
                                 tags=[f"tenant:{t}"])
            for t, n in self.flush_governor.tenant_shed_counts().items():
                self.stats.gauge("tenant.shed_samples", float(n),
                                 tags=[f"tenant:{t}"])
        # runtime gauges (analog of the Go runtime stats, flusher.go:32-47;
        # gc.number is cumulative completed collections, mem.rss_bytes is
        # CURRENT resident set from /proc — not the misleading peak)
        self.stats.gauge("gc.number", float(
            sum(s["collections"] for s in gc.get_stats())))
        rss = _current_rss_bytes()
        if rss is not None:
            self.stats.gauge("mem.rss_bytes", float(rss))
        # total duration from the tick-time clock: under the pipeline
        # this includes inter-stage queue wait, which is the honest
        # end-to-end latency of the interval's flush
        self.stats.time_in_nanoseconds(
            "flush.total_duration_ns",
            (time.time() - job.flush_start) * 1e9)
        self.last_flush_phases = phases
        self.last_emit_unix = time.time()

    @staticmethod
    def _tally_timeseries(snaps: list[FlushSnapshot]) -> int:
        """Merge per-worker unique-timeseries HLLs and estimate
        (reference Server.tallyTimeseries, flusher.go:134-143)."""
        import numpy as np
        from veneur_tpu.ops import hll as hll_ops
        regs = [s.unique_timeseries_registers for s in snaps
                if s.unique_timeseries_registers is not None]
        if not regs:
            return 0
        merged = regs[0]
        for r in regs[1:]:
            merged = np.maximum(merged, r)
        import math
        precision = int(math.log2(merged.shape[-1]))
        est = hll_ops.estimate(merged[None, :], precision=precision)
        return int(float(np.asarray(est)[0]))

    def _run_plugins_clipped(self, metrics, phases: dict) -> None:
        """Run the plugin pass in a worker thread joined at the flush
        interval — the same deadline clipping sinks get — so a hung
        plugin (blocked PUT, full disk) can never stall the emit stage
        past its tick. The thread is daemon: an overrun finishes (or
        dies with the process) without wedging shutdown."""
        t0 = time.perf_counter()
        t = threading.Thread(
            target=self._flush_plugins, args=(metrics,), daemon=True,
            name="flush-plugins",
        )
        t.start()
        t.join(timeout=self.interval)
        if t.is_alive():
            self.stats.count("plugins.flush_clipped_total", 1)
        phases["plugin_flush_s"] = time.perf_counter() - t0

    def _flush_plugins(self, metrics) -> None:
        """reference flusher.go:117-131: plugins run after the sinks.
        ``metrics`` is the ColumnarMetrics batch on the columnar path
        (iterable via the shared materialization) or the object list.
        Failures count — an exception here rides the self-telemetry
        stream as plugins.flush_errors_total, not just the log."""
        for plugin in self.plugins:
            start = time.time()
            tags = [f"plugin:{plugin.name()}"]
            try:
                plugin.flush(metrics, self.hostname)
            except Exception:
                log.exception("plugin %s flush failed", plugin.name())
                self.stats.count("plugins.flush_errors_total", 1, tags=tags)
            finally:
                self.stats.time_in_nanoseconds(
                    "plugins.flush_total_duration_ns",
                    (time.time() - start) * 1e9, tags=tags)

    def _flush_sink_columnar(self, sink: MetricSink, batch,
                             excluded_tags) -> None:
        start = time.time()
        tags = [f"sink:{sink.name()}"]
        try:
            # per-sink capability negotiation: try the native emit tier
            # first (native/emit.cpp serializers, GIL released); a False
            # return means the sink couldn't take this batch natively
            # and the Python columnar formatter runs instead
            handled = False
            if (self.flush_emit_native
                    and getattr(sink, "supports_native_emit", False)):
                handled = sink.flush_columnar_native(batch, excluded_tags)
            fn = getattr(sink, "flush_columnar", None)
            if handled:
                pass
            elif fn is not None:
                fn(batch, excluded_tags)
            else:
                # duck-typed sink (name()/flush() without the MetricSink
                # base): hand it the shared materialization, routed and
                # tag-stripped like the object path would
                metrics = filter_routed(batch.materialize(), sink.name())
                sink.flush(strip_excluded_tags(metrics, excluded_tags))
        except Exception:
            log.exception("sink %s columnar flush failed", sink.name())
            self.stats.count("flush.error_total", 1, tags=tags)
        else:
            self.stats.count(
                "sink.metrics_flushed_total", batch.count_for(sink.name()),
                tags=tags)
        finally:
            self.stats.time_in_nanoseconds(
                "sink.metric_flush_total_duration_ns",
                (time.time() - start) * 1e9, tags=tags)

    def _flush_sink(self, sink: MetricSink,
                    metrics: list[InterMetric]) -> None:
        start = time.time()
        tags = [f"sink:{sink.name()}"]
        try:
            sink.flush(metrics)
        except Exception:
            log.exception("sink %s flush failed", sink.name())
            self.stats.count("flush.error_total", 1, tags=tags)
        else:
            self.stats.count(
                "sink.metrics_flushed_total", len(metrics), tags=tags)
        finally:
            # canonical per-sink telemetry (reference sinks/sinks.go:11-24);
            # duration is recorded even on failure — that's when it matters
            self.stats.time_in_nanoseconds(
                "sink.metric_flush_total_duration_ns",
                (time.time() - start) * 1e9, tags=tags)

    # -- watchdog -----------------------------------------------------------

    def flush_watchdog(self) -> None:
        """Die if flushes stop happening, so process supervision restarts us
        (reference FlushWatchdog, server.go:948-990) — with one deliberate
        departure, the progress-aware deferral contract (health/policy.py):

        An overdue flush defers the panic WHILE ITS CHUNKS ARE COMPLETING.
        Chunked degraded-mode extraction makes a slow flush legitimate —
        bounded steps at the rate the hardware allows — and killing it
        would lose both the interval and the progress; sustained overload
        is the shedding layer's job (_adapt_spill_caps), not the
        watchdog's. A STALLED flush (no progress beat within the stall
        window) panics exactly as the reference would, as does a silent
        flush loop with nothing in flight."""
        missed = self.config.flush_watchdog_missed_flushes
        if missed == 0:
            return
        from veneur_tpu.health import watchdog_should_defer

        while not self._shutdown.is_set():
            if self._shutdown.wait(self.interval):
                return
            now = time.time()
            overdue = now - self.last_flush_unix
            if overdue > missed * self.interval:
                defer, why = watchdog_should_defer(
                    now, self.flush_governor, self.interval)
                if defer:
                    log.warning(
                        "flush watchdog: flush %.1fs overdue but "
                        "deferring (%s)", overdue, why)
                    self.stats.count("flush.watchdog_deferred_total", 1)
                    continue
                log.critical(
                    "flush watchdog: no flush for %.1fs (> %d intervals;"
                    " %s); aborting", overdue, missed, why,
                )
                os._exit(2)

    def start_watchdog(self) -> None:
        self._spawn(self.flush_watchdog, "flush-watchdog")

    # -- lifecycle ----------------------------------------------------------

    def shutdown(self) -> bool:
        """reference Server.Shutdown (server.go:1473). Idempotent — the
        /quitquitquit handler thread and the main loop may both call it.

        Returns False when a compute thread is still inside XLA/C++
        after the bounded join: the caller should exit via os._exit so
        interpreter finalization can't unwind it mid-frame."""
        self._shutdown.set()
        with self._shutdown_once_lock:
            if self._shutdown_done:
                # lost the once-race: the winner is mid-teardown, and
                # compute_threads_joined still holds its INITIAL True —
                # returning it now would tell the caller the join
                # succeeded before it ran (the caller would then let
                # interpreter finalization unwind a live XLA thread).
                # Wait for the winner; on timeout report False, the
                # conservative side (callers exit via os._exit).
                if not self._shutdown_complete.wait(timeout=30.0):
                    return False
                return self.compute_threads_joined
            self._shutdown_done = True
        try:
            return self._shutdown_teardown()
        finally:
            # set even when teardown raises: a loser blocked in the
            # wait above must not hang its full timeout on an exception
            self._shutdown_complete.set()

    def _shutdown_teardown(self) -> bool:
        """The winning shutdown() caller's teardown body."""
        self._stop_native_readers()
        if self.flush_pipeline is not None:
            # drain in-flight stages BEFORE sinks stop: the final
            # admitted interval's metrics must reach the sinks (the
            # shutdown contract tests/test_pipeline.py pins). Bounded —
            # a wedged sink forfeits the drain rather than the shutdown.
            if not self.flush_pipeline.stop(
                    drain=True, timeout=max(10.0, 2.0 * self.interval)):
                log.warning("flush pipeline did not drain within the "
                            "shutdown budget; in-flight flush data lost")
        # join the compute threads (bounded): a daemon thread still
        # inside XLA/C++ when the interpreter finalizes is force-unwound
        # mid-frame — glibc's "FATAL: exception not rethrown" abort
        # (reproduced by the overload soak exiting during a long flush).
        # Only threads spawned with compute=True are joined; listener
        # threads block in plain C syscalls (their sockets close below)
        # and joining them here would stall every shutdown instead.
        me = threading.current_thread()
        deadline = time.time() + 10.0
        for t in self._compute_threads:
            if t is me or not t.is_alive():
                continue
            t.join(timeout=max(0.1, deadline - time.time()))
        # a compute thread that outlived the bounded join is still inside
        # XLA/C++ (e.g. a starved multi-minute compile): the caller must
        # NOT let the interpreter finalize under it (glibc "FATAL:
        # exception not rethrown" / heap-corruption aborts at exit) —
        # exit with os._exit instead. All flush data is already out.
        self.compute_threads_joined = all(
            (t is me or not t.is_alive()) for t in self._compute_threads)
        if getattr(self, "_profile_dir", None):
            try:
                import jax.profiler

                jax.profiler.stop_trace()
                log.info("XLA profile written to %s", self._profile_dir)
            except Exception:
                log.exception("could not stop the JAX profiler")
            self._profile_dir = None
        self.stats.close()
        self.span_worker.stop()
        if self.forwarder is not None and hasattr(self.forwarder, "close"):
            # the spread forwarder settles its per-proxy spills and
            # stops its discovery refresher; single-destination
            # forwarders just close their channel
            try:
                self.forwarder.close()
            except Exception:
                log.exception("forwarder failed to close")
        for sink in list(self.metric_sinks) + list(self.span_sinks):
            try:
                sink.stop()
            except Exception:
                log.exception("sink %s failed to stop", sink.name())
        if self.import_server is not None:
            self.import_server.stop()
        if self.import_http is not None:
            self.import_http.stop()
        for scheme, server in self._query_servers:
            try:
                if scheme == "grpc":
                    server.stop(grace=0.5)
                else:
                    server.shutdown()
                    server.server_close()
            except Exception:
                log.exception("query listener (%s) failed to stop", scheme)
        self._query_servers.clear()
        for journal in self._journals.values():
            # final durability point: whatever is still spilled survives
            # for the next incarnation's recovery
            try:
                journal.sync()
                journal.close()
            except Exception:  # noqa: BLE001 — teardown must not wedge
                log.exception("spill journal close failed")
        self._journals.clear()
        handoff_fds = set()
        if self._handoff:
            for fds in self._listener_fds.values():
                handoff_fds.update(fds)
        for sock in self._sockets:
            try:
                if sock.fileno() in handoff_fds:
                    # fd rides through the re-exec; the kernel keeps
                    # queuing datagrams for the next process image
                    sock.detach()
                else:
                    sock.close()
            except OSError:
                pass
        for fd in self._socket_locks:
            try:
                os.close(fd)  # releases the flock
            except OSError:
                pass
        self._socket_locks.clear()
        return self.compute_threads_joined

    @property
    def version(self) -> str:
        return __version__

    @property
    def build_date(self) -> str:
        """Analog of the reference's linker-injected BUILD_DATE."""
        return os.environ.get("VENEUR_TPU_BUILD_DATE", "dev")
