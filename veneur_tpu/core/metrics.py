"""Core metric model: keys, scopes, parsed samples, and flushed points.

Behavioral spec: reference samplers/parser.go:22-96 (UDPMetric, MetricKey,
MetricScope) and samplers/samplers.go:16-127 (MetricType, RouteInformation,
InterMetric, aggregates, sink-routing tags).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional

# ---------------------------------------------------------------------------
# Scopes


class MetricScope(enum.IntEnum):
    """Where a metric is emitted (reference samplers/parser.go:66-70)."""

    MIXED = 0
    LOCAL_ONLY = 1
    GLOBAL_ONLY = 2


# Magic tags that set scope / sink routing at parse time
# (reference samplers/parser.go:394-408, samplers/samplers.go:110-127).
TAG_LOCAL_ONLY = "veneurlocalonly"
TAG_GLOBAL_ONLY = "veneurglobalonly"
SINK_ONLY_TAG_PREFIX = "veneursinkonly:"

# Tenant identity for the per-tenant QoS layer (core/tenancy.py). No
# reference analog — veneur has no tenant concept; the tag key is
# configurable (`tenant_tag_key`) and untagged traffic pools here.
DEFAULT_TENANT_TAG_KEY = "tenant"
DEFAULT_TENANT = "default"


# ---------------------------------------------------------------------------
# Metric identity


@dataclass(frozen=True)
class MetricKey:
    """Identity of a metric series: (name, type, deterministic joined tags).

    Reference: samplers/parser.go:72-96.
    """

    name: str
    type: str
    joined_tags: str

    def key_string(self) -> str:
        """Concatenation used for consistent-hash ring routing
        (reference samplers/parser.go:90-96)."""
        return self.name + self.type + self.joined_tags


# ---------------------------------------------------------------------------
# Parsed sample


@dataclass
class UDPMetric:
    """A single parsed client sample (reference samplers/parser.go:22-34).

    ``value`` is a float for counter/gauge/histogram/timer, a string for
    set, and an int status code for status checks.
    """

    key: MetricKey
    digest: int
    value: object
    sample_rate: float = 1.0
    tags: list[str] = field(default_factory=list)
    scope: MetricScope = MetricScope.MIXED
    timestamp: int = 0
    message: str = ""
    hostname: str = ""

    # Convenience accessors mirroring the embedded-struct style of the
    # reference's UDPMetric.
    @property
    def name(self) -> str:
        return self.key.name

    @property
    def type(self) -> str:
        return self.key.type

    @property
    def joined_tags(self) -> str:
        return self.key.joined_tags


def valid_metric(m: UDPMetric) -> bool:
    """Reference samplers/parser.go:211-216."""
    return bool(m.key.name) and m.value is not None


# ---------------------------------------------------------------------------
# Flushed points


class MetricType(enum.IntEnum):
    """Type of a flushed InterMetric (reference samplers/samplers.go:18-27)."""

    COUNTER = 0
    GAUGE = 1
    STATUS = 2


def route_info(tags: list[str]) -> Optional[frozenset[str]]:
    """Extract sink-routing info from ``veneursinkonly:`` tags.

    Returns None when the metric should go to every sink (the common case),
    else the set of sink names that should receive it.
    Reference: samplers/samplers.go:112-127.
    """
    info = None
    for tag in tags:
        if tag.startswith(SINK_ONLY_TAG_PREFIX):
            name = tag[len(SINK_ONLY_TAG_PREFIX):]
            info = frozenset([name]) if info is None else info | {name}
    return info


def tenant_of(tags: list[str], tag_key: str = DEFAULT_TENANT_TAG_KEY) -> str:
    """Extract the tenant id from a sample's tags at parse/ingest time.

    The tag key is configurable (``tenant_tag_key``); untagged traffic
    pools into ``DEFAULT_TENANT`` so single-tenant deployments see one
    uniform bucket. Same single-scan shape as ``route_info`` above —
    this runs on the per-sample hot path.
    """
    prefix = tag_key + ":"
    plen = len(prefix)
    for tag in tags:
        if tag.startswith(prefix):
            return tag[plen:] or DEFAULT_TENANT
    return DEFAULT_TENANT


def route_to(sinks: Optional[frozenset[str]], sink_name: str) -> bool:
    """A nil route table means every sink is eligible
    (reference samplers/samplers.go:38-44)."""
    return sinks is None or sink_name in sinks


@dataclass(slots=True)
class InterMetric:
    """A completed metric ready for sink flushing
    (reference samplers/samplers.go:48-61). slots: a flush materializes
    millions of these; slots cut per-instance memory ~3x and speed
    construction."""

    name: str
    timestamp: int
    value: float
    tags: list[str]
    type: MetricType
    message: str = ""
    hostname: str = ""
    # None => deliver to every sink; else only the named sinks.
    sinks: Optional[frozenset[str]] = None


# ---------------------------------------------------------------------------
# Histogram aggregate selection (reference samplers/samplers.go:63-98)


class Aggregate(enum.IntFlag):
    MIN = 1
    MAX = 2
    MEDIAN = 4
    AVERAGE = 8
    COUNT = 16
    SUM = 32
    HARMONIC_MEAN = 64


AGGREGATES_LOOKUP = {
    "min": Aggregate.MIN,
    "max": Aggregate.MAX,
    "median": Aggregate.MEDIAN,
    "avg": Aggregate.AVERAGE,
    "count": Aggregate.COUNT,
    "sum": Aggregate.SUM,
    "hmean": Aggregate.HARMONIC_MEAN,
}

AGGREGATE_NAMES = {
    Aggregate.MIN: "min",
    Aggregate.MAX: "max",
    Aggregate.MEDIAN: "median",
    Aggregate.AVERAGE: "avg",
    Aggregate.COUNT: "count",
    Aggregate.SUM: "sum",
    Aggregate.HARMONIC_MEAN: "hmean",
}


@dataclass
class HistogramAggregates:
    """Which aggregate series a histogram flush emits, plus their count
    (reference samplers/samplers.go:85-88)."""

    value: Aggregate
    count: int

    @classmethod
    def from_names(cls, names: list[str]) -> "HistogramAggregates":
        agg = Aggregate(0)
        n = 0
        for name in names:
            a = AGGREGATES_LOOKUP.get(name)
            if a is not None:
                agg |= a
                n += 1
        return cls(agg, n)


DEFAULT_AGGREGATES = HistogramAggregates.from_names(["min", "max", "count"])
