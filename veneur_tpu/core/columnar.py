"""Columnar InterMetric batches: the SoA flush path.

The reference materializes one Go struct per flushed metric
(generateInterMetrics, flusher.go:225-298) — cheap in Go, ~1µs each in
CPython. At 1M histogram series × ~6 output series that is several
seconds of host time per flush, which alone blows the 10s interval.
The TPU-native design therefore keeps the flush columnar end to end:
device extraction already produces dense per-row arrays, and this module
wraps them — masks and values computed with numpy vector ops, per-row
metadata referenced from the existing directory lists (never copied) —
so a flush at 1M series costs milliseconds to "generate".

Sinks that can consume columns directly (blackhole, prometheus — any
sink whose wire format is built per-row anyway) implement
``flush_columnar`` and never pay for Python objects; everything else
receives ``materialize()``, which produces exactly the objects
``generate_inter_metrics`` would have (same multiset; family-major
order). The Server picks the path per flush (core/server.py).

Semantics mirror flusher.generate_inter_metrics exactly, including the
mixed-scope double-count rules (flusher.go:61-74): equivalence is
pinned by tests/test_columnar.py against the object path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from veneur_tpu.core.metrics import InterMetric, MetricType

# aggregate columns appended after the [S, P] quantile block by the
# worker's packed extract (worker._pack_extract_columns): dmin, dmax,
# dsum, dcount, drecip, lmin, lmax, lsum, lweight, lrecip
EXTRACT_AGG_COLUMNS = 10


def unpack_extract_columns(packed: np.ndarray, p: int,
                           perm: Optional[np.ndarray] = None):
    """Split a packed extract array [S, P+10] back into the [S, P]
    quantile block and the ten [S] aggregate columns (the inverse of
    worker._pack_extract_columns, minus the f32 cast — that is one-way
    by design).

    ``perm``: optional row gather applied first — the series-sharded
    extract reads back in physical (shard-interleaved) row order and
    hands the logical-order permutation here."""
    if perm is not None:
        packed = packed[perm]
    qv = packed[:, :p]
    aggs = tuple(packed[:, p + i] for i in range(EXTRACT_AGG_COLUMNS))
    return qv, aggs


@dataclass
class MetricFamily:
    """One output series family over a row group: base-name suffix, type,
    per-row values, and an emission mask (None = every row emits)."""

    suffix: str
    type: MetricType
    values: np.ndarray  # f64[R]
    mask: Optional[np.ndarray]  # bool[R] or None

    def count(self, nrows: int) -> int:
        return int(self.mask.sum()) if self.mask is not None else nrows


@dataclass
class ColumnGroup:
    """Rows sharing a metadata table (histogram rows, set rows, counter
    rows, ...) and the families emitted over them.

    ``meta_at(i)`` returns (name, tags, sinks) for row i — an accessor
    into the directory's existing lists, so building a group never walks
    the rows."""

    nrows: int
    meta_at: Callable[[int], tuple]
    families: list[MetricFamily]
    # rows carrying veneursinkonly routing exist in this group (when
    # False, consumers skip all per-row routing checks)
    has_routing: bool = False
    # optional per-row wire fragment ("name \x1f tag \x1f ..." bytes)
    # accessor for native emitters; None entry = row needs the Python
    # path (separators in the data)
    frag_at: Optional[Callable[[int], Optional[bytes]]] = None
    # the pool's incremental \x1e-joined frag arena covering rows
    # [0, nrows) — handed to the native emit tier zero-copy (ctypes
    # views the bytearray's buffer directly); None = some row needs
    # the Python formatter
    meta_blob: Optional[bytearray] = None

    def count(self) -> int:
        return sum(f.count(self.nrows) for f in self.families)

    def rows_for(self, family: MetricFamily) -> np.ndarray:
        if family.mask is None:
            return np.arange(self.nrows)
        return np.nonzero(family.mask)[0]


@dataclass
class EmitGroupPlan:
    """One group's buffers packed for the native emit tier: the frag
    arena plus family columns stacked C-contiguous. Built once per flush
    and shared by every native-capable sink (each used to rebuild the
    blob and restack the columns per flush)."""

    nrows: int
    meta_blob: bytearray  # \x1e-joined "name \x1f tag..." records
    suffixes: list[str]
    family_types: np.ndarray  # i8[F]: 0 = counter, 1 = gauge
    values: np.ndarray  # f64[F, R] C-contiguous
    masks: np.ndarray  # u8[F, R] C-contiguous


@dataclass
class ColumnarMetrics:
    """One flush interval's metric output, columnar."""

    timestamp: int
    groups: list[ColumnGroup] = field(default_factory=list)
    # rare, already-materialized metrics (status checks)
    extras: list[InterMetric] = field(default_factory=list)

    def count(self) -> int:
        return sum(g.count() for g in self.groups) + len(self.extras)

    def __len__(self) -> int:
        return self.count()

    def __iter__(self):
        # drop-in for the object-path list (tests and embedders iterate
        # Server.flush()'s return); memoized, so iterating twice is cheap
        return iter(self.materialize())

    def count_for(self, sink_name: str) -> int:
        """Metrics actually routed to one sink (veneursinkonly rules) —
        the per-sink flushed-total the object path reports. Groups with
        no routed rows (the common case) contribute their full count
        without any per-row walk."""
        total = 0
        for g in self.groups:
            if not g.has_routing:
                total += g.count()
                continue
            meta_at = g.meta_at
            for fam in g.families:
                for i in g.rows_for(fam).tolist():
                    sinks = meta_at(i)[2]
                    if sinks is None or sink_name in sinks:
                        total += 1
        for m in self.extras:
            if m.sinks is None or sink_name in m.sinks:
                total += 1
        return total

    def emit_plan(self) -> list:
        """Per-group native emit plans (EmitGroupPlan), aligned with
        ``groups``; None entries mark groups the native serializers
        can't take (no frag arena, veneursinkonly routing, or a family
        type outside counter/gauge — those go through each sink's
        Python formatter). Memoized: in a multi-sink set every
        native-capable sink shares ONE stacking pass."""
        cached = getattr(self, "_emit_plan", None)
        if cached is not None:
            return cached
        from veneur_tpu.core.metrics import MetricType

        plans: list = []
        for g in self.groups:
            if (g.meta_blob is None or g.has_routing or not g.families
                    or any(f.type not in (MetricType.COUNTER,
                                          MetricType.GAUGE)
                           for f in g.families)):
                plans.append(None)
                continue
            plans.append(EmitGroupPlan(
                nrows=g.nrows,
                meta_blob=g.meta_blob,
                suffixes=[f.suffix for f in g.families],
                family_types=np.asarray(
                    [0 if f.type == MetricType.COUNTER else 1
                     for f in g.families], np.int8),
                values=np.stack([f.values for f in g.families]),
                masks=np.stack([
                    f.mask.astype(np.uint8) if f.mask is not None
                    else np.ones(g.nrows, np.uint8)
                    for f in g.families]),
            ))
        self._emit_plan = plans
        return plans

    def materialize(self) -> list[InterMetric]:
        """The compatibility path: the same InterMetric multiset the
        object generator emits, family-major. Memoized — in a mixed sink
        set every non-columnar sink shares ONE materialization (the base
        MetricSink.flush_columnar routes/filters per sink on top of it)."""
        cached = getattr(self, "_materialized", None)
        if cached is not None:
            return cached
        out: list[InterMetric] = []
        append = out.append
        ts = self.timestamp
        for g in self.groups:
            meta_at = g.meta_at
            for fam in g.families:
                suffix = fam.suffix
                mtype = fam.type
                vals = fam.values.tolist()  # one C pass boxes the floats
                for i in g.rows_for(fam).tolist():
                    name, tags, sinks = meta_at(i)
                    append(InterMetric(
                        name + suffix if suffix else name, ts,
                        vals[i], tags, mtype, sinks=sinks))
        out.extend(self.extras)
        self._materialized = out
        return out

    def iter_rows(self, sink_name: Optional[str] = None,
                  excluded_tags: Optional[set] = None,
                  include_extras: bool = True):
        """Yield (name, value, tags, type, ts) per emitted metric —
        the per-row feed for columnar sinks that format per metric.
        Applies veneursinkonly routing for ``sink_name`` and per-sink
        tag exclusion. Sinks that need the extras' message/hostname
        fields (status checks) pass include_extras=False and consume
        ``self.extras`` (full InterMetric objects) themselves."""
        ts = self.timestamp
        for g in self.groups:
            meta_at = g.meta_at
            check_routing = g.has_routing and sink_name is not None
            for fam in g.families:
                suffix = fam.suffix
                mtype = fam.type
                vals = fam.values.tolist()
                for i in g.rows_for(fam).tolist():
                    name, tags, sinks = meta_at(i)
                    if check_routing and sinks is not None \
                            and sink_name not in sinks:
                        continue
                    if excluded_tags:
                        tags = [t for t in tags
                                if t.split(":", 1)[0] not in excluded_tags]
                    yield (name + suffix if suffix else name,
                           vals[i], tags, mtype, ts)
        if not include_extras:
            return
        for m in self.extras:
            if sink_name is not None and m.sinks is not None \
                    and sink_name not in m.sinks:
                continue
            tags = m.tags
            if excluded_tags:
                tags = [t for t in tags
                        if t.split(":", 1)[0] not in excluded_tags]
            yield (m.name, m.value, tags, m.type, m.timestamp)
