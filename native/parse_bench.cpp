// Per-core DogStatsD parse+stage throughput microbench.
//
// VERDICT r4 item 4a: the 50M samples/s/chip north star is host-parse
// bound, and the round-4 artifacts only ever *extrapolated* per-core
// parse throughput from end-to-end runs. This bench measures it
// directly, phase by phase, with cycles/line (rdtsc):
//
//   parse    parse_line only (tokenize + value + tag normalize + digest)
//   commit   handle_line (parse + directory upsert + stage/SoA commit)
//   datagram vn_ingest over 25-line datagrams (the wire-facing API the
//            C++ readers call — includes line splitting)
//
// The corpus mirrors the production mix the overload soak blasts
// (timers with tags + sample rate, counters, gauges, HLL sets) plus a
// no-tag fast-path variant. Single-threaded by design: multiply by the
// deployment's reader-core budget (tools/bench_parse_percore.py runs
// the multi-process SO_REUSEPORT scaling harness where cores exist).
//
// Output: one JSON line on stdout.
//
// Build/run: make -C native parse_bench && ./native/parse_bench

#include "dogstatsd.cpp"

#include <chrono>
#include <cstdio>
#include <x86intrin.h>

namespace {

double now_s() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

std::vector<std::string> build_corpus(int n) {
  std::vector<std::string> lines;
  lines.reserve(n);
  char buf[256];
  for (int i = 0; i < n; ++i) {
    int series = i % 800;
    switch (i % 10) {
      case 0: case 1: case 2: case 3:  // 40% tagged timers
        std::snprintf(buf, sizeof buf,
                      "svc.req.latency.%d:%d.%02d|ms|@0.5|#env:prod,"
                      "region:us-east-1,service:api%d",
                      series, i % 300, i % 100, series % 16);
        break;
      case 4: case 5:  // 20% counters
        std::snprintf(buf, sizeof buf,
                      "svc.req.count.%d:%d|c|#env:prod,service:api%d",
                      series, 1 + i % 5, series % 16);
        break;
      case 6:  // 10% gauges
        std::snprintf(buf, sizeof buf, "svc.queue.depth.%d:%d|g|#env:prod",
                      series, i % 10000);
        break;
      case 7:  // 10% sets
        std::snprintf(buf, sizeof buf, "svc.users.%d:user%d|s|#env:prod",
                      series, i % 65536);
        break;
      default:  // 20% untagged timers (fast path)
        std::snprintf(buf, sizeof buf, "svc.db.time.%d:%d.%d|ms", series,
                      i % 200, i % 10);
        break;
    }
    lines.emplace_back(buf);
  }
  return lines;
}

}  // namespace

int main(int argc, char** argv) {
  int corpus_n = 4000;
  long long target_lines = 8'000'000;
  if (argc > 1) target_lines = std::atoll(argv[1]);

  auto lines = build_corpus(corpus_n);
  size_t total_bytes = 0;
  for (auto& l : lines) total_bytes += l.size();

  // -- phase 1: parse only ------------------------------------------------
  Scratch sc;
  Parsed p;
  long long parsed = 0;
  double sink = 0;  // defeat dead-code elimination
  double t0 = now_s();
  uint64_t c0 = __rdtsc();
  for (long long it = 0; parsed < target_lines; ++it) {
    const std::string& line = lines[it % corpus_n];
    if (parse_line(&sc, line, &p)) sink += p.value + p.digest;
    ++parsed;
  }
  uint64_t parse_cycles = __rdtsc() - c0;
  double parse_s = now_s() - t0;

  // -- phase 2: parse + commit (directory upsert + stage/SoA) -------------
  void* ctx = vn_ctx_new(14);
  vn_set_stage_depth(ctx, 64);
  long long committed = 0;
  t0 = now_s();
  c0 = __rdtsc();
  for (long long it = 0; committed < target_lines; ++it) {
    const std::string& line = lines[it % corpus_n];
    handle_line(static_cast<Ctx*>(ctx), line);
    ++committed;
    if ((it + 1) % 2'000'000 == 0) {
      // periodic drain keeps the SoA/stage memory bounded like the
      // runtime's pump does, at a realistic cadence
      vn_ctx_reset(ctx);
    }
  }
  uint64_t commit_cycles = __rdtsc() - c0;
  double commit_s = now_s() - t0;
  vn_ctx_free(ctx);

  // -- phase 3: full datagram API (vn_ingest, 25 lines/datagram) ----------
  std::vector<std::string> datagrams;
  {
    std::string d;
    for (int i = 0; i < corpus_n; ++i) {
      d += lines[i];
      if ((i + 1) % 25 == 0) {
        datagrams.push_back(d);
        d.clear();
      } else {
        d.push_back('\n');
      }
    }
    if (!d.empty()) datagrams.push_back(d);
  }
  ctx = vn_ctx_new(14);
  vn_set_stage_depth(ctx, 64);
  long long dg_lines = 0;
  t0 = now_s();
  c0 = __rdtsc();
  for (long long it = 0; dg_lines < target_lines; ++it) {
    const std::string& d = datagrams[it % datagrams.size()];
    vn_ingest(ctx, d.data(), static_cast<int>(d.size()));
    dg_lines += 25;
    if ((it + 1) % 80'000 == 0) vn_ctx_reset(ctx);
  }
  uint64_t dg_cycles = __rdtsc() - c0;
  double dg_s = now_s() - t0;
  vn_ctx_free(ctx);

  double avg_line = static_cast<double>(total_bytes) / corpus_n;
  std::printf(
      "{\"parse_lines_per_s\": %.0f, \"parse_cycles_per_line\": %.0f, "
      "\"commit_lines_per_s\": %.0f, \"commit_cycles_per_line\": %.0f, "
      "\"datagram_lines_per_s\": %.0f, \"datagram_cycles_per_line\": %.0f, "
      "\"avg_line_bytes\": %.1f, \"lines_timed\": %lld, \"sink\": %.3g}\n",
      parsed / parse_s, static_cast<double>(parse_cycles) / parsed,
      committed / commit_s, static_cast<double>(commit_cycles) / committed,
      dg_lines / dg_s, static_cast<double>(dg_cycles) / dg_lines, avg_line,
      target_lines, sink);
  return 0;
}
