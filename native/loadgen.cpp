// Wire-rate load generator / capture / replay (ISSUE 2 tentpole).
//
// A *ring* is an immutable sequence of pre-built datagrams. The synth
// path builds one from a declarative workload spec (metric-type mix,
// Zipf-distributed key cardinality, tag shape); the capture path
// records real datagrams off a socket; serialize/load round-trips a
// ring through a length-prefixed blob bit-exactly, so a captured
// incident can be replayed against the server byte-for-byte. The send
// loop cycles the ring at a paced rate with zero Python per packet —
// Python only starts/stops threads and reads counters, mirroring the
// reader ABI in dogstatsd.cpp.
//
// Pacing uses absolute deadlines (next_ns += lines * ns_per_line) and
// resyncs instead of bursting when it falls >50ms behind, the same
// policy as tools/_soak_common.make_blaster: a stalled sender must not
// follow the stall with an unrealistic packet burst.

#include <atomic>
#include <cerrno>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <time.h>
#include <unistd.h>

namespace {

// ---------------------------------------------------------------- RNG
// splitmix64: deterministic across platforms/compilers (std::
// distributions are implementation-defined, which would break the
// fixed-seed differential tests).
struct Rng {
    uint64_t s;
    explicit Rng(uint64_t seed) : s(seed) {}
    uint64_t next() {
        uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
        z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
        z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
        return z ^ (z >> 31);
    }
    double uniform() {  // [0, 1)
        return (double)(next() >> 11) * (1.0 / 9007199254740992.0);
    }
    uint64_t below(uint64_t n) { return n ? next() % n : 0; }
};

static uint64_t fnv1a64(const void* data, size_t n, uint64_t h) {
    const unsigned char* p = (const unsigned char*)data;
    for (size_t i = 0; i < n; i++) {
        h ^= p[i];
        h *= 1099511628211ULL;
    }
    return h;
}

static int64_t now_ns() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (int64_t)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

// --------------------------------------------------------------- Ring
struct Ring {
    std::vector<std::string> dgrams;
    std::vector<int32_t> lines;  // newline-delimited line count per dgram
    int64_t total_lines = 0;
    std::string blob;  // scratch for serialize (pointer stays valid
                       // until the next serialize call on this ring)
};

static int32_t count_lines(const std::string& d) {
    if (d.empty()) return 1;  // still a packet for pacing purposes
    int32_t n = 0;
    for (char c : d)
        if (c == '\n') n++;
    if (d.back() != '\n') n++;
    return n;
}

// Blob format (also the capture file format — load(serialize(r)) is
// bit-exact by construction): "VLG1" magic, u32le count, then per
// datagram u32le length + raw bytes.
static const uint32_t kMagic = 0x31474c56u;  // "VLG1" little-endian

static void put_u32(std::string& out, uint32_t v) {
    char b[4] = {(char)(v & 0xff), (char)((v >> 8) & 0xff),
                 (char)((v >> 16) & 0xff), (char)((v >> 24) & 0xff)};
    out.append(b, 4);
}

static bool get_u32(const unsigned char* p, size_t n, size_t& off,
                    uint32_t& v) {
    if (off + 4 > n) return false;
    v = (uint32_t)p[off] | ((uint32_t)p[off + 1] << 8) |
        ((uint32_t)p[off + 2] << 16) | ((uint32_t)p[off + 3] << 24);
    off += 4;
    return true;
}

// -------------------------------------------------------------- Synth
// Workload spec knobs mirror config.py's loadgen_* keys. Metric type
// order is fixed: c, g, ms, h, s — type_mix weights index into this.
static const char* kTypeSuffix[] = {"c", "g", "ms", "h", "s"};
static const int kNumTypes = 5;

static int64_t cum_pick(const std::vector<double>& cum, double u) {
    size_t lo = 0, hi = cum.size();
    while (lo < hi) {
        size_t mid = (lo + hi) / 2;
        if (cum[mid] <= u) lo = mid + 1; else hi = mid;
    }
    return (int64_t)(lo < cum.size() ? lo : cum.size() - 1);
}

struct Synth {
    Rng rng;
    std::vector<double> type_cum;    // cumulative type-mix weights
    std::vector<double> zipf_cum;    // cumulative Zipf key weights
    int64_t n_keys;
    int n_tags;
    int64_t tag_card;
    std::string prefix;
    // multi-tenant dimension (per-tenant QoS soak): <= 1 tenant means
    // NO tenant logic at all — zero extra RNG draws, no tenant tag,
    // byte-identical legacy output. With more, the LAST tenant id is
    // the abusive one: abusive_frac of lines go to it and its key
    // space churns over churn_keys names BEYOND n_keys (the
    // cardinality attack); innocents draw Zipf over the rest.
    int64_t n_tenants;
    double abusive_frac;
    int64_t churn_keys;
    std::vector<double> tenant_cum;  // Zipf over the innocent tenants

    Synth(uint64_t seed, const double* mix, int64_t keys, double zipf_s,
          int tags, int64_t tagc, const char* pfx, int pfx_len,
          int64_t tenants, double ab_frac, double tenant_zipf_s,
          int64_t churn)
        : rng(seed), n_keys(keys), n_tags(tags), tag_card(tagc),
          prefix(pfx, (size_t)pfx_len), n_tenants(tenants),
          abusive_frac(ab_frac), churn_keys(churn) {
        double acc = 0;
        for (int i = 0; i < kNumTypes; i++) {
            acc += (mix[i] > 0 ? mix[i] : 0);
            type_cum.push_back(acc);
        }
        zipf_cum.reserve((size_t)keys);
        double zacc = 0;
        for (int64_t k = 0; k < keys; k++) {
            zacc += 1.0 / std::pow((double)(k + 1), zipf_s);
            zipf_cum.push_back(zacc);
        }
        if (n_tenants > 1) {
            double tacc = 0;
            for (int64_t k = 0; k < n_tenants - 1; k++) {
                tacc += 1.0 / std::pow((double)(k + 1), tenant_zipf_s);
                tenant_cum.push_back(tacc);
            }
        }
    }

    int pick_type() {
        double u = rng.uniform() * type_cum.back();
        for (int i = 0; i < kNumTypes; i++)
            if (u < type_cum[i]) return i;
        return kNumTypes - 1;
    }

    int64_t pick_key() {
        return cum_pick(zipf_cum, rng.uniform() * zipf_cum.back());
    }

    // One DogStatsD line. Tag values are a deterministic function of
    // (key, slot) so a key names ONE series: realized series
    // cardinality equals realized key cardinality, not its product
    // with tag_card^n_tags.
    void emit_line(std::string& out) {
        int64_t tenant = -1;     // -1 = single-tenant legacy output
        int64_t key_override = -1;
        if (n_tenants > 1) {
            if (rng.uniform() < abusive_frac) {
                tenant = n_tenants - 1;
                if (churn_keys > 0)
                    key_override =
                        n_keys + (int64_t)rng.below((uint64_t)churn_keys);
            } else {
                tenant = cum_pick(tenant_cum,
                                  rng.uniform() * tenant_cum.back());
            }
        }
        int t = pick_type();
        int64_t key = key_override >= 0 ? key_override : pick_key();
        char buf[64];
        out += prefix;
        snprintf(buf, sizeof buf, ".%s%lld:", kTypeSuffix[t],
                 (long long)key);
        out += buf;
        switch (t) {
        case 0:  // counter: small positive integer deltas
            snprintf(buf, sizeof buf, "%llu",
                     (unsigned long long)(rng.below(100) + 1));
            break;
        case 1:  // gauge
            snprintf(buf, sizeof buf, "%llu.%02llu",
                     (unsigned long long)rng.below(10000),
                     (unsigned long long)rng.below(100));
            break;
        case 2:  // timer (ms)
        case 3:  // histogram
            snprintf(buf, sizeof buf, "%llu.%03llu",
                     (unsigned long long)rng.below(2000),
                     (unsigned long long)rng.below(1000));
            break;
        default:  // set: member id, cardinality bounded by tag_card
            snprintf(buf, sizeof buf, "e%llu",
                     (unsigned long long)rng.below(
                         (uint64_t)(tag_card > 0 ? tag_card : 64)));
            break;
        }
        out += buf;
        out += '|';
        out += kTypeSuffix[t];
        if (n_tags > 0 || tenant >= 0) {
            out += "|#";
            uint64_t h = fnv1a64(&key, sizeof key, 1469598103934665603ULL);
            for (int i = 0; i < n_tags; i++) {
                h = fnv1a64(&i, sizeof i, h);
                snprintf(buf, sizeof buf, "%st%d:v%llu",
                         i ? "," : "", i,
                         (unsigned long long)(tag_card > 0
                                                  ? h % (uint64_t)tag_card
                                                  : 0));
                out += buf;
            }
            if (tenant >= 0) {
                // tenant tag LAST, so single- and multi-tenant lines
                // share their prefix byte-for-byte up to it
                snprintf(buf, sizeof buf, "%stenant:t%lld",
                         n_tags > 0 ? "," : "", (long long)tenant);
                out += buf;
            }
        }
    }
};

// ------------------------------------------------------------- Sender
struct Sender {
    std::thread th;
    std::atomic<bool> stop{false};
    std::atomic<bool> done{false};
    std::atomic<int64_t> sent_lines{0};
    std::atomic<int64_t> sent_packets{0};
    std::atomic<int64_t> send_errors{0};
    std::atomic<int64_t> resyncs{0};
    std::atomic<int64_t> elapsed_ns{0};
    Ring* ring = nullptr;  // borrowed; caller keeps it alive
    int fd = -1;
    double lines_per_s = 0;
    int64_t max_lines = 0;  // 0 = until stopped
    bool stream_mode = false;
};

static void sender_loop(Sender* s) {
    const size_t n = s->ring->dgrams.size();
    const double ns_per_line =
        s->lines_per_s > 0 ? 1e9 / s->lines_per_s : 0.0;
    std::string scratch;
    int64_t start = now_ns();
    int64_t next_t = start;
    size_t i = 0;
    while (!s->stop.load(std::memory_order_relaxed)) {
        if (s->max_lines > 0 &&
            s->sent_lines.load(std::memory_order_relaxed) >= s->max_lines)
            break;
        const std::string& d = s->ring->dgrams[i];
        const int32_t lines = s->ring->lines[i];
        i = (i + 1 == n) ? 0 : i + 1;
        const char* data = d.data();
        size_t len = d.size();
        if (s->stream_mode) {
            // TCP framing: the stream reader splits on newlines, so a
            // datagram becomes its lines plus a trailing newline
            scratch.assign(d);
            if (scratch.empty() || scratch.back() != '\n')
                scratch += '\n';
            data = scratch.data();
            len = scratch.size();
        }
        ssize_t r = send(s->fd, data, len, 0);
        if (r < 0) {
            if (errno == EINTR) continue;  // retry same datagram
            s->send_errors.fetch_add(1, std::memory_order_relaxed);
        } else {
            s->sent_packets.fetch_add(1, std::memory_order_relaxed);
            s->sent_lines.fetch_add(lines, std::memory_order_relaxed);
        }
        if (ns_per_line > 0) {
            next_t += (int64_t)(lines * ns_per_line);
            int64_t now = now_ns();
            if (next_t - now > 2000) {
                struct timespec ts;
                ts.tv_sec = next_t / 1000000000LL;
                ts.tv_nsec = next_t % 1000000000LL;
                clock_nanosleep(CLOCK_MONOTONIC, TIMER_ABSTIME, &ts,
                                nullptr);
            } else if (now - next_t > 50000000LL) {
                // >50ms behind: resync, never burst the backlog
                next_t = now;
                s->resyncs.fetch_add(1, std::memory_order_relaxed);
            }
        }
    }
    s->elapsed_ns.store(now_ns() - start, std::memory_order_relaxed);
    s->done.store(true, std::memory_order_release);
}

// ------------------------------------------------------------ Capture
struct Capture {
    std::thread th;
    std::atomic<bool> stop{false};
    std::atomic<int64_t> packets{0};
    std::atomic<int64_t> bytes{0};
    std::atomic<int64_t> truncated{0};
    int fd = -1;
    int max_len = 0;
    int64_t max_packets = 0;  // 0 = unbounded
    std::vector<std::string> dgrams;  // thread-private until joined
};

static void capture_loop(Capture* c) {
    std::vector<char> buf((size_t)c->max_len + 1);
    while (!c->stop.load(std::memory_order_relaxed)) {
        if (c->max_packets > 0 &&
            (int64_t)c->dgrams.size() >= c->max_packets)
            break;
        ssize_t n = recv(c->fd, buf.data(), buf.size(), 0);
        if (n < 0) {
            if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
                continue;  // SO_RCVTIMEO poll tick
            break;
        }
        if (n > c->max_len) {
            // oversized datagram cannot be replayed bit-exactly
            c->truncated.fetch_add(1, std::memory_order_relaxed);
            continue;
        }
        c->dgrams.emplace_back(buf.data(), (size_t)n);
        c->packets.fetch_add(1, std::memory_order_relaxed);
        c->bytes.fetch_add(n, std::memory_order_relaxed);
    }
}

}  // namespace

#ifndef LG_SOURCE_HASH
#define LG_SOURCE_HASH "unstamped"
#endif

extern "C" {

const char* vn_lg_source_hash() { return LG_SOURCE_HASH; }

// ----- ring lifecycle -------------------------------------------------
void* vn_lg_ring_new() { return new Ring(); }

void vn_lg_ring_free(void* r) { delete (Ring*)r; }

long long vn_lg_ring_count(void* r) {
    return (long long)((Ring*)r)->dgrams.size();
}

long long vn_lg_ring_total_lines(void* r) {
    return (long long)((Ring*)r)->total_lines;
}

long long vn_lg_ring_total_bytes(void* r) {
    long long n = 0;
    for (const auto& d : ((Ring*)r)->dgrams) n += (long long)d.size();
    return n;
}

// Content hash over (length, bytes) pairs — the cheap bit-exactness
// assertion for capture→replay round trips.
unsigned long long vn_lg_ring_hash(void* r) {
    uint64_t h = 1469598103934665603ULL;
    for (const auto& d : ((Ring*)r)->dgrams) {
        uint64_t len = d.size();
        h = fnv1a64(&len, sizeof len, h);
        h = fnv1a64(d.data(), d.size(), h);
    }
    return h;
}

// Borrowed pointer to datagram i (valid until the ring is mutated or
// freed). Returns length, -1 if out of range.
long long vn_lg_ring_datagram(void* r, long long i, const char** out) {
    Ring* ring = (Ring*)r;
    if (i < 0 || (size_t)i >= ring->dgrams.size()) return -1;
    *out = ring->dgrams[(size_t)i].data();
    return (long long)ring->dgrams[(size_t)i].size();
}

// Append one externally-built datagram (used for SSF rings, whose
// payloads Python builds once at setup time via the generated
// protobuf; the per-packet send path stays in C++).
long long vn_lg_ring_append(void* r, const char* data, long long len,
                            int lines) {
    if (len < 0 || lines < 0) return -1;
    Ring* ring = (Ring*)r;
    ring->dgrams.emplace_back(data, (size_t)len);
    ring->lines.push_back(lines > 0 ? lines : 1);
    ring->total_lines += (lines > 0 ? lines : 1);
    return (long long)ring->dgrams.size();
}

// ----- synth ----------------------------------------------------------
// Build ~n_lines of DogStatsD traffic into the ring, packed into
// datagrams of at most dgram_target bytes. type_mix is 5 weights in
// fixed order {c, g, ms, h, s}. n_tenants <= 1 emits single-tenant
// traffic byte-identical to the pre-tenant synth; > 1 stamps a
// trailing tenant:tN tag per line (see struct Synth). Returns datagram
// count, -1 on bad args.
long long vn_lg_ring_synth(void* r, unsigned long long seed,
                           long long n_keys, double zipf_s,
                           const double* type_mix,
                           int n_tags, long long tag_card,
                           const char* prefix, int prefix_len,
                           int dgram_target, long long n_lines,
                           long long n_tenants, double abusive_frac,
                           double tenant_zipf_s, long long churn_keys) {
    if (!r || !type_mix || !prefix || n_keys <= 0 ||
        n_keys > (1LL << 24) || n_lines <= 0 || prefix_len <= 0 ||
        n_tags < 0 || n_tags > 16 || dgram_target < 64 ||
        dgram_target > 65507 || zipf_s < 0)
        return -1;
    if (n_tenants < 1 || n_tenants > 4096 || abusive_frac < 0 ||
        abusive_frac > 1 || tenant_zipf_s < 0 || churn_keys < 0)
        return -1;
    double mix_sum = 0;
    for (int i = 0; i < kNumTypes; i++) {
        if (type_mix[i] < 0) return -1;
        mix_sum += type_mix[i];
    }
    if (mix_sum <= 0) return -1;
    Ring* ring = (Ring*)r;
    Synth sy(seed, type_mix, n_keys, zipf_s, n_tags, tag_card, prefix,
             prefix_len, n_tenants, abusive_frac, tenant_zipf_s,
             churn_keys);
    std::string dgram, line;
    int32_t dlines = 0;
    for (int64_t i = 0; i < n_lines; i++) {
        line.clear();
        sy.emit_line(line);
        if (!dgram.empty() &&
            dgram.size() + 1 + line.size() > (size_t)dgram_target) {
            ring->dgrams.push_back(dgram);
            ring->lines.push_back(dlines);
            ring->total_lines += dlines;
            dgram.clear();
            dlines = 0;
        }
        if (!dgram.empty()) dgram += '\n';
        dgram += line;
        dlines++;
    }
    if (!dgram.empty()) {
        ring->dgrams.push_back(dgram);
        ring->lines.push_back(dlines);
        ring->total_lines += dlines;
    }
    return (long long)ring->dgrams.size();
}

// ----- serialize / load ----------------------------------------------
// Returns blob length and sets *out to a pointer owned by the ring
// (valid until the next serialize call or free).
long long vn_lg_ring_serialize(void* r, const char** out) {
    Ring* ring = (Ring*)r;
    ring->blob.clear();
    put_u32(ring->blob, kMagic);
    put_u32(ring->blob, (uint32_t)ring->dgrams.size());
    for (const auto& d : ring->dgrams) {
        put_u32(ring->blob, (uint32_t)d.size());
        ring->blob += d;
    }
    *out = ring->blob.data();
    return (long long)ring->blob.size();
}

// Replaces the ring's contents from a serialized blob. Returns
// datagram count, -1 on malformed input (ring left empty).
long long vn_lg_ring_load(void* r, const char* data, long long len) {
    Ring* ring = (Ring*)r;
    ring->dgrams.clear();
    ring->lines.clear();
    ring->total_lines = 0;
    if (!data || len < 8) return -1;
    const unsigned char* p = (const unsigned char*)data;
    size_t n = (size_t)len, off = 0;
    uint32_t magic = 0, count = 0;
    if (!get_u32(p, n, off, magic) || magic != kMagic) return -1;
    if (!get_u32(p, n, off, count)) return -1;
    for (uint32_t i = 0; i < count; i++) {
        uint32_t dlen = 0;
        if (!get_u32(p, n, off, dlen) || off + dlen > n) {
            ring->dgrams.clear();
            ring->lines.clear();
            ring->total_lines = 0;
            return -1;
        }
        ring->dgrams.emplace_back((const char*)p + off, (size_t)dlen);
        off += dlen;
        int32_t lines = count_lines(ring->dgrams.back());
        ring->lines.push_back(lines);
        ring->total_lines += lines;
    }
    return (long long)ring->dgrams.size();
}

// ----- sender ---------------------------------------------------------
// Starts the paced send thread over a connected socket fd. The fd and
// the ring stay owned by the caller and must outlive the sender.
// lines_per_s <= 0 means unpaced (max rate); max_lines 0 means run
// until stop. stream_mode appends newline framing for TCP sockets.
void* vn_lg_send_start(void* ring, int fd, double lines_per_s,
                       long long max_lines, int stream_mode) {
    Ring* rg = (Ring*)ring;
    if (!rg || rg->dgrams.empty() || fd < 0) return nullptr;
    Sender* s = new Sender();
    s->ring = rg;
    s->fd = fd;
    s->lines_per_s = lines_per_s;
    s->max_lines = max_lines;
    s->stream_mode = stream_mode != 0;
    s->th = std::thread(sender_loop, s);
    return s;
}

long long vn_lg_send_lines(void* s) {
    return ((Sender*)s)->sent_lines.load(std::memory_order_relaxed);
}
long long vn_lg_send_packets(void* s) {
    return ((Sender*)s)->sent_packets.load(std::memory_order_relaxed);
}
long long vn_lg_send_errors(void* s) {
    return ((Sender*)s)->send_errors.load(std::memory_order_relaxed);
}
long long vn_lg_send_resyncs(void* s) {
    return ((Sender*)s)->resyncs.load(std::memory_order_relaxed);
}
int vn_lg_send_done(void* s) {
    return ((Sender*)s)->done.load(std::memory_order_acquire) ? 1 : 0;
}

// Joins the thread (idempotent) and returns elapsed ns of the send
// loop (0 if it never ran). The sender and its final counters stay
// readable until vn_lg_send_free.
long long vn_lg_send_stop(void* sp) {
    Sender* s = (Sender*)sp;
    s->stop.store(true, std::memory_order_relaxed);
    if (s->th.joinable()) s->th.join();
    return s->elapsed_ns.load(std::memory_order_relaxed);
}

void vn_lg_send_free(void* sp) {
    Sender* s = (Sender*)sp;
    s->stop.store(true, std::memory_order_relaxed);
    if (s->th.joinable()) s->th.join();
    delete s;
}

// ----- capture --------------------------------------------------------
// Starts capturing datagrams from fd (made blocking with a 100ms
// receive timeout, mirroring vn_reader_start). fd ownership stays with
// the caller. max_packets 0 = unbounded.
void* vn_lg_capture_start(int fd, int max_len, long long max_packets) {
    if (fd < 0 || max_len <= 0 || max_len > (1 << 20)) return nullptr;
    int flags = fcntl(fd, F_GETFL, 0);
    if (flags < 0 || fcntl(fd, F_SETFL, flags & ~O_NONBLOCK) < 0)
        return nullptr;
    struct timeval tv;
    tv.tv_sec = 0;
    tv.tv_usec = 100000;
    if (setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv) < 0)
        return nullptr;
    Capture* c = new Capture();
    c->fd = fd;
    c->max_len = max_len;
    c->max_packets = max_packets;
    c->th = std::thread(capture_loop, c);
    return c;
}

long long vn_lg_capture_packets(void* c) {
    return ((Capture*)c)->packets.load(std::memory_order_relaxed);
}
long long vn_lg_capture_truncated(void* c) {
    return ((Capture*)c)->truncated.load(std::memory_order_relaxed);
}

// Stops the capture thread (joins it). Data stays in the capture
// handle until detached or freed.
long long vn_lg_capture_stop(void* cp) {
    Capture* c = (Capture*)cp;
    c->stop.store(true, std::memory_order_relaxed);
    if (c->th.joinable()) c->th.join();
    return (long long)c->dgrams.size();
}

// After stop: moves the captured datagrams into a NEW ring (the
// capture handle is left empty). Replay is then capture → detach →
// vn_lg_send_start on the ring.
void* vn_lg_capture_detach_ring(void* cp) {
    Capture* c = (Capture*)cp;
    if (c->th.joinable()) return nullptr;  // must stop first
    Ring* ring = new Ring();
    ring->dgrams = std::move(c->dgrams);
    c->dgrams.clear();
    for (const auto& d : ring->dgrams) {
        int32_t lines = count_lines(d);
        ring->lines.push_back(lines);
        ring->total_lines += lines;
    }
    return ring;
}

void vn_lg_capture_free(void* cp) {
    Capture* c = (Capture*)cp;
    c->stop.store(true, std::memory_order_relaxed);
    if (c->th.joinable()) c->th.join();
    delete c;
}

}  // extern "C"
